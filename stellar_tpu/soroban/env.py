"""Host environment for wasm contracts: the 64-bit tagged-``Val`` ABI
and the host-function import table — the layer soroban-env-host puts
between wasmi and the ledger (reference boundary:
``src/rust/src/lib.rs:61-83`` links soroban-env-host, which defines the
Val encoding and the env interface; the crate itself is external to the
reference tree, so the import names here are this framework's own —
the TAG layout and semantics mirror the published soroban-env-common
value scheme so the conversion logic is protocol-shaped).

A ``Val`` is a u64: low 8 bits tag, high 56 bits body. Small immediates
(u32/i32, small u64/i64, short symbols, bool/void) travel inline;
larger values live in a per-invocation object table addressed by
handle. Handles never cross contract frames: cross-contract calls
convert through SCVal at the boundary, so a callee cannot forge a
caller's handles (same isolation the reference host enforces).

Host imports use single-letter module names grouped by area (context
"x", ledger "l", vec "v", map "m", buf "b", int "i", address "a",
call "c", crypto "d") — the grouping soroban-env uses for its export
names.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from stellar_tpu.crypto.sha import sha256
from stellar_tpu.soroban.wasm import Trap
from stellar_tpu.xdr.contract import (
    SCAddress, SCMapEntry, SCVal, SCValType,
)
from stellar_tpu.xdr.runtime import to_bytes

__all__ = ["ValConverter", "make_imports", "EnvError",
           "TAG_FALSE", "TAG_TRUE", "TAG_VOID", "TAG_U32", "TAG_I32",
           "TAG_U64_SMALL", "TAG_I64_SMALL", "TAG_SYMBOL_SMALL",
           "TAG_U64_OBJ", "TAG_I64_OBJ", "TAG_U128_OBJ", "TAG_I128_OBJ",
           "TAG_BYTES_OBJ", "TAG_STRING_OBJ", "TAG_SYMBOL_OBJ",
           "TAG_VEC_OBJ", "TAG_MAP_OBJ", "TAG_ADDRESS_OBJ",
           "sym_to_small", "small_to_sym"]

T = SCValType

# Tag values mirror soroban-env-common's Tag enum
TAG_FALSE = 0
TAG_TRUE = 1
TAG_VOID = 2
TAG_ERROR = 3
TAG_U32 = 4
TAG_I32 = 5
TAG_U64_SMALL = 6
TAG_I64_SMALL = 7
TAG_TIMEPOINT_SMALL = 8
TAG_DURATION_SMALL = 9
TAG_U128_SMALL = 10
TAG_I128_SMALL = 11
TAG_U256_SMALL = 12
TAG_I256_SMALL = 13
TAG_SYMBOL_SMALL = 14
TAG_U64_OBJ = 64
TAG_I64_OBJ = 65
TAG_TIMEPOINT_OBJ = 66
TAG_DURATION_OBJ = 67
TAG_U128_OBJ = 68
TAG_I128_OBJ = 69
TAG_U256_OBJ = 70
TAG_I256_OBJ = 71
TAG_BYTES_OBJ = 72
TAG_STRING_OBJ = 73
TAG_SYMBOL_OBJ = 74
TAG_VEC_OBJ = 75
TAG_MAP_OBJ = 76
TAG_ADDRESS_OBJ = 77

_M56 = (1 << 56) - 1
_M64 = (1 << 64) - 1
_SMALL_MAX_U = _M56                      # unsigned small body range
_SMALL_MIN_I = -(1 << 55)
_SMALL_MAX_I = (1 << 55) - 1

_SYM_CHARS = "_0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ" \
    "abcdefghijklmnopqrstuvwxyz"
_SYM_CODE = {c: i + 1 for i, c in enumerate(_SYM_CHARS)}
_SYM_CHAR = {i + 1: c for i, c in enumerate(_SYM_CHARS)}


_SHORTS_CACHE = None


def _SHORTS():
    """Memoized long->(module, export-char) registry map; rebuilt
    per-frame it costs more than every handler the frame calls."""
    global _SHORTS_CACHE
    if _SHORTS_CACHE is None:
        from stellar_tpu.soroban.env_interface import long_to_short
        _SHORTS_CACHE = long_to_short()
    return _SHORTS_CACHE


class EnvError(Trap):
    """Host-env failure surfaced to wasm as a trap."""


class ContractError(EnvError):
    """fail_with_error trap carrying the contract's Error val so
    try_call can hand the CALLEE'S error back to the caller (the
    reference returns the failing frame's error value)."""

    def __init__(self, msg: str, error_sc):
        super().__init__(msg)
        self.error_sc = error_sc  # SCVal of arm SCV_ERROR


def _tag(val: int) -> int:
    return val & 0xFF


def _body(val: int) -> int:
    return (val >> 8) & _M56


def _make(tag: int, body: int = 0) -> int:
    return ((body & _M56) << 8) | tag


def cmp_scval(a, b, charge=None) -> int:
    """Deep total order over SCVals — the order obj_cmp exposes, map
    entries sort by, and from_scval validates on map ingestion (the
    genuine host rejects out-of-order maps at conversion).
    ``charge(cpu, mem)`` meters size-proportional comparison work so
    the instruction budget bounds real CPU (a flat per-call fee would
    let large-object compares run unmetered)."""
    if charge is not None:
        charge(50, 0)
    if a.arm != b.arm:
        return -1 if a.arm < b.arm else 1
    arm = a.arm
    if arm in (T.SCV_BOOL, T.SCV_U32, T.SCV_I32, T.SCV_U64,
               T.SCV_I64, T.SCV_TIMEPOINT, T.SCV_DURATION):
        return (a.value > b.value) - (a.value < b.value)
    if arm in (T.SCV_U128, T.SCV_I128):
        av = (a.value.hi << 64) | a.value.lo
        bv = (b.value.hi << 64) | b.value.lo
        return (av > bv) - (av < bv)
    if arm in (T.SCV_U256, T.SCV_I256):
        def n256(p):
            hh = p.hi_hi & _M64
            return (hh << 192) | (p.hi_lo << 128) | \
                (p.lo_hi << 64) | p.lo_lo
        if arm == T.SCV_I256 and \
                (a.value.hi_hi < 0) != (b.value.hi_hi < 0):
            return -1 if a.value.hi_hi < 0 else 1
        av, bv = n256(a.value), n256(b.value)
        return (av > bv) - (av < bv)
    if arm in (T.SCV_BYTES, T.SCV_STRING, T.SCV_SYMBOL):
        av, bv = bytes(a.value), bytes(b.value)
        if charge is not None:
            charge(len(av) + len(bv), 0)
        return (av > bv) - (av < bv)
    if arm == T.SCV_VEC:
        ai, bi = list(a.value or ()), list(b.value or ())
        for x, y in zip(ai, bi):
            r = cmp_scval(x, y, charge)
            if r:
                return r
        return (len(ai) > len(bi)) - (len(ai) < len(bi))
    if arm == T.SCV_MAP:
        ai, bi = list(a.value or ()), list(b.value or ())
        for x, y in zip(ai, bi):
            r = cmp_scval(x.key, y.key, charge)
            if r:
                return r
            r = cmp_scval(x.val, y.val, charge)
            if r:
                return r
        return (len(ai) > len(bi)) - (len(ai) < len(bi))
    ab_, bb_ = to_bytes(SCVal, a), to_bytes(SCVal, b)
    if charge is not None:
        charge(len(ab_) + len(bb_), 0)
    return (ab_ > bb_) - (ab_ < bb_)


def sym_to_small(s: bytes) -> int:
    """Pack a <=9-char symbol into a SymbolSmall body (6 bits/char)."""
    if len(s) > 9:
        raise ValueError("symbol too long for small form")
    body = 0
    for ch in s.decode("ascii"):
        code = _SYM_CODE.get(ch)
        if code is None:
            raise ValueError(f"bad symbol char {ch!r}")
        body = (body << 6) | code
    return _make(TAG_SYMBOL_SMALL, body)


from stellar_tpu.utils.cache import RandomEvictionCache
from stellar_tpu.soroban.cost_model import CostType as _COST

_SYM_DECODE_CACHE: RandomEvictionCache = RandomEvictionCache(16384)


def small_to_sym(val: int) -> bytes:
    # memoized: small symbols are frame-independent (the value IS the
    # encoding) and repeat heavily (storage keys, function names)
    cached = _SYM_DECODE_CACHE.maybe_get(val)
    if cached is not None:
        return cached
    body = _body(val)
    chars = []
    while body:
        ch = _SYM_CHAR.get(body & 0x3F)
        if ch is None:
            # a forged Val with an embedded zero 6-bit group must trap
            # the contract, not raise through the host
            raise EnvError("malformed SymbolSmall encoding")
        chars.append(ch)
        body >>= 6
    out = "".join(reversed(chars)).encode()
    _SYM_DECODE_CACHE.put(val, out)
    return out


class ValConverter:
    """SCVal <-> Val conversion plus the per-invocation object table."""

    def __init__(self, charge: Callable[[int, int], None]):
        # charge(cpu, mem) — wired to the host budget
        self.objs: List[Tuple[int, object]] = []  # (tag, payload)
        self.charge = charge

    # ---------------- object table ----------------

    def new_obj(self, tag: int, payload) -> int:
        self.charge(50, 16)
        self.objs.append((tag, payload))
        return _make(tag, len(self.objs) - 1)

    def obj(self, val: int, want_tag: int):
        tag = _tag(val)
        if tag != want_tag:
            raise EnvError(f"expected tag {want_tag}, got {tag}")
        idx = _body(val)
        if idx >= len(self.objs):
            raise EnvError("bad object handle")
        otag, payload = self.objs[idx]
        if otag != want_tag:
            raise EnvError("object tag mismatch")
        return payload

    # ---------------- SCVal -> Val ----------------

    def from_scval(self, v: "SCVal.Value") -> int:
        arm = v.arm
        if arm == T.SCV_BOOL:
            return _make(TAG_TRUE if v.value else TAG_FALSE)
        if arm == T.SCV_VOID:
            return _make(TAG_VOID)
        if arm == T.SCV_U32:
            return _make(TAG_U32, v.value & 0xFFFFFFFF)
        if arm == T.SCV_I32:
            return _make(TAG_I32, v.value & 0xFFFFFFFF)
        if arm == T.SCV_U64:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_U64_SMALL, v.value)
            return self.new_obj(TAG_U64_OBJ, v.value)
        if arm == T.SCV_I64:
            if _SMALL_MIN_I <= v.value <= _SMALL_MAX_I:
                return _make(TAG_I64_SMALL, v.value)
            return self.new_obj(TAG_I64_OBJ, v.value)
        if arm == T.SCV_TIMEPOINT:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_TIMEPOINT_SMALL, v.value)
            return self.new_obj(TAG_TIMEPOINT_OBJ, v.value)
        if arm == T.SCV_DURATION:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_DURATION_SMALL, v.value)
            return self.new_obj(TAG_DURATION_OBJ, v.value)
        if arm == T.SCV_U128:
            n = (v.value.hi << 64) | v.value.lo
            if n <= _SMALL_MAX_U:
                return _make(TAG_U128_SMALL, n)
            return self.new_obj(TAG_U128_OBJ, n)
        if arm == T.SCV_I128:
            n = (v.value.hi << 64) | v.value.lo
            if n >= 1 << 127:
                n -= 1 << 128
            if _SMALL_MIN_I <= n <= _SMALL_MAX_I:
                return _make(TAG_I128_SMALL, n)
            return self.new_obj(TAG_I128_OBJ, n)
        if arm == T.SCV_U256:
            p = v.value
            n = ((p.hi_hi << 192) | (p.hi_lo << 128) |
                 (p.lo_hi << 64) | p.lo_lo)
            if n <= _SMALL_MAX_U:
                return _make(TAG_U256_SMALL, n)
            return self.new_obj(TAG_U256_OBJ, n)
        if arm == T.SCV_I256:
            p = v.value
            n = ((p.hi_hi << 192) | (p.hi_lo << 128) |
                 (p.lo_hi << 64) | p.lo_lo)
            # hi_hi is signed in Int256Parts; normalize to signed 256
            if p.hi_hi < 0:
                n = ((p.hi_hi & _M64) << 192 | (p.hi_lo << 128) |
                     (p.lo_hi << 64) | p.lo_lo) - (1 << 256)
            if _SMALL_MIN_I <= n <= _SMALL_MAX_I:
                return _make(TAG_I256_SMALL, n)
            return self.new_obj(TAG_I256_OBJ, n)
        if arm == T.SCV_ERROR:
            err = v.value
            return _make(TAG_ERROR,
                         ((int(err.arm) & 0xFFFFFF) << 32) |
                         (int(err.value) & 0xFFFFFFFF))
        if arm == T.SCV_SYMBOL:
            if len(v.value) <= 9:
                try:
                    return sym_to_small(v.value)
                except ValueError:
                    pass
            return self.new_obj(TAG_SYMBOL_OBJ, bytes(v.value))
        if arm == T.SCV_BYTES:
            return self.new_obj(TAG_BYTES_OBJ, bytes(v.value))
        if arm == T.SCV_STRING:
            return self.new_obj(TAG_STRING_OBJ, bytes(v.value))
        if arm == T.SCV_VEC:
            items = [self.from_scval(e) for e in (v.value or ())]
            return self.new_obj(TAG_VEC_OBJ, items)
        if arm == T.SCV_MAP:
            # the host invariant every map op relies on (bisect in
            # map_put, positional unpack) is sorted-unique keys; maps
            # arriving from XDR/args are validated here exactly like
            # the genuine host, which rejects out-of-order maps at
            # conversion
            entries = list(v.value or ())
            for i in range(1, len(entries)):
                if cmp_scval(entries[i - 1].key, entries[i].key,
                             self.charge) >= 0:
                    raise EnvError("map keys not sorted-unique")
            pairs = [(self.from_scval(e.key), self.from_scval(e.val))
                     for e in entries]
            return self.new_obj(TAG_MAP_OBJ, pairs)
        if arm == T.SCV_ADDRESS:
            return self.new_obj(TAG_ADDRESS_OBJ, v.value)
        raise EnvError(f"SCVal arm {arm} has no Val form")

    # ---------------- Val -> SCVal ----------------

    def to_scval(self, val: int) -> "SCVal.Value":
        val &= _M64
        tag = _tag(val)
        if tag < 64 and tag != TAG_ERROR:
            # small tags ARE their value (no object table, no charges):
            # the conversion is pure, so memoize it process-wide. The
            # same counter values, symbols, and u32 codes recur every
            # invoke, and the SCVal churn was the single biggest
            # wasm-engine-only cost at scenario level. SCVals are
            # treated as immutable throughout (storage shares them the
            # same way, see _storage_args).
            hit = _SMALL_SCVAL_CACHE.maybe_get(val)
            if hit is not None:
                return hit
            sc = self._to_scval_uncached(val)
            _SMALL_SCVAL_CACHE.put(val, sc)
            return sc
        return self._to_scval_uncached(val)

    def _to_scval_uncached(self, val: int) -> "SCVal.Value":
        tag = _tag(val)
        body = _body(val)
        if tag == TAG_FALSE:
            return SCVal.make(T.SCV_BOOL, False)
        if tag == TAG_TRUE:
            return SCVal.make(T.SCV_BOOL, True)
        if tag == TAG_VOID:
            return SCVal.make(T.SCV_VOID)
        if tag == TAG_U32:
            return SCVal.make(T.SCV_U32, body & 0xFFFFFFFF)
        if tag == TAG_I32:
            b = body & 0xFFFFFFFF
            return SCVal.make(T.SCV_I32,
                              b - (1 << 32) if b >> 31 else b)
        if tag == TAG_U64_SMALL:
            return SCVal.make(T.SCV_U64, body)
        if tag == TAG_I64_SMALL:
            return SCVal.make(
                T.SCV_I64, body - (1 << 56) if body >> 55 else body)
        if tag == TAG_TIMEPOINT_SMALL:
            return SCVal.make(T.SCV_TIMEPOINT, body)
        if tag == TAG_DURATION_SMALL:
            return SCVal.make(T.SCV_DURATION, body)
        if tag == TAG_U128_SMALL:
            return self._u128(body)
        if tag == TAG_I128_SMALL:
            return self._i128(body - (1 << 56) if body >> 55 else body)
        if tag == TAG_U256_SMALL:
            return self._u256(body)
        if tag == TAG_I256_SMALL:
            return self._i256(body - (1 << 56) if body >> 55 else body)
        if tag == TAG_ERROR:
            return self._error(body)
        if tag == TAG_SYMBOL_SMALL:
            return SCVal.make(T.SCV_SYMBOL, small_to_sym(val))
        if tag == TAG_U64_OBJ:
            return SCVal.make(T.SCV_U64, self.obj(val, tag))
        if tag == TAG_I64_OBJ:
            return SCVal.make(T.SCV_I64, self.obj(val, tag))
        if tag == TAG_TIMEPOINT_OBJ:
            return SCVal.make(T.SCV_TIMEPOINT, self.obj(val, tag))
        if tag == TAG_DURATION_OBJ:
            return SCVal.make(T.SCV_DURATION, self.obj(val, tag))
        if tag == TAG_U128_OBJ:
            return self._u128(self.obj(val, tag))
        if tag == TAG_I128_OBJ:
            return self._i128(self.obj(val, tag))
        if tag == TAG_U256_OBJ:
            return self._u256(self.obj(val, tag))
        if tag == TAG_I256_OBJ:
            return self._i256(self.obj(val, tag))
        if tag == TAG_BYTES_OBJ:
            return SCVal.make(T.SCV_BYTES, self.obj(val, tag))
        if tag == TAG_STRING_OBJ:
            return SCVal.make(T.SCV_STRING, self.obj(val, tag))
        if tag == TAG_SYMBOL_OBJ:
            return SCVal.make(T.SCV_SYMBOL, self.obj(val, tag))
        if tag == TAG_VEC_OBJ:
            return SCVal.make(T.SCV_VEC, [
                self.to_scval(e) for e in self.obj(val, tag)])
        if tag == TAG_MAP_OBJ:
            return SCVal.make(T.SCV_MAP, [
                SCMapEntry(key=self.to_scval(k), val=self.to_scval(w))
                for k, w in self.obj(val, tag)])
        if tag == TAG_ADDRESS_OBJ:
            return SCVal.make(T.SCV_ADDRESS, self.obj(val, tag))
        raise EnvError(f"bad Val tag {tag}")

    @staticmethod
    def _u128(n: int):
        from stellar_tpu.xdr.contract import UInt128Parts
        return SCVal.make(T.SCV_U128, UInt128Parts(
            hi=(n >> 64) & _M64, lo=n & _M64))

    @staticmethod
    def _i128(n: int):
        from stellar_tpu.xdr.contract import Int128Parts
        u = n & ((1 << 128) - 1)
        hi = (u >> 64) & _M64
        if hi >= 1 << 63:
            hi -= 1 << 64  # Int128Parts.hi is a signed int64
        return SCVal.make(T.SCV_I128, Int128Parts(hi=hi, lo=u & _M64))

    @staticmethod
    def _u256(n: int):
        from stellar_tpu.xdr.contract import UInt256Parts
        return SCVal.make(T.SCV_U256, UInt256Parts(
            hi_hi=(n >> 192) & _M64, hi_lo=(n >> 128) & _M64,
            lo_hi=(n >> 64) & _M64, lo_lo=n & _M64))

    @staticmethod
    def _i256(n: int):
        from stellar_tpu.xdr.contract import Int256Parts
        u = n & ((1 << 256) - 1)
        hi_hi = (u >> 192) & _M64
        if hi_hi >= 1 << 63:
            hi_hi -= 1 << 64  # Int256Parts.hi_hi is a signed int64
        return SCVal.make(T.SCV_I256, Int256Parts(
            hi_hi=hi_hi, hi_lo=(u >> 128) & _M64,
            lo_hi=(u >> 64) & _M64, lo_lo=u & _M64))

    @staticmethod
    def _error(body: int):
        from stellar_tpu.xdr.contract import (
            SCError, SCErrorCode, SCErrorType,
        )
        etype = (body >> 32) & 0xFFFFFF
        code = body & 0xFFFFFFFF
        if etype not in SCErrorType.by_value:
            raise EnvError(f"bad error type {etype}")
        if etype != SCErrorType.SCE_CONTRACT and \
                code not in SCErrorCode.by_value:
            raise EnvError(f"bad error code {code}")
        return SCVal.make(T.SCV_ERROR, SCError.make(etype, code))


# ---------------------------------------------------------------------------
# Host-function imports
# ---------------------------------------------------------------------------

# small-tag Val -> SCVal memo (pure, chargeless conversions only)
_SMALL_SCVAL_CACHE: "RandomEvictionCache" = RandomEvictionCache(4096)

_DUR_BY_CODE = {0: "temporary", 1: "persistent", 2: "instance"}
# (contract id, small key val, storage code) -> (SCVal, dur, kb);
# see _storage_args for the safety argument
_STORAGE_ARGS_CACHE: RandomEvictionCache = RandomEvictionCache(8192)


def make_imports(env) -> Dict[Tuple[str, str], Callable]:
    """The import table for one contract frame. ``env`` is a
    ``WasmContractEnv`` (defined in host.py) carrying the host, the
    running contract's address, and the ValConverter."""
    cv: ValConverter = env.cv

    def _frame_version() -> int:
        """THE protocol version this frame runs under — shared by
        get_ledger_version, the era gates, and the link-time check, so
        a contract can never observe one version and be served
        another's function set. Headerless hosts (unit tests, direct
        simulation) run as the current protocol."""
        from stellar_tpu.protocol import CURRENT_LEDGER_PROTOCOL_VERSION
        hdr = getattr(env.host, "ledger_header", None)
        return hdr.ledgerVersion if hdr is not None \
            else CURRENT_LEDGER_PROTOCOL_VERSION

    def _u32_arg(val: int, what: str) -> int:
        if _tag(val) != TAG_U32:
            raise EnvError(f"{what}: expected U32 val")
        return _body(val) & 0xFFFFFFFF

    # ---- context ----

    def log(inst, val):
        env.host.budget.charge(100, 0)
        from stellar_tpu.soroban import host as host_mod
        if host_mod.DIAGNOSTIC_EVENTS_ENABLED:
            env.host.diagnostics.append(cv.to_scval(val))
        return _make(TAG_VOID)

    def ledger_sequence(inst):
        return _make(TAG_U32, env.host.ledger_seq)

    def ledger_timestamp(inst):
        ts = 0
        hdr = getattr(env.host, "ledger_header", None)
        if hdr is not None:
            ts = hdr.scpValue.closeTime
        return _make(TAG_U64_SMALL, ts) if ts <= _SMALL_MAX_U \
            else cv.new_obj(TAG_U64_OBJ, ts)

    def current_contract_address(inst):
        return cv.new_obj(TAG_ADDRESS_OBJ, env.contract_addr)

    def contract_event(inst, topics_val, data_val):
        topics_sc = cv.to_scval(topics_val)
        if topics_sc.arm != T.SCV_VEC:
            raise EnvError("event topics must be a vec")
        env.host.emit_event(env.contract_addr,
                            list(topics_sc.value or ()),
                            cv.to_scval(data_val))
        return _make(TAG_VOID)

    def fail(inst):
        raise EnvError("contract called fail")

    # ---- ledger ----

    # hot-path bindings hoisted out of the per-call handlers (a
    # function-level import costs ~1-2us and storage ops run several
    # times per invoke)
    from stellar_tpu.ledger.ledger_txn import key_bytes as _key_bytes
    from stellar_tpu.soroban.host import (
        contract_data_key as _contract_data_key,
    )
    from stellar_tpu.xdr.contract import (
        ContractDataDurability as _Durability,
    )

    def _storage_args(k_val, t_val):
        """(key_scval, durability|None, kb|None) — durability None
        means instance storage; key is converted exactly once.

        Small-tag keys (tag < 64: the value IS the encoding, no
        object-table indirection) are memoized per contract id:
        storage keys like a counter's symbol repeat every tx, and for
        small tags the conversion path is charge-free, so a cache hit
        is metering-identical to a rebuild. The cached SCVal/LedgerKey
        are shared — storage treats keys as immutable."""
        code = _u32_arg(t_val, "storage type")
        kind = _DUR_BY_CODE.get(code)
        if kind is None:
            raise EnvError("bad storage type")
        cacheable = (_tag(k_val) < 64 and kind != "instance" and
                     isinstance(env.contract_addr.value, bytes))
        if cacheable:
            ckey = (env.contract_addr.value, k_val, code)
            hit = _STORAGE_ARGS_CACHE.maybe_get(ckey)
            if hit is not None:
                return hit
        # single derivation path — first call and cache hit MUST stay
        # behavior-identical (metering parity)
        key_sc = cv.to_scval(k_val)
        if kind == "instance":
            return key_sc, None, None
        dur = _Durability.PERSISTENT if kind == "persistent" \
            else _Durability.TEMPORARY
        kb = _key_bytes(_contract_data_key(env.contract_addr, key_sc,
                                           dur))
        out = (key_sc, dur, kb)
        if cacheable:
            _STORAGE_ARGS_CACHE.put(ckey, out)
        return out

    def put_contract_data(inst, k_val, v_val, t_val):
        key_sc, dur, _kb = _storage_args(k_val, t_val)
        if dur is None:
            env.instance_put(key_sc, cv.to_scval(v_val))
        else:
            env.data_put(key_sc, cv.to_scval(v_val), dur)
        return _make(TAG_VOID)

    def get_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        sc = env.instance_get(key_sc) if dur is None \
            else env.data_get(kb)
        if sc is None:
            raise EnvError("missing contract data")
        return cv.from_scval(sc)

    def has_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        sc = env.instance_get(key_sc) if dur is None \
            else env.data_get(kb)
        return _make(TAG_TRUE if sc is not None else TAG_FALSE)

    def del_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        if dur is None:
            env.instance_del(key_sc)
        else:
            env.data_del(kb)
        return _make(TAG_VOID)

    def extend_contract_data_ttl(inst, k_val, t_val, thresh_val,
                                 ext_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        if dur is None:
            raise EnvError("use the instance TTL host fn for "
                           "instance storage")
        env.host.extend_ttl(kb, _u32_arg(thresh_val, "threshold"),
                            _u32_arg(ext_val, "extend_to"))
        return _make(TAG_VOID)

    def extend_instance_and_code_ttl(inst, thresh_val, ext_val):
        """Extend the current contract's instance entry AND its code
        entry (reference extend_current_contract_instance_and_code_ttl)."""
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.soroban.host import (
            contract_code_key, contract_data_key,
        )
        from stellar_tpu.xdr.contract import (
            ContractDataDurability, ContractExecutableType,
        )
        thresh = _u32_arg(thresh_val, "threshold")
        ext = _u32_arg(ext_val, "extend_to")
        inst_kb = key_bytes(contract_data_key(
            env.contract_addr,
            SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            ContractDataDurability.PERSISTENT))
        env.host.extend_ttl(inst_kb, thresh, ext)
        slot = env.host.storage.entries.get(inst_kb)
        if slot is not None and slot[0] is not None:
            instance = slot[0].data.value.val.value
            if instance.executable.arm == \
                    ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
                code_kb = key_bytes(contract_code_key(
                    instance.executable.value))
                env.host.extend_ttl(code_kb, thresh, ext)
        return _make(TAG_VOID)

    # ---- vec ----
    # Structural ops charge proportionally to the work they do (copy
    # size, entries compared) — a flat per-call fee would let real CPU
    # and memory run unbounded relative to the instruction budget
    # (reference: soroban's per-cost-type calibrated charges).

    def vec_new(inst):
        return cv.new_obj(TAG_VEC_OBJ, [])

    def vec_push_back(inst, vec_val, item):
        items = list(cv.obj(vec_val, TAG_VEC_OBJ))
        env.host.budget.charge(10 + len(items), 8 * (len(items) + 1))
        items.append(item & _M64)
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_get(inst, vec_val, i_val):
        items = cv.obj(vec_val, TAG_VEC_OBJ)
        i = _u32_arg(i_val, "vec index")
        if i >= len(items):
            raise EnvError("vec index out of bounds")
        return items[i]

    def vec_len(inst, vec_val):
        return _make(TAG_U32, len(cv.obj(vec_val, TAG_VEC_OBJ)))

    # ---- map (entries kept sorted by canonical SCVal key bytes) ----

    def _map_key_bytes(v: int) -> bytes:
        kb = to_bytes(SCVal, cv.to_scval(v))
        # the encode itself is the dominant cost of every compare
        env.host.budget.charge(30 + 2 * len(kb), 0)
        return kb

    def map_new(inst):
        return cv.new_obj(TAG_MAP_OBJ, [])

    def map_put(inst, map_val, k, v):
        # the pair list is kept sorted in the deep Val order (the SAME
        # total order obj_cmp exposes, so map_key_by_pos /
        # vec_binary_search over map_keys stay mutually consistent);
        # bisect to the slot in O(log n) compares
        pairs = list(cv.obj(map_val, TAG_MAP_OBJ))
        env.host.budget.charge(10 + len(pairs), 16 * (len(pairs) + 1))
        lo, hi = 0, len(pairs)
        while lo < hi:
            mid = (lo + hi) // 2
            if _cmp_vals(pairs[mid][0], k) < 0:
                lo = mid + 1
            else:
                hi = mid
        if lo < len(pairs) and _cmp_vals(pairs[lo][0], k) == 0:
            pairs[lo] = (k & _M64, v & _M64)
        else:
            pairs.insert(lo, (k & _M64, v & _M64))
        return cv.new_obj(TAG_MAP_OBJ, pairs)

    def map_get(inst, map_val, k):
        kb = _map_key_bytes(k)
        for pk, pv in cv.obj(map_val, TAG_MAP_OBJ):
            if _map_key_bytes(pk) == kb:
                return pv
        raise EnvError("map key not found")

    def map_has(inst, map_val, k):
        kb = _map_key_bytes(k)
        for pk, _pv in cv.obj(map_val, TAG_MAP_OBJ):
            if _map_key_bytes(pk) == kb:
                return _make(TAG_TRUE)
        return _make(TAG_FALSE)

    def map_len(inst, map_val):
        return _make(TAG_U32, len(cv.obj(map_val, TAG_MAP_OBJ)))

    # ---- bytes / string / symbol <-> linear memory ----

    def bytes_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        env.host.budget.charge(50 + 2 * n, n)
        return cv.new_obj(TAG_BYTES_OBJ, inst.mem_read(ptr, n))

    def bytes_copy_to_linear_memory(inst, b_val, off_val, ptr_val,
                                    len_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        off = _u32_arg(off_val, "offset")
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        if off + n > len(data):
            raise EnvError("bytes copy out of range")
        env.host.budget.charge(50 + 2 * n, 0)
        inst.mem_write(ptr, data[off:off + n])
        return _make(TAG_VOID)

    def bytes_len(inst, b_val):
        return _make(TAG_U32, len(cv.obj(b_val, TAG_BYTES_OBJ)))

    def bytes_get(inst, b_val, i_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        i = _u32_arg(i_val, "index")
        if i >= len(data):
            raise EnvError("bytes index out of bounds")
        return _make(TAG_U32, data[i])

    def symbol_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        raw = inst.mem_read(ptr, n)
        env.host.budget.charge(50 + 2 * n, n)
        if n <= 9:
            try:
                return sym_to_small(raw)
            except ValueError:
                pass
        return cv.new_obj(TAG_SYMBOL_OBJ, raw)

    def string_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        env.host.budget.charge(50 + 2 * n, n)
        return cv.new_obj(TAG_STRING_OBJ, inst.mem_read(ptr, n))

    # ---- int object conversions (raw wasm i64 <-> Val) ----

    def obj_from_u64(inst, raw):
        raw &= _M64
        if raw <= _SMALL_MAX_U:
            return _make(TAG_U64_SMALL, raw)
        return cv.new_obj(TAG_U64_OBJ, raw)

    def obj_to_u64(inst, val):
        sc = cv.to_scval(val)
        if sc.arm != T.SCV_U64:
            raise EnvError("not a u64")
        return sc.value

    def obj_from_i64(inst, raw):
        raw &= _M64
        signed = raw - (1 << 64) if raw >> 63 else raw
        if _SMALL_MIN_I <= signed <= _SMALL_MAX_I:
            return _make(TAG_I64_SMALL, signed)
        return cv.new_obj(TAG_I64_OBJ, signed)

    def obj_to_i64(inst, val):
        sc = cv.to_scval(val)
        if sc.arm != T.SCV_I64:
            raise EnvError("not an i64")
        return sc.value & _M64

    # ---- address / auth ----

    def require_auth(inst, addr_val):
        addr = cv.obj(addr_val, TAG_ADDRESS_OBJ)
        env.host.require_auth(
            SCVal.make(T.SCV_ADDRESS, addr), env.invocation,
            env.depth)
        return _make(TAG_VOID)

    # ---- cross-contract call ----

    def call(inst, addr_val, fn_val, args_val):
        addr_sc = cv.to_scval(addr_val)
        fn_sc = cv.to_scval(fn_val)
        args_sc = cv.to_scval(args_val)
        if addr_sc.arm != T.SCV_ADDRESS or fn_sc.arm != T.SCV_SYMBOL \
                or args_sc.arm != T.SCV_VEC:
            raise EnvError("call needs (address, symbol, vec)")
        rv = env.host.call_contract(addr_sc.value, fn_sc.value,
                                    list(args_sc.value or ()),
                                    env.depth + 1)
        return cv.from_scval(rv)

    # ---- crypto ----

    def compute_sha256(inst, b_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        env.charge_type(_COST.ComputeSha256Hash, len(data))
        return cv.new_obj(TAG_BYTES_OBJ, sha256(data))

    # ---- prng (deterministic per-frame stream; reference "p") ----

    def _frame_prng():
        if env.prng is None:
            env.prng = env.host.fork_prng()
        return env.prng

    def prng_u64_in_inclusive_range(inst, lo_raw, hi_raw):
        env.host.budget.charge(100, 0)
        return _frame_prng().u64_in_range(lo_raw & _M64,
                                          hi_raw & _M64) & _M64

    def prng_bytes_new(inst, len_val):
        n = _u32_arg(len_val, "prng length")
        env.host.budget.charge(100 + 2 * n, n)
        return cv.new_obj(TAG_BYTES_OBJ, _frame_prng().take(n))

    def prng_reseed(inst, b_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        env.host.budget.charge(100 + len(data), 0)
        _frame_prng().reseed(data)
        return _make(TAG_VOID)

    def prng_vec_shuffle(inst, vec_val):
        items = list(cv.obj(vec_val, TAG_VEC_OBJ))
        env.host.budget.charge(100 + 10 * len(items),
                               8 * (len(items) + 1))
        prng = _frame_prng()
        # Fisher-Yates with the deterministic frame stream
        for i in range(len(items) - 1, 0, -1):
            j = prng.u64_in_range(0, i)
            items[i], items[j] = items[j], items[i]
        return cv.new_obj(TAG_VEC_OBJ, items)

    # =====================================================================
    # modern-env surface (the genuine soroban interface; every handler
    # below also registers under its single-char export name)
    # =====================================================================

    # identity-stable across env.reset() (frame pooling): forwards to
    # the CURRENT frame's budget
    charge = env.charge
    # metered cost-model charge: ContractCostType + the calibrated
    # (const, linear) tables (soroban/cost_model.py; reference
    # NetworkConfig.cpp initial params, upgradable consensus state)
    charge_ct = env.charge_type
    CT = _COST

    def _bytes_of(val):
        return cv.obj(val, TAG_BYTES_OBJ)

    def _sym_bytes(val) -> bytes:
        if _tag(val) == TAG_SYMBOL_SMALL:
            return small_to_sym(val)
        return cv.obj(val, TAG_SYMBOL_OBJ)

    def _str_bytes(val) -> bytes:
        return cv.obj(val, TAG_STRING_OBJ)

    def _raw64(v: int) -> int:
        return v & _M64

    # ---- deep total order (obj_cmp and the vec search family) ----

    def _cmp_sc(a, b) -> int:
        return cmp_scval(a, b, charge)

    def _cmp_vals(a_val: int, b_val: int) -> int:
        return _cmp_sc(cv.to_scval(a_val), cv.to_scval(b_val))

    # ---- context ----

    def obj_cmp(inst, a_val, b_val):
        return _raw64(_cmp_vals(a_val, b_val))

    def log_from_linear_memory(inst, msg_pos, msg_len, vals_pos,
                               vals_len):
        mp = _u32_arg(msg_pos, "msg pos")
        ml = _u32_arg(msg_len, "msg len")
        vp = _u32_arg(vals_pos, "vals pos")
        vl = _u32_arg(vals_len, "vals len")
        charge(100 + 2 * ml + 10 * vl, 0)
        from stellar_tpu.soroban import host as host_mod
        if host_mod.DIAGNOSTIC_EVENTS_ENABLED:
            msg = inst.mem_read(mp, ml)
            vals = [cv.to_scval(int.from_bytes(
                inst.mem_read(vp + 8 * i, 8), "little"))
                for i in range(vl)]
            env.host.diagnostics.append(SCVal.make(T.SCV_VEC, [
                SCVal.make(T.SCV_STRING, msg)] + vals))
        return _make(TAG_VOID)

    def get_ledger_version(inst):
        return _make(TAG_U32, _frame_version())

    def fail_with_error(inst, err_val):
        from stellar_tpu.xdr.contract import (
            SCError, SCErrorCode, SCErrorType,
        )
        if _tag(err_val) != TAG_ERROR:
            raise EnvError("fail_with_error needs an Error val")
        sc = cv.to_scval(err_val)
        if sc.value.arm != SCErrorType.SCE_CONTRACT:
            # only contract-typed errors may be raised by contracts;
            # anything else is replaced (reference host behavior)
            sc = SCVal.make(T.SCV_ERROR, SCError.make(
                SCErrorType.SCE_CONTEXT,
                SCErrorCode.SCEC_UNEXPECTED_TYPE))
        raise ContractError(
            f"contract failure: error type {sc.value.arm} "
            f"code {sc.value.value}", sc)

    def get_ledger_network_id(inst):
        charge(100, 32)
        return cv.new_obj(TAG_BYTES_OBJ, env.host.network_id)

    def get_max_live_until_ledger(inst):
        return _make(TAG_U32, env.host.ledger_seq +
                     env.host.config.max_entry_ttl - 1)

    # ---- int: 128/256-bit objects + arithmetic ----

    def obj_from_u128_pieces(inst, hi, lo):
        n = (_raw64(hi) << 64) | _raw64(lo)
        if n <= _SMALL_MAX_U:
            return _make(TAG_U128_SMALL, n)
        return cv.new_obj(TAG_U128_OBJ, n)

    def _u128_of(val) -> int:
        tag = _tag(val)
        if tag == TAG_U128_SMALL:
            return _body(val)
        return cv.obj(val, TAG_U128_OBJ)

    def obj_to_u128_lo64(inst, val):
        return _u128_of(val) & _M64

    def obj_to_u128_hi64(inst, val):
        return (_u128_of(val) >> 64) & _M64

    def obj_from_i128_pieces(inst, hi, lo):
        hi_s = _raw64(hi)
        if hi_s >> 63:
            hi_s -= 1 << 64
        n = (hi_s << 64) | _raw64(lo)
        if _SMALL_MIN_I <= n <= _SMALL_MAX_I:
            return _make(TAG_I128_SMALL, n)
        return cv.new_obj(TAG_I128_OBJ, n)

    def _i128_of(val) -> int:
        tag = _tag(val)
        if tag == TAG_I128_SMALL:
            b = _body(val)
            return b - (1 << 56) if b >> 55 else b
        return cv.obj(val, TAG_I128_OBJ)

    def obj_to_i128_lo64(inst, val):
        return _i128_of(val) & _M64

    def obj_to_i128_hi64(inst, val):
        return (_i128_of(val) >> 64) & _M64

    _U256_MAX = (1 << 256) - 1
    _I256_MIN = -(1 << 255)
    _I256_MAX = (1 << 255) - 1

    def _mk_u256(n: int):
        if n <= _SMALL_MAX_U:
            return _make(TAG_U256_SMALL, n)
        return cv.new_obj(TAG_U256_OBJ, n)

    def _mk_i256(n: int):
        if _SMALL_MIN_I <= n <= _SMALL_MAX_I:
            return _make(TAG_I256_SMALL, n)
        return cv.new_obj(TAG_I256_OBJ, n)

    def _u256_of(val) -> int:
        tag = _tag(val)
        if tag == TAG_U256_SMALL:
            return _body(val)
        return cv.obj(val, TAG_U256_OBJ)

    def _i256_of(val) -> int:
        tag = _tag(val)
        if tag == TAG_I256_SMALL:
            b = _body(val)
            return b - (1 << 56) if b >> 55 else b
        return cv.obj(val, TAG_I256_OBJ)

    def obj_from_u256_pieces(inst, hi_hi, hi_lo, lo_hi, lo_lo):
        n = ((_raw64(hi_hi) << 192) | (_raw64(hi_lo) << 128) |
             (_raw64(lo_hi) << 64) | _raw64(lo_lo))
        return _mk_u256(n)

    def obj_to_u256_hi_hi(inst, val):
        return (_u256_of(val) >> 192) & _M64

    def obj_to_u256_hi_lo(inst, val):
        return (_u256_of(val) >> 128) & _M64

    def obj_to_u256_lo_hi(inst, val):
        return (_u256_of(val) >> 64) & _M64

    def obj_to_u256_lo_lo(inst, val):
        return _u256_of(val) & _M64

    def obj_from_i256_pieces(inst, hi_hi, hi_lo, lo_hi, lo_lo):
        hh = _raw64(hi_hi)
        if hh >> 63:
            hh -= 1 << 64
        n = ((hh << 192) | (_raw64(hi_lo) << 128) |
             (_raw64(lo_hi) << 64) | _raw64(lo_lo))
        return _mk_i256(n)

    def obj_to_i256_hi_hi(inst, val):
        return (_i256_of(val) >> 192) & _M64

    def obj_to_i256_hi_lo(inst, val):
        return (_i256_of(val) >> 128) & _M64

    def obj_to_i256_lo_hi(inst, val):
        return (_i256_of(val) >> 64) & _M64

    def obj_to_i256_lo_lo(inst, val):
        return _i256_of(val) & _M64

    def u256_val_from_be_bytes(inst, b_val):
        data = _bytes_of(b_val)
        if len(data) != 32:
            raise EnvError("u256 bytes must be exactly 32")
        charge(100, 32)
        return _mk_u256(int.from_bytes(data, "big"))

    def u256_val_to_be_bytes(inst, val):
        charge(100, 32)
        return cv.new_obj(TAG_BYTES_OBJ,
                          _u256_of(val).to_bytes(32, "big"))

    def i256_val_from_be_bytes(inst, b_val):
        data = _bytes_of(b_val)
        if len(data) != 32:
            raise EnvError("i256 bytes must be exactly 32")
        charge(100, 32)
        n = int.from_bytes(data, "big")
        if n > _I256_MAX:
            n -= 1 << 256
        return _mk_i256(n)

    def i256_val_to_be_bytes(inst, val):
        charge(100, 32)
        return cv.new_obj(
            TAG_BYTES_OBJ,
            (_i256_of(val) & _U256_MAX).to_bytes(32, "big"))

    def _u256_binop(op, ct=None):
        def fn(inst, a_val, b_val):
            charge_ct(CT.Int256AddSub if ct is None else ct)
            a, b = _u256_of(a_val), _u256_of(b_val)
            r = op(a, b)
            if r is None or not (0 <= r <= _U256_MAX):
                raise EnvError("u256 arithmetic out of range")
            return _mk_u256(r)
        return fn

    def _i256_binop(op, ct=None):
        def fn(inst, a_val, b_val):
            charge_ct(CT.Int256AddSub if ct is None else ct)
            a, b = _i256_of(a_val), _i256_of(b_val)
            r = op(a, b)
            if r is None or not (_I256_MIN <= r <= _I256_MAX):
                raise EnvError("i256 arithmetic out of range")
            return _mk_i256(r)
        return fn

    def _div(a, b):
        if b == 0:
            return None
        q = abs(a) // abs(b)
        return q if (a >= 0) == (b >= 0) else -q  # truncating

    def _rem_euclid(a, b):
        if b == 0:
            return None
        return a % abs(b)  # Python % with positive modulus is Euclidean

    def _pow_checked(a, b, limit):
        # bases 0/±1 succeed at ANY u32 exponent; for |a| >= 2 an
        # exponent above 256 always overflows 256 bits
        if a == 0:
            return 1 if b == 0 else 0
        if a == 1:
            return 1
        if a == -1:
            return 1 if b % 2 == 0 else -1
        if b > 256:
            return None
        r = 1
        for _ in range(b):
            r *= a
            if abs(r) > limit:
                return None
        return r

    u256_add = _u256_binop(lambda a, b: a + b)
    u256_sub = _u256_binop(lambda a, b: a - b)
    u256_mul = _u256_binop(lambda a, b: a * b, _COST.Int256Mul)
    u256_div = _u256_binop(_div, _COST.Int256Div)
    u256_rem_euclid = _u256_binop(_rem_euclid, _COST.Int256Div)
    i256_add = _i256_binop(lambda a, b: a + b)
    i256_sub = _i256_binop(lambda a, b: a - b)
    i256_mul = _i256_binop(lambda a, b: a * b, _COST.Int256Mul)
    i256_div = _i256_binop(_div, _COST.Int256Div)
    i256_rem_euclid = _i256_binop(_rem_euclid, _COST.Int256Div)

    def u256_pow(inst, a_val, p_val):
        charge_ct(CT.Int256Pow)
        p = _u32_arg(p_val, "pow exponent")
        r = _pow_checked(_u256_of(a_val), p, _U256_MAX)
        if r is None or r > _U256_MAX:
            raise EnvError("u256 pow out of range")
        return _mk_u256(r)

    def i256_pow(inst, a_val, p_val):
        charge_ct(CT.Int256Pow)
        p = _u32_arg(p_val, "pow exponent")
        r = _pow_checked(_i256_of(a_val), p, 1 << 256)
        if r is None or not (_I256_MIN <= r <= _I256_MAX):
            raise EnvError("i256 pow out of range")
        return _mk_i256(r)

    def u256_shl(inst, a_val, s_val):
        charge_ct(CT.Int256Shift)
        s = _u32_arg(s_val, "shift")
        if s >= 256:
            raise EnvError("u256 shift out of range")
        # checked_shl semantics: only the shift amount can error;
        # bits shifted past 256 are discarded
        return _mk_u256((_u256_of(a_val) << s) & _U256_MAX)

    def u256_shr(inst, a_val, s_val):
        charge_ct(CT.Int256Shift)
        s = _u32_arg(s_val, "shift")
        if s >= 256:
            raise EnvError("u256 shift out of range")
        return _mk_u256(_u256_of(a_val) >> s)

    def i256_shl(inst, a_val, s_val):
        charge_ct(CT.Int256Shift)
        s = _u32_arg(s_val, "shift")
        if s >= 256:
            raise EnvError("i256 shift out of range")
        # checked_shl: wrap into the signed 256-bit range, bits drop
        r = (_i256_of(a_val) << s) & _U256_MAX
        if r > _I256_MAX:
            r -= 1 << 256
        return _mk_i256(r)

    def i256_shr(inst, a_val, s_val):
        charge_ct(CT.Int256Shift)
        s = _u32_arg(s_val, "shift")
        if s >= 256:
            raise EnvError("i256 shift out of range")
        return _mk_i256(_i256_of(a_val) >> s)  # arithmetic shift

    def timepoint_obj_from_u64(inst, raw):
        raw = _raw64(raw)
        if raw <= _SMALL_MAX_U:
            return _make(TAG_TIMEPOINT_SMALL, raw)
        return cv.new_obj(TAG_TIMEPOINT_OBJ, raw)

    def timepoint_obj_to_u64(inst, val):
        if _tag(val) == TAG_TIMEPOINT_SMALL:
            return _body(val)
        return cv.obj(val, TAG_TIMEPOINT_OBJ)

    def duration_obj_from_u64(inst, raw):
        raw = _raw64(raw)
        if raw <= _SMALL_MAX_U:
            return _make(TAG_DURATION_SMALL, raw)
        return cv.new_obj(TAG_DURATION_OBJ, raw)

    def duration_obj_to_u64(inst, val):
        if _tag(val) == TAG_DURATION_SMALL:
            return _body(val)
        return cv.obj(val, TAG_DURATION_OBJ)

    # ---- vec (remaining surface) ----

    def _vec_of(val):
        return cv.obj(val, TAG_VEC_OBJ)

    def _vec_index(items, i_val, what="vec index", allow_end=False):
        i = _u32_arg(i_val, what)
        limit = len(items) + (1 if allow_end else 0)
        if i >= limit:
            raise EnvError(f"{what} out of bounds")
        return i

    def vec_put(inst, vec_val, i_val, x):
        items = list(_vec_of(vec_val))
        i = _vec_index(items, i_val)
        charge(10 + len(items), 8 * len(items))
        items[i] = x & _M64
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_del(inst, vec_val, i_val):
        items = list(_vec_of(vec_val))
        i = _vec_index(items, i_val)
        charge(10 + len(items), 8 * len(items))
        del items[i]
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_push_front(inst, vec_val, x):
        items = list(_vec_of(vec_val))
        charge(10 + len(items), 8 * (len(items) + 1))
        return cv.new_obj(TAG_VEC_OBJ, [x & _M64] + items)

    def vec_pop_front(inst, vec_val):
        items = list(_vec_of(vec_val))
        if not items:
            raise EnvError("pop from empty vec")
        charge(10 + len(items), 8 * len(items))
        return cv.new_obj(TAG_VEC_OBJ, items[1:])

    def vec_pop_back(inst, vec_val):
        items = list(_vec_of(vec_val))
        if not items:
            raise EnvError("pop from empty vec")
        charge(10 + len(items), 8 * len(items))
        return cv.new_obj(TAG_VEC_OBJ, items[:-1])

    def vec_front(inst, vec_val):
        items = _vec_of(vec_val)
        if not items:
            raise EnvError("front of empty vec")
        return items[0]

    def vec_back(inst, vec_val):
        items = _vec_of(vec_val)
        if not items:
            raise EnvError("back of empty vec")
        return items[-1]

    def vec_insert(inst, vec_val, i_val, x):
        items = list(_vec_of(vec_val))
        i = _vec_index(items, i_val, allow_end=True)
        charge(10 + len(items), 8 * (len(items) + 1))
        items.insert(i, x & _M64)
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_append(inst, v1_val, v2_val):
        a, b = list(_vec_of(v1_val)), list(_vec_of(v2_val))
        charge(10 + len(a) + len(b), 8 * (len(a) + len(b)))
        return cv.new_obj(TAG_VEC_OBJ, a + b)

    def vec_slice(inst, vec_val, start_val, end_val):
        items = _vec_of(vec_val)
        start = _u32_arg(start_val, "slice start")
        end = _u32_arg(end_val, "slice end")
        if start > end or end > len(items):
            raise EnvError("vec slice out of range")
        charge(10 + (end - start), 8 * (end - start))
        return cv.new_obj(TAG_VEC_OBJ, list(items[start:end]))

    def vec_first_index_of(inst, vec_val, x):
        for i, item in enumerate(_vec_of(vec_val)):
            if _cmp_vals(item, x) == 0:
                return _make(TAG_U32, i)
        return _make(TAG_VOID)

    def vec_last_index_of(inst, vec_val, x):
        items = _vec_of(vec_val)
        for i in range(len(items) - 1, -1, -1):
            if _cmp_vals(items[i], x) == 0:
                return _make(TAG_U32, i)
        return _make(TAG_VOID)

    def vec_binary_search(inst, vec_val, x):
        """u64 result: (1<<32)|index when found, else the insertion
        point in the low 32 bits (the soroban result convention)."""
        items = _vec_of(vec_val)
        lo, hi = 0, len(items)
        while lo < hi:
            mid = (lo + hi) // 2
            r = _cmp_vals(items[mid], x)
            if r == 0:
                return (1 << 32) | mid
            if r < 0:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def vec_new_from_linear_memory(inst, vals_pos, len_val):
        vp = _u32_arg(vals_pos, "vals pos")
        n = _u32_arg(len_val, "len")
        charge(50 + 10 * n, 8 * n)
        items = [int.from_bytes(inst.mem_read(vp + 8 * i, 8),
                                "little") for i in range(n)]
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_unpack_to_linear_memory(inst, vec_val, vals_pos, len_val):
        items = _vec_of(vec_val)
        vp = _u32_arg(vals_pos, "vals pos")
        n = _u32_arg(len_val, "len")
        if n != len(items):
            raise EnvError("vec unpack length mismatch")
        charge(50 + 10 * n, 0)
        for i, item in enumerate(items):
            inst.mem_write(vp + 8 * i, (item & _M64).to_bytes(
                8, "little"))
        return _make(TAG_VOID)

    # ---- map (remaining surface) ----

    def _map_of(val):
        return cv.obj(val, TAG_MAP_OBJ)

    def map_del(inst, map_val, k):
        pairs = list(_map_of(map_val))
        kb = _map_key_bytes(k)
        for i, (pk, _pv) in enumerate(pairs):
            if _map_key_bytes(pk) == kb:
                charge(10 + len(pairs), 16 * len(pairs))
                del pairs[i]
                return cv.new_obj(TAG_MAP_OBJ, pairs)
        raise EnvError("map key not found")

    def map_key_by_pos(inst, map_val, i_val):
        pairs = _map_of(map_val)
        i = _u32_arg(i_val, "map pos")
        if i >= len(pairs):
            raise EnvError("map pos out of bounds")
        return pairs[i][0]

    def map_val_by_pos(inst, map_val, i_val):
        pairs = _map_of(map_val)
        i = _u32_arg(i_val, "map pos")
        if i >= len(pairs):
            raise EnvError("map pos out of bounds")
        return pairs[i][1]

    def map_keys(inst, map_val):
        pairs = _map_of(map_val)
        charge(10 + len(pairs), 8 * len(pairs))
        return cv.new_obj(TAG_VEC_OBJ, [pk for pk, _ in pairs])

    def map_values(inst, map_val):
        pairs = _map_of(map_val)
        charge(10 + len(pairs), 8 * len(pairs))
        return cv.new_obj(TAG_VEC_OBJ, [pv for _, pv in pairs])

    def _key_slices(inst, keys_pos: int, n: int):
        """n (ptr,len) u32-pairs at keys_pos -> symbol byte strings
        (the SDK's struct-field-name slices)."""
        out = []
        for i in range(n):
            pair = inst.mem_read(keys_pos + 8 * i, 8)
            ptr = int.from_bytes(pair[:4], "little")
            ln = int.from_bytes(pair[4:], "little")
            charge(20 + ln, ln)
            out.append(inst.mem_read(ptr, ln))
        return out

    def _sym_val(raw: bytes) -> int:
        if len(raw) <= 9:
            try:
                return sym_to_small(raw)
            except ValueError:
                pass
        return cv.new_obj(TAG_SYMBOL_OBJ, raw)

    def map_new_from_linear_memory(inst, keys_pos, vals_pos, len_val):
        kp = _u32_arg(keys_pos, "keys pos")
        vp = _u32_arg(vals_pos, "vals pos")
        n = _u32_arg(len_val, "len")
        charge(50 + 20 * n, 16 * n)
        import functools
        keys = [_sym_val(raw) for raw in _key_slices(inst, kp, n)]
        vals = [int.from_bytes(inst.mem_read(vp + 8 * i, 8), "little")
                for i in range(n)]
        pairs = sorted(zip(keys, vals), key=functools.cmp_to_key(
            lambda a, b: _cmp_vals(a[0], b[0])))
        for i in range(1, len(pairs)):
            if _map_key_bytes(pairs[i - 1][0]) == \
                    _map_key_bytes(pairs[i][0]):
                raise EnvError("duplicate map key")
        return cv.new_obj(TAG_MAP_OBJ, [list(p) for p in pairs])

    def map_unpack_to_linear_memory(inst, map_val, keys_pos, vals_pos,
                                    len_val):
        pairs = _map_of(map_val)
        kp = _u32_arg(keys_pos, "keys pos")
        vp = _u32_arg(vals_pos, "vals pos")
        n = _u32_arg(len_val, "len")
        if n != len(pairs):
            raise EnvError("map unpack length mismatch")
        charge(50 + 20 * n, 0)
        want = _key_slices(inst, kp, n)
        for i, (pk, pv) in enumerate(pairs):
            if _sym_bytes(pk) != want[i]:
                raise EnvError("map unpack key mismatch")
            inst.mem_write(vp + 8 * i,
                           (pv & _M64).to_bytes(8, "little"))
        return _make(TAG_VOID)

    # ---- buf: serialize + string/symbol + full bytes surface ----

    def serialize_to_bytes(inst, val):
        data = to_bytes(SCVal, cv.to_scval(val))
        charge_ct(CT.ValSer, len(data))
        return cv.new_obj(TAG_BYTES_OBJ, data)

    def deserialize_from_bytes(inst, b_val):
        from stellar_tpu.xdr.runtime import from_bytes as _fb
        data = _bytes_of(b_val)
        charge_ct(CT.ValDeser, len(data))
        try:
            sc = _fb(SCVal, bytes(data))
        except Exception:
            raise EnvError("unparsable SCVal bytes")
        return cv.from_scval(sc)

    def string_copy_to_linear_memory(inst, s_val, s_pos, lm_pos,
                                     len_val):
        data = _str_bytes(s_val)
        sp = _u32_arg(s_pos, "string pos")
        lp = _u32_arg(lm_pos, "lm pos")
        n = _u32_arg(len_val, "len")
        if sp + n > len(data):
            raise EnvError("string copy out of range")
        charge(50 + 2 * n, 0)
        inst.mem_write(lp, data[sp:sp + n])
        return _make(TAG_VOID)

    def symbol_copy_to_linear_memory(inst, s_val, s_pos, lm_pos,
                                     len_val):
        data = _sym_bytes(s_val)
        sp = _u32_arg(s_pos, "symbol pos")
        lp = _u32_arg(lm_pos, "lm pos")
        n = _u32_arg(len_val, "len")
        if sp + n > len(data):
            raise EnvError("symbol copy out of range")
        charge(50 + 2 * n, 0)
        inst.mem_write(lp, data[sp:sp + n])
        return _make(TAG_VOID)

    def string_len(inst, s_val):
        return _make(TAG_U32, len(_str_bytes(s_val)))

    def symbol_len(inst, s_val):
        return _make(TAG_U32, len(_sym_bytes(s_val)))

    def bytes_copy_from_linear_memory(inst, b_val, b_pos, lm_pos,
                                      len_val):
        data = _bytes_of(b_val)
        bp = _u32_arg(b_pos, "bytes pos")
        lp = _u32_arg(lm_pos, "lm pos")
        n = _u32_arg(len_val, "len")
        if bp > len(data):
            raise EnvError("bytes pos out of range")
        charge(50 + 2 * n, n)
        chunk = inst.mem_read(lp, n)
        return cv.new_obj(TAG_BYTES_OBJ,
                          bytes(data[:bp]) + chunk +
                          bytes(data[bp + n:]))

    def bytes_new(inst):
        return cv.new_obj(TAG_BYTES_OBJ, b"")

    def bytes_put(inst, b_val, i_val, u_val):
        data = bytearray(_bytes_of(b_val))
        i = _u32_arg(i_val, "bytes index")
        u = _u32_arg(u_val, "byte value")
        if i >= len(data) or u > 255:
            raise EnvError("bytes put out of range")
        charge(10 + len(data), len(data))
        data[i] = u
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data))

    def bytes_del(inst, b_val, i_val):
        data = bytearray(_bytes_of(b_val))
        i = _u32_arg(i_val, "bytes index")
        if i >= len(data):
            raise EnvError("bytes del out of range")
        charge(10 + len(data), len(data))
        del data[i]
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data))

    def bytes_push(inst, b_val, u_val):
        data = _bytes_of(b_val)
        u = _u32_arg(u_val, "byte value")
        if u > 255:
            raise EnvError("byte value out of range")
        charge(10 + len(data), len(data) + 1)
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data) + bytes([u]))

    def bytes_pop(inst, b_val):
        data = _bytes_of(b_val)
        if not data:
            raise EnvError("pop from empty bytes")
        charge(10 + len(data), len(data))
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data[:-1]))

    def bytes_front(inst, b_val):
        data = _bytes_of(b_val)
        if not data:
            raise EnvError("front of empty bytes")
        return _make(TAG_U32, data[0])

    def bytes_back(inst, b_val):
        data = _bytes_of(b_val)
        if not data:
            raise EnvError("back of empty bytes")
        return _make(TAG_U32, data[-1])

    def bytes_insert(inst, b_val, i_val, u_val):
        data = bytearray(_bytes_of(b_val))
        i = _u32_arg(i_val, "bytes index")
        u = _u32_arg(u_val, "byte value")
        if i > len(data) or u > 255:
            raise EnvError("bytes insert out of range")
        charge(10 + len(data), len(data) + 1)
        data.insert(i, u)
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data))

    def bytes_append(inst, b1_val, b2_val):
        a, b = _bytes_of(b1_val), _bytes_of(b2_val)
        charge(10 + len(a) + len(b), len(a) + len(b))
        return cv.new_obj(TAG_BYTES_OBJ, bytes(a) + bytes(b))

    def bytes_slice(inst, b_val, start_val, end_val):
        data = _bytes_of(b_val)
        start = _u32_arg(start_val, "slice start")
        end = _u32_arg(end_val, "slice end")
        if start > end or end > len(data):
            raise EnvError("bytes slice out of range")
        charge(10 + (end - start), end - start)
        return cv.new_obj(TAG_BYTES_OBJ, bytes(data[start:end]))

    def symbol_index_in_linear_memory(inst, sym_val, slices_pos,
                                      len_val):
        target = _sym_bytes(sym_val)
        sp = _u32_arg(slices_pos, "slices pos")
        n = _u32_arg(len_val, "len")
        for i, raw in enumerate(_key_slices(inst, sp, n)):
            if raw == target:
                return _make(TAG_U32, i)
        raise EnvError("symbol not found in linear memory slices")

    # ---- crypto ----

    def verify_sig_ed25519(inst, pk_val, payload_val, sig_val):
        pk = _bytes_of(pk_val)
        payload = _bytes_of(payload_val)
        sig = _bytes_of(sig_val)
        if len(pk) != 32 or len(sig) != 64:
            raise EnvError("bad ed25519 key/signature length")
        charge_ct(CT.VerifyEd25519Sig, len(payload))
        from stellar_tpu.crypto.keys import PublicKey, verify_sig
        if not verify_sig(PublicKey(bytes(pk)), bytes(payload),
                          bytes(sig)):
            raise EnvError("ed25519 signature verification failed")
        return _make(TAG_VOID)

    def compute_hash_keccak256(inst, b_val):
        data = _bytes_of(b_val)
        charge_ct(CT.ComputeKeccak256Hash, len(data))
        from stellar_tpu.crypto.keccak import keccak256
        return cv.new_obj(TAG_BYTES_OBJ, keccak256(bytes(data)))

    def recover_key_ecdsa_secp256k1(inst, digest_val, sig_val,
                                    rid_val):
        digest = _bytes_of(digest_val)
        sig = _bytes_of(sig_val)
        rid = _u32_arg(rid_val, "recovery id")
        charge_ct(CT.DecodeEcdsaCurve256Sig)
        charge_ct(CT.RecoverEcdsaSecp256k1Key)
        from stellar_tpu.crypto.secp256 import (
            EcdsaError, recover_secp256k1,
        )
        try:
            pk = recover_secp256k1(bytes(digest), bytes(sig), rid)
        except EcdsaError as e:
            raise EnvError(f"secp256k1 recover: {e}")
        return cv.new_obj(TAG_BYTES_OBJ, pk)

    # ---- BLS12-381 (protocol 22, CAP-59) ----

    def _bls():
        from stellar_tpu.crypto import bls12_381 as B
        return B

    def _g1_arg(val, check_subgroup=True):
        B = _bls()
        try:
            return B.g1_decode(bytes(_bytes_of(val)),
                               subgroup_check=check_subgroup)
        except B.BlsError as e:
            raise EnvError(f"bls12-381 g1: {e}")

    def _g2_arg(val, check_subgroup=True):
        B = _bls()
        try:
            return B.g2_decode(bytes(_bytes_of(val)),
                               subgroup_check=check_subgroup)
        except B.BlsError as e:
            raise EnvError(f"bls12-381 g2: {e}")

    def _fr_arg(val) -> int:
        return _u256_of(val) % _bls().R

    def bls12_381_check_g1_is_in_subgroup(inst, p_val):
        charge_ct(CT.Bls12381DecodeFp, iterations=2)
        charge_ct(CT.Bls12381G1CheckPointOnCurve)
        charge_ct(CT.Bls12381G1CheckPointInSubgroup)
        B = _bls()
        pt = _g1_arg(p_val, check_subgroup=False)
        try:
            B.g1_check(pt)
            return _make(TAG_TRUE)
        except B.BlsError:
            return _make(TAG_FALSE)

    def bls12_381_g1_add(inst, a_val, b_val):
        # add validates on-curve only (CAP-59: no subgroup check here)
        charge_ct(CT.Bls12381G1Add)
        charge_ct(CT.Bls12381EncodeFp, iterations=2)
        B = _bls()
        return cv.new_obj(TAG_BYTES_OBJ, B.g1_encode(B.g1_add(
            _g1_arg(a_val, check_subgroup=False),
            _g1_arg(b_val, check_subgroup=False))))

    def bls12_381_g1_mul(inst, p_val, k_val):
        charge_ct(CT.Bls12381G1Mul)
        charge_ct(CT.Bls12381EncodeFp, iterations=2)
        B = _bls()
        return cv.new_obj(TAG_BYTES_OBJ, B.g1_encode(
            B.g1_mul(_fr_arg(k_val), _g1_arg(p_val))))

    def bls12_381_g1_msm(inst, points_val, scalars_val):
        B = _bls()
        pts = [_g1_arg(v) for v in _vec_of(points_val)]
        ks = [_fr_arg(v) for v in _vec_of(scalars_val)]
        if len(pts) != len(ks):
            raise EnvError("bls12-381 msm length mismatch")
        charge_ct(CT.Bls12381G1Msm, len(pts))
        return cv.new_obj(TAG_BYTES_OBJ,
                          B.g1_encode(B.g1_msm(list(zip(ks, pts)))))

    def _h2c():
        from stellar_tpu.crypto import h2c
        return h2c

    def bls12_381_map_fp_to_g1(inst, fp_val):
        # RFC 9380 map_to_curve: SSWU + 11-isogeny, NO cofactor
        # clearing (reference host WBMap semantics — the result is
        # on-curve but generally outside the r-subgroup); constants
        # derived and verified by tools/derive_h2c.py (reproduces the
        # RFC's own curve parameters and Z = 11)
        charge_ct(CT.Bls12381MapFpToG1)
        raw = bytes(_bytes_of(fp_val))
        if len(raw) != 48:
            raise EnvError("fp encoding must be 48 bytes")
        u = int.from_bytes(raw, "big")
        if u >= _bls().P:
            raise EnvError("fp value out of range")
        return cv.new_obj(TAG_BYTES_OBJ,
                          _bls().g1_encode(_h2c().map_fp_to_g1(u)))

    def bls12_381_hash_to_g1(inst, msg_val, dst_val):
        msg = bytes(_bytes_of(msg_val))
        charge_ct(CT.Bls12381HashToG1, len(msg))
        dst = bytes(_bytes_of(dst_val))
        if not dst or len(dst) > 255:
            raise EnvError("dst must be 1..255 bytes")
        return cv.new_obj(TAG_BYTES_OBJ,
                          _bls().g1_encode(_h2c().hash_to_g1(msg, dst)))

    def bls12_381_check_g2_is_in_subgroup(inst, p_val):
        charge_ct(CT.Bls12381DecodeFp, iterations=4)
        charge_ct(CT.Bls12381G2CheckPointOnCurve)
        charge_ct(CT.Bls12381G2CheckPointInSubgroup)
        B = _bls()
        pt = _g2_arg(p_val, check_subgroup=False)
        try:
            B.g2_check(pt)
            return _make(TAG_TRUE)
        except B.BlsError:
            return _make(TAG_FALSE)

    def bls12_381_g2_add(inst, a_val, b_val):
        charge_ct(CT.Bls12381G2Add)
        charge_ct(CT.Bls12381EncodeFp, iterations=4)
        B = _bls()
        return cv.new_obj(TAG_BYTES_OBJ, B.g2_encode(B.g2_add(
            _g2_arg(a_val, check_subgroup=False),
            _g2_arg(b_val, check_subgroup=False))))

    def bls12_381_g2_mul(inst, p_val, k_val):
        charge_ct(CT.Bls12381G2Mul)
        charge_ct(CT.Bls12381EncodeFp, iterations=4)
        B = _bls()
        return cv.new_obj(TAG_BYTES_OBJ, B.g2_encode(
            B.g2_mul(_fr_arg(k_val), _g2_arg(p_val))))

    def bls12_381_g2_msm(inst, points_val, scalars_val):
        B = _bls()
        pts = [_g2_arg(v) for v in _vec_of(points_val)]
        ks = [_fr_arg(v) for v in _vec_of(scalars_val)]
        if len(pts) != len(ks):
            raise EnvError("bls12-381 msm length mismatch")
        charge_ct(CT.Bls12381G2Msm, len(pts))
        return cv.new_obj(TAG_BYTES_OBJ,
                          B.g2_encode(B.g2_msm(list(zip(ks, pts)))))

    def bls12_381_map_fp2_to_g2(inst, fp2_val):
        # same wire convention as the g2 point codec: c1 || c0
        charge_ct(CT.Bls12381MapFp2ToG2)
        raw = bytes(_bytes_of(fp2_val))
        if len(raw) != 96:
            raise EnvError("fp2 encoding must be 96 bytes")
        c1 = int.from_bytes(raw[:48], "big")
        c0 = int.from_bytes(raw[48:], "big")
        if c0 >= _bls().P or c1 >= _bls().P:
            raise EnvError("fp2 value out of range")
        return cv.new_obj(TAG_BYTES_OBJ,
                          _bls().g2_encode(_h2c().map_fp2_to_g2((c0, c1))))

    def bls12_381_hash_to_g2(inst, msg_val, dst_val):
        msg = bytes(_bytes_of(msg_val))
        charge_ct(CT.Bls12381HashToG2, len(msg))
        dst = bytes(_bytes_of(dst_val))
        if not dst or len(dst) > 255:
            raise EnvError("dst must be 1..255 bytes")
        return cv.new_obj(TAG_BYTES_OBJ,
                          _bls().g2_encode(_h2c().hash_to_g2(msg, dst)))

    def bls12_381_multi_pairing_check(inst, vp1_val, vp2_val):
        B = _bls()
        ps = [_g1_arg(v) for v in _vec_of(vp1_val)]
        qs = [_g2_arg(v) for v in _vec_of(vp2_val)]
        if len(ps) != len(qs) or not ps:
            raise EnvError("bls12-381 pairing vector mismatch")
        charge_ct(CT.Bls12381Pairing, len(ps))
        ok = B.pairing_check(list(zip(ps, qs)))
        return _make(TAG_TRUE if ok else TAG_FALSE)

    def _fr_result(n: int):
        return _mk_u256(n % _bls().R)

    def bls12_381_fr_add(inst, a_val, b_val):
        charge_ct(CT.Bls12381FrAddSub)
        return _fr_result(_bls().fr_add(_fr_arg(a_val), _fr_arg(b_val)))

    def bls12_381_fr_sub(inst, a_val, b_val):
        charge_ct(CT.Bls12381FrAddSub)
        return _fr_result(_bls().fr_sub(_fr_arg(a_val), _fr_arg(b_val)))

    def bls12_381_fr_mul(inst, a_val, b_val):
        charge_ct(CT.Bls12381FrMul)
        return _fr_result(_bls().fr_mul(_fr_arg(a_val), _fr_arg(b_val)))

    def bls12_381_fr_pow(inst, a_val, e_val):
        charge_ct(CT.Bls12381FrPow, 64)  # input: exponent bit-width
        # the exponent is a tagged U64Val, not a raw wasm u64
        e_sc = cv.to_scval(e_val)
        if e_sc.arm != T.SCV_U64:
            raise EnvError("fr_pow exponent must be a u64")
        return _fr_result(_bls().fr_pow(_fr_arg(a_val), e_sc.value))

    def bls12_381_fr_inv(inst, a_val):
        charge_ct(CT.Bls12381FrInv)
        B = _bls()
        try:
            return _fr_result(B.fr_inv(_fr_arg(a_val)))
        except B.BlsError as e:
            raise EnvError(f"bls12-381 fr: {e}")

    def verify_sig_ecdsa_secp256r1(inst, pk_val, digest_val, sig_val):
        pk = _bytes_of(pk_val)
        digest = _bytes_of(digest_val)
        sig = _bytes_of(sig_val)
        charge_ct(CT.Sec1DecodePointUncompressed)
        charge_ct(CT.DecodeEcdsaCurve256Sig)
        charge_ct(CT.VerifyEcdsaSecp256r1Sig)
        from stellar_tpu.crypto.secp256 import (
            SECP256R1, EcdsaError, verify_ecdsa,
        )
        try:
            ok = verify_ecdsa(SECP256R1, bytes(pk), bytes(digest),
                              bytes(sig))
        except EcdsaError as e:
            raise EnvError(f"secp256r1 verify: {e}")
        if not ok:
            raise EnvError("secp256r1 signature verification failed")
        return _make(TAG_VOID)

    # ---- ledger (create/upload/id-derivation surface) ----

    def _addr_of(val):
        return cv.obj(val, TAG_ADDRESS_OBJ)

    def _from_address_preimage(deployer, salt: bytes):
        from stellar_tpu.xdr.contract import (
            ContractIDPreimage, ContractIDPreimageFromAddress,
            ContractIDPreimageType,
        )
        return ContractIDPreimage.make(
            ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ADDRESS,
            ContractIDPreimageFromAddress(address=deployer,
                                          salt=salt))

    def create_contract(inst, deployer_val, wasm_hash_val, salt_val):
        from stellar_tpu.soroban.host import _address_bytes, _create
        from stellar_tpu.xdr.contract import (
            ContractExecutable, ContractExecutableType,
            CreateContractArgs, SorobanAuthorizedFunction,
            SorobanAuthorizedFunctionType,
        )
        deployer = _addr_of(deployer_val)
        wasm_hash = bytes(_bytes_of(wasm_hash_val))
        salt = bytes(_bytes_of(salt_val))
        if len(wasm_hash) != 32 or len(salt) != 32:
            raise EnvError("wasm hash and salt must be 32 bytes")
        cc = CreateContractArgs(
            contractIDPreimage=_from_address_preimage(deployer, salt),
            executable=ContractExecutable.make(
                ContractExecutableType.CONTRACT_EXECUTABLE_WASM,
                wasm_hash))
        # a deployer other than the running contract must authorize
        if _address_bytes(deployer) != \
                _address_bytes(env.contract_addr):
            inv = SorobanAuthorizedFunction.make(
                SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CREATE_CONTRACT_HOST_FN,
                cc)
            env.host.require_auth(
                SCVal.make(T.SCV_ADDRESS, deployer), inv, env.depth)
        rv = _create(env.host, cc, env.host.network_id)
        return cv.from_scval(rv)

    def create_asset_contract(inst, asset_val):
        from stellar_tpu.soroban.host import _create
        from stellar_tpu.xdr.contract import (
            ContractExecutable, ContractExecutableType,
            ContractIDPreimage, ContractIDPreimageType,
            CreateContractArgs,
        )
        from stellar_tpu.xdr.runtime import from_bytes as _fb
        from stellar_tpu.xdr.types import Asset
        try:
            asset = _fb(Asset, bytes(_bytes_of(asset_val)))
        except Exception:
            raise EnvError("unparsable Asset XDR")
        cc = CreateContractArgs(
            contractIDPreimage=ContractIDPreimage.make(
                ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET,
                asset),
            executable=ContractExecutable.make(
                ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET))
        rv = _create(env.host, cc, env.host.network_id)
        return cv.from_scval(rv)

    def get_contract_id(inst, deployer_val, salt_val):
        from stellar_tpu.soroban.host import derive_contract_id
        from stellar_tpu.xdr.contract import contract_address
        deployer = _addr_of(deployer_val)
        salt = bytes(_bytes_of(salt_val))
        if len(salt) != 32:
            raise EnvError("salt must be 32 bytes")
        charge(500, 32)
        cid = derive_contract_id(
            env.host.network_id,
            _from_address_preimage(deployer, salt))
        return cv.new_obj(TAG_ADDRESS_OBJ, contract_address(cid))

    def get_asset_contract_id(inst, asset_val):
        from stellar_tpu.soroban.host import derive_contract_id
        from stellar_tpu.xdr.contract import (
            ContractIDPreimage, ContractIDPreimageType,
            contract_address,
        )
        from stellar_tpu.xdr.runtime import from_bytes as _fb
        from stellar_tpu.xdr.types import Asset
        try:
            asset = _fb(Asset, bytes(_bytes_of(asset_val)))
        except Exception:
            raise EnvError("unparsable Asset XDR")
        charge(500, 32)
        cid = derive_contract_id(
            env.host.network_id,
            ContractIDPreimage.make(
                ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET,
                asset))
        return cv.new_obj(TAG_ADDRESS_OBJ, contract_address(cid))

    def upload_wasm(inst, b_val):
        from stellar_tpu.soroban.host import _upload
        rv = _upload(env.host, bytes(_bytes_of(b_val)),
                     env.host.storage.read_write)
        return cv.from_scval(rv)

    def update_current_contract_wasm(inst, hash_val):
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.soroban.host import (
            _wrap_entry, contract_code_key, contract_data_key,
        )
        from stellar_tpu.xdr.contract import (
            ContractDataDurability, ContractDataEntry,
            ContractExecutable, ContractExecutableType,
            SCContractInstance,
        )
        from stellar_tpu.xdr.types import (
            ExtensionPoint, LedgerEntryType,
        )
        new_hash = bytes(_bytes_of(hash_val))
        if len(new_hash) != 32:
            raise EnvError("wasm hash must be 32 bytes")
        if env.host.storage.get(
                key_bytes(contract_code_key(new_hash))) is None:
            raise EnvError("new wasm not uploaded")
        key = SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE)
        lk = contract_data_key(env.contract_addr, key,
                               ContractDataDurability.PERSISTENT)
        kb = key_bytes(lk)
        entry = env.host.storage.get(kb)
        if entry is None:
            raise EnvError("missing instance entry")
        inst_v = entry.data.value.val.value
        new_inst = ContractDataEntry(
            ext=ExtensionPoint.make(0), contract=env.contract_addr,
            key=key, durability=ContractDataDurability.PERSISTENT,
            val=SCVal.make(T.SCV_CONTRACT_INSTANCE, SCContractInstance(
                executable=ContractExecutable.make(
                    ContractExecutableType.CONTRACT_EXECUTABLE_WASM,
                    new_hash),
                storage=inst_v.storage)))
        env.host.storage.put(kb, _wrap_entry(
            LedgerEntryType.CONTRACT_DATA, new_inst,
            env.host.ledger_seq), None)
        return _make(TAG_VOID)

    def extend_contract_instance_and_code_ttl(inst, addr_val,
                                              thresh_val, ext_val):
        """Like extend_current_contract_instance_and_code_ttl but for
        an arbitrary contract address."""
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.soroban.host import (
            contract_code_key, contract_data_key,
        )
        from stellar_tpu.xdr.contract import (
            ContractDataDurability, ContractExecutableType,
        )
        target = _addr_of(addr_val)
        thresh = _u32_arg(thresh_val, "threshold")
        ext = _u32_arg(ext_val, "extend_to")
        inst_kb = key_bytes(contract_data_key(
            target, SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            ContractDataDurability.PERSISTENT))
        env.host.extend_ttl(inst_kb, thresh, ext)
        slot = env.host.storage.entries.get(inst_kb)
        if slot is not None and slot[0] is not None:
            instance = slot[0].data.value.val.value
            if instance.executable.arm == \
                    ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
                env.host.extend_ttl(
                    key_bytes(contract_code_key(
                        instance.executable.value)), thresh, ext)
        return _make(TAG_VOID)

    # ---- call: try_call with frame rollback ----

    def try_call(inst, addr_val, fn_val, args_val):
        from stellar_tpu.soroban.host import HostError
        from stellar_tpu.xdr.contract import SCError, SCErrorCode
        addr_sc = cv.to_scval(addr_val)
        fn_sc = cv.to_scval(fn_val)
        args_sc = cv.to_scval(args_val)
        if addr_sc.arm != T.SCV_ADDRESS or fn_sc.arm != T.SCV_SYMBOL \
                or args_sc.arm != T.SCV_VEC:
            raise EnvError("try_call needs (address, symbol, vec)")
        snap = env.host.snapshot()
        try:
            rv = env.host.call_contract(addr_sc.value, fn_sc.value,
                                        list(args_sc.value or ()),
                                        env.depth + 1)
        except HostError as e:
            if e.kind == HostError.BUDGET:
                raise  # metering exhaustion is never catchable
            env.host.restore(snap)
            if e.error_sc is not None:
                # hand the CALLEE'S fail_with_error val to the caller
                return cv.from_scval(e.error_sc)
            from stellar_tpu.xdr.contract import SCErrorType
            return cv.from_scval(SCVal.make(T.SCV_ERROR, SCError.make(
                SCErrorType.SCE_CONTEXT,
                SCErrorCode.SCEC_INVALID_ACTION)))
        return cv.from_scval(rv)

    # ---- address ----

    def require_auth_for_args(inst, addr_val, args_val):
        from stellar_tpu.xdr.contract import (
            InvokeContractArgs, SorobanAuthorizedFunction,
            SorobanAuthorizedFunctionType,
        )
        addr = _addr_of(addr_val)
        args_sc = cv.to_scval(args_val)
        if args_sc.arm != T.SCV_VEC:
            raise EnvError("require_auth_for_args needs a vec")
        if env.invocation is None or env.invocation.arm != \
                SorobanAuthorizedFunctionType \
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN:
            raise EnvError("no contract invocation context")
        cur = env.invocation.value
        inv = SorobanAuthorizedFunction.make(
            SorobanAuthorizedFunctionType
            .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
            InvokeContractArgs(contractAddress=cur.contractAddress,
                               functionName=cur.functionName,
                               args=list(args_sc.value or ())))
        env.host.require_auth(SCVal.make(T.SCV_ADDRESS, addr), inv,
                              env.depth)
        return _make(TAG_VOID)

    def strkey_to_address(inst, key_val):
        from stellar_tpu.crypto import strkey as sk
        from stellar_tpu.xdr.contract import (
            SCAddressType, contract_address,
        )
        from stellar_tpu.xdr.types import account_id
        tag = _tag(key_val)
        if tag == TAG_BYTES_OBJ:
            raw = bytes(_bytes_of(key_val))
        elif tag == TAG_STRING_OBJ:
            raw = bytes(_str_bytes(key_val))
        else:
            raise EnvError("strkey must be bytes or string")
        charge(200, 0)
        try:
            s = raw.decode("ascii")
        except UnicodeDecodeError:
            raise EnvError("strkey must be ascii")
        try:
            if s.startswith("G"):
                addr = SCAddress.make(
                    SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                    account_id(sk.decode_account(s)))
            elif s.startswith("C"):
                addr = contract_address(sk.decode_contract(s))
            else:
                raise EnvError("unsupported strkey kind")
        except sk.StrKeyError as e:
            raise EnvError(f"bad strkey: {e}")
        return cv.new_obj(TAG_ADDRESS_OBJ, addr)

    def address_to_strkey(inst, addr_val):
        from stellar_tpu.crypto import strkey as sk
        from stellar_tpu.xdr.contract import SCAddressType
        from stellar_tpu.xdr.types import account_ed25519
        addr = _addr_of(addr_val)
        charge(200, 64)
        if addr.arm == SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
            s = sk.encode_account(account_ed25519(addr.value))
        else:
            s = sk.encode_contract(addr.value)
        return cv.new_obj(TAG_STRING_OBJ, s.encode("ascii"))

    def authorize_as_curr_contract(inst, auth_vec_val):
        """Register sub-invocation authorizations by the RUNNING
        contract (reference authorize_as_curr_contract). Entry shape
        accepted here: vec [address, fn-symbol, args-vec] per entry —
        the flattened invocation list (the reference takes the
        recursive InvokerContractAuthEntry tree; this registry keys
        on the same (contract, fn, args) identity require_auth
        matches on)."""
        from stellar_tpu.soroban.host import _address_bytes
        from stellar_tpu.xdr.contract import (
            InvokeContractArgs, SorobanAuthorizedFunction,
            SorobanAuthorizedFunctionType,
        )
        entries_sc = cv.to_scval(auth_vec_val)
        if entries_sc.arm != T.SCV_VEC:
            raise EnvError("authorize_as_curr_contract needs a vec")
        my_ab = _address_bytes(env.contract_addr)
        for entry in (entries_sc.value or ()):
            if entry.arm != T.SCV_VEC or len(entry.value or ()) != 3:
                raise EnvError("auth entry must be "
                               "[address, symbol, args]")
            addr_sc, fn_sc, args_sc = entry.value
            if addr_sc.arm != T.SCV_ADDRESS or \
                    fn_sc.arm != T.SCV_SYMBOL or \
                    args_sc.arm != T.SCV_VEC:
                raise EnvError("auth entry must be "
                               "[address, symbol, args]")
            inv = SorobanAuthorizedFunction.make(
                SorobanAuthorizedFunctionType
                .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN,
                InvokeContractArgs(contractAddress=addr_sc.value,
                                   functionName=fn_sc.value,
                                   args=list(args_sc.value or ())))
            charge(100, 64)
            env.host.contract_auths.setdefault(my_ab, []).append(
                (len(env.host.frame_addrs),
                 to_bytes(SorobanAuthorizedFunction, inv)))
        return _make(TAG_VOID)

    # ---- test ----

    def dummy0(inst):
        return _make(TAG_VOID)

    def protocol_gated_dummy(inst):
        # era availability comes from the central MIN_PROTOCOL gate
        return _make(TAG_VOID)

    # =====================================================================
    # the import table: every canonical handler registers under BOTH
    # its (module, single-char export) name — what genuine SDK
    # contracts import (see env_interface.py) — and (module, long
    # name); the historical aliases this repo's earlier builder
    # contracts imported stay bound to the same closures.
    # =====================================================================

    canonical = {
        # context "x"
        "log_from_linear_memory": ("x", log_from_linear_memory),
        "obj_cmp": ("x", obj_cmp),
        "contract_event": ("x", contract_event),
        "get_ledger_version": ("x", get_ledger_version),
        "get_ledger_sequence": ("x", ledger_sequence),
        "get_ledger_timestamp": ("x", ledger_timestamp),
        "fail_with_error": ("x", fail_with_error),
        "get_ledger_network_id": ("x", get_ledger_network_id),
        "get_current_contract_address":
            ("x", current_contract_address),
        "get_max_live_until_ledger": ("x", get_max_live_until_ledger),
        # int "i"
        "obj_from_u64": ("i", obj_from_u64),
        "obj_to_u64": ("i", obj_to_u64),
        "obj_from_i64": ("i", obj_from_i64),
        "obj_to_i64": ("i", obj_to_i64),
        "obj_from_u128_pieces": ("i", obj_from_u128_pieces),
        "obj_to_u128_lo64": ("i", obj_to_u128_lo64),
        "obj_to_u128_hi64": ("i", obj_to_u128_hi64),
        "obj_from_i128_pieces": ("i", obj_from_i128_pieces),
        "obj_to_i128_lo64": ("i", obj_to_i128_lo64),
        "obj_to_i128_hi64": ("i", obj_to_i128_hi64),
        "obj_from_u256_pieces": ("i", obj_from_u256_pieces),
        "u256_val_from_be_bytes": ("i", u256_val_from_be_bytes),
        "u256_val_to_be_bytes": ("i", u256_val_to_be_bytes),
        "obj_to_u256_hi_hi": ("i", obj_to_u256_hi_hi),
        "obj_to_u256_hi_lo": ("i", obj_to_u256_hi_lo),
        "obj_to_u256_lo_hi": ("i", obj_to_u256_lo_hi),
        "obj_to_u256_lo_lo": ("i", obj_to_u256_lo_lo),
        "obj_from_i256_pieces": ("i", obj_from_i256_pieces),
        "i256_val_from_be_bytes": ("i", i256_val_from_be_bytes),
        "i256_val_to_be_bytes": ("i", i256_val_to_be_bytes),
        "obj_to_i256_hi_hi": ("i", obj_to_i256_hi_hi),
        "obj_to_i256_hi_lo": ("i", obj_to_i256_hi_lo),
        "obj_to_i256_lo_hi": ("i", obj_to_i256_lo_hi),
        "obj_to_i256_lo_lo": ("i", obj_to_i256_lo_lo),
        "u256_add": ("i", u256_add),
        "u256_sub": ("i", u256_sub),
        "u256_mul": ("i", u256_mul),
        "u256_div": ("i", u256_div),
        "u256_rem_euclid": ("i", u256_rem_euclid),
        "u256_pow": ("i", u256_pow),
        "u256_shl": ("i", u256_shl),
        "u256_shr": ("i", u256_shr),
        "i256_add": ("i", i256_add),
        "i256_sub": ("i", i256_sub),
        "i256_mul": ("i", i256_mul),
        "i256_div": ("i", i256_div),
        "i256_rem_euclid": ("i", i256_rem_euclid),
        "i256_pow": ("i", i256_pow),
        "i256_shl": ("i", i256_shl),
        "i256_shr": ("i", i256_shr),
        "timepoint_obj_from_u64": ("i", timepoint_obj_from_u64),
        "timepoint_obj_to_u64": ("i", timepoint_obj_to_u64),
        "duration_obj_from_u64": ("i", duration_obj_from_u64),
        "duration_obj_to_u64": ("i", duration_obj_to_u64),
        # map "m"
        "map_new": ("m", map_new),
        "map_put": ("m", map_put),
        "map_get": ("m", map_get),
        "map_del": ("m", map_del),
        "map_len": ("m", map_len),
        "map_has": ("m", map_has),
        "map_key_by_pos": ("m", map_key_by_pos),
        "map_val_by_pos": ("m", map_val_by_pos),
        "map_keys": ("m", map_keys),
        "map_values": ("m", map_values),
        "map_new_from_linear_memory":
            ("m", map_new_from_linear_memory),
        "map_unpack_to_linear_memory":
            ("m", map_unpack_to_linear_memory),
        # vec "v"
        "vec_new": ("v", vec_new),
        "vec_put": ("v", vec_put),
        "vec_get": ("v", vec_get),
        "vec_del": ("v", vec_del),
        "vec_len": ("v", vec_len),
        "vec_push_front": ("v", vec_push_front),
        "vec_pop_front": ("v", vec_pop_front),
        "vec_push_back": ("v", vec_push_back),
        "vec_pop_back": ("v", vec_pop_back),
        "vec_front": ("v", vec_front),
        "vec_back": ("v", vec_back),
        "vec_insert": ("v", vec_insert),
        "vec_append": ("v", vec_append),
        "vec_slice": ("v", vec_slice),
        "vec_first_index_of": ("v", vec_first_index_of),
        "vec_last_index_of": ("v", vec_last_index_of),
        "vec_binary_search": ("v", vec_binary_search),
        "vec_new_from_linear_memory":
            ("v", vec_new_from_linear_memory),
        "vec_unpack_to_linear_memory":
            ("v", vec_unpack_to_linear_memory),
        # ledger "l"
        "put_contract_data": ("l", put_contract_data),
        "has_contract_data": ("l", has_contract_data),
        "get_contract_data": ("l", get_contract_data),
        "del_contract_data": ("l", del_contract_data),
        "extend_contract_data_ttl": ("l", extend_contract_data_ttl),
        "extend_current_contract_instance_and_code_ttl":
            ("l", extend_instance_and_code_ttl),
        "extend_contract_instance_and_code_ttl":
            ("l", extend_contract_instance_and_code_ttl),
        "create_contract": ("l", create_contract),
        "create_asset_contract": ("l", create_asset_contract),
        "get_asset_contract_id": ("l", get_asset_contract_id),
        "upload_wasm": ("l", upload_wasm),
        "update_current_contract_wasm":
            ("l", update_current_contract_wasm),
        "get_contract_id": ("l", get_contract_id),
        # call "d"
        "call": ("d", call),
        "try_call": ("d", try_call),
        # buf "b"
        "serialize_to_bytes": ("b", serialize_to_bytes),
        "deserialize_from_bytes": ("b", deserialize_from_bytes),
        "string_copy_to_linear_memory":
            ("b", string_copy_to_linear_memory),
        "symbol_copy_to_linear_memory":
            ("b", symbol_copy_to_linear_memory),
        "string_new_from_linear_memory":
            ("b", string_new_from_linear_memory),
        "symbol_new_from_linear_memory":
            ("b", symbol_new_from_linear_memory),
        "string_len": ("b", string_len),
        "symbol_len": ("b", symbol_len),
        "bytes_copy_to_linear_memory":
            ("b", bytes_copy_to_linear_memory),
        "bytes_copy_from_linear_memory":
            ("b", bytes_copy_from_linear_memory),
        "bytes_new_from_linear_memory":
            ("b", bytes_new_from_linear_memory),
        "bytes_new": ("b", bytes_new),
        "bytes_put": ("b", bytes_put),
        "bytes_get": ("b", bytes_get),
        "bytes_del": ("b", bytes_del),
        "bytes_len": ("b", bytes_len),
        "bytes_push": ("b", bytes_push),
        "bytes_pop": ("b", bytes_pop),
        "bytes_front": ("b", bytes_front),
        "bytes_back": ("b", bytes_back),
        "bytes_insert": ("b", bytes_insert),
        "bytes_append": ("b", bytes_append),
        "bytes_slice": ("b", bytes_slice),
        "symbol_index_in_linear_memory":
            ("b", symbol_index_in_linear_memory),
        # crypto "c"
        "compute_hash_sha256": ("c", compute_sha256),
        "verify_sig_ed25519": ("c", verify_sig_ed25519),
        "compute_hash_keccak256": ("c", compute_hash_keccak256),
        "recover_key_ecdsa_secp256k1":
            ("c", recover_key_ecdsa_secp256k1),
        "verify_sig_ecdsa_secp256r1":
            ("c", verify_sig_ecdsa_secp256r1),
        "bls12_381_check_g1_is_in_subgroup":
            ("c", bls12_381_check_g1_is_in_subgroup),
        "bls12_381_g1_add": ("c", bls12_381_g1_add),
        "bls12_381_g1_mul": ("c", bls12_381_g1_mul),
        "bls12_381_g1_msm": ("c", bls12_381_g1_msm),
        "bls12_381_map_fp_to_g1": ("c", bls12_381_map_fp_to_g1),
        "bls12_381_hash_to_g1": ("c", bls12_381_hash_to_g1),
        "bls12_381_check_g2_is_in_subgroup":
            ("c", bls12_381_check_g2_is_in_subgroup),
        "bls12_381_g2_add": ("c", bls12_381_g2_add),
        "bls12_381_g2_mul": ("c", bls12_381_g2_mul),
        "bls12_381_g2_msm": ("c", bls12_381_g2_msm),
        "bls12_381_map_fp2_to_g2": ("c", bls12_381_map_fp2_to_g2),
        "bls12_381_hash_to_g2": ("c", bls12_381_hash_to_g2),
        "bls12_381_multi_pairing_check":
            ("c", bls12_381_multi_pairing_check),
        "bls12_381_fr_add": ("c", bls12_381_fr_add),
        "bls12_381_fr_sub": ("c", bls12_381_fr_sub),
        "bls12_381_fr_mul": ("c", bls12_381_fr_mul),
        "bls12_381_fr_pow": ("c", bls12_381_fr_pow),
        "bls12_381_fr_inv": ("c", bls12_381_fr_inv),
        # address "a"
        "require_auth_for_args": ("a", require_auth_for_args),
        "require_auth": ("a", require_auth),
        "strkey_to_address": ("a", strkey_to_address),
        "address_to_strkey": ("a", address_to_strkey),
        "authorize_as_curr_contract":
            ("a", authorize_as_curr_contract),
        # test "t"
        "dummy0": ("t", dummy0),
        "protocol_gated_dummy": ("t", protocol_gated_dummy),
        # prng "p"
        "prng_reseed": ("p", prng_reseed),
        "prng_bytes_new": ("p", prng_bytes_new),
        "prng_u64_in_inclusive_range":
            ("p", prng_u64_in_inclusive_range),
        "prng_vec_shuffle": ("p", prng_vec_shuffle),
    }

    # protocol-era gating (reference pins one soroban-env-host crate
    # per protocol, src/rust/Cargo.toml:51-80, so a p21-era replay
    # cannot see p22 functions). Two layers, because the import table
    # is pooled across frames and the frame's protocol can differ per
    # tx: (1) LINK time — check_import_binding reads __min_protocol__ /
    # __frame_version__ and refuses the import like the reference's
    # per-era host would (import-but-never-call still fails); (2) CALL
    # time — defense in depth for direct handler invocation.
    from stellar_tpu.soroban.env_interface import MIN_PROTOCOL
    from stellar_tpu.soroban.wasm import handler_arity as _harity

    def _version_gated(long_name, min_proto, fn):
        def gated(inst, *args):
            version = _frame_version()
            if version < min_proto:
                raise EnvError(
                    f"{long_name} requires protocol {min_proto}; "
                    f"ledger is protocol {version}")
            return fn(inst, *args)
        gated.__env_arity__ = _harity(fn)  # keep link-check visibility
        gated.__min_protocol__ = min_proto
        gated.__frame_version__ = _frame_version
        gated.__name__ = f"{long_name}_p{min_proto}_gate"
        return gated

    table: Dict[Tuple[str, str], Callable] = {}
    shorts = _SHORTS()
    for long_name, (mod, fn) in canonical.items():
        smod, schar = shorts[long_name]
        # a handler filed under a different module than the registry
        # would otherwise register its short name under the wrong key
        # and fail only at contract link time
        assert smod == mod, f"module mismatch for {long_name}"
        min_proto = MIN_PROTOCOL.get(long_name)
        if min_proto is not None:
            fn = _version_gated(long_name, min_proto, fn)
        table[(mod, long_name)] = fn
        table[(mod, schar)] = fn

    # historical aliases (this repo's earlier internal dialect, kept
    # for wasm_builder contracts already pinned in goldens/fixtures)
    table.update({
        ("x", "log"): log,
        ("x", "ledger_sequence"): ledger_sequence,
        ("x", "ledger_timestamp"): ledger_timestamp,
        ("x", "current_contract_address"): current_contract_address,
        ("x", "contract_event"): contract_event,
        ("x", "fail"): fail,
        ("l", "put_contract_data"): put_contract_data,
        ("l", "get_contract_data"): get_contract_data,
        ("l", "has_contract_data"): has_contract_data,
        ("l", "del_contract_data"): del_contract_data,
        ("l", "extend_contract_data_ttl"): extend_contract_data_ttl,
        ("l", "extend_instance_and_code_ttl"):
            extend_instance_and_code_ttl,
        ("v", "vec_new"): vec_new,
        ("v", "vec_push_back"): vec_push_back,
        ("v", "vec_get"): vec_get,
        ("v", "vec_len"): vec_len,
        ("m", "map_new"): map_new,
        ("m", "map_put"): map_put,
        ("m", "map_get"): map_get,
        ("m", "map_has"): map_has,
        ("m", "map_len"): map_len,
        ("b", "bytes_new_from_linear_memory"):
            bytes_new_from_linear_memory,
        ("b", "bytes_copy_to_linear_memory"):
            bytes_copy_to_linear_memory,
        ("b", "bytes_len"): bytes_len,
        ("b", "bytes_get"): bytes_get,
        ("b", "symbol_new_from_linear_memory"):
            symbol_new_from_linear_memory,
        ("b", "string_new_from_linear_memory"):
            string_new_from_linear_memory,
        ("i", "obj_from_u64"): obj_from_u64,
        ("i", "obj_to_u64"): obj_to_u64,
        ("i", "obj_from_i64"): obj_from_i64,
        ("i", "obj_to_i64"): obj_to_i64,
        ("a", "require_auth"): require_auth,
        ("c", "call"): call,
        ("d", "compute_sha256"): compute_sha256,
        ("p", "prng_u64_in_inclusive_range"):
            prng_u64_in_inclusive_range,
        ("p", "prng_bytes_new"): prng_bytes_new,
        ("p", "prng_reseed"): prng_reseed,
    })
    return table
