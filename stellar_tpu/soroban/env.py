"""Host environment for wasm contracts: the 64-bit tagged-``Val`` ABI
and the host-function import table — the layer soroban-env-host puts
between wasmi and the ledger (reference boundary:
``src/rust/src/lib.rs:61-83`` links soroban-env-host, which defines the
Val encoding and the env interface; the crate itself is external to the
reference tree, so the import names here are this framework's own —
the TAG layout and semantics mirror the published soroban-env-common
value scheme so the conversion logic is protocol-shaped).

A ``Val`` is a u64: low 8 bits tag, high 56 bits body. Small immediates
(u32/i32, small u64/i64, short symbols, bool/void) travel inline;
larger values live in a per-invocation object table addressed by
handle. Handles never cross contract frames: cross-contract calls
convert through SCVal at the boundary, so a callee cannot forge a
caller's handles (same isolation the reference host enforces).

Host imports use single-letter module names grouped by area (context
"x", ledger "l", vec "v", map "m", buf "b", int "i", address "a",
call "c", crypto "d") — the grouping soroban-env uses for its export
names.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from stellar_tpu.crypto.sha import sha256
from stellar_tpu.soroban.wasm import Trap
from stellar_tpu.xdr.contract import (
    SCAddress, SCMapEntry, SCVal, SCValType,
)
from stellar_tpu.xdr.runtime import to_bytes

__all__ = ["ValConverter", "make_imports", "EnvError",
           "TAG_FALSE", "TAG_TRUE", "TAG_VOID", "TAG_U32", "TAG_I32",
           "TAG_U64_SMALL", "TAG_I64_SMALL", "TAG_SYMBOL_SMALL",
           "TAG_U64_OBJ", "TAG_I64_OBJ", "TAG_U128_OBJ", "TAG_I128_OBJ",
           "TAG_BYTES_OBJ", "TAG_STRING_OBJ", "TAG_SYMBOL_OBJ",
           "TAG_VEC_OBJ", "TAG_MAP_OBJ", "TAG_ADDRESS_OBJ",
           "sym_to_small", "small_to_sym"]

T = SCValType

# Tag values mirror soroban-env-common's Tag enum
TAG_FALSE = 0
TAG_TRUE = 1
TAG_VOID = 2
TAG_ERROR = 3
TAG_U32 = 4
TAG_I32 = 5
TAG_U64_SMALL = 6
TAG_I64_SMALL = 7
TAG_TIMEPOINT_SMALL = 8
TAG_DURATION_SMALL = 9
TAG_U128_SMALL = 10
TAG_I128_SMALL = 11
TAG_SYMBOL_SMALL = 14
TAG_U64_OBJ = 64
TAG_I64_OBJ = 65
TAG_TIMEPOINT_OBJ = 66
TAG_DURATION_OBJ = 67
TAG_U128_OBJ = 68
TAG_I128_OBJ = 69
TAG_BYTES_OBJ = 72
TAG_STRING_OBJ = 73
TAG_SYMBOL_OBJ = 74
TAG_VEC_OBJ = 75
TAG_MAP_OBJ = 76
TAG_ADDRESS_OBJ = 77

_M56 = (1 << 56) - 1
_M64 = (1 << 64) - 1
_SMALL_MAX_U = _M56                      # unsigned small body range
_SMALL_MIN_I = -(1 << 55)
_SMALL_MAX_I = (1 << 55) - 1

_SYM_CHARS = "_0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ" \
    "abcdefghijklmnopqrstuvwxyz"
_SYM_CODE = {c: i + 1 for i, c in enumerate(_SYM_CHARS)}
_SYM_CHAR = {i + 1: c for i, c in enumerate(_SYM_CHARS)}


class EnvError(Trap):
    """Host-env failure surfaced to wasm as a trap."""


def _tag(val: int) -> int:
    return val & 0xFF


def _body(val: int) -> int:
    return (val >> 8) & _M56


def _make(tag: int, body: int = 0) -> int:
    return ((body & _M56) << 8) | tag


def sym_to_small(s: bytes) -> int:
    """Pack a <=9-char symbol into a SymbolSmall body (6 bits/char)."""
    if len(s) > 9:
        raise ValueError("symbol too long for small form")
    body = 0
    for ch in s.decode("ascii"):
        code = _SYM_CODE.get(ch)
        if code is None:
            raise ValueError(f"bad symbol char {ch!r}")
        body = (body << 6) | code
    return _make(TAG_SYMBOL_SMALL, body)


def small_to_sym(val: int) -> bytes:
    body = _body(val)
    chars = []
    while body:
        ch = _SYM_CHAR.get(body & 0x3F)
        if ch is None:
            # a forged Val with an embedded zero 6-bit group must trap
            # the contract, not raise through the host
            raise EnvError("malformed SymbolSmall encoding")
        chars.append(ch)
        body >>= 6
    return "".join(reversed(chars)).encode()


class ValConverter:
    """SCVal <-> Val conversion plus the per-invocation object table."""

    def __init__(self, charge: Callable[[int, int], None]):
        # charge(cpu, mem) — wired to the host budget
        self.objs: List[Tuple[int, object]] = []  # (tag, payload)
        self.charge = charge

    # ---------------- object table ----------------

    def new_obj(self, tag: int, payload) -> int:
        self.charge(50, 16)
        self.objs.append((tag, payload))
        return _make(tag, len(self.objs) - 1)

    def obj(self, val: int, want_tag: int):
        tag = _tag(val)
        if tag != want_tag:
            raise EnvError(f"expected tag {want_tag}, got {tag}")
        idx = _body(val)
        if idx >= len(self.objs):
            raise EnvError("bad object handle")
        otag, payload = self.objs[idx]
        if otag != want_tag:
            raise EnvError("object tag mismatch")
        return payload

    # ---------------- SCVal -> Val ----------------

    def from_scval(self, v: "SCVal.Value") -> int:
        arm = v.arm
        if arm == T.SCV_BOOL:
            return _make(TAG_TRUE if v.value else TAG_FALSE)
        if arm == T.SCV_VOID:
            return _make(TAG_VOID)
        if arm == T.SCV_U32:
            return _make(TAG_U32, v.value & 0xFFFFFFFF)
        if arm == T.SCV_I32:
            return _make(TAG_I32, v.value & 0xFFFFFFFF)
        if arm == T.SCV_U64:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_U64_SMALL, v.value)
            return self.new_obj(TAG_U64_OBJ, v.value)
        if arm == T.SCV_I64:
            if _SMALL_MIN_I <= v.value <= _SMALL_MAX_I:
                return _make(TAG_I64_SMALL, v.value)
            return self.new_obj(TAG_I64_OBJ, v.value)
        if arm == T.SCV_TIMEPOINT:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_TIMEPOINT_SMALL, v.value)
            return self.new_obj(TAG_TIMEPOINT_OBJ, v.value)
        if arm == T.SCV_DURATION:
            if v.value <= _SMALL_MAX_U:
                return _make(TAG_DURATION_SMALL, v.value)
            return self.new_obj(TAG_DURATION_OBJ, v.value)
        if arm == T.SCV_U128:
            n = (v.value.hi << 64) | v.value.lo
            if n <= _SMALL_MAX_U:
                return _make(TAG_U128_SMALL, n)
            return self.new_obj(TAG_U128_OBJ, n)
        if arm == T.SCV_I128:
            n = (v.value.hi << 64) | v.value.lo
            if n >= 1 << 127:
                n -= 1 << 128
            if _SMALL_MIN_I <= n <= _SMALL_MAX_I:
                return _make(TAG_I128_SMALL, n)
            return self.new_obj(TAG_I128_OBJ, n)
        if arm == T.SCV_SYMBOL:
            if len(v.value) <= 9:
                try:
                    return sym_to_small(v.value)
                except ValueError:
                    pass
            return self.new_obj(TAG_SYMBOL_OBJ, bytes(v.value))
        if arm == T.SCV_BYTES:
            return self.new_obj(TAG_BYTES_OBJ, bytes(v.value))
        if arm == T.SCV_STRING:
            return self.new_obj(TAG_STRING_OBJ, bytes(v.value))
        if arm == T.SCV_VEC:
            items = [self.from_scval(e) for e in (v.value or ())]
            return self.new_obj(TAG_VEC_OBJ, items)
        if arm == T.SCV_MAP:
            pairs = [(self.from_scval(e.key), self.from_scval(e.val))
                     for e in (v.value or ())]
            return self.new_obj(TAG_MAP_OBJ, pairs)
        if arm == T.SCV_ADDRESS:
            return self.new_obj(TAG_ADDRESS_OBJ, v.value)
        raise EnvError(f"SCVal arm {arm} has no Val form")

    # ---------------- Val -> SCVal ----------------

    def to_scval(self, val: int) -> "SCVal.Value":
        val &= _M64
        tag = _tag(val)
        body = _body(val)
        if tag == TAG_FALSE:
            return SCVal.make(T.SCV_BOOL, False)
        if tag == TAG_TRUE:
            return SCVal.make(T.SCV_BOOL, True)
        if tag == TAG_VOID:
            return SCVal.make(T.SCV_VOID)
        if tag == TAG_U32:
            return SCVal.make(T.SCV_U32, body & 0xFFFFFFFF)
        if tag == TAG_I32:
            b = body & 0xFFFFFFFF
            return SCVal.make(T.SCV_I32,
                              b - (1 << 32) if b >> 31 else b)
        if tag == TAG_U64_SMALL:
            return SCVal.make(T.SCV_U64, body)
        if tag == TAG_I64_SMALL:
            return SCVal.make(
                T.SCV_I64, body - (1 << 56) if body >> 55 else body)
        if tag == TAG_TIMEPOINT_SMALL:
            return SCVal.make(T.SCV_TIMEPOINT, body)
        if tag == TAG_DURATION_SMALL:
            return SCVal.make(T.SCV_DURATION, body)
        if tag == TAG_U128_SMALL:
            return self._u128(body)
        if tag == TAG_I128_SMALL:
            return self._i128(body - (1 << 56) if body >> 55 else body)
        if tag == TAG_SYMBOL_SMALL:
            return SCVal.make(T.SCV_SYMBOL, small_to_sym(val))
        if tag == TAG_U64_OBJ:
            return SCVal.make(T.SCV_U64, self.obj(val, tag))
        if tag == TAG_I64_OBJ:
            return SCVal.make(T.SCV_I64, self.obj(val, tag))
        if tag == TAG_TIMEPOINT_OBJ:
            return SCVal.make(T.SCV_TIMEPOINT, self.obj(val, tag))
        if tag == TAG_DURATION_OBJ:
            return SCVal.make(T.SCV_DURATION, self.obj(val, tag))
        if tag == TAG_U128_OBJ:
            return self._u128(self.obj(val, tag))
        if tag == TAG_I128_OBJ:
            return self._i128(self.obj(val, tag))
        if tag == TAG_BYTES_OBJ:
            return SCVal.make(T.SCV_BYTES, self.obj(val, tag))
        if tag == TAG_STRING_OBJ:
            return SCVal.make(T.SCV_STRING, self.obj(val, tag))
        if tag == TAG_SYMBOL_OBJ:
            return SCVal.make(T.SCV_SYMBOL, self.obj(val, tag))
        if tag == TAG_VEC_OBJ:
            return SCVal.make(T.SCV_VEC, [
                self.to_scval(e) for e in self.obj(val, tag)])
        if tag == TAG_MAP_OBJ:
            return SCVal.make(T.SCV_MAP, [
                SCMapEntry(key=self.to_scval(k), val=self.to_scval(w))
                for k, w in self.obj(val, tag)])
        if tag == TAG_ADDRESS_OBJ:
            return SCVal.make(T.SCV_ADDRESS, self.obj(val, tag))
        raise EnvError(f"bad Val tag {tag}")

    @staticmethod
    def _u128(n: int):
        from stellar_tpu.xdr.contract import UInt128Parts
        return SCVal.make(T.SCV_U128, UInt128Parts(
            hi=(n >> 64) & _M64, lo=n & _M64))

    @staticmethod
    def _i128(n: int):
        from stellar_tpu.xdr.contract import Int128Parts
        u = n & ((1 << 128) - 1)
        hi = (u >> 64) & _M64
        if hi >= 1 << 63:
            hi -= 1 << 64  # Int128Parts.hi is a signed int64
        return SCVal.make(T.SCV_I128, Int128Parts(hi=hi, lo=u & _M64))


# ---------------------------------------------------------------------------
# Host-function imports
# ---------------------------------------------------------------------------

_DUR_BY_CODE = {0: "temporary", 1: "persistent", 2: "instance"}


def make_imports(env) -> Dict[Tuple[str, str], Callable]:
    """The import table for one contract frame. ``env`` is a
    ``WasmContractEnv`` (defined in host.py) carrying the host, the
    running contract's address, and the ValConverter."""
    cv: ValConverter = env.cv

    def _u32_arg(val: int, what: str) -> int:
        if _tag(val) != TAG_U32:
            raise EnvError(f"{what}: expected U32 val")
        return _body(val) & 0xFFFFFFFF

    # ---- context ----

    def log(inst, val):
        env.host.budget.charge(100, 0)
        from stellar_tpu.soroban import host as host_mod
        if host_mod.DIAGNOSTIC_EVENTS_ENABLED:
            env.host.diagnostics.append(cv.to_scval(val))
        return _make(TAG_VOID)

    def ledger_sequence(inst):
        return _make(TAG_U32, env.host.ledger_seq)

    def ledger_timestamp(inst):
        ts = 0
        hdr = getattr(env.host, "ledger_header", None)
        if hdr is not None:
            ts = hdr.scpValue.closeTime
        return _make(TAG_U64_SMALL, ts) if ts <= _SMALL_MAX_U \
            else cv.new_obj(TAG_U64_OBJ, ts)

    def current_contract_address(inst):
        return cv.new_obj(TAG_ADDRESS_OBJ, env.contract_addr)

    def contract_event(inst, topics_val, data_val):
        topics_sc = cv.to_scval(topics_val)
        if topics_sc.arm != T.SCV_VEC:
            raise EnvError("event topics must be a vec")
        env.host.emit_event(env.contract_addr,
                            list(topics_sc.value or ()),
                            cv.to_scval(data_val))
        return _make(TAG_VOID)

    def fail(inst):
        raise EnvError("contract called fail")

    # ---- ledger ----

    def _storage_args(k_val, t_val):
        """(key_scval, durability|None, kb|None) — durability None
        means instance storage; key is converted exactly once."""
        from stellar_tpu.soroban.host import contract_data_key
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.xdr.contract import ContractDataDurability
        code = _u32_arg(t_val, "storage type")
        kind = _DUR_BY_CODE.get(code)
        if kind is None:
            raise EnvError("bad storage type")
        key_sc = cv.to_scval(k_val)
        if kind == "instance":
            return key_sc, None, None
        dur = ContractDataDurability.PERSISTENT \
            if kind == "persistent" else ContractDataDurability.TEMPORARY
        kb = key_bytes(contract_data_key(env.contract_addr, key_sc,
                                         dur))
        return key_sc, dur, kb

    def put_contract_data(inst, k_val, v_val, t_val):
        key_sc, dur, _kb = _storage_args(k_val, t_val)
        if dur is None:
            env.instance_put(key_sc, cv.to_scval(v_val))
        else:
            env.data_put(key_sc, cv.to_scval(v_val), dur)
        return _make(TAG_VOID)

    def get_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        sc = env.instance_get(key_sc) if dur is None \
            else env.data_get(kb)
        if sc is None:
            raise EnvError("missing contract data")
        return cv.from_scval(sc)

    def has_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        sc = env.instance_get(key_sc) if dur is None \
            else env.data_get(kb)
        return _make(TAG_TRUE if sc is not None else TAG_FALSE)

    def del_contract_data(inst, k_val, t_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        if dur is None:
            env.instance_del(key_sc)
        else:
            env.data_del(kb)
        return _make(TAG_VOID)

    def extend_contract_data_ttl(inst, k_val, t_val, thresh_val,
                                 ext_val):
        key_sc, dur, kb = _storage_args(k_val, t_val)
        if dur is None:
            raise EnvError("use the instance TTL host fn for "
                           "instance storage")
        env.host.extend_ttl(kb, _u32_arg(thresh_val, "threshold"),
                            _u32_arg(ext_val, "extend_to"))
        return _make(TAG_VOID)

    def extend_instance_and_code_ttl(inst, thresh_val, ext_val):
        """Extend the current contract's instance entry AND its code
        entry (reference extend_current_contract_instance_and_code_ttl)."""
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.soroban.host import (
            contract_code_key, contract_data_key,
        )
        from stellar_tpu.xdr.contract import (
            ContractDataDurability, ContractExecutableType,
        )
        thresh = _u32_arg(thresh_val, "threshold")
        ext = _u32_arg(ext_val, "extend_to")
        inst_kb = key_bytes(contract_data_key(
            env.contract_addr,
            SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            ContractDataDurability.PERSISTENT))
        env.host.extend_ttl(inst_kb, thresh, ext)
        slot = env.host.storage.entries.get(inst_kb)
        if slot is not None and slot[0] is not None:
            instance = slot[0].data.value.val.value
            if instance.executable.arm == \
                    ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
                code_kb = key_bytes(contract_code_key(
                    instance.executable.value))
                env.host.extend_ttl(code_kb, thresh, ext)
        return _make(TAG_VOID)

    # ---- vec ----
    # Structural ops charge proportionally to the work they do (copy
    # size, entries compared) — a flat per-call fee would let real CPU
    # and memory run unbounded relative to the instruction budget
    # (reference: soroban's per-cost-type calibrated charges).

    def vec_new(inst):
        return cv.new_obj(TAG_VEC_OBJ, [])

    def vec_push_back(inst, vec_val, item):
        items = list(cv.obj(vec_val, TAG_VEC_OBJ))
        env.host.budget.charge(10 + len(items), 8 * (len(items) + 1))
        items.append(item & _M64)
        return cv.new_obj(TAG_VEC_OBJ, items)

    def vec_get(inst, vec_val, i_val):
        items = cv.obj(vec_val, TAG_VEC_OBJ)
        i = _u32_arg(i_val, "vec index")
        if i >= len(items):
            raise EnvError("vec index out of bounds")
        return items[i]

    def vec_len(inst, vec_val):
        return _make(TAG_U32, len(cv.obj(vec_val, TAG_VEC_OBJ)))

    # ---- map (entries kept sorted by canonical SCVal key bytes) ----

    def _map_key_bytes(v: int) -> bytes:
        kb = to_bytes(SCVal, cv.to_scval(v))
        # the encode itself is the dominant cost of every compare
        env.host.budget.charge(30 + 2 * len(kb), 0)
        return kb

    def map_new(inst):
        return cv.new_obj(TAG_MAP_OBJ, [])

    def map_put(inst, map_val, k, v):
        pairs = list(cv.obj(map_val, TAG_MAP_OBJ))
        env.host.budget.charge(10 + len(pairs), 16 * (len(pairs) + 1))
        kb = _map_key_bytes(k)
        for i, (pk, _pv) in enumerate(pairs):
            if _map_key_bytes(pk) == kb:
                pairs[i] = (k & _M64, v & _M64)
                break
        else:
            pairs.append((k & _M64, v & _M64))
            pairs.sort(key=lambda p: _map_key_bytes(p[0]))
        return cv.new_obj(TAG_MAP_OBJ, pairs)

    def map_get(inst, map_val, k):
        kb = _map_key_bytes(k)
        for pk, pv in cv.obj(map_val, TAG_MAP_OBJ):
            if _map_key_bytes(pk) == kb:
                return pv
        raise EnvError("map key not found")

    def map_has(inst, map_val, k):
        kb = _map_key_bytes(k)
        for pk, _pv in cv.obj(map_val, TAG_MAP_OBJ):
            if _map_key_bytes(pk) == kb:
                return _make(TAG_TRUE)
        return _make(TAG_FALSE)

    def map_len(inst, map_val):
        return _make(TAG_U32, len(cv.obj(map_val, TAG_MAP_OBJ)))

    # ---- bytes / string / symbol <-> linear memory ----

    def bytes_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        env.host.budget.charge(50 + 2 * n, n)
        return cv.new_obj(TAG_BYTES_OBJ, inst.mem_read(ptr, n))

    def bytes_copy_to_linear_memory(inst, b_val, off_val, ptr_val,
                                    len_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        off = _u32_arg(off_val, "offset")
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        if off + n > len(data):
            raise EnvError("bytes copy out of range")
        env.host.budget.charge(50 + 2 * n, 0)
        inst.mem_write(ptr, data[off:off + n])
        return _make(TAG_VOID)

    def bytes_len(inst, b_val):
        return _make(TAG_U32, len(cv.obj(b_val, TAG_BYTES_OBJ)))

    def bytes_get(inst, b_val, i_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        i = _u32_arg(i_val, "index")
        if i >= len(data):
            raise EnvError("bytes index out of bounds")
        return _make(TAG_U32, data[i])

    def symbol_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        raw = inst.mem_read(ptr, n)
        env.host.budget.charge(50 + 2 * n, n)
        if n <= 9:
            try:
                return sym_to_small(raw)
            except ValueError:
                pass
        return cv.new_obj(TAG_SYMBOL_OBJ, raw)

    def string_new_from_linear_memory(inst, ptr_val, len_val):
        ptr = _u32_arg(ptr_val, "ptr")
        n = _u32_arg(len_val, "len")
        env.host.budget.charge(50 + 2 * n, n)
        return cv.new_obj(TAG_STRING_OBJ, inst.mem_read(ptr, n))

    # ---- int object conversions (raw wasm i64 <-> Val) ----

    def obj_from_u64(inst, raw):
        raw &= _M64
        if raw <= _SMALL_MAX_U:
            return _make(TAG_U64_SMALL, raw)
        return cv.new_obj(TAG_U64_OBJ, raw)

    def obj_to_u64(inst, val):
        sc = cv.to_scval(val)
        if sc.arm != T.SCV_U64:
            raise EnvError("not a u64")
        return sc.value

    def obj_from_i64(inst, raw):
        raw &= _M64
        signed = raw - (1 << 64) if raw >> 63 else raw
        if _SMALL_MIN_I <= signed <= _SMALL_MAX_I:
            return _make(TAG_I64_SMALL, signed)
        return cv.new_obj(TAG_I64_OBJ, signed)

    def obj_to_i64(inst, val):
        sc = cv.to_scval(val)
        if sc.arm != T.SCV_I64:
            raise EnvError("not an i64")
        return sc.value & _M64

    # ---- address / auth ----

    def require_auth(inst, addr_val):
        addr = cv.obj(addr_val, TAG_ADDRESS_OBJ)
        env.host.require_auth(
            SCVal.make(T.SCV_ADDRESS, addr), env.invocation,
            env.depth)
        return _make(TAG_VOID)

    # ---- cross-contract call ----

    def call(inst, addr_val, fn_val, args_val):
        addr_sc = cv.to_scval(addr_val)
        fn_sc = cv.to_scval(fn_val)
        args_sc = cv.to_scval(args_val)
        if addr_sc.arm != T.SCV_ADDRESS or fn_sc.arm != T.SCV_SYMBOL \
                or args_sc.arm != T.SCV_VEC:
            raise EnvError("call needs (address, symbol, vec)")
        rv = env.host.call_contract(addr_sc.value, fn_sc.value,
                                    list(args_sc.value or ()),
                                    env.depth + 1)
        return cv.from_scval(rv)

    # ---- crypto ----

    def compute_sha256(inst, b_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        env.host.budget.charge(2000 + 30 * len(data), 32)
        return cv.new_obj(TAG_BYTES_OBJ, sha256(data))

    # ---- prng (deterministic per-frame stream; reference "p") ----

    def _frame_prng():
        if env.prng is None:
            env.prng = env.host.fork_prng()
        return env.prng

    def prng_u64_in_inclusive_range(inst, lo_raw, hi_raw):
        env.host.budget.charge(100, 0)
        return _frame_prng().u64_in_range(lo_raw & _M64,
                                          hi_raw & _M64) & _M64

    def prng_bytes_new(inst, len_val):
        n = _u32_arg(len_val, "prng length")
        env.host.budget.charge(100 + 2 * n, n)
        return cv.new_obj(TAG_BYTES_OBJ, _frame_prng().take(n))

    def prng_reseed(inst, b_val):
        data = cv.obj(b_val, TAG_BYTES_OBJ)
        env.host.budget.charge(100 + len(data), 0)
        _frame_prng().reseed(data)
        return _make(TAG_VOID)

    return {
        ("x", "log"): log,
        ("x", "ledger_sequence"): ledger_sequence,
        ("x", "ledger_timestamp"): ledger_timestamp,
        ("x", "current_contract_address"): current_contract_address,
        ("x", "contract_event"): contract_event,
        ("x", "fail"): fail,
        ("l", "put_contract_data"): put_contract_data,
        ("l", "get_contract_data"): get_contract_data,
        ("l", "has_contract_data"): has_contract_data,
        ("l", "del_contract_data"): del_contract_data,
        ("l", "extend_contract_data_ttl"): extend_contract_data_ttl,
        ("l", "extend_instance_and_code_ttl"):
            extend_instance_and_code_ttl,
        ("v", "vec_new"): vec_new,
        ("v", "vec_push_back"): vec_push_back,
        ("v", "vec_get"): vec_get,
        ("v", "vec_len"): vec_len,
        ("m", "map_new"): map_new,
        ("m", "map_put"): map_put,
        ("m", "map_get"): map_get,
        ("m", "map_has"): map_has,
        ("m", "map_len"): map_len,
        ("b", "bytes_new_from_linear_memory"):
            bytes_new_from_linear_memory,
        ("b", "bytes_copy_to_linear_memory"):
            bytes_copy_to_linear_memory,
        ("b", "bytes_len"): bytes_len,
        ("b", "bytes_get"): bytes_get,
        ("b", "symbol_new_from_linear_memory"):
            symbol_new_from_linear_memory,
        ("b", "string_new_from_linear_memory"):
            string_new_from_linear_memory,
        ("i", "obj_from_u64"): obj_from_u64,
        ("i", "obj_to_u64"): obj_to_u64,
        ("i", "obj_from_i64"): obj_from_i64,
        ("i", "obj_to_i64"): obj_to_i64,
        ("a", "require_auth"): require_auth,
        ("c", "call"): call,
        ("d", "compute_sha256"): compute_sha256,
        ("p", "prng_u64_in_inclusive_range"):
            prng_u64_in_inclusive_range,
        ("p", "prng_bytes_new"): prng_bytes_new,
        ("p", "prng_reseed"): prng_reseed,
    }
