"""Canonical wasm example contracts, assembled in-process — the role
the reference's checked-in soroban test fixtures play
(``src/testdata/soroban/*.wasm``): real compiled modules for tests,
golden tx-meta scenarios, and the load generator to exercise the wasm
VM end-to-end (upload -> create -> invoke through the close pipeline).
"""

from __future__ import annotations

from stellar_tpu.soroban.env import (
    TAG_TRUE, TAG_U32, TAG_VOID, sym_to_small,
)
from stellar_tpu.soroban.wasm_builder import Code, I64, ModuleBuilder

__all__ = ["counter_wasm", "ttl_wasm", "custom_account_wasm",
           "KEY_COUNT_VAL"]


def _u32val(v: int) -> int:
    return ((v & 0xFFFFFFFF) << 8) | TAG_U32


KEY_COUNT_VAL = sym_to_small(b"count")
_SYM_INCR = sym_to_small(b"incr")
_T_PERSISTENT = _u32val(1)  # storage-type code: persistent


def counter_wasm(with_burst: bool = False) -> bytes:
    """The counter contract as a real wasm module.

    Exports:
      - ``incr()``       -> new count (U32 val): get/put persistent
        storage + emits an ``incr`` event
      - ``auth_incr(a)`` -> require_auth(a) then incr()
      - ``sha8(x)``      -> first byte of sha256(le64(x)) (U32 val);
        exercises linear memory + bytes objects + crypto
      - ``boom()``       -> traps (unreachable)
      - ``spin()``       -> infinite loop (budget-trap fodder)
    """
    b = ModuleBuilder()
    has_fn = b.import_func("l", "has_contract_data", [I64, I64], [I64])
    get_fn = b.import_func("l", "get_contract_data", [I64, I64], [I64])
    put_fn = b.import_func("l", "put_contract_data",
                           [I64, I64, I64], [I64])
    event_fn = b.import_func("x", "contract_event", [I64, I64], [I64])
    vec_new_fn = b.import_func("v", "vec_new", [], [I64])
    vec_push_fn = b.import_func("v", "vec_push_back",
                                [I64, I64], [I64])
    auth_fn = b.import_func("a", "require_auth", [I64], [I64])
    bytes_new_fn = b.import_func("b", "bytes_new_from_linear_memory",
                                 [I64, I64], [I64])
    bytes_get_fn = b.import_func("b", "bytes_get", [I64, I64], [I64])
    sha_fn = b.import_func("d", "compute_sha256", [I64], [I64])

    b.add_memory(1)

    # incr() -> i64 val; local 0 holds the new counter val
    c = Code()
    c.i64_const(KEY_COUNT_VAL).i64_const(_T_PERSISTENT).call(has_fn)
    c.i64_const(TAG_TRUE).i64_eq()
    c.if_(I64)
    c.i64_const(KEY_COUNT_VAL).i64_const(_T_PERSISTENT).call(get_fn)
    c.else_()
    c.i64_const(_u32val(0))
    c.end()
    # old val -> count -> count+1 -> new val
    c.i64_const(8).i64_shr_u().i64_const(1).i64_add()
    c.i64_const(8).i64_shl().i64_const(TAG_U32).i64_or()
    c.local_set(0)
    # put(key, new, persistent)
    c.i64_const(KEY_COUNT_VAL).local_get(0)
    c.i64_const(_T_PERSISTENT).call(put_fn).drop()
    # contract_event([sym "incr"], new)
    c.call(vec_new_fn).i64_const(_SYM_INCR).call(vec_push_fn)
    c.local_get(0).call(event_fn).drop()
    c.local_get(0).end()
    incr_idx = b.add_func([], [I64], [I64], c, export="incr")

    # auth_incr(addr) -> require_auth then incr
    c = Code()
    c.local_get(0).call(auth_fn).drop()
    c.call(incr_idx).end()
    b.add_func([I64], [I64], [], c, export="auth_incr")

    # sha8(x): mem[0:8] = le64(x); sha256(bytes); first byte as U32 val
    c = Code()
    c.i32_const(0).local_get(0).i64_const(8).i64_shr_u().i64_store()
    c.i64_const(_u32val(0)).i64_const(_u32val(8)).call(bytes_new_fn)
    c.call(sha_fn)
    c.i64_const(_u32val(0)).call(bytes_get_fn)
    c.end()
    b.add_func([I64], [I64], [], c, export="sha8")

    # boom(): trap
    b.add_func([], [I64], [], Code().unreachable().end(),
               export="boom")

    # spin(): infinite loop — must die by budget, not wall clock
    c = Code()
    c.loop(0x40).br(0).end()
    c.i64_const(TAG_VOID).end()
    b.add_func([], [I64], [], c, export="spin")

    if with_burst:
        # auth_incr_burst(addr, k) -> auth_incr + k extra ("burst",
        # countdown) events (the wasm twin of the scval variant;
        # APPLY_LOAD_EVENT_COUNT shaping). Appended AFTER the default
        # exports so the with_burst=False bytes — whose code hash the
        # golden metas pin — are untouched. local2 = remaining count,
        # local3 = incr result
        c = Code()
        c.local_get(0).call(auth_fn).drop()
        c.call(incr_idx).local_set(3)
        c.local_get(1).i64_const(8).i64_shr_u().local_set(2)  # raw k
        c.block(0x40)
        c.local_get(2).i64_eqz().br_if(0)
        c.loop(0x40)
        c.call(vec_new_fn).i64_const(sym_to_small(b"burst"))
        c.call(vec_push_fn)
        # data = current countdown as a U32 val (the scval twin)
        c.local_get(2).i64_const(8).i64_shl()
        c.i64_const(TAG_U32).i64_or()
        c.call(event_fn).drop()
        c.local_get(2).i64_const(1).i64_sub().local_tee(2)
        c.i64_const(0).i64_ne().br_if(0)
        c.end()
        c.end()
        c.local_get(3).end()
        b.add_func([I64, I64], [I64], [I64, I64], c,
                   export="auth_incr_burst")

    return b.build()


def sum_wasm() -> bytes:
    """Compute-bound contract: ``sum(n)`` iterates ``n`` times
    accumulating ``1 + 2 + ... + n`` in raw i64 arithmetic and returns
    it as a U32 val. No host calls inside the loop — this is the
    shape where a native engine's per-instruction cost dominates (the
    benchmark counterpart of the host-call-bound counter).
    ``sum_scval_program()`` is its semantic twin for the interpreter."""
    b = ModuleBuilder()
    b.add_memory(1)
    c = Code()
    # local0 = arg (U32Val n), local1 = i (raw), local2 = acc (raw)
    c.local_get(0).i64_const(8).i64_shr_u().local_set(1)
    c.block(0x40)
    c.local_get(1).i64_eqz().br_if(0)
    c.loop(0x40)
    c.local_get(2).local_get(1).i64_add().local_set(2)
    c.local_get(1).i64_const(1).i64_sub().local_tee(1)
    c.i64_const(0).i64_ne().br_if(0)
    c.end()
    c.end()
    # U32 val: (acc << 8) | 4 — same return arm as the scval twin
    c.local_get(2).i64_const(8).i64_shl().i64_const(4).i64_or()
    c.end()
    b.add_func([I64], [I64], [I64, I64], c, export="sum")
    return b.build()


def sum_scval_program() -> bytes:
    """The SCVal-interpreter twin of :func:`sum_wasm`: ``sum(n)``
    returns ``1 + 2 + ... + n`` as a U32. Loop invariant on the stack
    is ``[acc, i]`` with ``i`` counting down; 9 interpreted
    instructions per iteration."""
    from stellar_tpu.soroban.host import assemble_program, ins, sym, u32
    from stellar_tpu.xdr.contract import SCVal, SCValType
    return assemble_program({
        "sum": [
            ins("push", u32(0)),                     # 0: [acc]
            ins("arg", u32(0)),                      # 1: [acc, i=n]
            ins("dup"),                              # 2: loop top
            ins("jz", u32(7)),                       # 3: i==0 -> 11
            ins("swap"),                             # 4: [i, acc]
            ins("over"),                             # 5: [i, acc, i]
            ins("add"),                              # 6: [i, acc+i]
            ins("swap"),                             # 7: [acc', i]
            ins("push", u32(1)),                     # 8
            ins("sub"),                              # 9: [acc', i-1]
            ins("jmp", SCVal.make(SCValType.SCV_I32, -9)),  # 10 -> 2
            ins("drop"),                             # 11: [acc']
            ins("ret"),                              # 12
        ],
    })


def ttl_wasm() -> bytes:
    """TTL-exercising contract: ``setup()`` writes a persistent entry;
    ``bump(threshold, extend_to)`` extends that entry's TTL from inside
    the contract; ``bump_self(threshold, extend_to)`` extends the
    instance + code TTLs (reference extend_contract_data_ttl /
    extend_current_contract_instance_and_code_ttl host fns)."""
    b = ModuleBuilder()
    put_fn = b.import_func("l", "put_contract_data",
                           [I64, I64, I64], [I64])
    ext_fn = b.import_func("l", "extend_contract_data_ttl",
                           [I64, I64, I64, I64], [I64])
    self_fn = b.import_func("l", "extend_instance_and_code_ttl",
                            [I64, I64], [I64])
    key = KEY_COUNT_VAL  # rides the standard harness footprint

    c = Code()
    c.i64_const(key).i64_const(_u32val(1)).i64_const(_T_PERSISTENT)
    c.call(put_fn).end()
    b.add_func([], [I64], [], c, export="setup")

    c = Code()
    c.i64_const(key).i64_const(_T_PERSISTENT)
    c.local_get(0).local_get(1).call(ext_fn).end()
    b.add_func([I64, I64], [I64], [], c, export="bump")

    c = Code()
    c.local_get(0).local_get(1).call(self_fn).end()
    b.add_func([I64, I64], [I64], [], c, export="bump_self")
    return b.build()


def custom_account_wasm() -> bytes:
    """Minimal CUSTOM ACCOUNT (reference account abstraction): the
    host dispatches ``__check_auth(signature_payload, signatures)``
    for contract-address credentials; this one approves when the
    signature Val equals the symbol ``letmein``."""
    b = ModuleBuilder()
    c = Code()
    c.local_get(1).i64_const(sym_to_small(b"letmein")).i64_eq()
    c.if_(0x40).else_().unreachable().end()
    c.i64_const(TAG_VOID).end()
    b.add_func([I64, I64], [I64], [], c, export="__check_auth")
    return b.build()
