"""Soroban host: deterministic, metered contract execution behind the
same boundary the reference crosses into Rust (``src/rust/src/lib.rs``
``invoke_host_function``, :61-83,182-195 — declared entries + auth in,
modified entries + events + consumption out; the C++ side at
``src/transactions/InvokeHostFunctionOpFrame.cpp:489`` only marshals).

Two execution engines sit behind the boundary:

- **wasm** (the real thing): code beginning with ``\\0asm`` is a wasm
  binary, validated at upload and executed by the metered wasm-MVP
  interpreter in ``soroban/wasm.py`` through the tagged-Val host ABI
  in ``soroban/env.py`` — the same wasmi-shaped stack the reference
  links behind ``invoke_host_function``.
- **legacy SCVal programs**: the XDR of an SCVal map {function symbol
  -> instruction vector} over a small stack machine, kept for the
  auditable golden scenarios that predate the wasm VM.

Either way, everything is metered against the same cpu/mem budget
shape, storage is footprint-enforced, and auth entries verify real
ed25519 signatures over the canonical HashIDPreimage — fee, footprint,
auth-signature, and TTL semantics exercise the full reference surface.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from stellar_tpu.crypto.sha import sha256
from stellar_tpu.xdr.contract import (
    ContractDataDurability, ContractDataEntry, ContractEvent,
    ContractEventType, ContractEventV0, ContractExecutable,
    ContractExecutableType, ContractIDPreimageType, HashIDPreimageContractID,
    HostFunctionType, LedgerKeyContractCode, LedgerKeyContractData,
    SCAddress, SCAddressType, SCContractInstance, SCMapEntry, SCNonceKey,
    SCVal, SCValType, SorobanCredentialsType,
)
from stellar_tpu.xdr.runtime import Packer, from_bytes, to_bytes
from stellar_tpu.xdr.types import (
    EnvelopeType, ExtensionPoint, LedgerEntry, LedgerEntryType, LedgerKey,
    LedgerKeyTtl, TTLEntry, account_ed25519,
)

__all__ = ["HostError", "InvokeOutput", "invoke_host_function",
           "contract_data_key", "contract_code_key", "ttl_key_for",
           "derive_contract_id", "make_instance_val", "assemble_program",
           "ins", "sym", "u32", "i64", "scbytes", "scaddress_contract",
           "scaddress_account", "auth_payload_hash"]

T = SCValType


class HostError(Exception):
    TRAPPED = "trapped"
    BUDGET = "budget"
    ARCHIVED = "archived"
    AUTH = "auth"

    def __init__(self, kind: str, msg: str, error_sc=None):
        super().__init__(msg)
        self.kind = kind
        # SCVal (arm SCV_ERROR) when the failing frame raised a
        # specific contract error (fail_with_error); try_call returns
        # it to the caller
        self.error_sc = error_sc


# ---------------------------------------------------------------------------
# SCVal construction sugar (also used by tests / the loadgen)
# ---------------------------------------------------------------------------

def sym(s: str):
    return SCVal.make(T.SCV_SYMBOL, s.encode())


def u32(v: int):
    return SCVal.make(T.SCV_U32, v)


def i64(v: int):
    return SCVal.make(T.SCV_I64, v)


def scbytes(b: bytes):
    return SCVal.make(T.SCV_BYTES, b)


def scaddress_contract(contract_id: bytes):
    return SCAddress.make(SCAddressType.SC_ADDRESS_TYPE_CONTRACT,
                          contract_id)


def scaddress_account(account_id_v):
    return SCAddress.make(SCAddressType.SC_ADDRESS_TYPE_ACCOUNT,
                          account_id_v)


def ins(op: str, *args):
    """One instruction: vec [op-symbol, args...]."""
    return SCVal.make(T.SCV_VEC, [sym(op)] + list(args))


def assemble_program(functions: Dict[str, List]) -> bytes:
    """{fn name: [instructions]} -> contract code bytes."""
    entries = [SCMapEntry(key=sym(name),
                          val=SCVal.make(T.SCV_VEC, body))
               for name, body in sorted(functions.items())]
    return to_bytes(SCVal, SCVal.make(T.SCV_MAP, entries))


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------

def contract_data_key(contract: "SCAddress.Value", key, durability):
    return LedgerKey.make(
        LedgerEntryType.CONTRACT_DATA,
        LedgerKeyContractData(contract=contract, key=key,
                              durability=durability))


def contract_code_key(code_hash: bytes):
    return LedgerKey.make(LedgerEntryType.CONTRACT_CODE,
                          LedgerKeyContractCode(hash=code_hash))


def ttl_key_for(lk) -> "LedgerKey.Value":
    """TTL entries are keyed by the hash of the data/code key they
    guard (reference ``getTTLKey``)."""
    return LedgerKey.make(
        LedgerEntryType.TTL,
        LedgerKeyTtl(keyHash=sha256(to_bytes(LedgerKey, lk))))


def derive_contract_id(network_id: bytes, preimage) -> bytes:
    """SHA-256 of HashIDPreimage{ENVELOPE_TYPE_CONTRACT_ID, ...}
    (reference ``makeFullContractIdPreimage`` + xdrSha256)."""
    p = Packer()
    p.pack_int(EnvelopeType.ENVELOPE_TYPE_CONTRACT_ID)
    HashIDPreimageContractID.pack(
        p, HashIDPreimageContractID(networkID=network_id,
                                    contractIDPreimage=preimage))
    return sha256(p.bytes())


def auth_payload_hash(network_id: bytes, nonce: int,
                      expiration_ledger: int, invocation) -> bytes:
    """The signed payload of a SorobanAuthorizationEntry (reference
    HashIDPreimage ENVELOPE_TYPE_SOROBAN_AUTHORIZATION)."""
    from stellar_tpu.xdr.contract import (
        HashIDPreimageSorobanAuthorization,
    )
    p = Packer()
    p.pack_int(EnvelopeType.ENVELOPE_TYPE_SOROBAN_AUTHORIZATION)
    HashIDPreimageSorobanAuthorization.pack(
        p, HashIDPreimageSorobanAuthorization(
            networkID=network_id, nonce=nonce,
            signatureExpirationLedger=expiration_ledger,
            invocation=invocation))
    return sha256(p.bytes())


def make_instance_val(code_hash: bytes):
    return SCVal.make(T.SCV_CONTRACT_INSTANCE, SCContractInstance(
        executable=ContractExecutable.make(
            ContractExecutableType.CONTRACT_EXECUTABLE_WASM, code_hash),
        storage=None))


# ---------------------------------------------------------------------------
# Budget + storage
# ---------------------------------------------------------------------------

# interpreter cost model (plays the role of the wasm cost types)
CPU_PER_INSTRUCTION = 500
CPU_PER_STORAGE_OP = 2_000
CPU_PER_BYTE = 2
MEM_PER_STACK_SLOT = 64
# one wasm instruction in budget cpu units (reference soroban cost
# model's WasmInsnExec ~ 4 cpu instructions per wasm instruction)
CPU_PER_WASM_INSN = 4

# record contract log/diagnostic calls into InvokeOutput.diagnostics
# (reference ENABLE_SOROBAN_DIAGNOSTIC_EVENTS; set by Application)
DIAGNOSTIC_EVENTS_ENABLED = False

# execute wasm through the native C++ engine when its build is
# available (identical semantics + charge stream; differential tests
# pin it) — False forces the pure-Python engine
USE_NATIVE_WASM = True


from stellar_tpu.protocol import CURRENT_LEDGER_PROTOCOL_VERSION
from stellar_tpu.soroban import cost_model as _cm

_DEFAULT_COST_PARAMS = None


def _default_cost_params():
    """Current-protocol initial tables, computed once per process (the
    fallback when a budget is built without explicit params)."""
    global _DEFAULT_COST_PARAMS
    if _DEFAULT_COST_PARAMS is None:
        _DEFAULT_COST_PARAMS = (
            _cm.initial_cost_params(CURRENT_LEDGER_PROTOCOL_VERSION,
                                    "cpu"),
            _cm.initial_cost_params(CURRENT_LEDGER_PROTOCOL_VERSION,
                                    "mem"))
    return _DEFAULT_COST_PARAMS


class _Budget:
    def __init__(self, cpu_limit: int, mem_limit: int,
                 cpu_params=None, mem_params=None):
        self.cpu_limit = cpu_limit
        self.mem_limit = mem_limit
        self.cpu = 0
        self.mem = 0
        # calibrated metered cost vectors [(const, linear)] indexed by
        # ContractCostType (soroban/cost_model.py); None = the
        # reference's initial tables for the current protocol
        self.cpu_params = cpu_params
        self.mem_params = mem_params

    def charge(self, cpu: int, mem: int = 0):
        self.cpu += cpu
        self.mem += mem
        if self.cpu > self.cpu_limit or self.mem > self.mem_limit:
            raise HostError(HostError.BUDGET, "budget exceeded")

    def wasm_insn_cost(self) -> int:
        """Per-wasm-instruction cpu price from the active cost table
        (WasmInsnExec const term) — upgradable consensus state, so the
        engines must read it here, never a compile-time constant."""
        if self.cpu_params is None:
            self.cpu_params, self.mem_params = _default_cost_params()
        return self.cpu_params[0][0] if self.cpu_params else \
            CPU_PER_WASM_INSN

    def charge_type(self, type_idx: int, input_size: int = 0,
                    iterations: int = 1):
        """Charge by ContractCostType through the calibrated linear
        model (reference: Budget::charge with a CostType — both the
        cpu-instructions and memory-bytes dimensions at once). Runs on
        the metered hot path: no per-call imports (_cm is bound at
        module load)."""
        if self.cpu_params is None:
            self.cpu_params, self.mem_params = _default_cost_params()
        cpu = _cm.eval_cost(self.cpu_params, type_idx, input_size)
        mem = _cm.eval_cost(self.mem_params, type_idx, input_size)
        if iterations != 1:
            cpu *= iterations
            mem *= iterations
        self.charge(cpu, mem)


class _Storage:
    """Footprint-enforced entry access with read/write accounting."""

    def __init__(self, entries: Dict[bytes, Tuple], read_only: set,
                 read_write: set, budget: _Budget, ledger_seq: int):
        # kb -> [LedgerEntry|None, live_until|None, dirty]
        self.entries = {kb: [e, lu, False]
                        for kb, (e, lu) in entries.items()}
        self.read_only = read_only
        self.read_write = read_write
        self.budget = budget
        self.ledger_seq = ledger_seq
        # declared-resource accounting charges each entry ONCE per axis
        # (reference: footprint entries load once / write once at the
        # end), however often the contract touches it
        self._read_charged: set = set()
        self._write_sizes: Dict[bytes, int] = {}
        # kb -> serialized LedgerEntry size; entries only change via
        # put() (which recomputes), so repeated gets reuse the size
        # instead of re-serializing the whole entry each access
        self._entry_sizes: Dict[bytes, int] = {}
        self.read_bytes = 0
        # kb -> new live_until from in-contract TTL extensions
        # (separate from dirty slots: a TTL-only bump must not rewrite
        # the data entry, mirroring ExtendFootprintTTLOp semantics)
        self.ttl_extensions: Dict[bytes, int] = {}

    @property
    def write_bytes(self) -> int:
        return sum(self._write_sizes.values())

    def _check_live(self, kb: bytes, slot):
        lu = slot[1]
        if slot[0] is not None and lu is not None and lu < self.ledger_seq:
            raise HostError(HostError.ARCHIVED, "entry is archived")

    def get(self, kb: bytes):
        if kb not in self.read_only and kb not in self.read_write:
            raise HostError(HostError.TRAPPED,
                            "read outside declared footprint")
        slot = self.entries.get(kb)
        if slot is None or slot[0] is None:
            return None
        self._check_live(kb, slot)
        size = self._entry_sizes.get(kb)
        if size is None:
            size = len(to_bytes(LedgerEntry, slot[0]))
            self._entry_sizes[kb] = size
        if kb not in self._read_charged:
            self._read_charged.add(kb)
            self.read_bytes += size
        self.budget.charge(CPU_PER_STORAGE_OP + CPU_PER_BYTE * size)
        return slot[0]

    def put(self, kb: bytes, entry: LedgerEntry,
            live_until: Optional[int]):
        if kb not in self.read_write:
            raise HostError(HostError.TRAPPED,
                            "write outside declared footprint")
        size = len(to_bytes(LedgerEntry, entry))
        self._write_sizes[kb] = size  # final size counts, once per key
        self._entry_sizes[kb] = size
        self.budget.charge(CPU_PER_STORAGE_OP + CPU_PER_BYTE * size, size)
        slot = self.entries.setdefault(kb, [None, None, False])
        slot[0] = entry
        if live_until is not None and \
                (slot[1] is None or slot[1] < live_until):
            slot[1] = live_until
        slot[2] = True

    def delete(self, kb: bytes):
        if kb not in self.read_write:
            raise HostError(HostError.TRAPPED,
                            "delete outside declared footprint")
        self.budget.charge(CPU_PER_STORAGE_OP)
        slot = self.entries.setdefault(kb, [None, None, False])
        slot[0] = None
        slot[2] = True
        self._entry_sizes.pop(kb, None)


# ---------------------------------------------------------------------------
# Auth
# ---------------------------------------------------------------------------

def _address_bytes(addr) -> bytes:
    return to_bytes(SCAddress, addr)


class _AuthContext:
    """Verified-but-unconsumed authorizations (reference host's
    require_auth against SorobanAuthorizationEntry trees; one level —
    no sub-invocations until cross-contract calls land).

    CONTRACT-address credentials are CUSTOM ACCOUNTS (reference
    account abstraction): their signatures are not checked here but by
    dispatching ``__check_auth(payload, signatures)`` on the contract
    itself, deferred to the first matching ``require`` (the host must
    exist to run contract code). A rejecting or trapping __check_auth
    fails authorization; reentrant dispatch is refused."""

    def __init__(self, auth_entries, source_account, network_id: bytes,
                 ledger_seq: int, storage: _Storage, verify_sig):
        # addr bytes -> [(fn, check_cell|None)]; a check cell is one
        # auth ENTRY's deferred __check_auth state, shared by every fn
        # the entry's invocation tree authorizes and dispatched only
        # when one of THOSE fns is actually required (unused entries
        # stay unchecked, like the reference)
        self.available: Dict[bytes, list] = {}
        self.source_addr = _address_bytes(
            scaddress_account(source_account))
        self.storage = storage
        self.host = None  # back-ref set by invoke_host_function
        self._checking_addr: Optional[bytes] = None
        for entry in auth_entries:
            cred = entry.credentials
            cell = None
            if cred.arm == \
                    SorobanCredentialsType.SOROBAN_CREDENTIALS_SOURCE_ACCOUNT:
                key = self.source_addr
            else:
                ac = cred.value  # SorobanAddressCredentials
                if ac.signatureExpirationLedger < ledger_seq:
                    raise HostError(HostError.AUTH,
                                    "auth signature expired")
                payload = auth_payload_hash(
                    network_id, ac.nonce, ac.signatureExpirationLedger,
                    entry.rootInvocation)
                key = _address_bytes(ac.address)
                if ac.address.arm == \
                        SCAddressType.SC_ADDRESS_TYPE_CONTRACT:
                    cell = {"ac": ac, "payload": payload,
                            "verified": False}
                else:
                    self._verify_address_signature(ac, payload,
                                                   verify_sig)
                self._consume_nonce(ac, ledger_seq)
            # the whole invocation tree is authorized: flatten root +
            # subInvocations (cross-contract calls consume sub-entries)
            fns: list = []
            self._flatten(entry.rootInvocation, fns)
            self.available.setdefault(key, []).extend(
                (fn, cell) for fn in fns)

    @staticmethod
    def _flatten(inv, out: list):
        out.append(inv.function)
        for sub in inv.subInvocations:
            _AuthContext._flatten(sub, out)

    def _verify_address_signature(self, ac, payload: bytes, verify_sig):
        """Signature SCVal: vec of maps {public_key: bytes, signature:
        bytes} — the account-contract format the reference host checks."""
        if ac.address.arm != SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
            raise HostError(HostError.AUTH,
                            "only account addresses supported")
        want = account_ed25519(ac.address.value)
        sig_val = ac.signature
        if sig_val.arm != T.SCV_VEC or not sig_val.value:
            raise HostError(HostError.AUTH, "malformed auth signature")
        ok = False
        for item in sig_val.value:
            if item.arm != T.SCV_MAP:
                raise HostError(HostError.AUTH, "malformed auth signature")
            kv = {e.key.value: e.val.value for e in item.value}
            pk, sg = kv.get(b"public_key"), kv.get(b"signature")
            if pk is None or sg is None:
                raise HostError(HostError.AUTH, "malformed auth signature")
            from stellar_tpu.crypto.keys import PublicKey
            if not verify_sig(PublicKey(pk), payload, sg):
                raise HostError(HostError.AUTH, "bad auth signature")
            if pk == want:
                ok = True
        if not ok:
            raise HostError(HostError.AUTH,
                            "no signature from the authorizing address")

    def _consume_nonce(self, ac, ledger_seq: int):
        """Replay protection: a TEMPORARY nonce entry must not already
        exist and is created to the signature's expiration (reference
        host ``consume_nonce``). The entry rides the declared
        footprint."""
        nonce_key = contract_data_key(
            ac.address, SCVal.make(T.SCV_LEDGER_KEY_NONCE,
                                   SCNonceKey(nonce=ac.nonce)),
            ContractDataDurability.TEMPORARY)
        from stellar_tpu.ledger.ledger_txn import key_bytes
        kb = key_bytes(nonce_key)
        if self.storage.get(kb) is not None:
            raise HostError(HostError.AUTH, "auth nonce already used")
        entry = ContractDataEntry(
            ext=ExtensionPoint.make(0), contract=ac.address,
            key=SCVal.make(T.SCV_LEDGER_KEY_NONCE,
                           SCNonceKey(nonce=ac.nonce)),
            durability=ContractDataDurability.TEMPORARY,
            val=SCVal.make(T.SCV_VOID))
        self.storage.put(kb, _wrap_entry(
            LedgerEntryType.CONTRACT_DATA, entry, ledger_seq),
            ac.signatureExpirationLedger)

    def require(self, addr_bytes: bytes, invoked_fn, depth: int = 0):
        """Consume one matching authorization or trap (reference
        require_auth semantics); a custom-account entry runs ITS
        __check_auth (once) before its first fn is consumed."""
        from stellar_tpu.xdr.contract import SorobanAuthorizedFunction
        if self._checking_addr == addr_bytes:
            # require_auth for the account whose __check_auth is
            # currently running: refuse reentry (reference rule)
            raise HostError(HostError.AUTH,
                            "reentrant require_auth in __check_auth")
        want = to_bytes(SorobanAuthorizedFunction, invoked_fn)
        entries = self.available.get(addr_bytes, [])
        for i, (fn, cell) in enumerate(entries):
            if to_bytes(SorobanAuthorizedFunction, fn) == want:
                if cell is not None and not cell["verified"]:
                    self._run_check_auth(addr_bytes, cell, depth)
                    cell["verified"] = True
                # the list was not mutated by the dispatch: reentrant
                # requires for this address are refused above, and
                # other addresses touch their own lists only — but
                # re-locate defensively rather than pop a stale index
                try:
                    entries.remove((fn, cell))
                except ValueError:
                    raise HostError(HostError.AUTH,
                                    "authorization consumed reentrantly")
                return
        raise HostError(HostError.AUTH, "missing authorization")

    def _run_check_auth(self, addr_bytes: bytes, cell, depth: int):
        if self.host is None:
            raise HostError(HostError.AUTH,
                            "custom account auth unavailable")
        if self._checking_addr is not None:
            raise HostError(HostError.AUTH, "reentrant __check_auth")
        self._checking_addr = addr_bytes
        try:
            ac = cell["ac"]
            try:
                # depth continues the CURRENT chain: __check_auth does
                # not reset the shared call-depth budget
                self.host.call_contract(
                    ac.address, b"__check_auth",
                    [scbytes(cell["payload"]), ac.signature],
                    depth + 1)
            except HostError as e:
                if e.kind == HostError.BUDGET:
                    raise
                raise HostError(
                    HostError.AUTH,
                    f"__check_auth rejected authorization: {e}")
        finally:
            self._checking_addr = None


def _wrap_entry(t, body, ledger_seq: int) -> LedgerEntry:
    return LedgerEntry(
        lastModifiedLedgerSeq=ledger_seq,
        data=LedgerEntry._types[1].make(t, body),
        ext=LedgerEntry._types[2].make(0))


# ---------------------------------------------------------------------------
# The interpreter
# ---------------------------------------------------------------------------

_DUR = {b"temporary": ContractDataDurability.TEMPORARY,
        b"persistent": ContractDataDurability.PERSISTENT}

_INT_ARMS = {T.SCV_U32: (0, 2**32 - 1), T.SCV_I32: (-2**31, 2**31 - 1),
             T.SCV_U64: (0, 2**64 - 1), T.SCV_I64: (-2**63, 2**63 - 1)}


def _truthy(v) -> bool:
    if v.arm == T.SCV_BOOL:
        return bool(v.value)
    if v.arm == T.SCV_VOID:
        return False
    if v.arm in _INT_ARMS:
        return v.value != 0
    return True


MAX_CALL_DEPTH = 10


class _Interp:
    def __init__(self, host: "_Host", contract_addr, program: Dict,
                 invocation=None, depth: int = 0):
        self.host = host
        self.contract_addr = contract_addr
        self.program = program  # fn name bytes -> list of instructions
        self.invocation = invocation  # SorobanAuthorizedFunction
        self.depth = depth

    def run(self, fn_name: bytes, args: List):
        body = self.program.get(fn_name)
        if body is None:
            raise HostError(HostError.TRAPPED,
                            f"no such function {fn_name!r}")
        stack: List = []
        budget = self.host.budget
        pc = 0
        n = len(body)
        while pc < n:
            budget.charge(CPU_PER_INSTRUCTION, MEM_PER_STACK_SLOT)
            instr = body[pc]
            pc += 1
            if instr.arm != T.SCV_VEC or not instr.value or \
                    instr.value[0].arm != T.SCV_SYMBOL:
                raise HostError(HostError.TRAPPED, "malformed instruction")
            op = instr.value[0].value
            a = instr.value[1:]
            if op == b"push":
                stack.append(a[0])
            elif op == b"arg":
                i = a[0].value
                if i >= len(args):
                    raise HostError(HostError.TRAPPED, "arg out of range")
                stack.append(args[i])
            elif op == b"dup":
                stack.append(stack[-1])
            elif op == b"drop":
                stack.pop()
            elif op == b"swap":
                stack[-1], stack[-2] = stack[-2], stack[-1]
            elif op == b"over":
                # copy the second item to the top: [a, b] -> [a, b, a]
                stack.append(stack[-2])
            elif op in (b"add", b"sub", b"mul", b"div", b"mod"):
                rhs, lhs = stack.pop(), stack.pop()
                stack.append(self._arith(op, lhs, rhs))
            elif op in (b"eq", b"lt", b"gt"):
                rhs, lhs = stack.pop(), stack.pop()
                stack.append(self._compare(op, lhs, rhs))
            elif op == b"not":
                stack.append(SCVal.make(T.SCV_BOOL,
                                        not _truthy(stack.pop())))
            elif op == b"jmp":
                pc += a[0].value
            elif op == b"jz":
                if not _truthy(stack.pop()):
                    pc += a[0].value
            elif op in (b"get", b"put", b"del", b"has"):
                self._storage_op(op, a, stack)
            elif op == b"require_auth":
                addr = stack.pop()
                self.host.require_auth(addr, self.invocation,
                                       self.depth)
            elif op == b"call":
                # cross-contract call: ["call", n_args]; stack holds
                # [addr, fn_symbol, arg1..argN]
                n_args = a[0].value if a else 0
                call_args = [stack.pop() for _ in range(n_args)][::-1]
                fn_sym = stack.pop()
                addr_val = stack.pop()
                if addr_val.arm != T.SCV_ADDRESS or \
                        fn_sym.arm != T.SCV_SYMBOL:
                    raise HostError(HostError.TRAPPED,
                                    "call needs (address, symbol)")
                stack.append(self.host.call_contract(
                    addr_val.value, fn_sym.value, call_args,
                    self.depth + 1))
            elif op == b"event":
                data = stack.pop()
                topic = stack.pop()
                self.host.emit_event(self.contract_addr, [topic], data)
            elif op == b"ret":
                return stack.pop() if stack else SCVal.make(T.SCV_VOID)
            elif op == b"fail":
                raise HostError(HostError.TRAPPED, "explicit trap")
            elif op == b"len":
                v = stack.pop()
                if v.arm not in (T.SCV_VEC, T.SCV_MAP, T.SCV_BYTES):
                    raise HostError(HostError.TRAPPED, "len on non-seq")
                stack.append(u32(len(v.value or ())))
            elif op == b"index":
                i, v = stack.pop(), stack.pop()
                if v.arm != T.SCV_VEC or i.value >= len(v.value or ()):
                    raise HostError(HostError.TRAPPED, "bad index")
                stack.append(v.value[i.value])
            else:
                raise HostError(HostError.TRAPPED,
                                f"unknown op {op!r}")
        return SCVal.make(T.SCV_VOID)

    def _arith(self, op, lhs, rhs):
        if lhs.arm != rhs.arm or lhs.arm not in _INT_ARMS:
            raise HostError(HostError.TRAPPED, "type mismatch")
        lo, hi = _INT_ARMS[lhs.arm]
        x, y = lhs.value, rhs.value
        if op in (b"div", b"mod") and y == 0:
            raise HostError(HostError.TRAPPED, "division by zero")
        r = {b"add": x + y, b"sub": x - y, b"mul": x * y,
             b"div": x // y if (x >= 0) == (y >= 0) else -((-x) // y)
             if y != 0 else 0,
             b"mod": x % y if y != 0 else 0}[op]
        if not (lo <= r <= hi):
            raise HostError(HostError.TRAPPED, "arithmetic overflow")
        return SCVal.make(lhs.arm, r)

    def _compare(self, op, lhs, rhs):
        if lhs.arm != rhs.arm:
            raise HostError(HostError.TRAPPED, "type mismatch")
        if lhs.arm in _INT_ARMS or lhs.arm in (T.SCV_BYTES, T.SCV_SYMBOL,
                                               T.SCV_STRING):
            x, y = lhs.value, rhs.value
        else:
            x, y = to_bytes(SCVal, lhs), to_bytes(SCVal, rhs)
        r = {b"eq": x == y, b"lt": x < y, b"gt": x > y}[op]
        return SCVal.make(T.SCV_BOOL, r)

    def _storage_op(self, op, a, stack):
        from stellar_tpu.ledger.ledger_txn import key_bytes
        if a and a[0].arm == T.SCV_SYMBOL and a[0].value == b"instance":
            return self._instance_storage_op(op, stack)
        dur = _DUR.get(a[0].value if a else b"persistent")
        if dur is None:
            raise HostError(HostError.TRAPPED, "bad durability")
        host = self.host
        if op == b"put":
            val = stack.pop()
            key = stack.pop()
            host.data_put(self.contract_addr, key, val, dur)
        else:
            key = stack.pop()
            kb = key_bytes(
                contract_data_key(self.contract_addr, key, dur))
            if op == b"get":
                v = host.data_get(kb)
                stack.append(v if v is not None
                             else SCVal.make(T.SCV_VOID))
            elif op == b"has":
                stack.append(SCVal.make(T.SCV_BOOL,
                                        host.data_get(kb) is not None))
            else:
                host.data_del(kb)

    def _instance_storage_op(self, op, stack):
        """Instance storage: the SCMap inside the contract's instance
        entry (reference host instance storage — shares the instance's
        lifetime and footprint slot)."""
        host = self.host
        val = stack.pop() if op == b"put" else None
        key = stack.pop()
        if op == b"get":
            v = host.instance_get(self.contract_addr, key)
            stack.append(v if v is not None else SCVal.make(T.SCV_VOID))
        elif op == b"has":
            stack.append(SCVal.make(
                T.SCV_BOOL,
                host.instance_get(self.contract_addr, key) is not None))
        elif op == b"put":
            host.instance_put(self.contract_addr, key, val)
        else:
            host.instance_del(self.contract_addr, key)


# ---------------------------------------------------------------------------
# The host entry point
# ---------------------------------------------------------------------------

@dataclass
class InvokeOutput:
    success: bool
    return_value: Optional[object] = None
    # kb -> (LedgerEntry|None, live_until|None) for dirtied slots
    modified: Dict[bytes, Tuple] = field(default_factory=dict)
    # kb -> new live_until for TTL-only extensions (entry untouched)
    ttl_extensions: Dict[bytes, int] = field(default_factory=dict)
    events: List = field(default_factory=list)
    # contract log/debug output (SCVals), populated only when
    # DIAGNOSTIC_EVENTS_ENABLED (never consensus-visible)
    diagnostics: List = field(default_factory=list)
    cpu_insns: int = 0
    mem_bytes: int = 0
    read_bytes: int = 0
    write_bytes: int = 0
    error: Optional[str] = None  # HostError kind


class _Prng:
    """Deterministic host PRNG (reference soroban ``prng`` module):
    counter-mode SHA-256 over a per-invocation seed. Every node
    derives the identical stream, so contract randomness is
    consensus-safe; each contract frame forks its own stream
    (reference: per-frame PRNGs forked from the base)."""

    __slots__ = ("_seed", "_counter", "_buf")

    def __init__(self, seed: bytes):
        self._seed = seed
        self._counter = 0
        self._buf = b""

    def take(self, n: int) -> bytes:
        while len(self._buf) < n:
            self._buf += sha256(
                self._seed + self._counter.to_bytes(8, "little"))
            self._counter += 1
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def u64(self) -> int:
        return int.from_bytes(self.take(8), "little")

    def u64_in_range(self, lo: int, hi: int) -> int:
        if lo > hi:
            raise HostError(HostError.TRAPPED, "empty prng range")
        span = hi - lo + 1
        if span == 1 << 64:
            return self.u64()
        # rejection sampling: deterministic AND unbiased
        limit = ((1 << 64) // span) * span
        while True:
            v = self.u64()
            if v < limit:
                return lo + (v % span)

    def fork(self, salt: bytes) -> "_Prng":
        return _Prng(sha256(self._seed + salt))

    def reseed(self, seed: bytes):
        self._seed = sha256(seed)
        self._counter = 0
        self._buf = b""


class _Host:
    def __init__(self, storage: _Storage, budget: _Budget, auth,
                 config, ledger_seq: int,
                 prng_seed: Optional[bytes] = None,
                 network_id: bytes = b"\x00" * 32):
        self.storage = storage
        self.budget = budget
        self.auth = auth
        self.config = config
        self.ledger_seq = ledger_seq
        self.network_id = network_id
        self.events: List = []
        self._events_size = 0  # running serialized size (limit check)
        self.diagnostics: List = []
        self.base_prng = _Prng(prng_seed if prng_seed is not None
                               else b"\x00" * 32)
        self._prng_forks = 0
        # active contract frames (SCAddress bytes, bottom -> top):
        # drives the direct-contract-invoker implicit authorization
        self.frame_addrs: List[bytes] = []
        # authorize_as_curr_contract registrations, scoped to the
        # granting frame: authorizer addr bytes ->
        # [(granting frame depth, SorobanAuthorizedFunction bytes)];
        # pruned when the granting frame exits (reference: these
        # entries live only for the current invocation)
        self.contract_auths: Dict[bytes, List[Tuple[int, bytes]]] = {}

    def snapshot(self):
        """Frame snapshot for try_call rollback: storage slots +
        accounting, events, and auth consumption state. The budget is
        deliberately NOT captured — metering consumed by a failed
        callee stays consumed (reference try_call semantics)."""
        st = self.storage
        return (
            {kb: list(slot) for kb, slot in st.entries.items()},
            dict(st.ttl_extensions),
            len(self.events), len(self.diagnostics),
            # deep-copy the per-entry __check_auth cells: a rolled-back
            # frame must not leave cell["verified"]=True behind while
            # the storage effects that verification depended on are
            # undone
            {k: [(fn, dict(c) if c is not None else None)
                 for fn, c in v]
             for k, v in self.auth.available.items()}
            if self.auth is not None else None,
            {k: list(v) for k, v in self.contract_auths.items()},
            set(st._read_charged), dict(st._write_sizes),
            st.read_bytes, self._events_size, dict(st._entry_sizes),
        )

    def restore(self, snap):
        st = self.storage
        (st.entries, st.ttl_extensions, n_ev, n_diag, avail,
         cauths, st._read_charged, st._write_sizes,
         st.read_bytes, self._events_size, st._entry_sizes) = snap
        del self.events[n_ev:]
        del self.diagnostics[n_diag:]
        if avail is not None:
            self.auth.available = avail
        self.contract_auths = cauths

    def fork_prng(self) -> _Prng:
        """A fresh per-frame PRNG stream (deterministic fork order)."""
        self._prng_forks += 1
        return self.base_prng.fork(
            self._prng_forks.to_bytes(8, "little"))

    def require_auth(self, addr, invocation, depth: int = 0):
        if addr.arm != T.SCV_ADDRESS:
            raise HostError(HostError.TRAPPED,
                            "require_auth on non-address")
        ab = _address_bytes(addr.value)
        # the DIRECT caller contract is implicitly authorized for the
        # frame it invoked (reference contract-invoker rule); deeper
        # sub-invocations need authorize_as_curr_contract entries
        if len(self.frame_addrs) >= 2 and ab == self.frame_addrs[-2]:
            return
        regs = self.contract_auths.get(ab)
        if regs and invocation is not None:
            from stellar_tpu.xdr.contract import (
                SorobanAuthorizedFunction,
            )
            want = to_bytes(SorobanAuthorizedFunction, invocation)
            for i, (_d, fb) in enumerate(regs):
                if fb == want:
                    regs.pop(i)
                    return
        self.auth.require(ab, invocation, depth)

    def prune_contract_auths(self):
        """Drop authorize_as_curr_contract grants whose granting frame
        has exited (called on every frame pop)."""
        live = len(self.frame_addrs)
        for ab in list(self.contract_auths):
            kept = [(d, fb) for d, fb in self.contract_auths[ab]
                    if d <= live]
            if kept:
                self.contract_auths[ab] = kept
            else:
                del self.contract_auths[ab]

    def call_contract(self, addr, fn_name: bytes, args: List,
                      depth: int):
        """Cross-contract invocation sharing budget/storage/auth."""
        if depth > MAX_CALL_DEPTH:
            raise HostError(HostError.TRAPPED, "call depth exceeded")
        from stellar_tpu.xdr.contract import InvokeContractArgs
        return _run_contract(
            self, InvokeContractArgs(contractAddress=addr,
                                     functionName=fn_name,
                                     args=list(args)), depth)

    def emit_event(self, contract_addr, topics, data):
        ev = ContractEvent(
            ext=ExtensionPoint.make(0),
            contractID=contract_addr.value,
            type=ContractEventType.CONTRACT,
            body=ContractEvent._types[3].make(
                0, ContractEventV0(topics=topics, data=data)))
        size = len(to_bytes(ContractEvent, ev))
        # running total, NOT a re-serialization of every prior event
        # (that would be quadratic in the event count)
        if self._events_size + size > \
                self.config.tx_max_contract_events_size_bytes:
            raise HostError(HostError.BUDGET, "events size limit")
        self.budget.charge(CPU_PER_INSTRUCTION + CPU_PER_BYTE * size, size)
        self._events_size += size
        self.events.append(ev)

    # ---- contract-data storage (shared by both execution engines) ----

    def data_put(self, contract_addr, key, val, dur):
        from stellar_tpu.ledger.ledger_txn import key_bytes
        entry = ContractDataEntry(
            ext=ExtensionPoint.make(0), contract=contract_addr,
            key=key, durability=dur, val=val)
        kb = key_bytes(contract_data_key(contract_addr, key, dur))
        is_new = self.storage.entries.get(kb, [None])[0] is None
        live_until = None
        if is_new:
            ttl = self.config.min_persistent_ttl \
                if dur == ContractDataDurability.PERSISTENT \
                else self.config.min_temporary_ttl
            live_until = self.ledger_seq + ttl - 1
        self.storage.put(kb, _wrap_entry(
            LedgerEntryType.CONTRACT_DATA, entry, self.ledger_seq),
            live_until)

    def data_get(self, kb: bytes):
        """Stored SCVal for a data key, or None."""
        e = self.storage.get(kb)
        return None if e is None else e.data.value.val

    def data_del(self, kb: bytes):
        self.storage.delete(kb)

    def _instance_entry(self, contract_addr):
        from stellar_tpu.ledger.ledger_txn import key_bytes
        kb = key_bytes(contract_data_key(
            contract_addr, SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            ContractDataDurability.PERSISTENT))
        entry = self.storage.get(kb)
        if entry is None:
            raise HostError(HostError.TRAPPED, "missing instance entry")
        return kb, entry.data.value.val.value  # SCContractInstance

    def instance_get(self, contract_addr, key):
        _kb, inst = self._instance_entry(contract_addr)
        key_b = to_bytes(SCVal, key)
        for e in (inst.storage or ()):
            if to_bytes(SCVal, e.key) == key_b:
                return e.val
        return None

    def instance_put(self, contract_addr, key, val):
        self._instance_update(contract_addr, key, val, delete=False)

    def instance_del(self, contract_addr, key):
        self._instance_update(contract_addr, key, None, delete=True)

    def extend_ttl(self, kb: bytes, threshold: int, extend_to: int):
        """In-contract TTL extension (reference host
        ``extend_contract_data_ttl``): when the entry's remaining
        lifetime sits below ``threshold`` ledgers, raise live_until to
        now + extend_to (capped by max_entry_ttl). Declared-footprint
        keys only; read-only keys allowed (like ExtendFootprintTTLOp)."""
        st = self.storage
        if kb not in st.read_only and kb not in st.read_write:
            raise HostError(HostError.TRAPPED,
                            "TTL extension outside declared footprint")
        if threshold > extend_to:
            raise HostError(HostError.TRAPPED,
                            "TTL threshold above extend_to")
        if extend_to > self.config.max_entry_ttl - 1:
            raise HostError(HostError.TRAPPED, "extend_to above max TTL")
        slot = st.entries.get(kb)
        if slot is None or slot[0] is None:
            raise HostError(HostError.TRAPPED,
                            "missing entry for TTL extension")
        st._check_live(kb, slot)
        self.budget.charge(CPU_PER_STORAGE_OP)
        cur_live = st.ttl_extensions.get(kb, slot[1])
        if cur_live is None:
            return  # entry carries no TTL (nothing to extend)
        if cur_live - self.ledger_seq < threshold:
            new_live = self.ledger_seq + extend_to
            if new_live > cur_live:
                st.ttl_extensions[kb] = new_live

    def _instance_update(self, contract_addr, key, val, delete: bool):
        kb, inst = self._instance_entry(contract_addr)
        storage = list(inst.storage or ())
        key_b = to_bytes(SCVal, key)
        idx = next((i for i, e in enumerate(storage)
                    if to_bytes(SCVal, e.key) == key_b), None)
        if delete:
            if idx is None:
                return
            del storage[idx]
        elif idx is not None:
            storage[idx] = SCMapEntry(key=key, val=val)
        else:
            storage.append(SCMapEntry(key=key, val=val))
            storage.sort(key=lambda e: to_bytes(SCVal, e.key))
        new_inst = ContractDataEntry(
            ext=ExtensionPoint.make(0), contract=contract_addr,
            key=SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE),
            durability=ContractDataDurability.PERSISTENT,
            val=SCVal.make(T.SCV_CONTRACT_INSTANCE, SCContractInstance(
                executable=inst.executable, storage=storage or None)))
        self.storage.put(kb, _wrap_entry(
            LedgerEntryType.CONTRACT_DATA, new_inst, self.ledger_seq),
            None)


def invoke_host_function(host_fn, footprint_entries: Dict[bytes, Tuple],
                         read_only: set, read_write: set, auth_entries,
                         source_account, network_id: bytes,
                         ledger_seq: int, config,
                         cpu_limit: Optional[int] = None,
                         ledger_header=None,
                         tx_hash: Optional[bytes] = None) -> InvokeOutput:
    """Execute one HostFunction against declared state (the lib.rs
    boundary). ``footprint_entries``: kb -> (LedgerEntry|None,
    live_until|None) for every declared key that exists."""
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.ledger.network_config import effective_cost_params
    proto = ledger_header.ledgerVersion if ledger_header is not None \
        else CURRENT_LEDGER_PROTOCOL_VERSION
    budget = _Budget(cpu_limit if cpu_limit is not None
                     else config.tx_max_instructions,
                     config.tx_memory_limit,
                     cpu_params=effective_cost_params(config, proto,
                                                      "cpu"),
                     mem_params=effective_cost_params(config, proto,
                                                      "mem"))
    storage = _Storage(footprint_entries, read_only, read_write, budget,
                       ledger_seq)
    out = InvokeOutput(success=False)
    host = None
    try:
        auth = _AuthContext(auth_entries, source_account, network_id,
                            ledger_seq, storage, _verify_sig)
        # PRNG seed: every node derives the same stream for this
        # invocation (reference: per-tx sub-seed) — the TX HASH makes
        # it unique per transaction, so a copycat invocation in the
        # same ledger cannot predict another tx's stream
        from stellar_tpu.xdr.contract import HostFunction as _HF
        prng_seed = sha256(network_id +
                           ledger_seq.to_bytes(8, "little") +
                           (tx_hash if tx_hash is not None
                            else to_bytes(_HF, host_fn)))
        host = _Host(storage, budget, auth, config, ledger_seq,
                     prng_seed=prng_seed, network_id=network_id)
        auth.host = host  # custom-account __check_auth dispatch
        host.ledger_header = ledger_header  # classic reserve math (SAC)
        t = host_fn.arm
        if t == HostFunctionType.HOST_FUNCTION_TYPE_UPLOAD_CONTRACT_WASM:
            rv = _upload(host, host_fn.value, read_write)
        elif t in (HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT,
                   HostFunctionType.HOST_FUNCTION_TYPE_CREATE_CONTRACT_V2):
            rv = _create(host, host_fn.value, network_id)
        elif t == HostFunctionType.HOST_FUNCTION_TYPE_INVOKE_CONTRACT:
            rv = _invoke(host, host_fn.value)
        else:
            raise HostError(HostError.TRAPPED, "unknown host function")
        out.success = True
        out.return_value = rv
        out.events = host.events
        out.diagnostics = host.diagnostics
    except HostError as e:
        out.error = e.kind
        # diagnostics accumulated up to the failure still surface —
        # debugging trapping contracts is their main use
        if host is not None:
            out.diagnostics = host.diagnostics
    out.cpu_insns = budget.cpu
    out.mem_bytes = budget.mem
    out.read_bytes = storage.read_bytes
    out.write_bytes = storage.write_bytes
    if out.success:
        out.modified = {kb: (slot[0],
                             max(slot[1] or 0,
                                 storage.ttl_extensions.get(kb, 0))
                             or None)
                        for kb, slot in storage.entries.items()
                        if slot[2]}
        out.ttl_extensions = {
            kb: lu for kb, lu in storage.ttl_extensions.items()
            if kb not in out.modified}
    return out


def _verify_sig(pk, payload, sig) -> bool:
    from stellar_tpu.crypto.keys import verify_sig
    return verify_sig(pk, payload, sig)


from stellar_tpu.utils.cache import RandomEvictionCache as _REC

_PROGRAM_CACHE: "_REC" = _REC(128)


def _parse_program(code: bytes) -> Dict[bytes, List]:
    """Decoded SCVal program for ``code``, memoized by content hash —
    the interpreter-side analogue of the parsed-wasm module cache."""
    h = sha256(code)
    cached = _PROGRAM_CACHE.maybe_get(h)
    if cached is not None:
        return cached
    try:
        val = from_bytes(SCVal, code)
    except Exception:
        raise HostError(HostError.TRAPPED, "unparsable contract code")
    if val.arm != T.SCV_MAP or val.value is None:
        raise HostError(HostError.TRAPPED, "contract code not a map")
    prog = {}
    for e in val.value:
        if e.key.arm != T.SCV_SYMBOL or e.val.arm != T.SCV_VEC:
            raise HostError(HostError.TRAPPED, "bad function entry")
        prog[e.key.value] = list(e.val.value or ())
    _PROGRAM_CACHE.put(h, prog)
    return prog


_MODULE_CACHE: "_REC" = _REC(128)


def _parsed_module(code: bytes):
    """Validated WasmModule for ``code``, memoized by content hash
    (the reference host caches parsed+validated wasmi modules per code
    entry the same way)."""
    return _parsed_module_tracked(code)[0]


def _parsed_module_tracked(code: bytes):
    """(module, cache_hit) — the hit flag drives instantiation
    metering (parse costs are charged only on first touch)."""
    from stellar_tpu.soroban.wasm import parse_module
    h = sha256(code)
    mod = _MODULE_CACHE.maybe_get(h)
    if mod is not None:
        return mod, True
    mod = parse_module(code)
    _MODULE_CACHE.put(h, mod)
    return mod, False


def _module_section_counts(module):
    """Per-section sizes in the order of the ParseWasm*/InstantiateWasm*
    cost types (instructions, functions, globals, table entries, types,
    data segments, elem segments, imports, exports, data bytes)."""
    cached = getattr(module, "_section_counts", None)
    if cached is None:
        cached = module._section_counts = (
            sum(len(f.ops) for f in module.funcs),
            len(module.funcs),
            len(module.globals),
            module.table_min,
            len(module.types),
            len(module.data),
            len(module.elements),
            len(module.imports),
            len(module.exports),
            sum(len(d) for _off, d in module.data),
        )
    return cached


_PARSE_COST_TYPES = (
    _cm.CostType.ParseWasmInstructions, _cm.CostType.ParseWasmFunctions,
    _cm.CostType.ParseWasmGlobals, _cm.CostType.ParseWasmTableEntries,
    _cm.CostType.ParseWasmTypes, _cm.CostType.ParseWasmDataSegments,
    _cm.CostType.ParseWasmElemSegments, _cm.CostType.ParseWasmImports,
    _cm.CostType.ParseWasmExports,
    _cm.CostType.ParseWasmDataSegmentBytes,
)
_INSTANTIATE_COST_TYPES = (
    _cm.CostType.InstantiateWasmInstructions,
    _cm.CostType.InstantiateWasmFunctions,
    _cm.CostType.InstantiateWasmGlobals,
    _cm.CostType.InstantiateWasmTableEntries,
    _cm.CostType.InstantiateWasmTypes,
    _cm.CostType.InstantiateWasmDataSegments,
    _cm.CostType.InstantiateWasmElemSegments,
    _cm.CostType.InstantiateWasmImports,
    _cm.CostType.InstantiateWasmExports,
    _cm.CostType.InstantiateWasmDataSegmentBytes,
)


def _charge_vm_instantiation(budget, module, code_len: int,
                             protocol: int) -> None:
    """Era-correct VM setup metering: p20 charges VmInstantiation over
    the code length; p21+ splits it — ParseWasm* plus InstantiateWasm*
    by section, EVERY invocation (reference updateCpuCostParamsEntryForV21
    rationale, NetworkConfig.cpp:355+; the p21/p22 host re-parses per
    invocation). Deliberately independent of the process-local module
    cache: metering is consensus, and a cache-dependent charge would
    differ between a warm node and a freshly restarted one."""
    if protocol < 21:
        budget.charge_type(_cm.CostType.VmInstantiation, code_len)
        return
    counts = _module_section_counts(module)
    for ct, n in zip(_PARSE_COST_TYPES, counts):
        budget.charge_type(ct, n)
    for ct, n in zip(_INSTANTIATE_COST_TYPES, counts):
        budget.charge_type(ct, n)


class WasmContractEnv:
    """Per-contract-frame bridge between the wasm host imports
    (``soroban/env.py``) and the shared ``_Host`` services. Envs (and
    their import tables, ~140 closures) are POOLED per thread and
    reset per frame — the Val object table is cleared on acquire, so
    handles still never leak across contract boundaries.

    Everything the import-table closures capture must stay
    identity-stable across a reset: the env itself, its ValConverter,
    and the ``charge`` indirection below (the budget it forwards to is
    re-pointed on acquire)."""

    def __init__(self, host: "_Host", contract_addr, invocation,
                 depth: int):
        from stellar_tpu.soroban.env import ValConverter
        self.host = host
        self.contract_addr = contract_addr
        self.invocation = invocation
        self.depth = depth
        self.cv = ValConverter(self.charge)
        self.prng = None  # per-frame stream, forked on first use

    def charge(self, cpu: int, mem: int = 0):
        # stable bound method: closures capture THIS, the budget
        # behind it follows the host of the current frame
        self.host.budget.charge(cpu, mem)

    def charge_type(self, type_idx: int, input_size: int = 0,
                    iterations: int = 1):
        # metered cost-model charge (ContractCostType + calibrated
        # params) — same identity-stability contract as ``charge``
        self.host.budget.charge_type(type_idx, input_size, iterations)

    def reset(self, host: "_Host", contract_addr, invocation,
              depth: int):
        self.host = host
        self.contract_addr = contract_addr
        self.invocation = invocation
        self.depth = depth
        self.cv.objs.clear()
        self.prng = None

    # storage bridges
    def data_put(self, key_sc, val_sc, dur):
        self.host.data_put(self.contract_addr, key_sc, val_sc, dur)

    def data_get(self, kb):
        return self.host.data_get(kb)

    def data_del(self, kb):
        self.host.data_del(kb)

    def instance_get(self, key_sc):
        return self.host.instance_get(self.contract_addr, key_sc)

    def instance_put(self, key_sc, val_sc):
        self.host.instance_put(self.contract_addr, key_sc, val_sc)

    def instance_del(self, key_sc):
        self.host.instance_del(self.contract_addr, key_sc)


import threading as _threading

_env_pool = _threading.local()


def _acquire_wasm_env(host: "_Host", contract_addr, invocation,
                      depth: int):
    """(env, modern import table) from the per-thread pool — building
    the table is ~100us of closure construction, pure overhead when
    paid per frame. Nested frames pop deeper entries; release returns
    them."""
    free = getattr(_env_pool, "free", None)
    if free is None:
        free = _env_pool.free = []
    if free:
        env, imports = free.pop()
        env.reset(host, contract_addr, invocation, depth)
        return env, imports
    from stellar_tpu.soroban.env import make_imports
    env = WasmContractEnv(host, contract_addr, invocation, depth)
    return env, make_imports(env)


def _release_wasm_env(env, imports):
    # drop every reference to the finished frame — a pooled idle env
    # must not pin the invoke's host, auth tree, or PRNG state alive
    env.cv.objs.clear()
    env.host = None
    env.contract_addr = None
    env.invocation = None
    env.prng = None
    _env_pool.free.append((env, imports))


def _run_wasm_contract(host: "_Host", contract_addr, code: bytes,
                       fn_name: bytes, args: List, invocation,
                       depth: int):
    """Execute one exported function of a wasm contract (the wasmi
    dispatch inside the reference's soroban-env-host)."""
    from stellar_tpu.soroban.env import make_imports
    from stellar_tpu.soroban.wasm import Trap, WasmError, WasmInstance
    try:
        module = _parsed_module(code)
    except WasmError as e:
        raise HostError(HostError.TRAPPED, f"invalid wasm: {e}")
    budget = host.budget
    hdr = getattr(host, "ledger_header", None)
    proto = hdr.ledgerVersion if hdr is not None else \
        CURRENT_LEDGER_PROTOCOL_VERSION
    _charge_vm_instantiation(budget, module, len(code), proto)

    # per-instruction tick price comes from the UPGRADABLE cost table
    # (WasmInsnExec const term), not the compile-time default
    cpu_per_insn = budget.wasm_insn_cost()

    def charge(n_insns: int):
        budget.charge(n_insns * cpu_per_insn)

    def mem_charge(n_bytes: int):
        budget.charge(0, n_bytes)

    pooled = None
    try:
        try:
            fn = fn_name.decode("utf-8")
        except UnicodeDecodeError:
            raise HostError(HostError.TRAPPED, "bad function name")
        from stellar_tpu.soroban.legacy_abi import (
            from_rawval, is_legacy_module, make_legacy_imports, to_rawval,
        )
        if is_legacy_module(module):
            # pre-1.0 fixture dialect: 4-bit-tag RawVals + the tiny
            # early import surface; same engines, different codec
            env = WasmContractEnv(host, contract_addr, invocation,
                                  depth)
            imports = make_legacy_imports(env)
            vals = [to_rawval(a) for a in args]
            decode = from_rawval
        else:
            env, imports = _acquire_wasm_env(host, contract_addr,
                                             invocation, depth)
            pooled = (env, imports)
            vals = [env.cv.from_scval(a) for a in args]
            decode = env.cv.to_scval
        if USE_NATIVE_WASM:
            from stellar_tpu.soroban import native_wasm
            if native_wasm.available():
                rv = native_wasm.run_export(
                    module, imports, budget, cpu_per_insn, fn,
                    vals, cache_imports=pooled is not None)
                return decode(rv) if rv is not None \
                    else SCVal.make(T.SCV_VOID)
        inst = WasmInstance(module, imports, charge, mem_charge)
        if not inst.exports_function(fn):
            raise HostError(HostError.TRAPPED,
                            f"no exported function {fn!r}")
        rv = inst.invoke(fn, vals)
        return decode(rv) if rv is not None \
            else SCVal.make(T.SCV_VOID)
    except WasmError as e:
        raise HostError(HostError.TRAPPED, f"invalid wasm: {e}")
    except Trap as e:
        raise HostError(HostError.TRAPPED, str(e),
                        error_sc=getattr(e, "error_sc", None))
    except HostError:
        raise
    except Exception as e:
        # defense in depth: the VM's inputs are attacker-shaped; any
        # unexpected failure must trap THIS transaction, never escape
        # and abort the ledger close (the reference host catches Rust
        # panics at the FFI boundary the same way)
        raise HostError(HostError.TRAPPED,
                        f"host internal error: {type(e).__name__}: {e}")
    finally:
        if pooled is not None:
            _release_wasm_env(*pooled)


def _upload(host: "_Host", code: bytes, read_write: set):
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.xdr.contract import ContractCodeEntry
    if len(code) > host.config.max_contract_size:
        raise HostError(HostError.BUDGET, "contract too large")
    if code[:4] == b"\x00asm":
        # full decode + validation at upload, exactly like the
        # reference host rejecting malformed modules before they can
        # be created (charging by code size)
        host.budget.charge(CPU_PER_BYTE * 40 * len(code), len(code))
        from stellar_tpu.soroban.wasm import WasmError
        try:
            _parsed_module(code)
        except WasmError as e:
            raise HostError(HostError.TRAPPED, f"invalid wasm: {e}")
    else:
        _parse_program(code)  # legacy SCVal program must at least parse
    h = sha256(code)
    lk = contract_code_key(h)
    kb = key_bytes(lk)
    entry = ContractCodeEntry(
        ext=ContractCodeEntry._types[0].make(0), hash=h, code=code)
    host.storage.put(kb, _wrap_entry(LedgerEntryType.CONTRACT_CODE,
                                     entry, host.ledger_seq),
                     host.ledger_seq + host.config.min_persistent_ttl - 1)
    return scbytes(h)


def _create(host: "_Host", args, network_id: bytes):
    from stellar_tpu.ledger.ledger_txn import key_bytes
    contract_id = derive_contract_id(network_id, args.contractIDPreimage)
    addr = scaddress_contract(contract_id)
    storage = None
    if args.executable.arm == \
            ContractExecutableType.CONTRACT_EXECUTABLE_WASM:
        if args.contractIDPreimage.arm == \
                ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET:
            raise HostError(HostError.TRAPPED,
                            "asset preimage needs the asset executable")
        code_kb = key_bytes(contract_code_key(args.executable.value))
        if host.storage.get(code_kb) is None:
            raise HostError(HostError.TRAPPED,
                            "executable code not uploaded")
    else:
        # Stellar Asset Contract: deployable only from an asset
        # preimage; the wrapped asset rides in instance storage
        if args.contractIDPreimage.arm != \
                ContractIDPreimageType.CONTRACT_ID_PREIMAGE_FROM_ASSET:
            raise HostError(HostError.TRAPPED,
                            "asset executable needs an asset preimage")
        from stellar_tpu.soroban.asset_contract import (
            asset_instance_storage,
        )
        storage = asset_instance_storage(args.contractIDPreimage.value)
    key = SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE)
    lk = contract_data_key(addr, key, ContractDataDurability.PERSISTENT)
    kb = key_bytes(lk)
    if host.storage.get(kb) is not None:
        raise HostError(HostError.TRAPPED, "contract already exists")
    inst = ContractDataEntry(
        ext=ExtensionPoint.make(0), contract=addr, key=key,
        durability=ContractDataDurability.PERSISTENT,
        val=SCVal.make(T.SCV_CONTRACT_INSTANCE, SCContractInstance(
            executable=args.executable, storage=storage)))
    host.storage.put(kb, _wrap_entry(LedgerEntryType.CONTRACT_DATA,
                                     inst, host.ledger_seq),
                     host.ledger_seq + host.config.min_persistent_ttl - 1)
    return SCVal.make(T.SCV_ADDRESS, addr)


def _run_contract(host: "_Host", args, depth: int = 0):
    host.frame_addrs.append(_address_bytes(args.contractAddress))
    try:
        return _run_contract_inner(host, args, depth)
    finally:
        host.frame_addrs.pop()
        host.prune_contract_auths()


def _run_contract_inner(host: "_Host", args, depth: int = 0):
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.xdr.contract import (
        SorobanAuthorizedFunction, SorobanAuthorizedFunctionType,
    )
    addr = args.contractAddress
    key = SCVal.make(T.SCV_LEDGER_KEY_CONTRACT_INSTANCE)
    lk = contract_data_key(addr, key, ContractDataDurability.PERSISTENT)
    inst_entry = host.storage.get(key_bytes(lk))
    if inst_entry is None:
        raise HostError(HostError.TRAPPED, "contract does not exist")
    inst = inst_entry.data.value.val.value  # SCContractInstance
    if inst.executable.arm == \
            ContractExecutableType.CONTRACT_EXECUTABLE_STELLAR_ASSET:
        from stellar_tpu.soroban.asset_contract import asset_contract_call
        from stellar_tpu.xdr.contract import (
            SorobanAuthorizedFunction, SorobanAuthorizedFunctionType,
        )
        invocation = SorobanAuthorizedFunction.make(
            SorobanAuthorizedFunctionType
            .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN, args)
        return asset_contract_call(host, addr, inst, args.functionName,
                                   list(args.args), invocation,
                                   depth=depth)
    code_entry = host.storage.get(
        key_bytes(contract_code_key(inst.executable.value)))
    if code_entry is None:
        raise HostError(HostError.TRAPPED, "missing contract code")
    code = code_entry.data.value.code
    invocation = SorobanAuthorizedFunction.make(
        SorobanAuthorizedFunctionType
        .SOROBAN_AUTHORIZED_FUNCTION_TYPE_CONTRACT_FN, args)
    if code[:4] == b"\x00asm":
        return _run_wasm_contract(host, addr, code, args.functionName,
                                  list(args.args), invocation, depth)
    prog = _parse_program(code)
    interp = _Interp(host, addr, prog, invocation=invocation,
                     depth=depth)
    return interp.run(args.functionName, list(args.args))


def _invoke(host: "_Host", args):
    return _run_contract(host, args, depth=0)
