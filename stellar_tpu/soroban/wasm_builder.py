"""In-process wasm assembler: build real wasm-MVP binaries from Python
(no wat toolchain ships in this environment). Used by tests, the load
generator, and docs examples to produce genuinely compiled contract
modules for the wasm VM (``soroban/wasm.py``) — the same role the
reference's checked-in ``.wasm`` fixtures play for soroban-env-host
(``src/testdata/soroban/*.wasm``).

Minimal by design: emit exactly the integer-MVP subset the VM executes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

__all__ = ["Code", "ModuleBuilder", "I32", "I64", "leb_u", "leb_s"]

I32, I64 = 0x7F, 0x7E


def leb_u(v: int) -> bytes:
    if v < 0:
        raise ValueError("unsigned LEB of negative")
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def leb_s(v: int) -> bytes:
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        done = (v == 0 and not b & 0x40) or (v == -1 and b & 0x40)
        out.append(b if done else b | 0x80)
        if done:
            return bytes(out)


class Code:
    """Instruction emitter for one function body. Tracks block depth so
    ``ModuleBuilder.add_func`` knows whether the body already carries
    its terminating ``end`` (byte inspection can't tell: a trailing
    LEB byte 0x0B, e.g. ``i64_const(11)``, looks identical)."""

    def __init__(self):
        self.b = bytearray()
        self._depth = 0
        self._ended = False

    def raw(self, *bs: int) -> "Code":
        self.b.extend(bs)
        return self

    # control
    def unreachable(self):
        return self.raw(0x00)

    def nop(self):
        return self.raw(0x01)

    def block(self, bt: int = 0x40):
        self._depth += 1
        return self.raw(0x02, bt)

    def loop(self, bt: int = 0x40):
        self._depth += 1
        return self.raw(0x03, bt)

    def if_(self, bt: int = 0x40):
        self._depth += 1
        return self.raw(0x04, bt)

    def else_(self):
        return self.raw(0x05)

    def end(self):
        if self._depth:
            self._depth -= 1
        else:
            self._ended = True
        return self.raw(0x0B)

    def br(self, depth: int):
        self.b.append(0x0C)
        self.b.extend(leb_u(depth))
        return self

    def br_if(self, depth: int):
        self.b.append(0x0D)
        self.b.extend(leb_u(depth))
        return self

    def br_table(self, depths: Sequence[int], default: int):
        self.b.append(0x0E)
        self.b.extend(leb_u(len(depths)))
        for d in depths:
            self.b.extend(leb_u(d))
        self.b.extend(leb_u(default))
        return self

    def return_(self):
        return self.raw(0x0F)

    def call(self, func_idx: int):
        self.b.append(0x10)
        self.b.extend(leb_u(func_idx))
        return self

    def call_indirect(self, type_idx: int):
        self.b.append(0x11)
        self.b.extend(leb_u(type_idx))
        self.b.append(0x00)
        return self

    # parametric / variable
    def drop(self):
        return self.raw(0x1A)

    def select(self):
        return self.raw(0x1B)

    def local_get(self, i: int):
        self.b.append(0x20)
        self.b.extend(leb_u(i))
        return self

    def local_set(self, i: int):
        self.b.append(0x21)
        self.b.extend(leb_u(i))
        return self

    def local_tee(self, i: int):
        self.b.append(0x22)
        self.b.extend(leb_u(i))
        return self

    def global_get(self, i: int):
        self.b.append(0x23)
        self.b.extend(leb_u(i))
        return self

    def global_set(self, i: int):
        self.b.append(0x24)
        self.b.extend(leb_u(i))
        return self

    # memory
    def _mem(self, op: int, align: int, offset: int):
        self.b.append(op)
        self.b.extend(leb_u(align))
        self.b.extend(leb_u(offset))
        return self

    def i32_load(self, offset: int = 0, align: int = 2):
        return self._mem(0x28, align, offset)

    def i64_load(self, offset: int = 0, align: int = 3):
        return self._mem(0x29, align, offset)

    def i32_load8_u(self, offset: int = 0):
        return self._mem(0x2D, 0, offset)

    def i64_load8_u(self, offset: int = 0):
        return self._mem(0x31, 0, offset)

    def i32_store(self, offset: int = 0, align: int = 2):
        return self._mem(0x36, align, offset)

    def i64_store(self, offset: int = 0, align: int = 3):
        return self._mem(0x37, align, offset)

    def i32_store8(self, offset: int = 0):
        return self._mem(0x3A, 0, offset)

    def memory_size(self):
        return self.raw(0x3F, 0x00)

    def memory_grow(self):
        return self.raw(0x40, 0x00)

    def memory_copy(self):
        """Bulk memory: [dst, src, n] -> [] (0xFC 10)."""
        return self.raw(0xFC, 0x0A, 0x00, 0x00)

    def memory_fill(self):
        """Bulk memory: [dst, val, n] -> [] (0xFC 11)."""
        return self.raw(0xFC, 0x0B, 0x00)

    # consts
    def i32_const(self, v: int):
        self.b.append(0x41)
        self.b.extend(leb_s(v if v < 1 << 31 else v - (1 << 32)))
        return self

    def i64_const(self, v: int):
        self.b.append(0x42)
        self.b.extend(leb_s(v if v < 1 << 63 else v - (1 << 64)))
        return self

    def __getattr__(self, name: str):
        """Opcode-by-name fallback: ``c.i64_add()``, ``c.i32_eqz()``,
        ``c.i64_shr_u()`` etc. map straight to their opcodes."""
        op = _BY_NAME.get(name)
        if op is None:
            raise AttributeError(name)

        def emit():
            self.b.append(op)
            return self
        return emit


_BY_NAME = {
    "i32_eqz": 0x45, "i32_eq": 0x46, "i32_ne": 0x47, "i32_lt_s": 0x48,
    "i32_lt_u": 0x49, "i32_gt_s": 0x4A, "i32_gt_u": 0x4B,
    "i32_le_s": 0x4C, "i32_le_u": 0x4D, "i32_ge_s": 0x4E,
    "i32_ge_u": 0x4F,
    "i64_eqz": 0x50, "i64_eq": 0x51, "i64_ne": 0x52, "i64_lt_s": 0x53,
    "i64_lt_u": 0x54, "i64_gt_s": 0x55, "i64_gt_u": 0x56,
    "i64_le_s": 0x57, "i64_le_u": 0x58, "i64_ge_s": 0x59,
    "i64_ge_u": 0x5A,
    "i32_clz": 0x67, "i32_ctz": 0x68, "i32_popcnt": 0x69,
    "i32_add": 0x6A, "i32_sub": 0x6B, "i32_mul": 0x6C,
    "i32_div_s": 0x6D, "i32_div_u": 0x6E, "i32_rem_s": 0x6F,
    "i32_rem_u": 0x70, "i32_and": 0x71, "i32_or": 0x72,
    "i32_xor": 0x73, "i32_shl": 0x74, "i32_shr_s": 0x75,
    "i32_shr_u": 0x76, "i32_rotl": 0x77, "i32_rotr": 0x78,
    "i64_clz": 0x79, "i64_ctz": 0x7A, "i64_popcnt": 0x7B,
    "i64_add": 0x7C, "i64_sub": 0x7D, "i64_mul": 0x7E,
    "i64_div_s": 0x7F, "i64_div_u": 0x80, "i64_rem_s": 0x81,
    "i64_rem_u": 0x82, "i64_and": 0x83, "i64_or": 0x84,
    "i64_xor": 0x85, "i64_shl": 0x86, "i64_shr_s": 0x87,
    "i64_shr_u": 0x88, "i64_rotl": 0x89, "i64_rotr": 0x8A,
    "i32_wrap_i64": 0xA7, "i64_extend_i32_s": 0xAC,
    "i64_extend_i32_u": 0xAD,
    "i32_extend8_s": 0xC0, "i32_extend16_s": 0xC1,
    "i64_extend8_s": 0xC2, "i64_extend16_s": 0xC3,
    "i64_extend32_s": 0xC4,
}


class ModuleBuilder:
    def __init__(self):
        self._types: List[Tuple[Tuple[int, ...], Tuple[int, ...]]] = []
        self._imports: List[Tuple[str, str, int]] = []
        self._funcs: List[Tuple[int, List[int], bytes]] = []
        self._mem: Optional[Tuple[int, Optional[int]]] = None
        self._globals: List[Tuple[int, bool, int]] = []
        self._exports: List[Tuple[str, int, int]] = []
        self._table_min = 0
        self._elems: List[Tuple[int, List[int]]] = []
        self._data: List[Tuple[int, bytes]] = []
        self._start: Optional[int] = None

    # -------- declarations --------

    def type_idx(self, params: Sequence[int],
                 results: Sequence[int]) -> int:
        key = (tuple(params), tuple(results))
        if key in self._types:
            return self._types.index(key)
        self._types.append(key)
        return len(self._types) - 1

    def import_func(self, mod: str, name: str, params: Sequence[int],
                    results: Sequence[int]) -> int:
        if self._funcs:
            raise ValueError("declare imports before functions")
        self._imports.append((mod, name, self.type_idx(params, results)))
        return len(self._imports) - 1

    def add_func(self, params: Sequence[int], results: Sequence[int],
                 locals_: Sequence[int], code: Code,
                 export: Optional[str] = None) -> int:
        ti = self.type_idx(params, results)
        body = bytes(code.b)
        if not code._ended:
            body += b"\x0B"
        self._funcs.append((ti, list(locals_), body))
        idx = len(self._imports) + len(self._funcs) - 1
        if export is not None:
            self._exports.append((export, 0, idx))
        return idx

    def add_memory(self, min_pages: int, max_pages: Optional[int] = None,
                   export: Optional[str] = None):
        self._mem = (min_pages, max_pages)
        if export is not None:
            self._exports.append((export, 2, 0))
        return self

    def add_global(self, valtype: int, mutable: bool, init: int) -> int:
        self._globals.append((valtype, mutable, init))
        return len(self._globals) - 1

    def add_table(self, min_size: int):
        self._table_min = min_size
        return self

    def add_elem(self, offset: int, func_idxs: Sequence[int]):
        self._elems.append((offset, list(func_idxs)))
        return self

    def add_data(self, offset: int, data: bytes):
        self._data.append((offset, data))
        return self

    def set_start(self, func_idx: int):
        self._start = func_idx
        return self

    def export(self, name: str, kind: int, idx: int):
        self._exports.append((name, kind, idx))
        return self

    # -------- emission --------

    @staticmethod
    def _section(sec_id: int, payload: bytes) -> bytes:
        return bytes([sec_id]) + leb_u(len(payload)) + payload

    @staticmethod
    def _vec(items: List[bytes]) -> bytes:
        return leb_u(len(items)) + b"".join(items)

    @staticmethod
    def _name(s: str) -> bytes:
        raw = s.encode()
        return leb_u(len(raw)) + raw

    def build(self) -> bytes:
        out = bytearray(b"\x00asm\x01\x00\x00\x00")
        if self._types:
            out += self._section(1, self._vec([
                b"\x60" + leb_u(len(p)) + bytes(p) +
                leb_u(len(r)) + bytes(r)
                for p, r in self._types]))
        if self._imports:
            out += self._section(2, self._vec([
                self._name(m) + self._name(n) + b"\x00" + leb_u(ti)
                for m, n, ti in self._imports]))
        if self._funcs:
            out += self._section(3, self._vec(
                [leb_u(ti) for ti, _, _ in self._funcs]))
        if self._table_min:
            out += self._section(4, self._vec(
                [b"\x70\x00" + leb_u(self._table_min)]))
        if self._mem is not None:
            mn, mx = self._mem
            lim = (b"\x01" + leb_u(mn) + leb_u(mx)
                   if mx is not None else b"\x00" + leb_u(mn))
            out += self._section(5, self._vec([lim]))
        if self._globals:
            out += self._section(6, self._vec([
                bytes([vt, 1 if mut else 0]) +
                (b"\x41" + leb_s(init) if vt == I32
                 else b"\x42" + leb_s(init)) + b"\x0B"
                for vt, mut, init in self._globals]))
        if self._exports:
            out += self._section(7, self._vec([
                self._name(n) + bytes([k]) + leb_u(i)
                for n, k, i in self._exports]))
        if self._start is not None:
            out += self._section(8, leb_u(self._start))
        if self._elems:
            out += self._section(9, self._vec([
                b"\x00\x41" + leb_s(off) + b"\x0B" +
                self._vec([leb_u(fi) for fi in idxs])
                for off, idxs in self._elems]))
        if self._funcs:
            bodies = []
            for _, locals_, body in self._funcs:
                groups = []
                i = 0
                while i < len(locals_):
                    j = i
                    while j < len(locals_) and locals_[j] == locals_[i]:
                        j += 1
                    groups.append(leb_u(j - i) + bytes([locals_[i]]))
                    i = j
                inner = self._vec(groups) + body
                bodies.append(leb_u(len(inner)) + inner)
            out += self._section(10, self._vec(bodies))
        if self._data:
            out += self._section(11, self._vec([
                b"\x00\x41" + leb_s(off) + b"\x0B" +
                leb_u(len(d)) + d
                for off, d in self._data]))
        return bytes(out)
