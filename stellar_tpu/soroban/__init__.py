from stellar_tpu.soroban.host import (  # noqa: F401
    HostError, InvokeOutput, invoke_host_function,
)
