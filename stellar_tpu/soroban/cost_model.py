"""The soroban metered cost model: ContractCostType + calibrated params.

The reference prices every host operation with a per-type linear model
``cpu_or_mem = const_term + linear_term * input / 128`` whose
calibrated parameters are CONSENSUS STATE, stored in two
CONFIG_SETTING ledger entries (cpu instructions / memory bytes) and
created or re-tuned at each protocol upgrade. The tables below
transcribe the reference's own initial values
(``src/ledger/NetworkConfig.cpp:240-330`` for the v20 cpu table,
``:360-440`` v21, ``:445-550`` v22; ``:607-840`` the memory tables) —
these are network constants, exactly like the ledger close cadence.

Type indices are the XDR ``ContractCostType`` enum order: the v20
table covers 0..22 (..ChaCha20DrawBytes), v21 appends 23..44
(wasm parse/instantiate split, secp256r1), v22 appends 45..69 (the
BLS12-381 family). Index order is cross-checked against the
reference's committed pubnet settings files
(``soroban-settings/pubnet_phase*.json``) by ``tests/test_cost_model``.

The linear term is fixed-point with a 1/128 scale (the soroban-env
``ScaledU64`` convention); ``eval_cost`` keeps the divisor in one
place should that convention ever need revisiting.
"""

from __future__ import annotations

from typing import List, Tuple

__all__ = ["COST_TYPES", "cost_type_index", "initial_cost_params",
           "upgrade_cost_params", "eval_cost", "CostType",
           "COST_LINEAR_SCALE_BITS", "n_cost_types_for_protocol"]

COST_LINEAR_SCALE_BITS = 7  # linear_term is in 1/128 units

# name -> (index, min protocol era); order IS the XDR enum order
_P20, _P21, _P22 = 20, 21, 22

COST_TYPES: List[Tuple[str, int]] = [
    ("WasmInsnExec", _P20),               # 0
    ("MemAlloc", _P20),
    ("MemCpy", _P20),
    ("MemCmp", _P20),
    ("DispatchHostFunction", _P20),
    ("VisitObject", _P20),                # 5
    ("ValSer", _P20),
    ("ValDeser", _P20),
    ("ComputeSha256Hash", _P20),
    ("ComputeEd25519PubKey", _P20),
    ("VerifyEd25519Sig", _P20),           # 10
    ("VmInstantiation", _P20),
    ("VmCachedInstantiation", _P20),
    ("InvokeVmFunction", _P20),
    ("ComputeKeccak256Hash", _P20),
    ("DecodeEcdsaCurve256Sig", _P20),     # 15
    ("RecoverEcdsaSecp256k1Key", _P20),
    ("Int256AddSub", _P20),
    ("Int256Mul", _P20),
    ("Int256Div", _P20),
    ("Int256Pow", _P20),                  # 20
    ("Int256Shift", _P20),
    ("ChaCha20DrawBytes", _P20),
    ("ParseWasmInstructions", _P21),      # 23
    ("ParseWasmFunctions", _P21),
    ("ParseWasmGlobals", _P21),
    ("ParseWasmTableEntries", _P21),
    ("ParseWasmTypes", _P21),
    ("ParseWasmDataSegments", _P21),
    ("ParseWasmElemSegments", _P21),
    ("ParseWasmImports", _P21),           # 30
    ("ParseWasmExports", _P21),
    ("ParseWasmDataSegmentBytes", _P21),
    ("InstantiateWasmInstructions", _P21),
    ("InstantiateWasmFunctions", _P21),
    ("InstantiateWasmGlobals", _P21),     # 35
    ("InstantiateWasmTableEntries", _P21),
    ("InstantiateWasmTypes", _P21),
    ("InstantiateWasmDataSegments", _P21),
    ("InstantiateWasmElemSegments", _P21),
    ("InstantiateWasmImports", _P21),     # 40
    ("InstantiateWasmExports", _P21),
    ("InstantiateWasmDataSegmentBytes", _P21),
    ("Sec1DecodePointUncompressed", _P21),
    ("VerifyEcdsaSecp256r1Sig", _P21),    # 44
    ("Bls12381EncodeFp", _P22),           # 45
    ("Bls12381DecodeFp", _P22),
    ("Bls12381G1CheckPointOnCurve", _P22),
    ("Bls12381G1CheckPointInSubgroup", _P22),
    ("Bls12381G2CheckPointOnCurve", _P22),
    ("Bls12381G2CheckPointInSubgroup", _P22),  # 50
    ("Bls12381G1ProjectiveToAffine", _P22),
    ("Bls12381G2ProjectiveToAffine", _P22),
    ("Bls12381G1Add", _P22),
    ("Bls12381G1Mul", _P22),
    ("Bls12381G1Msm", _P22),              # 55
    ("Bls12381MapFpToG1", _P22),
    ("Bls12381HashToG1", _P22),
    ("Bls12381G2Add", _P22),
    ("Bls12381G2Mul", _P22),
    ("Bls12381G2Msm", _P22),              # 60
    ("Bls12381MapFp2ToG2", _P22),
    ("Bls12381HashToG2", _P22),
    ("Bls12381Pairing", _P22),
    ("Bls12381FrFromU256", _P22),
    ("Bls12381FrToU256", _P22),           # 65
    ("Bls12381FrAddSub", _P22),
    ("Bls12381FrMul", _P22),
    ("Bls12381FrPow", _P22),
    ("Bls12381FrInv", _P22),              # 69
]

_INDEX = {name: i for i, (name, _era) in enumerate(COST_TYPES)}


class CostType:
    """Symbolic index constants (CostType.VerifyEd25519Sig == 10)."""


for _name, _i in _INDEX.items():
    setattr(CostType, _name, _i)


def cost_type_index(name: str) -> int:
    return _INDEX[name]


def n_cost_types_for_protocol(protocol: int) -> int:
    """Table length at a protocol era (reference resizes the vectors
    at each upgrade: 23 at p20, 45 at p21, 70 at p22+)."""
    return sum(1 for _n, era in COST_TYPES if era <= protocol)


# (const_term, linear_term) by index; v21/v22 dicts OVERLAY the earlier
# era's table (v21 re-tunes VmCachedInstantiation, adds 23..44; v22
# adds 45..69) — reference updateCpuCostParamsEntryForV21/V22.
_CPU_V20 = [
    (4, 0), (434, 16), (42, 16), (44, 16), (310, 0), (61, 0),
    (230, 29), (59052, 4001), (3738, 7012), (40253, 0), (377524, 4068),
    (451626, 45405), (451626, 45405), (1948, 0), (3766, 5969),
    (710, 0), (2315295, 0), (4404, 0), (4947, 0), (4911, 0), (4286, 0),
    (913, 0), (1058, 501),
]
_CPU_V21 = {
    "VmCachedInstantiation": (41142, 634),
    "ParseWasmInstructions": (73077, 25410),
    "ParseWasmFunctions": (0, 540752),
    "ParseWasmGlobals": (0, 176363),
    "ParseWasmTableEntries": (0, 29989),
    "ParseWasmTypes": (0, 1061449),
    "ParseWasmDataSegments": (0, 237336),
    "ParseWasmElemSegments": (0, 328476),
    "ParseWasmImports": (0, 701845),
    "ParseWasmExports": (0, 429383),
    "ParseWasmDataSegmentBytes": (0, 28),
    "InstantiateWasmInstructions": (43030, 0),
    "InstantiateWasmFunctions": (0, 7556),
    "InstantiateWasmGlobals": (0, 10711),
    "InstantiateWasmTableEntries": (0, 3300),
    "InstantiateWasmTypes": (0, 0),
    "InstantiateWasmDataSegments": (0, 23038),
    "InstantiateWasmElemSegments": (0, 42488),
    "InstantiateWasmImports": (0, 828974),
    "InstantiateWasmExports": (0, 297100),
    "InstantiateWasmDataSegmentBytes": (0, 14),
    "Sec1DecodePointUncompressed": (1882, 0),
    "VerifyEcdsaSecp256r1Sig": (3000906, 0),
}
_CPU_V22 = {
    "Bls12381EncodeFp": (661, 0),
    "Bls12381DecodeFp": (985, 0),
    "Bls12381G1CheckPointOnCurve": (1934, 0),
    "Bls12381G1CheckPointInSubgroup": (730510, 0),
    "Bls12381G2CheckPointOnCurve": (5921, 0),
    "Bls12381G2CheckPointInSubgroup": (1057822, 0),
    "Bls12381G1ProjectiveToAffine": (92642, 0),
    "Bls12381G2ProjectiveToAffine": (100742, 0),
    "Bls12381G1Add": (7689, 0),
    "Bls12381G1Mul": (2458985, 0),
    "Bls12381G1Msm": (2426722, 96397671),
    "Bls12381MapFpToG1": (1541554, 0),
    "Bls12381HashToG1": (3211191, 6713),
    "Bls12381G2Add": (25207, 0),
    "Bls12381G2Mul": (7873219, 0),
    "Bls12381G2Msm": (8035968, 309667335),
    "Bls12381MapFp2ToG2": (2420202, 0),
    "Bls12381HashToG2": (7050564, 6797),
    "Bls12381Pairing": (10558948, 632860943),
    "Bls12381FrFromU256": (1994, 0),
    "Bls12381FrToU256": (1155, 0),
    "Bls12381FrAddSub": (74, 0),
    "Bls12381FrMul": (332, 0),
    "Bls12381FrPow": (691, 74558),
    "Bls12381FrInv": (35421, 0),
}

_MEM_V20 = [
    (0, 0), (16, 128), (0, 0), (0, 0), (0, 0), (0, 0),
    (242, 384), (0, 384), (0, 0), (0, 0), (0, 0),
    (130065, 5064), (130065, 5064), (14, 0), (0, 0),
    (0, 0), (181, 0), (99, 0), (99, 0), (99, 0), (99, 0),
    (99, 0), (0, 0),
]
_MEM_V21 = {
    "VmCachedInstantiation": (69472, 1217),
    "ParseWasmInstructions": (17564, 6457),
    "ParseWasmFunctions": (0, 47464),
    "ParseWasmGlobals": (0, 13420),
    "ParseWasmTableEntries": (0, 6285),
    "ParseWasmTypes": (0, 64670),
    "ParseWasmDataSegments": (0, 29074),
    "ParseWasmElemSegments": (0, 48095),
    "ParseWasmImports": (0, 103229),
    "ParseWasmExports": (0, 36394),
    "ParseWasmDataSegmentBytes": (0, 257),
    "InstantiateWasmInstructions": (70704, 0),
    "InstantiateWasmFunctions": (0, 14613),
    "InstantiateWasmGlobals": (0, 6833),
    "InstantiateWasmTableEntries": (0, 1025),
    "InstantiateWasmTypes": (0, 0),
    "InstantiateWasmDataSegments": (0, 129632),
    "InstantiateWasmElemSegments": (0, 13665),
    "InstantiateWasmImports": (0, 97637),
    "InstantiateWasmExports": (0, 9176),
    "InstantiateWasmDataSegmentBytes": (0, 126),
    "Sec1DecodePointUncompressed": (0, 0),
    "VerifyEcdsaSecp256r1Sig": (0, 0),
}
_MEM_V22 = {
    "Bls12381EncodeFp": (0, 0),
    "Bls12381DecodeFp": (0, 0),
    "Bls12381G1CheckPointOnCurve": (0, 0),
    "Bls12381G1CheckPointInSubgroup": (0, 0),
    "Bls12381G2CheckPointOnCurve": (0, 0),
    "Bls12381G2CheckPointInSubgroup": (0, 0),
    "Bls12381G1ProjectiveToAffine": (0, 0),
    "Bls12381G2ProjectiveToAffine": (0, 0),
    "Bls12381G1Add": (0, 0),
    "Bls12381G1Mul": (0, 0),
    "Bls12381G1Msm": (109494, 354667),
    "Bls12381MapFpToG1": (5552, 0),
    "Bls12381HashToG1": (9424, 0),
    "Bls12381G2Add": (0, 0),
    "Bls12381G2Mul": (0, 0),
    "Bls12381G2Msm": (219654, 354667),
    "Bls12381MapFp2ToG2": (3344, 0),
    "Bls12381HashToG2": (6816, 0),
    "Bls12381Pairing": (2204, 9340474),
    "Bls12381FrFromU256": (0, 0),
    "Bls12381FrToU256": (248, 0),
    "Bls12381FrAddSub": (0, 0),
    "Bls12381FrMul": (0, 0),
    "Bls12381FrPow": (0, 128),
    "Bls12381FrInv": (0, 0),
}


def _apply_era_overlay(params, era: int, dimension: str):
    """Extend to the era's vector length and overlay its new/retuned
    entries (shared by initial tables and era-crossing upgrades)."""
    overlay = {21: (_CPU_V21, _MEM_V21), 22: (_CPU_V22, _MEM_V22)}[era]
    table = overlay[0] if dimension == "cpu" else overlay[1]
    length = {21: 45, 22: 70}[era]
    if len(params) < length:
        params.extend([(0, 0)] * (length - len(params)))
    for name, cl in table.items():
        params[_INDEX[name]] = cl
    return params


def initial_cost_params(protocol: int, dimension: str
                        ) -> List[Tuple[int, int]]:
    """The reference's initial (const, linear) vector for a protocol
    era — what the upgrade path installs into the CONFIG_SETTING
    entries when crossing into soroban/p21/p22."""
    params = list(_CPU_V20 if dimension == "cpu" else _MEM_V20)
    for era in (21, 22):
        if protocol >= era:
            _apply_era_overlay(params, era, dimension)
    return params


def upgrade_cost_params(params, from_protocol: int, to_protocol: int,
                        dimension: str):
    """Carry an existing cost vector across a protocol-era crossing the
    way the reference's updateCpuCostParamsEntryForV21/V22 do: extend
    and overlay only the eras BETWEEN from and to (keyed on the actual
    previous protocol, never inferred from vector length) — values an
    operator upgrade already tuned within earlier eras are preserved."""
    out = list(params)
    for era in (21, 22):
        if from_protocol < era <= to_protocol:
            _apply_era_overlay(out, era, dimension)
    return out


def eval_cost(params: List[Tuple[int, int]], type_idx: int,
              input_size: int = 0) -> int:
    """const + linear * input / 128 (saturating at table bounds: an
    out-of-era type costs nothing, matching a shorter vector)."""
    if type_idx >= len(params):
        return 0
    const, linear = params[type_idx]
    if linear and input_size:
        return const + ((linear * input_size) >> COST_LINEAR_SCALE_BITS)
    return const
