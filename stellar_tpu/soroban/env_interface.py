"""The soroban env interface registry: module chars, function order,
and the derived single-char export names real SDK-compiled contracts
import (reference boundary: the ``soroban-env-host`` crates linked at
``src/rust/src/lib.rs:61-83`` — their interface definition file is not
vendored in the reference snapshot, so this table reconstructs the
published interface).

Export-name scheme (verified against the reference's own compiled
fixtures, see ``legacy_abi.py``): every host module exports under a
single-character module name, and each function's export name is its
index within the module encoded over the alphabet
``_ 0-9 a-z A-Z`` — index 0 is ``"_"``, index 1 is ``"0"``, index 11
is ``"a"``, and so on.

Evidence tiers for the orderings below:

- **fixture-verified**: ``("l","_")`` = ``put_contract_data`` and
  ``("l","2")`` = ``del_contract_data`` are imported by
  ``/root/reference/src/testdata/example_contract_data.wasm`` with the
  CRUD arity, pinning the ledger module's first four entries.
- **derived**: the remaining orderings follow the published
  soroban-env interface (module groupings and declaration order as of
  protocol 20-22). They live in this one table precisely so a
  mis-derived index is a one-line fix.

``make_imports`` (env.py) registers every handler under BOTH its
``(module_char, export_char)`` name — what real contracts import —
and ``(module_char, long_name)`` for the readable dialect this repo's
own ``wasm_builder`` contracts use.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

__all__ = ["EXPORT_CHARS", "MODULES", "export_name", "short_to_long",
           "long_to_short", "evidence_tier", "describe_binding",
           "FIXTURE_VERIFIED", "MIN_PROTOCOL", "SOROBAN_LAUNCH_PROTOCOL"]

# Minimum ledger protocol at which each host function exists, mirroring
# the reference's one-host-crate-per-protocol-era scheme
# (src/rust/Cargo.toml:51-80: p21/p22 hosts are pinned so historical
# replay is bit-exact). Functions absent here exist from the soroban
# launch protocol (20). CAP-51 (secp256r1) shipped in protocol 21;
# CAP-59 (BLS12-381 family) in protocol 22.
SOROBAN_LAUNCH_PROTOCOL = 20


def _current_protocol() -> int:
    from stellar_tpu.protocol import CURRENT_LEDGER_PROTOCOL_VERSION
    return CURRENT_LEDGER_PROTOCOL_VERSION


MIN_PROTOCOL: Dict[str, int] = {
    # the reference's vnext-gated test hook: enabled only at the
    # current protocol (tracks the version constant, not an era)
    "protocol_gated_dummy": _current_protocol(),
    "verify_sig_ecdsa_secp256r1": 21,
    "bls12_381_check_g1_is_in_subgroup": 22,
    "bls12_381_g1_add": 22,
    "bls12_381_g1_mul": 22,
    "bls12_381_g1_msm": 22,
    "bls12_381_map_fp_to_g1": 22,
    "bls12_381_hash_to_g1": 22,
    "bls12_381_check_g2_is_in_subgroup": 22,
    "bls12_381_g2_add": 22,
    "bls12_381_g2_mul": 22,
    "bls12_381_g2_msm": 22,
    "bls12_381_map_fp2_to_g2": 22,
    "bls12_381_hash_to_g2": 22,
    "bls12_381_multi_pairing_check": 22,
    "bls12_381_fr_add": 22,
    "bls12_381_fr_sub": 22,
    "bls12_381_fr_mul": 22,
    "bls12_381_fr_pow": 22,
    "bls12_381_fr_inv": 22,
}

# (module char, long name) orderings pinned by offline artifacts — the
# reference's own SDK-compiled fixtures import these with known
# semantics (see legacy_abi.py and tests/test_reference_fixtures.py).
# Everything else in MODULES is tier "derived".
FIXTURE_VERIFIED = frozenset([
    ("l", "put_contract_data"),
    ("l", "has_contract_data"),
    ("l", "get_contract_data"),
    ("l", "del_contract_data"),
])

EXPORT_CHARS = ("_0123456789abcdefghijklmnopqrstuvwxyz"
                "ABCDEFGHIJKLMNOPQRSTUVWXYZ")

# module char -> (module name, [function long names in export order])
MODULES: Dict[str, Tuple[str, List[str]]] = {
    "x": ("context", [
        "log_from_linear_memory",
        "obj_cmp",
        "contract_event",
        "get_ledger_version",
        "get_ledger_sequence",
        "get_ledger_timestamp",
        "fail_with_error",
        "get_ledger_network_id",
        "get_current_contract_address",
        "get_max_live_until_ledger",
    ]),
    "i": ("int", [
        "obj_from_u64",
        "obj_to_u64",
        "obj_from_i64",
        "obj_to_i64",
        "obj_from_u128_pieces",
        "obj_to_u128_lo64",
        "obj_to_u128_hi64",
        "obj_from_i128_pieces",
        "obj_to_i128_lo64",
        "obj_to_i128_hi64",
        "obj_from_u256_pieces",
        "u256_val_from_be_bytes",
        "u256_val_to_be_bytes",
        "obj_to_u256_hi_hi",
        "obj_to_u256_hi_lo",
        "obj_to_u256_lo_hi",
        "obj_to_u256_lo_lo",
        "obj_from_i256_pieces",
        "i256_val_from_be_bytes",
        "i256_val_to_be_bytes",
        "obj_to_i256_hi_hi",
        "obj_to_i256_hi_lo",
        "obj_to_i256_lo_hi",
        "obj_to_i256_lo_lo",
        "u256_add",
        "u256_sub",
        "u256_mul",
        "u256_div",
        "u256_rem_euclid",
        "u256_pow",
        "u256_shl",
        "u256_shr",
        "i256_add",
        "i256_sub",
        "i256_mul",
        "i256_div",
        "i256_rem_euclid",
        "i256_pow",
        "i256_shl",
        "i256_shr",
        "timepoint_obj_from_u64",
        "timepoint_obj_to_u64",
        "duration_obj_from_u64",
        "duration_obj_to_u64",
    ]),
    "m": ("map", [
        "map_new",
        "map_put",
        "map_get",
        "map_del",
        "map_len",
        "map_has",
        "map_key_by_pos",
        "map_val_by_pos",
        "map_keys",
        "map_values",
        "map_new_from_linear_memory",
        "map_unpack_to_linear_memory",
    ]),
    "v": ("vec", [
        "vec_new",
        "vec_put",
        "vec_get",
        "vec_del",
        "vec_len",
        "vec_push_front",
        "vec_pop_front",
        "vec_push_back",
        "vec_pop_back",
        "vec_front",
        "vec_back",
        "vec_insert",
        "vec_append",
        "vec_slice",
        "vec_first_index_of",
        "vec_last_index_of",
        "vec_binary_search",
        "vec_new_from_linear_memory",
        "vec_unpack_to_linear_memory",
    ]),
    "l": ("ledger", [
        # first four fixture-verified (see module docstring)
        "put_contract_data",
        "has_contract_data",
        "get_contract_data",
        "del_contract_data",
        "extend_contract_data_ttl",
        "extend_current_contract_instance_and_code_ttl",
        "extend_contract_instance_and_code_ttl",
        "create_contract",
        "create_asset_contract",
        "get_asset_contract_id",
        "upload_wasm",
        "update_current_contract_wasm",
        "get_contract_id",
    ]),
    "d": ("call", [
        "call",
        "try_call",
    ]),
    "b": ("buf", [
        "serialize_to_bytes",
        "deserialize_from_bytes",
        "string_copy_to_linear_memory",
        "symbol_copy_to_linear_memory",
        "string_new_from_linear_memory",
        "symbol_new_from_linear_memory",
        "string_len",
        "symbol_len",
        "bytes_copy_to_linear_memory",
        "bytes_copy_from_linear_memory",
        "bytes_new_from_linear_memory",
        "bytes_new",
        "bytes_put",
        "bytes_get",
        "bytes_del",
        "bytes_len",
        "bytes_push",
        "bytes_pop",
        "bytes_front",
        "bytes_back",
        "bytes_insert",
        "bytes_append",
        "bytes_slice",
        "symbol_index_in_linear_memory",
    ]),
    "c": ("crypto", [
        "compute_hash_sha256",
        "verify_sig_ed25519",
        "compute_hash_keccak256",
        "recover_key_ecdsa_secp256k1",
        "verify_sig_ecdsa_secp256r1",
        # protocol 22 (CAP-59) BLS12-381 family
        "bls12_381_check_g1_is_in_subgroup",
        "bls12_381_g1_add",
        "bls12_381_g1_mul",
        "bls12_381_g1_msm",
        "bls12_381_map_fp_to_g1",
        "bls12_381_hash_to_g1",
        "bls12_381_check_g2_is_in_subgroup",
        "bls12_381_g2_add",
        "bls12_381_g2_mul",
        "bls12_381_g2_msm",
        "bls12_381_map_fp2_to_g2",
        "bls12_381_hash_to_g2",
        "bls12_381_multi_pairing_check",
        "bls12_381_fr_add",
        "bls12_381_fr_sub",
        "bls12_381_fr_mul",
        "bls12_381_fr_pow",
        "bls12_381_fr_inv",
    ]),
    "a": ("address", [
        "require_auth_for_args",
        "require_auth",
        "strkey_to_address",
        "address_to_strkey",
        "authorize_as_curr_contract",
    ]),
    "t": ("test", [
        "dummy0",
        "protocol_gated_dummy",
    ]),
    "p": ("prng", [
        "prng_reseed",
        "prng_bytes_new",
        "prng_u64_in_inclusive_range",
        "prng_vec_shuffle",
    ]),
}


def export_name(index: int) -> str:
    """Index -> export name: single char for 0..62, then two chars."""
    n = len(EXPORT_CHARS)
    if index < n:
        return EXPORT_CHARS[index]
    return EXPORT_CHARS[index // n - 1] + EXPORT_CHARS[index % n]


def short_to_long() -> Dict[Tuple[str, str], str]:
    """{(module_char, export_char): long function name}."""
    out = {}
    for mod_char, (_mod_name, fns) in MODULES.items():
        for i, fn in enumerate(fns):
            out[(mod_char, export_name(i))] = fn
    return out


def long_to_short() -> Dict[str, Tuple[str, str]]:
    """{long function name: (module_char, export_char)} — long names
    are unique across modules in the soroban interface."""
    out = {}
    for mod_char, (_mod_name, fns) in MODULES.items():
        for i, fn in enumerate(fns):
            out[fn] = (mod_char, export_name(i))
    return out


def evidence_tier(mod_char: str, long_name: str) -> str:
    """'fixture-verified' when an offline artifact pins this ordering,
    else 'derived' (see module docstring for what each tier means)."""
    return "fixture-verified" \
        if (mod_char, long_name) in FIXTURE_VERIFIED else "derived"


def describe_binding(mod_char: str, export_char: str) -> str:
    """Human context for a link error on (module char, export name):
    which long name the registry derivation chose, at which index, and
    under which evidence tier — so a mis-derived ordering reads as
    exactly that, not as a mystery arity bug."""
    entry = MODULES.get(mod_char)
    if entry is None:
        return ""
    mod_name, fns = entry
    long = short_to_long().get((mod_char, export_char))
    if long is None:
        if len(export_char) > 2 or export_char in fns:
            # the readable long-name dialect (wasm_builder contracts /
            # historical aliases) — not a registry-derived binding
            return f" (module {mod_name!r}: long-name alias import, " \
                   f"not registry-derived)"
        return f" (module {mod_name!r}: no registry entry for export " \
               f"{export_char!r})"
    idx = fns.index(long)
    return (f" (registry: module {mod_name!r} index {idx} -> "
            f"{long!r}, evidence tier: "
            f"{evidence_tier(mod_char, long)} — if the tier is "
            f"'derived', suspect the ordering in env_interface.MODULES)")
