"""Stellar Asset Contract: the built-in contract bridging classic
assets into Soroban (reference: the SAC inside soroban-env-host, reached
through ``CONTRACT_EXECUTABLE_STELLAR_ASSET``; deployed with
``CONTRACT_ID_PREIMAGE_FROM_ASSET``).

Supported SEP-41 subset: ``balance``, ``transfer``, ``mint``, ``name``
— over ACCOUNT addresses (classic accounts / trustlines mutated through
the footprint-gated host storage, reusing the classic balance rules) —
plus CONTRACT addresses held as contract-data balance entries. Amounts
are i128 SCVals like the reference.
"""

from __future__ import annotations

from typing import Optional

from stellar_tpu.xdr.contract import (
    ContractDataDurability, Int128Parts, SCAddressType, SCVal, SCValType,
)
from stellar_tpu.xdr.runtime import from_bytes, to_bytes
from stellar_tpu.xdr.types import Asset, AssetType, LedgerEntryType

__all__ = ["asset_contract_call", "asset_instance_storage"]

T = SCValType
I128_MAX = 2**127 - 1


def _i128(v: int):
    if not (-2**127 <= v <= I128_MAX):
        raise ValueError("i128 overflow")
    u = v & (2**128 - 1)
    return SCVal.make(T.SCV_I128, Int128Parts(hi=(u >> 64) - (1 << 64)
                                              if (u >> 64) >= (1 << 63)
                                              else (u >> 64),
                                              lo=u & (2**64 - 1)))


def _from_i128(val) -> int:
    if val.arm != T.SCV_I128:
        from stellar_tpu.soroban.host import HostError
        raise HostError(HostError.TRAPPED, "amount must be i128")
    return (val.value.hi << 64) + val.value.lo


def asset_instance_storage(asset) -> list:
    """The instance-storage map entry recording which asset this SAC
    instance wraps."""
    from stellar_tpu.xdr.contract import SCMapEntry
    return [SCMapEntry(
        key=SCVal.make(T.SCV_SYMBOL, b"asset"),
        val=SCVal.make(T.SCV_BYTES, to_bytes(Asset, asset)))]


def _asset_of_instance(inst) -> "Asset.Value":
    for e in (inst.storage or ()):
        if e.key.arm == T.SCV_SYMBOL and e.key.value == b"asset":
            return from_bytes(Asset, e.val.value)
    from stellar_tpu.soroban.host import HostError
    raise HostError(HostError.TRAPPED, "SAC instance missing asset")


def _issuer_raw(asset) -> Optional[bytes]:
    if asset.arm == AssetType.ASSET_TYPE_NATIVE:
        return None
    return asset.value.issuer.value


class _ClassicBridge:
    """Classic balance access through the host's footprint-gated
    storage."""

    def __init__(self, host, asset):
        self.host = host
        self.asset = asset

    def _account_kb(self, raw: bytes) -> bytes:
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.tx.op_frame import account_key
        from stellar_tpu.xdr.types import account_id
        return key_bytes(account_key(account_id(raw)))

    def _trustline_kb(self, raw: bytes) -> bytes:
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.tx.asset_utils import trustline_key
        from stellar_tpu.xdr.types import account_id
        return key_bytes(trustline_key(account_id(raw), self.asset))

    def _entry_for(self, raw: bytes):
        from stellar_tpu.soroban.host import HostError
        native = self.asset.arm == AssetType.ASSET_TYPE_NATIVE
        if not native and _issuer_raw(self.asset) == raw:
            return None  # the issuer has no line in its own asset
        kb = self._account_kb(raw) if native else self._trustline_kb(raw)
        e = self.host.storage.get(kb)
        if e is None:
            raise HostError(HostError.TRAPPED,
                            "missing account/trustline in footprint")
        return (kb, e)

    def balance(self, raw: bytes) -> int:
        got = self._entry_for(raw)
        if got is None:
            return I128_MAX  # issuer: unbounded
        _, e = got
        return e.data.value.balance

    def add(self, raw: bytes, delta: int) -> bool:
        from stellar_tpu.tx.account_utils import add_balance
        got = self._entry_for(raw)
        if got is None:
            return True  # issuer mints/burns
        kb, e = got
        # a fake minimal header for reserve math: the host knows the
        # real one via config? classic reserve rules need the ledger
        # header — carried on the host
        if not add_balance(self.host.ledger_header, e, delta):
            return False
        self.host.storage.put(kb, e, None)
        return True


def _addr_raw(addr_val):
    from stellar_tpu.soroban.host import HostError
    if addr_val.arm != T.SCV_ADDRESS:
        raise HostError(HostError.TRAPPED, "expected address")
    return addr_val.value


def asset_contract_call(host, contract_addr, inst, fn_name: bytes,
                        args, invocation, depth: int = 0):
    """Dispatch one SAC function (reference SAC entry points)."""
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.soroban.host import (
        HostError, _address_bytes, contract_data_key, sym,
    )
    asset = _asset_of_instance(inst)
    bridge = _ClassicBridge(host, asset)

    def holder_balance(addr) -> int:
        if addr.arm == SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
            return bridge.balance(addr.value.value)
        # contract holders: a contract-data balance entry under the SAC
        lk = contract_data_key(
            contract_addr,
            SCVal.make(T.SCV_VEC, [sym("Balance"),
                                   SCVal.make(T.SCV_ADDRESS, addr)]),
            ContractDataDurability.PERSISTENT)
        e = host.storage.get(key_bytes(lk))
        return _from_i128(e.data.value.val) if e is not None else 0

    def holder_add(addr, delta: int):
        if addr.arm == SCAddressType.SC_ADDRESS_TYPE_ACCOUNT:
            if not bridge.add(addr.value.value, delta):
                raise HostError(HostError.TRAPPED,
                                "classic balance update failed")
            return
        lk = contract_data_key(
            contract_addr,
            SCVal.make(T.SCV_VEC, [sym("Balance"),
                                   SCVal.make(T.SCV_ADDRESS, addr)]),
            ContractDataDurability.PERSISTENT)
        kb = key_bytes(lk)
        cur = holder_balance(addr)
        new = cur + delta
        if new < 0 or new > I128_MAX:
            raise HostError(HostError.TRAPPED, "balance out of range")
        from stellar_tpu.soroban.host import _wrap_entry
        from stellar_tpu.xdr.contract import ContractDataEntry
        from stellar_tpu.xdr.types import ExtensionPoint
        entry = ContractDataEntry(
            ext=ExtensionPoint.make(0), contract=contract_addr,
            key=SCVal.make(T.SCV_VEC, [sym("Balance"),
                                       SCVal.make(T.SCV_ADDRESS, addr)]),
            durability=ContractDataDurability.PERSISTENT,
            val=_i128(new))
        host.storage.put(kb, _wrap_entry(
            LedgerEntryType.CONTRACT_DATA, entry, host.ledger_seq),
            host.ledger_seq + host.config.min_persistent_ttl - 1)

    if fn_name == b"balance":
        return _i128(holder_balance(_addr_raw(args[0])))
    if fn_name == b"name":
        if asset.arm == AssetType.ASSET_TYPE_NATIVE:
            return SCVal.make(T.SCV_STRING, b"native")
        code = asset.value.assetCode.rstrip(b"\x00")
        return SCVal.make(T.SCV_STRING, code)
    if fn_name == b"transfer":
        frm = _addr_raw(args[0])
        to = _addr_raw(args[1])
        amount = _from_i128(args[2])
        if amount < 0:
            raise HostError(HostError.TRAPPED, "negative amount")
        host.auth.require(_address_bytes(frm), invocation, depth)
        holder_add(frm, -amount)
        holder_add(to, amount)
        host.emit_event(contract_addr,
                        [sym("transfer")], _i128(amount))
        return SCVal.make(T.SCV_VOID)
    if fn_name == b"mint":
        to = _addr_raw(args[0])
        amount = _from_i128(args[1])
        if amount < 0:
            raise HostError(HostError.TRAPPED, "negative amount")
        issuer = _issuer_raw(asset)
        if issuer is None:
            raise HostError(HostError.TRAPPED, "native cannot mint")
        from stellar_tpu.soroban.host import scaddress_account
        from stellar_tpu.xdr.types import account_id
        host.auth.require(
            _address_bytes(scaddress_account(account_id(issuer))),
            invocation, depth)
        holder_add(to, amount)
        host.emit_event(contract_addr, [sym("mint")], _i128(amount))
        return SCVal.make(T.SCV_VOID)
    raise HostError(HostError.TRAPPED,
                    f"unknown SAC function {fn_name!r}")
