"""ctypes bridge to the native wasm execution engine
(``native/wasm_exec.cpp``) — the C++ runtime component playing wasmi's
role behind ``invoke_host_function``. The Python side keeps decode +
validation (``soroban/wasm.py``); this hands the flattened op lists to
the native interpreter, with host imports bouncing back through a
callback and ALL budget charges flowing through the real soroban
budget. Both engines share one charge-stream contract (64-op ticks,
flush before calls/grows, HOST_CALL_COST on crossings), so consumed
cpu and budget-exhaustion points are bit-identical — a node may run
either engine without consensus divergence (differential tests pin
this).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Dict, Optional, Tuple

from stellar_tpu.soroban.wasm import (
    HOST_CALL_COST, MAX_PAGES, Trap, WasmModule,
)

__all__ = ["available", "run_export"]

_HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_HERE, "native", "wasm_exec.cpp")
_LIB = os.path.join(_HERE, "build", "libwasmexec.so")
_EXT_SRC = os.path.join(_HERE, "native", "wasm_ext.cpp")


def _ext_lib_path() -> str:
    # ABI-tagged: the extension links against a specific CPython's
    # internals (unlike libwasmexec.so, which is Python-free), so a
    # stale .so from another interpreter version must never be loaded
    import sys
    return os.path.join(_HERE, "build",
                        f"wasm_ext.{sys.implementation.cache_tag}.so")


_lock = threading.Lock()
_lib = None
_tried = False
_ext = None
_ext_tried = False


def _build_lib(srcs, out_path: str, extra_flags=(), timeout: int = 180):
    """Compile-if-stale with an atomic publish: concurrent processes
    must never dlopen a half-written library (the consensus path runs
    through these)."""
    src_mtime = max(os.path.getmtime(s) for s in srcs)
    if os.path.exists(out_path) and \
            os.path.getmtime(out_path) >= src_mtime:
        return
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    tmp = out_path + f".tmp.{os.getpid()}"
    subprocess.run(
        ["g++", "-O2", "-shared", "-fPIC", *extra_flags,
         "-o", tmp, srcs[0]],
        check=True, capture_output=True, timeout=timeout)
    os.replace(tmp, out_path)

ST_OK, ST_TRAP, ST_BUDGET, ST_HOST = 0, 1, 2, 3

_TRAP_MESSAGES = {
    1: "unreachable executed",
    2: "memory access out of bounds",
    3: "integer divide by zero",
    4: "integer overflow",
    5: "call stack exhausted",
    6: "uninitialized table element",
    7: "indirect call type mismatch",
    8: "data segment out of bounds",
}

_i64p = ctypes.POINTER(ctypes.c_int64)
_i32p = ctypes.POINTER(ctypes.c_int32)
_u8p = ctypes.POINTER(ctypes.c_uint8)

_HOST_CB = ctypes.CFUNCTYPE(
    ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32, _i64p,
    ctypes.c_int32, _i64p, _i64p, ctypes.c_int64, _u8p,
    ctypes.c_int64)
_MEM_CB = ctypes.CFUNCTYPE(ctypes.c_int32,
                           ctypes.c_void_p, ctypes.c_int64)


class _FuncDesc(ctypes.Structure):
    _fields_ = [("ops_off", ctypes.c_int64),
                ("n_ops", ctypes.c_int64),
                ("n_locals", ctypes.c_int32),
                ("n_params", ctypes.c_int32),
                ("n_results", ctypes.c_int32),
                ("type_id", ctypes.c_int32),
                ("result_is32", ctypes.c_int32),
                ("_pad", ctypes.c_int32)]


class _ProgramDesc(ctypes.Structure):
    _fields_ = [("ops", _i32p), ("imm_a", _i64p), ("imm_b", _i64p),
                ("imm_c", _i64p), ("br_pool", _i64p),
                ("funcs", ctypes.POINTER(_FuncDesc)),
                ("n_funcs", ctypes.c_int32),
                ("import_nparams", _i32p),
                ("import_nresults", _i32p),
                ("import_result32", _i32p),
                ("n_imports", ctypes.c_int32),
                ("globals_init", _i64p),
                ("n_globals", ctypes.c_int32),
                ("table", _i32p), ("table_len", ctypes.c_int32),
                ("data_blob", _u8p), ("data_offs", _i64p),
                ("data_lens", _i64p), ("n_data", ctypes.c_int32),
                ("mem_min_pages", ctypes.c_int32),
                ("mem_max_pages", ctypes.c_int32),
                ("start_func", ctypes.c_int32),
                ("func_type_ids", _i32p)]


class _RunResult(ctypes.Structure):
    _fields_ = [("status", ctypes.c_int32),
                ("trap_code", ctypes.c_int32),
                ("value", ctypes.c_int64),
                ("has_value", ctypes.c_int32),
                ("executed", ctypes.c_int64),
                ("charged", ctypes.c_int64)]


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            _build_lib([_SRC], _LIB, timeout=120)
            lib = ctypes.CDLL(_LIB)
            lib.wasm_run.argtypes = [
                ctypes.POINTER(_ProgramDesc), ctypes.c_int32, _i64p,
                ctypes.c_int32, _HOST_CB, _MEM_CB, ctypes.c_void_p,
                ctypes.c_int64, ctypes.POINTER(_RunResult)]
            lib.wasm_run.restype = ctypes.c_int32
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def _load_ext():
    """The CPython-extension trampoline (native/wasm_ext.cpp): same
    engine, ~5x cheaper host-call crossings than CFUNCTYPE. Falls back
    to the ctypes path when the toolchain can't build extensions."""
    global _ext, _ext_tried
    if _ext_tried:
        return _ext
    with _lock:
        if _ext_tried:
            return _ext
        _ext_tried = True
        try:
            import importlib.util
            import sysconfig
            lib_path = _ext_lib_path()
            inc = sysconfig.get_paths()["include"]
            _build_lib([_EXT_SRC, _SRC], lib_path,
                       extra_flags=[f"-I{inc}",
                                    f"-I{os.path.dirname(_SRC)}"])
            spec = importlib.util.spec_from_file_location(
                "wasm_ext", lib_path)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _ext = mod
        except Exception:
            _ext = None
        return _ext


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# flattening: WasmModule -> ProgramDesc (cached on the module)
# ---------------------------------------------------------------------------

_M64 = (1 << 64) - 1


def _s64(v: int) -> int:
    v &= _M64
    return v - (1 << 64) if v >> 63 else v


def _compile(module: WasmModule):
    """Flatten the decoded module into the arrays the native engine
    consumes; kept alive as a tuple on the module."""
    cached = getattr(module, "_native_prog", None)
    if cached is not None:
        return cached
    # canonical type ids by STRUCTURE (call_indirect compares types
    # structurally, like the Python engine)
    type_ids: Dict[Tuple, int] = {}

    def tid(ft) -> int:
        key = (ft.params, ft.results)
        return type_ids.setdefault(key, len(type_ids))

    ops_l, ia_l, ib_l, ic_l = [], [], [], []
    pool = []
    funcs = (_FuncDesc * max(1, len(module.funcs)))()
    for i, f in enumerate(module.funcs):
        off = len(ops_l)
        for op, imm in f.ops:
            a = b = c = 0
            if op in (0x0C, 0x0D):
                a, b, c = imm
            elif op == 0x0E:
                a = len(pool)
                b = len(imm)
                pool.extend(imm)
            elif op == 0x11:
                a = tid(module.types[imm])
            elif isinstance(imm, int):
                a = _s64(imm)
            ops_l.append(op)
            ia_l.append(a)
            ib_l.append(b)
            ic_l.append(c)
        from stellar_tpu.soroban.wasm import I32 as _I32
        funcs[i] = _FuncDesc(
            ops_off=off, n_ops=len(f.ops),
            n_locals=len(f.locals), n_params=len(f.type.params),
            n_results=len(f.type.results), type_id=tid(f.type),
            result_is32=1 if (f.type.results and
                              f.type.results[0] == _I32) else 0)

    n_ops = max(1, len(ops_l))
    ops = (ctypes.c_int32 * n_ops)(*ops_l)
    ia = (ctypes.c_int64 * n_ops)(*ia_l)
    ib = (ctypes.c_int64 * n_ops)(*ib_l)
    ic = (ctypes.c_int64 * n_ops)(*ic_l)
    pool_arr = (ctypes.c_int64 * max(1, len(pool) * 3))(
        *[x for tr in pool for x in tr])

    from stellar_tpu.soroban.wasm import I32
    n_imp = max(1, len(module.imports))
    imp_np = (ctypes.c_int32 * n_imp)(
        *[len(t.params) for _m, _n, t in module.imports] or [0])
    imp_nr = (ctypes.c_int32 * n_imp)(
        *[len(t.results) for _m, _n, t in module.imports] or [0])
    imp_r32 = (ctypes.c_int32 * n_imp)(
        *[1 if (t.results and t.results[0] == I32) else 0
          for _m, _n, t in module.imports] or [0])

    n_glob = max(1, len(module.globals))
    globs = (ctypes.c_int64 * n_glob)(
        *[_s64(g[2]) for g in module.globals] or [0])

    table_init = [-1] * module.table_min
    for offt, idxs in module.elements:
        if offt < 0 or offt + len(idxs) > len(table_init):
            # the Python engine traps at instantiation; clamping here
            # would diverge (code-review r3 finding)
            raise Trap("element segment out of bounds")
        for j, fi in enumerate(idxs):
            table_init[offt + j] = fi
    table = (ctypes.c_int32 * max(1, len(table_init)))(
        *table_init or [0])

    blob = b"".join(d for _o, d in module.data)
    blob_arr = (ctypes.c_uint8 * max(1, len(blob)))(*blob or [0])
    n_data = max(1, len(module.data))
    doffs = (ctypes.c_int64 * n_data)(
        *[o for o, _d in module.data] or [0])
    dlens = (ctypes.c_int64 * n_data)(
        *[len(d) for _o, d in module.data] or [0])

    n_all = len(module.imports) + len(module.funcs)
    ftids = (ctypes.c_int32 * max(1, n_all))(
        *([tid(module.func_type(i)) for i in range(n_all)] or [0]))

    desc = _ProgramDesc(
        ops=ops, imm_a=ia, imm_b=ib, imm_c=ic, br_pool=pool_arr,
        funcs=funcs, n_funcs=len(module.funcs),
        import_nparams=imp_np, import_nresults=imp_nr,
        import_result32=imp_r32,
        n_imports=len(module.imports),
        globals_init=globs, n_globals=len(module.globals),
        table=table, table_len=len(table_init),
        data_blob=blob_arr, data_offs=doffs, data_lens=dlens,
        n_data=len(module.data),
        mem_min_pages=module.mem_min,
        mem_max_pages=(module.mem_max if module.mem_max is not None
                       else -1),
        start_func=(module.start if module.start is not None else -1),
        func_type_ids=ftids)
    # keep every array alive with the desc
    prog = (desc, ops, ia, ib, ic, pool_arr, funcs, imp_np, imp_nr,
            imp_r32, globs, table, blob_arr, doffs, dlens, ftids)
    module._native_prog = prog
    # the descriptor address is stable for the prog's lifetime; caching
    # it saves a ctypes.addressof per invoke on the hot path
    module._native_desc_addr = ctypes.addressof(desc)
    return prog


class _MemShim:
    """WasmInstance-compatible memory facade over the C++ engine's
    linear memory, valid for the duration of one host callback."""

    __slots__ = ("ptr", "size")

    def __init__(self):
        self.ptr = None
        self.size = 0

    def _base(self) -> Optional[int]:
        p = self.ptr
        if isinstance(p, int):  # extension path passes a raw address
            return p or None
        return ctypes.cast(p, ctypes.c_void_p).value if p else None

    def mem_read(self, ptr: int, n: int) -> bytes:
        if ptr < 0 or n < 0 or ptr + n > self.size:
            raise Trap("memory access out of bounds")
        if n == 0:
            return b""  # zero-length reads succeed even with no memory
        base = self._base()
        if base is None:
            raise Trap("memory access out of bounds")
        return ctypes.string_at(base + ptr, n)

    def mem_write(self, ptr: int, data: bytes):
        if ptr < 0 or ptr + len(data) > self.size:
            raise Trap("memory access out of bounds")
        if not data:
            return
        base = self._base()
        if base is None:
            raise Trap("memory access out of bounds")
        ctypes.memmove(base + ptr, data, len(data))


class _RunCtx:
    """Per-invocation state behind the PERSISTENT ctypes callbacks.
    Creating a CFUNCTYPE wrapper costs more than a typical 3-op
    contract's whole execution; instead one pair of callbacks per
    thread closes over a swappable context (stacked for reentrant
    ``call`` dispatch)."""

    __slots__ = ("host_fns", "budget", "cpu_per_insn", "shim",
                 "exc_box", "settled")

    def __init__(self, host_fns, budget, cpu_per_insn):
        self.host_fns = host_fns
        self.budget = budget
        self.cpu_per_insn = cpu_per_insn
        self.shim = _MemShim()
        self.exc_box = []
        self.settled = 0  # op-ticks already charged to the real budget

    def remaining_ticks(self) -> int:
        room = self.budget.cpu_limit - self.budget.cpu
        return max(0, room // self.cpu_per_insn)

    def settle(self, charged_so_far: int, extra_cpu: int = 0):
        """Charge the engine's op ticks into the REAL budget before any
        host-side charge decision, so host-fn charges and wasm ticks
        share ONE exhaustion point, exactly like the Python engine
        (which charges every tick chunk straight into the budget). By
        construction the engine only runs ticks it was granted, so a
        settle inside the grant never raises; the FINAL settle of a
        budget-trapped run carries the failing chunk and raises at the
        same point the Python engine's chunk charge does."""
        delta = charged_so_far - self.settled
        if delta:
            self.settled = charged_so_far
            self.budget.charge(delta * self.cpu_per_insn)
        if extra_cpu:
            # separate charge call: the budget value observable at an
            # exhaustion trap must match the Python engine's, which
            # charges tick chunks and the crossing cost independently
            self.budget.charge(extra_cpu)


_HOST_CALL_CPU = HOST_CALL_COST  # local alias for the dispatch hot path

_tls = threading.local()


def _thread_stack():
    """Per-thread context stack shared by BOTH dispatch paths; kept
    separate so the extension path never pays CFUNCTYPE construction."""
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _thread_cbs():
    """(ctx_stack, host_cb, mem_cb) — one persistent callback pair per
    thread; ``ctx_stack[-1]`` is the active invocation's context."""
    cbs = getattr(_tls, "cbs", None)
    if cbs is None:
        stack = _thread_stack()

        def host_cb(_c, import_idx, args_p, nargs, result_p,
                    ticks_left_p, charged_so_far, mem_p, mem_len):
            ctx = stack[-1]
            try:
                # one combined charge: settled ticks + crossing cost
                ctx.settle(charged_so_far,
                           HOST_CALL_COST * ctx.cpu_per_insn)
                shim = ctx.shim
                shim.ptr = mem_p
                shim.size = mem_len
                call_args = [args_p[i] & _M64 for i in range(nargs)]
                rv = ctx.host_fns[import_idx](shim, *call_args)
                result_p[0] = _s64(rv if rv is not None else 0)
                ticks_left_p[0] = ctx.remaining_ticks()
                return 0
            except BaseException as e:
                ctx.exc_box.append(e)
                return 1

        def mem_cb(_c, n_bytes):
            ctx = stack[-1]
            try:
                ctx.budget.charge(0, n_bytes)
                return 0
            except BaseException as e:
                ctx.exc_box.append(e)
                return 1

        cbs = (stack, _HOST_CB(host_cb), _MEM_CB(mem_cb))
        _tls.cbs = cbs
    return cbs


def _thread_dispatchers():
    """(ctx_stack, host_dispatch, mem_dispatch) for the extension
    trampoline — shares the ctx stack with the ctypes path. The
    dispatchers record exceptions in the active context and return
    None, mirroring the CFUNCTYPE path's exc_box control flow."""
    d = getattr(_tls, "disp", None)
    if d is None:
        stack = _thread_stack()

        def host_dispatch(import_idx, args_tup, charged,
                          mem_addr, mem_len):
            ctx = stack[-1]
            try:
                # settle + crossing charge, inlined: this runs for
                # EVERY host call of every contract — the two charge
                # calls below are the metering contract (tick chunk and
                # crossing cost charged separately, matching the Python
                # engine's observable exhaustion points)
                budget = ctx.budget
                cpi = ctx.cpu_per_insn
                delta = charged - ctx.settled
                ctx.settled = charged
                ticks_cpu = delta * cpi
                cross_cpu = _HOST_CALL_CPU * cpi
                new_cpu = budget.cpu + ticks_cpu + cross_cpu
                if new_cpu <= budget.cpu_limit:
                    budget.cpu = new_cpu  # fast path: no exhaustion
                else:
                    # slow path keeps the Python engine's exact two
                    # observable exhaustion points (tick chunk, then
                    # crossing cost)
                    if ticks_cpu:
                        budget.charge(ticks_cpu)
                    budget.charge(cross_cpu)
                shim = ctx.shim
                shim.ptr = mem_addr
                shim.size = mem_len
                rv = ctx.host_fns[import_idx](shim, *args_tup)
                room = budget.cpu_limit - budget.cpu
                return ((rv if rv is not None else 0) & _M64,
                        room // cpi if room > 0 else 0)
            except BaseException as e:
                ctx.exc_box.append(e)
                return None

        def mem_dispatch(n_bytes):
            ctx = stack[-1]
            try:
                ctx.budget.charge(0, n_bytes)
                return True
            except BaseException as e:
                ctx.exc_box.append(e)
                return None

        d = (stack, host_dispatch, mem_dispatch)
        _tls.disp = d
    return d


def run_export(module: WasmModule, imports: Dict, budget,
               cpu_per_insn: int, fn_name: str, args,
               cache_imports: bool = False) -> Optional[int]:
    """Execute ``fn_name(args)`` natively. Charges ride the REAL
    ``budget``; raises Trap (or re-raises whatever a host import
    raised) exactly like the Python engine.

    ``cache_imports=True`` memoizes the resolved import list on the
    module keyed by the imports dict's identity — pass it ONLY for
    pooled, process-lifetime import tables (the modern host env pool):
    caching an ad-hoc dict would pin its closed-over host graph alive
    on the globally cached module."""
    lib = _load()
    assert lib is not None
    # instantiation-order parity with the Python engine: initial
    # memory is charged FIRST (WasmInstance.__init__), then element
    # segments validate, then start runs, and only then do export /
    # arity checks trap — so budget-vs-trap classification matches
    if module.mem_min:
        budget.charge(0, module.mem_min * 65536)
    prog = _compile(module)  # raises the element-segment Trap
    desc = prog[0]
    func_idx = -1
    export_error = f"no exported function {fn_name!r}"
    exp = module.exports.get(fn_name)
    if exp is not None and exp[0] == "func":
        ft = module.func_type(exp[1])
        if len(args) != len(ft.params):
            export_error = f"{fn_name!r} expects {len(ft.params)} args"
            args = []
        else:
            func_idx = exp[1]

    # resolve the import table once per (module, imports-dict) pair —
    # the per-thread env pool reuses its imports dict, so steady-state
    # invokes skip the per-import lookups entirely
    cache = getattr(module, "_host_fns_cache", None)
    if cache is not None and cache[0] is imports:
        host_fns, gated = cache[1], cache[2]
        if gated:
            # the cached resolution skipped the full link checks, but
            # the frame's PROTOCOL can differ per invoke (pooled
            # imports serve many txs) — era refusal must re-run
            from stellar_tpu.soroban.wasm import check_import_era
            for mod, name, fn in gated:
                check_import_era(mod, name, fn)
    else:
        host_fns = []
        gated = []
        from stellar_tpu.soroban.wasm import (
            WasmError, check_import_binding,
        )
        for mod, name, t in module.imports:
            fn = imports.get((mod, name))
            if fn is None:
                raise WasmError(f"unresolved import {mod}.{name}")
            check_import_binding(mod, name, t, fn)
            host_fns.append(fn)
            if getattr(fn, "__min_protocol__", None) is not None:
                gated.append((mod, name, fn))
        if cache_imports:
            module._host_fns_cache = (imports, host_fns, gated)

    # reuse one ctx + result struct per thread depth-slot: allocation
    # (a _MemShim, an exc list, a ctypes struct) costs as much as a
    # small contract's whole host work. Reentrant ``call`` frames get
    # fresh objects (pool is per-depth via the stack length).
    pool = getattr(_tls, "ctx_pool", None)
    if pool is None:
        pool = _tls.ctx_pool = []
    depth = len(_thread_stack())
    while len(pool) <= depth:
        r = _RunResult()
        pool.append((_RunCtx([], None, 1), r, ctypes.addressof(r)))
    ctx, out, out_addr = pool[depth]
    ctx.host_fns = host_fns
    ctx.budget = budget
    ctx.cpu_per_insn = cpu_per_insn
    ctx.settled = 0
    out.charged = 0
    exc_box = ctx.exc_box
    try:
        if (ext := _load_ext()) is not None:
            stack, hd, md = _thread_dispatchers()
            stack.append(ctx)
            try:
                try:
                    ext.run(module._native_desc_addr, func_idx,
                            [a & _M64 for a in args],
                            ctx.remaining_ticks(), hd, md, out_addr)
                except BaseException as e:
                    # trampoline-internal failure: out is filled —
                    # settle like the normal path, then surface the
                    # recorded host exception if one exists
                    ctx.settle(out.charged)
                    if exc_box:
                        raise exc_box[0] from None
                    raise e
            finally:
                stack.pop()
            rc = out.status
        else:
            stack, hcb, mcb = _thread_cbs()
            stack.append(ctx)
            try:
                rc = lib.wasm_run(
                    ctypes.byref(desc), func_idx,
                    (ctypes.c_int64 * max(1, len(args)))(
                        *[_s64(a & _M64) for a in args] or [0]),
                    len(args), hcb, mcb, None,
                    ctx.remaining_ticks(), ctypes.byref(out))
            finally:
                stack.pop()

        # settle the remaining wasm-op charges; a budget-trapped run's
        # failing chunk raises here, mirroring the Python engine's
        # chunk charge exactly
        ctx.settle(out.charged)
        if rc == ST_OK:
            return (out.value & _M64) if out.has_value else None
        if rc == ST_HOST:
            raise exc_box[0] if exc_box else Trap("host call failed")
        if rc == ST_BUDGET:
            # charged included the failing chunk: budget.charge above
            # must have raised; reaching here means accounting drifted
            raise AssertionError("native budget accounting out of sync")
        if out.trap_code == 9:  # missing export / arity, post-start
            raise Trap(export_error)
        raise Trap(_TRAP_MESSAGES.get(out.trap_code,
                                      f"trap {out.trap_code}"))
    finally:
        # drop run references NOW, not at the next same-depth invoke: a
        # pooled ctx holding the last run's exception (whose traceback
        # pins the whole host/storage graph), budget, and import table
        # would otherwise retain them for the thread's lifetime
        ctx.host_fns = ()
        ctx.budget = None
        exc_box.clear()
