"""Legacy (pre-1.0) soroban env ABI: the 2022-era ``RawVal`` encoding
and host import surface spoken by the reference's own compiled test
fixtures (``/root/reference/src/testdata/example_add_i32.wasm``,
``example_contract_data.wasm`` — env interface version 2, read from
their ``contractenvmetav0`` sections).

Derived by disassembling those fixtures with this repo's own decoder,
NOT from any external source:

- ``add`` checks ``(val & 15) == 3`` on both args, computes the
  overflow-checked i32 sum of ``val >> 4``, and returns
  ``(sum << 4) | 3``  → bit0 = 1 means "tagged", tag = ``(val>>1)&7``
  with payload in bits 4..63; tag 1 is I32 (``(1<<1)|1 = 3``).
- ``put``/``del`` check ``(val & 15) == 9`` → tag 4 = Symbol (6-bit
  chars, same ``_0-9A-Za-z`` alphabet as the modern SymbolSmall, up to
  10 chars in the 60-bit payload), call imports ``("l","_")`` =
  ``put_contract_data(k, v)`` / ``("l","2")`` = ``del_contract_data(k)``
  and return ``5`` = Static/Void (tag 2, payload 0).
- bit0 = 0 is a positive "u63" immediate: value = ``val >> 1``.

Contracts whose env-meta interface version predates the
``protocol << 32`` scheme (i.e. ``< 1 << 32``) are linked against this
table; everything else gets the modern env (``soroban/env.py``).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

from stellar_tpu.soroban.env import EnvError
from stellar_tpu.xdr.contract import SCVal, SCValType

__all__ = ["is_legacy_module", "to_rawval", "from_rawval",
           "make_legacy_imports", "LEGACY_VOID"]

T = SCValType

_M64 = (1 << 64) - 1

# tag values from the 2022 RawVal scheme (bit0=1, tag in bits 1..3,
# payload in bits 4..63)
_TAG_U32 = 0
_TAG_I32 = 1
_TAG_STATIC = 2
_TAG_OBJECT = 3
_TAG_SYMBOL = 4
_TAG_BITSET = 5
_TAG_STATUS = 6

_STATIC_VOID = 0
_STATIC_TRUE = 1
_STATIC_FALSE = 2

LEGACY_VOID = (_STATIC_VOID << 4) | (_TAG_STATIC << 1) | 1  # == 5


def is_legacy_module(module) -> bool:
    """True when the module was compiled against a pre-1.0 env
    interface (version below the ``protocol << 32`` scheme)."""
    v = module.env_meta_version
    return v is not None and v < (1 << 32)


def _tagged(tag: int, payload: int) -> int:
    return ((payload & ((1 << 60) - 1)) << 4) | ((tag & 7) << 1) | 1


def to_rawval(sc) -> int:
    """SCVal -> legacy RawVal (immediates only: the fixtures never
    exchange object handles across the boundary)."""
    arm = sc.arm
    if arm == T.SCV_VOID:
        return LEGACY_VOID
    if arm == T.SCV_BOOL:
        return _tagged(_TAG_STATIC,
                       _STATIC_TRUE if sc.value else _STATIC_FALSE)
    if arm == T.SCV_U32:
        return _tagged(_TAG_U32, sc.value & 0xFFFFFFFF)
    if arm == T.SCV_I32:
        return _tagged(_TAG_I32, sc.value & 0xFFFFFFFF)
    if arm == T.SCV_U64:
        # the only arm that round-trips through the u63 immediate;
        # I64/Timepoint/Duration would come back re-typed as U64, so
        # they are refused rather than silently rewritten
        if sc.value < 1 << 63:
            return (sc.value << 1) & _M64
        raise EnvError("u64 too large for legacy u63 immediate")
    if arm == T.SCV_SYMBOL:
        if len(sc.value) > 10:
            raise EnvError("symbol too long for legacy encoding")
        # same 6-bit alphabet as the modern SymbolSmall but 10 chars
        # fit the 60-bit legacy payload
        from stellar_tpu.soroban.env import _SYM_CODE
        body = 0
        for ch in sc.value.decode("ascii"):
            code = _SYM_CODE.get(ch)
            if code is None:
                raise EnvError(f"bad symbol char {ch!r}")
            body = (body << 6) | code
        return _tagged(_TAG_SYMBOL, body)
    raise EnvError(f"SCVal arm {arm} has no legacy RawVal form")


def from_rawval(val: int):
    """Legacy RawVal -> SCVal (immediates only)."""
    val &= _M64
    if not val & 1:
        return SCVal.make(T.SCV_U64, val >> 1)
    tag = (val >> 1) & 7
    payload = val >> 4
    if tag == _TAG_STATIC:
        if payload == _STATIC_VOID:
            return SCVal.make(T.SCV_VOID)
        if payload == _STATIC_TRUE:
            return SCVal.make(T.SCV_BOOL, True)
        if payload == _STATIC_FALSE:
            return SCVal.make(T.SCV_BOOL, False)
        raise EnvError(f"unknown legacy static value {payload}")
    if tag == _TAG_U32:
        return SCVal.make(T.SCV_U32, payload & 0xFFFFFFFF)
    if tag == _TAG_I32:
        p = payload & 0xFFFFFFFF
        return SCVal.make(T.SCV_I32, p - (1 << 32) if p >> 31 else p)
    if tag == _TAG_SYMBOL:
        # re-tag into the modern SymbolSmall layout for the shared
        # 6-bit decoder (identical alphabet; legacy payload may carry
        # 10 chars = 60 bits, decode manually above 56 bits)
        chars = []
        body = payload
        from stellar_tpu.soroban.env import _SYM_CHAR
        while body:
            ch = _SYM_CHAR.get(body & 0x3F)
            if ch is None:
                raise EnvError("malformed legacy symbol")
            chars.append(ch)
            body >>= 6
        return SCVal.make(T.SCV_SYMBOL,
                          "".join(reversed(chars)).encode())
    raise EnvError(f"legacy RawVal tag {tag} not supported")


def make_legacy_imports(env) -> Dict[Tuple[str, str], Callable]:
    """Import table for a legacy-ABI contract frame. ``env`` is the
    same ``WasmContractEnv`` the modern table binds; storage goes
    through the same footprint-enforced host services. Pre-durability
    contract data is linked to PERSISTENT storage (the only kind that
    existed)."""
    from stellar_tpu.ledger.ledger_txn import key_bytes
    from stellar_tpu.soroban.host import contract_data_key
    from stellar_tpu.xdr.contract import ContractDataDurability

    dur = ContractDataDurability.PERSISTENT

    def _kb(k_raw: int):
        key_sc = from_rawval(k_raw)
        return key_sc, key_bytes(
            contract_data_key(env.contract_addr, key_sc, dur))

    def put_contract_data(inst, k_raw, v_raw):
        env.data_put(from_rawval(k_raw), from_rawval(v_raw), dur)
        return LEGACY_VOID

    def has_contract_data(inst, k_raw):
        _, kb = _kb(k_raw)
        present = env.data_get(kb) is not None
        return _tagged(_TAG_STATIC,
                       _STATIC_TRUE if present else _STATIC_FALSE)

    def get_contract_data(inst, k_raw):
        _, kb = _kb(k_raw)
        sc = env.data_get(kb)
        if sc is None:
            raise EnvError("missing contract data")
        return to_rawval(sc)

    def del_contract_data(inst, k_raw):
        _, kb = _kb(k_raw)
        env.data_del(kb)
        return LEGACY_VOID

    return {
        ("l", "_"): put_contract_data,
        ("l", "0"): has_contract_data,
        ("l", "1"): get_contract_data,
        ("l", "2"): del_contract_data,
    }
