"""Batched SHA-256 on the batch-dispatch substrate — workload #2.

The reference's replay and bucket paths are hash-bound once signatures
batch: catchup prefetches a whole checkpoint's signatures in 16k-row
coalesced device batches (PR 6 lineage), after which the remaining
serial host work is thousands of small INDEPENDENT SHA-256 digests —
ledger-header hashes in chain verification, per-tx contents hashes in
TxSet splitting, bucket-level hashes in the bucket list. This module
rides those digests on the same engine that serves ed25519 verify
(:class:`stellar_tpu.parallel.batch_engine.BatchEngine`): same jit
buckets, per-device fault domains, degraded re-shard, circuit
breakers, watchdog fetches, sampled result-integrity audit
(differential oracle: ``hashlib.sha256``), and host failover —
``docs/robustness.md`` "Engine and workload plugins".

Row semantics: an item is one ``bytes`` message; the result row is its
(8,) uint32 big-endian digest words
(:func:`stellar_tpu.ops.sha256.digest_words_to_bytes` renders bytes).
The gate mask is FITS-ON-DEVICE: messages longer than the plugin's
block capacity (``max_blocks * 64 - 9`` bytes) are hashed on the host
by ``finalize`` — a capacity decision, never a correctness one
(results are bit-identical to ``hashlib`` either way, which is also
what the audit re-checks).

:func:`hash_many` is the consumer API (catchup chain verification,
bucket-level hashing, contents-hash prefetch): hashlib below
``MIN_DEVICE_HASH_BATCH`` rows or whenever no accelerator is live
(XLA-on-CPU loses to hashlib, same policy as
``keys.batch_verify_into_cache``), the device engine above it — so on
host-only processes the consumers are exactly the serial code they
replaced.

Determinism: this module is inside the consensus nondet-lint scope
(hash results ARE consensus state — header/bucket/TxSet identities).
It reads no clocks and no RNGs; which backend served a digest changes
latency, never bytes (host failover + audit pin that).
"""

from __future__ import annotations

import hashlib
import os
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

from stellar_tpu.parallel import batch_engine
from stellar_tpu.parallel.batch_engine import BatchEngine, Workload

__all__ = ["Sha256Workload", "BatchHasher", "default_hasher",
           "hash_many", "DEFAULT_HASH_BUCKET_SIZES", "MAX_BLOCKS",
           "MIN_DEVICE_HASH_BATCH"]

# The hash workload's jit bucket ladder. Smaller top than verify's:
# hash rows carry max_blocks * 64 bytes each (vs 128 for verify), so
# a 16k-row hash bucket would move ~8 MB per dispatch — 2048 keeps a
# bucket within the relay budget measured for verify.
DEFAULT_HASH_BUCKET_SIZES = (128, 512, 2048)

# Block capacity per row: 8 blocks = messages up to 503 bytes cover
# ledger headers, bucket levels, and typical tx contents preimages;
# longer messages (whole tx-set XDR blobs) hash on the host via the
# gate. The overflow prover proves the kernel at exactly this capacity
# and every bucket size (tools/analyze.py, docs/sha256_bounds.json).
MAX_BLOCKS = int(os.environ.get("HASH_MAX_BLOCKS", "8"))

# below this, hash_many uses hashlib directly — a device round trip
# costs more than hashing a handful of rows on the host
MIN_DEVICE_HASH_BATCH = 32


class Sha256Workload(Workload):
    """SHA-256 plugin: host packing in ``encode``, the FIPS 180-4
    kernel (:mod:`stellar_tpu.ops.sha256`) on device, ``hashlib`` as
    the bit-identical host oracle for failover and audit."""

    metrics_ns = "crypto.hash"
    span_ns = "hash"

    def __init__(self, max_blocks: int = MAX_BLOCKS):
        self.max_blocks = int(max_blocks)

    def encode(self, items: Sequence[bytes]
               ) -> Tuple[np.ndarray, tuple]:
        from stellar_tpu.ops import sha256 as sk
        words, active, fits = sk.pack_messages(items, self.max_blocks)
        return fits, (words, active)

    def pad_rows(self) -> tuple:
        # zero words, zero active blocks: a padded lane's state never
        # advances past H0 — cheapest possible lane, sliced off
        return (np.zeros((1, self.max_blocks, 16), dtype=np.uint32),
                np.zeros((1, self.max_blocks), dtype=bool))

    def kernel_fn(self):
        from stellar_tpu.ops import sha256 as sk
        return sk.sha256_kernel

    def empty_result(self, n: int) -> np.ndarray:
        return np.zeros((n, 8), dtype=np.uint32)

    def host_result(self, items: Sequence[bytes]) -> np.ndarray:
        from stellar_tpu.ops import sha256 as sk
        return sk.host_digest_words(items)

    def finalize(self, gate: np.ndarray, out: np.ndarray,
                 items: Sequence[bytes]) -> np.ndarray:
        if gate.all():
            return out
        # oversize rows: host-hashed here, by capacity (not failure)
        res = out.copy()
        idxs = np.flatnonzero(~gate)
        res[idxs] = self.host_result([items[i] for i in idxs])
        return res


class BatchHasher(BatchEngine):
    """Batched SHA-256 with the engine's jit bucket cache and fault
    domains — the :class:`Sha256Workload` riding the generic engine.
    Same constructor contract as ``BatchVerifier`` plus the block
    capacity."""

    def __init__(self, mesh=None,
                 bucket_sizes=DEFAULT_HASH_BUCKET_SIZES,
                 max_blocks: int = MAX_BLOCKS):
        super().__init__(Sha256Workload(max_blocks), mesh=mesh,
                         bucket_sizes=bucket_sizes)

    def hash_batch(self, msgs: Sequence[bytes]) -> List[bytes]:
        """Digests for ``msgs``, bit-identical to ``hashlib.sha256``,
        in order. The root span covers the whole blocking call
        (per-phase attribution via
        ``batch_engine.phase_attribution(..., span_ns="hash")``)."""
        from stellar_tpu.ops import sha256 as sk
        words = self.compute_batch(msgs)
        return [sk.digest_words_to_bytes(row) for row in words]

    def hash_words(self, msgs: Sequence[bytes]) -> np.ndarray:
        """Digest word rows (n, 8) uint32 — the raw engine result
        (differential suites compare these directly)."""
        return self.compute_batch(msgs)


_default: Optional[BatchHasher] = None
_default_lock = threading.Lock()


def default_hasher() -> BatchHasher:
    """Process-wide hasher, mesh-sharded with zero config like
    ``default_verifier`` (the two workloads share the physical mesh
    and its per-device health registry)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = BatchHasher(
                mesh=batch_engine._auto_mesh(),
                bucket_sizes=DEFAULT_HASH_BUCKET_SIZES)
        return _default


def hash_many(blobs: Sequence[bytes]) -> List[bytes]:
    """SHA-256 each blob — the drop-in for serial per-item
    ``sha256()`` loops on bulk paths (catchup chain verification,
    bucket-level hashing, TxSet contents-hash prefetch).

    Small batches, and any process without a live accelerator, use
    ``hashlib`` directly (bit-identical, and faster than XLA-on-CPU —
    the same auto-mode policy as ``keys.batch_verify_into_cache``);
    large batches on a live device ride the engine with its audit and
    failover. Either way the returned bytes are exactly
    ``hashlib.sha256(blob).digest()``."""
    blobs = list(blobs)
    if len(blobs) < MIN_DEVICE_HASH_BATCH or \
            not batch_engine.device_available(block=False):
        return [hashlib.sha256(b).digest() for b in blobs]
    return default_hasher().hash_batch(blobs)
