"""StrKey: Stellar's human-readable key encoding.

Base32 (RFC 4648, no padding on decode-check) over
``version byte || payload || CRC16-XModem (little-endian)`` — the format
implemented by the reference's ``src/crypto/StrKey.cpp`` /
``SecretKey::getStrKeyPublic`` (G... accounts, S... seeds, T/X for
pre-auth-tx & hash-x signers, P... signed payloads, C... contracts).
"""

from __future__ import annotations

import base64

__all__ = [
    "VER_ACCOUNT", "VER_SEED", "VER_PRE_AUTH_TX", "VER_HASH_X",
    "VER_SIGNED_PAYLOAD", "VER_MUXED_ACCOUNT", "VER_CONTRACT",
    "encode", "decode", "encode_account", "decode_account",
    "encode_seed", "decode_seed", "encode_contract", "decode_contract",
]

# version bytes = base32 leading character, per the public strkey spec
VER_ACCOUNT = 6 << 3          # 'G'
VER_MUXED_ACCOUNT = 12 << 3   # 'M'
VER_SEED = 18 << 3            # 'S'
VER_PRE_AUTH_TX = 19 << 3     # 'T'
VER_HASH_X = 23 << 3          # 'X'
VER_SIGNED_PAYLOAD = 15 << 3  # 'P'
VER_CONTRACT = 2 << 3         # 'C'


class StrKeyError(ValueError):
    pass


def _crc16_xmodem(data: bytes) -> int:
    crc = 0
    for byte in data:
        crc ^= byte << 8
        for _ in range(8):
            crc = ((crc << 1) ^ 0x1021) if crc & 0x8000 else (crc << 1)
            crc &= 0xFFFF
    return crc


def encode(version: int, payload: bytes) -> str:
    body = bytes([version]) + payload
    crc = _crc16_xmodem(body)
    body += bytes([crc & 0xFF, crc >> 8])
    return base64.b32encode(body).decode().rstrip("=")


def decode(expected_version: int, s: str) -> bytes:
    if not s or s != s.upper():
        raise StrKeyError("strkey must be upper-case base32")
    pad = (-len(s)) % 8
    # valid strkeys never need >6 pad chars and must round-trip exactly
    try:
        raw = base64.b32decode(s + "=" * pad)
    except Exception as e:
        raise StrKeyError(f"bad base32: {e}") from e
    if base64.b32encode(raw).decode().rstrip("=") != s:
        raise StrKeyError("non-canonical base32")
    if len(raw) < 3:
        raise StrKeyError("strkey too short")
    body, crc_bytes = raw[:-2], raw[-2:]
    crc = _crc16_xmodem(body)
    if crc_bytes != bytes([crc & 0xFF, crc >> 8]):
        raise StrKeyError("strkey checksum mismatch")
    if body[0] != expected_version:
        raise StrKeyError(
            f"strkey version {body[0]} != expected {expected_version}")
    return body[1:]


def encode_account(ed25519: bytes) -> str:
    return encode(VER_ACCOUNT, ed25519)


def decode_account(s: str) -> bytes:
    out = decode(VER_ACCOUNT, s)
    if len(out) != 32:
        raise StrKeyError("account strkey must hold 32 bytes")
    return out


def encode_seed(seed: bytes) -> str:
    return encode(VER_SEED, seed)


def decode_seed(s: str) -> bytes:
    out = decode(VER_SEED, s)
    if len(out) != 32:
        raise StrKeyError("seed strkey must hold 32 bytes")
    return out


def encode_contract(h: bytes) -> str:
    return encode(VER_CONTRACT, h)


def decode_contract(s: str) -> bytes:
    out = decode(VER_CONTRACT, s)
    if len(out) != 32:
        raise StrKeyError("contract strkey must hold 32 bytes")
    return out
