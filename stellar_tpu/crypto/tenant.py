"""Multi-tenant QoS primitives for the resident verify service.

The service's three priority lanes (``scp`` > ``auth`` > ``bulk``)
isolate WORKLOAD CLASSES, but the north star serves a fleet of
independent submitters — the committee-scale traffic shape from
"Performance of EdDSA and BLS Signatures in Committee-Based Consensus"
(PAPERS.md): many validators hammering one verification service, where
one misbehaving submitter must degrade ITSELF, not everyone sharing
its lane. This module supplies the tenant half of that story
(``docs/robustness.md`` "Tenants"):

* **tenant identities + policies** — a tenant is a short caller-chosen
  id (``[A-Za-z0-9][A-Za-z0-9._-]{0,63}``); each carries a scheduling
  WEIGHT and optional depth/byte QUOTAS nested inside the lane's
  existing budgets. The implicit :data:`DEFAULT_TENANT` (un-tenanted
  submissions) is quota-exempt unless explicitly configured, so legacy
  callers see byte-identical admission behavior;
* **deterministic weighted-fair scheduling**
  (:class:`TenantLaneQueue`) — start-time fair queueing over per-tenant
  FIFOs with SEQUENCE-BASED virtual time: integer arithmetic over
  admission sequence numbers and item counts, zero clock reads in any
  scheduling decision (this module sits inside the consensus
  nondet-lint scope with NO allowlist entry), so two replicas fed the
  same arrival order produce bit-identical dispatch orders;
* **per-tenant SLO burn rates** (:class:`TenantSloMonitor`) — the
  PR 10 :class:`~stellar_tpu.crypto.verify_service.SloMonitor`
  discipline (event-count sliding windows, burn = observed bad
  fraction / budgeted bad fraction) applied per tenant, with a hard
  **metric-cardinality guard**: gauges are published under RANK-keyed
  names (``crypto.verify.tenant.topk.<rank>.*`` + a ``.id`` label
  gauge naming the tenant) plus a ``tenant.other`` rollup, so a
  thousand-tenant fleet mints a BOUNDED set of series no matter how
  tenants churn — the PR 10 ``TimeSeriesRing`` hard cap
  (``MAX_SERIES``) can never be blown by tenant cardinality, and
  ``dropped_series`` stays 0 (pinned in ``tests/test_timeline.py``).

The tenant-keyed SHED draw lives in
:func:`stellar_tpu.crypto.audit.keep_under_shed` (``tenant=`` key);
this module only resolves each tenant's effective keep fraction
(:func:`shed_keep_fraction`): a tenant over its own quota high-water
sheds proportionally harder, so a flooding tenant's rows go first
while in-quota tenants keep the lane's ladder fraction.

Thread safety: policy/monitor state mutates under this module's locks;
:class:`TenantLaneQueue` owns NO lock — it is service-internal state,
only ever touched with the service's condition variable held (the
``_locked`` calling convention of ``verify_service``).
"""

from __future__ import annotations

import os
import re
import threading
from collections import deque
from typing import Dict, List, Optional, Tuple

from stellar_tpu.utils.env import env_true
from stellar_tpu.utils.metrics import (
    fresh_burn_window, push_burn_window, registry, trim_burn_window,
)

__all__ = [
    "DEFAULT_TENANT", "OTHER_TENANT", "WFQ_SCALE",
    "TenantLaneQueue", "TenantSloMonitor", "tenant_slo",
    "validate_tenant", "shed_key", "shed_keep_fraction",
    "tenant_policy", "set_tenant_policy", "configure_tenants",
    "clear_tenant_policies", "peer_tenant",
]

# the implicit tenant of un-tenanted submissions; quota-exempt unless
# explicitly configured, so pre-tenant callers keep their exact
# admission behavior (the lane budgets still bound them)
DEFAULT_TENANT = "default"

# reserved rollup id for tenants past the tracking cap ("~" is outside
# the tenant-id alphabet, so no real tenant can collide with it)
OTHER_TENANT = "~other"

# virtual-time scale: costs are integers (items x WFQ_SCALE / weight)
# so the scheduler's arithmetic is exact — no float drift between
# replicas, no rounding order-dependence
WFQ_SCALE = 1 << 20

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}\Z")

# ---------------- policy knobs ----------------
# Env defaults let tools/tests set these without a Config; a node
# pushes its VERIFY_TENANT_* Config knobs through configure_tenants()
# (same pattern as verify_service.configure_service). 0 = unlimited:
# tenancy is opt-in — quotas bind only once an operator sizes them.

TENANT_DEPTH = int(os.environ.get("VERIFY_TENANT_DEPTH", "0"))
TENANT_BYTES = int(os.environ.get("VERIFY_TENANT_BYTES", "0"))
# rank-keyed burn-rate gauges published per snapshot (the
# metric-cardinality guard's K)
TENANT_TOPK = int(os.environ.get("VERIFY_TENANT_TOPK", "8"))
# hard cap on individually-tracked tenants (counters + SLO windows);
# tenants past the cap fold into OTHER_TENANT — counted, never silent
TENANT_TRACK_CAP = int(os.environ.get("VERIFY_TENANT_TRACK_CAP",
                                      "4096"))
# per-tenant SLO defaults (the bulk-lane shape: tenants are submitter
# populations, not consensus lanes)
TENANT_P99_MS = float(os.environ.get("VERIFY_TENANT_P99_MS", "30000"))
TENANT_LATENCY_TARGET = float(os.environ.get(
    "VERIFY_TENANT_LATENCY_TARGET", "0.99"))
TENANT_SHED_BUDGET = float(os.environ.get("VERIFY_TENANT_SHED_BUDGET",
                                          "0.5"))
TENANT_SLO_WINDOW = int(os.environ.get("VERIFY_TENANT_SLO_WINDOW",
                                       "256"))
# fraction of a tenant's depth quota at which its backlog counts as
# over high-water for the shed pass (mirrors SHED_HIGHWATER_FRAC)
TENANT_HIGHWATER_FRAC = 0.75
# tenant identity adoption (ISSUE 15 follow-on to ISSUE 14): when on,
# the herder SCP-envelope and overlay peer-auth adopters tag their
# service round trips tenant="peer-<id prefix>" via peer_tenant(), so
# REAL peers ride per-tenant quotas/fair-share/burn rates. Off by
# default — identity-to-tenant mapping is an operator policy choice
# (pre-adoption behavior stays byte-identical).
TENANT_FROM_PEER = env_true("VERIFY_TENANT_FROM_PEER")
# hex bytes of the peer id used as the tenant tag: 4 bytes = 8 hex
# chars, collision-safe for committee-scale fleets while keeping ids
# short enough for metric/event attributes
PEER_TENANT_PREFIX_BYTES = 4

_policy_lock = threading.Lock()
# tenant -> {"weight": int, "depth": Optional[int],
#            "bytes": Optional[int]} (None = inherit the global knob)
_policies: Dict[str, dict] = {}


def configure_tenants(depth: Optional[int] = None,
                      nbytes: Optional[int] = None,
                      topk: Optional[int] = None,
                      track_cap: Optional[int] = None,
                      p99_ms: Optional[float] = None,
                      latency_target: Optional[float] = None,
                      shed_budget: Optional[float] = None,
                      window: Optional[int] = None,
                      from_peer: Optional[bool] = None) -> None:
    """Push the global tenant knobs (Config / tools); None keeps the
    current value. Quota knobs take effect on the next admission
    check; SLO knobs on the next window push."""
    global TENANT_DEPTH, TENANT_BYTES, TENANT_TOPK, TENANT_TRACK_CAP
    global TENANT_P99_MS, TENANT_LATENCY_TARGET, TENANT_SHED_BUDGET
    global TENANT_FROM_PEER
    with _policy_lock:
        if from_peer is not None:
            TENANT_FROM_PEER = bool(from_peer)
        if depth is not None:
            TENANT_DEPTH = max(0, int(depth))
        if nbytes is not None:
            TENANT_BYTES = max(0, int(nbytes))
        if topk is not None:
            TENANT_TOPK = max(1, int(topk))
        if track_cap is not None:
            TENANT_TRACK_CAP = max(8, int(track_cap))
        if p99_ms is not None:
            TENANT_P99_MS = max(1.0, float(p99_ms))
        if latency_target is not None:
            TENANT_LATENCY_TARGET = min(0.999999,
                                        max(0.0, float(latency_target)))
        if shed_budget is not None:
            TENANT_SHED_BUDGET = min(1.0, max(1e-6, float(shed_budget)))
    tenant_slo.configure(window=window)


def set_tenant_policy(tenant: str, weight: Optional[int] = None,
                      depth: Optional[int] = None,
                      nbytes: Optional[int] = None) -> None:
    """Per-tenant override: scheduling weight (fair-share multiplier,
    >= 1) and/or quota overrides. Setting a policy on
    :data:`DEFAULT_TENANT` opts the un-tenanted stream into quotas."""
    t = validate_tenant(tenant)
    with _policy_lock:
        pol = _policies.setdefault(t, {"weight": 1, "depth": None,
                                       "bytes": None})
        if weight is not None:
            pol["weight"] = max(1, int(weight))
        if depth is not None:
            pol["depth"] = max(0, int(depth))
        if nbytes is not None:
            pol["bytes"] = max(0, int(nbytes))


def clear_tenant_policies() -> None:
    """Drop every per-tenant override (tests / reconfiguration)."""
    with _policy_lock:
        _policies.clear()


def tenant_policy(tenant: str) -> Tuple[int, int, int]:
    """Resolved ``(weight, depth_quota, byte_quota)`` for ``tenant``
    (0 = unlimited). The default tenant inherits NO quota unless a
    policy was set explicitly — lane budgets alone bound the
    un-tenanted stream, exactly the pre-tenant behavior."""
    with _policy_lock:
        pol = _policies.get(tenant)
        if pol is not None:
            depth = TENANT_DEPTH if pol["depth"] is None else pol["depth"]
            nbytes = TENANT_BYTES if pol["bytes"] is None else pol["bytes"]
            return pol["weight"], depth, nbytes
        if tenant == DEFAULT_TENANT:
            return 1, 0, 0
        return 1, TENANT_DEPTH, TENANT_BYTES


def validate_tenant(tenant: Optional[str]) -> str:
    """Normalize + validate a caller-supplied tenant id (None -> the
    default tenant). Ids are bounded and alphanumeric-ish so they are
    safe as metric/event attribute values."""
    if tenant is None:
        return DEFAULT_TENANT
    if not isinstance(tenant, str) or not _ID_RE.match(tenant):
        raise ValueError(
            f"invalid tenant id {tenant!r} (want "
            "[A-Za-z0-9][A-Za-z0-9._-]{0,63})")
    return tenant


def peer_tenant(peer_id: Optional[bytes]) -> Optional[str]:
    """The tenant tag for one real peer identity (ISSUE 15 follow-on):
    ``"peer-<first 4 bytes hex>"`` of an ed25519 node id when
    :data:`TENANT_FROM_PEER` is on, else ``None`` (the quota-exempt
    un-tenanted stream — byte-identical pre-adoption admission). The
    tag is derived from the PUBLIC identity alone, so every replica
    maps one peer to one tenant without coordination."""
    if not TENANT_FROM_PEER or not peer_id:
        return None
    if not isinstance(peer_id, (bytes, bytearray)) or \
            len(peer_id) < PEER_TENANT_PREFIX_BYTES:
        return None
    return "peer-" + bytes(peer_id[:PEER_TENANT_PREFIX_BYTES]).hex()


def shed_key(tenant: str) -> bytes:
    """The tenant key mixed into the content-seeded shed draw
    (:func:`stellar_tpu.crypto.audit.keep_under_shed`). Empty for the
    default tenant, so pre-tenant replicas' draws are byte-identical
    to the historical rule."""
    return b"" if tenant == DEFAULT_TENANT else tenant.encode("ascii")


def shed_keep_fraction(base_keep: float, queued_subs: int,
                       depth_quota: int, level: int = 1) -> float:
    """A tenant's effective keep fraction for one shed pass.

    Three regimes, all pure arithmetic of queue state (deterministic
    in arrival order, no clocks, no RNG):

    * **quota-less tenants** (``depth_quota`` 0 — including the
      default/un-tenanted stream): the lane-ladder fraction
      ``base_keep``, exactly the pre-tenant rule;
    * **in-quota tenants** (backlog <= their quota high-water): at
      shed level 1 (backlog) they are PROTECTED (keep 1.0) — their
      possible backlog is bounded by their quota, so the flood valve
      targets the offenders instead of taxing everyone; at level >= 2
      (dispatch-degraded — capacity itself collapsed) nobody is
      protected and they keep ``base_keep``;
    * **over-quota tenants**: ``base_keep`` divided by how far over
      high-water they sit — a flooder at 8x keeps ``base_keep / 8``:
      its own rows shed first.
    """
    if depth_quota <= 0 or queued_subs <= 0:
        return base_keep
    highwater = max(1, int(depth_quota * TENANT_HIGHWATER_FRAC))
    over = queued_subs / highwater
    if over <= 1.0:
        return base_keep if level >= 2 else 1.0
    return base_keep / over


class TenantLaneQueue:
    """Deterministic weighted-fair queue of admitted submissions for
    ONE lane: per-tenant FIFOs under start-time fair queueing.

    Virtual-time accounting (all Python ints, exact):

    * a submission of ``n`` items from tenant ``t`` (weight ``w``)
      gets ``vstart = max(lane_vtime, t's last vfinish)`` and
      ``vfinish = vstart + max(1, n) * WFQ_SCALE // w``;
    * :meth:`pop` serves the tenant head with the smallest
      ``(vfinish, seq)`` — seq (the admission sequence number) breaks
      ties, so the minimum is unique and the dispatch order is a pure
      function of arrival order;
    * lane virtual time advances to the served head's ``vstart``
      (start-time fair queueing), so a tenant idling through a busy
      period re-enters at the CURRENT virtual time — it cannot bank
      idle credit and then monopolize the lane.

    No clocks, no RNG, no per-process hash state anywhere in the
    decision path (nondet-lint scoped, no allowlist). NOT thread-safe
    by itself: every method is called with the owning service's
    condition variable held."""

    __slots__ = ("_q", "_vfin_last", "_vtime", "_bytes", "_len")

    def __init__(self):
        self._q: Dict[str, deque] = {}
        self._vfin_last: Dict[str, int] = {}
        self._vtime = 0
        self._bytes: Dict[str, int] = {}
        # maintained submission count: __len__ runs on EVERY admission
        # check and gauge publish under the service's hot lock, so it
        # must not walk the per-tenant FIFOs
        self._len = 0

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def depth(self, tenant: str) -> int:
        """Queued submissions for ``tenant`` (the depth-quota check)."""
        q = self._q.get(tenant)
        return len(q) if q else 0

    def queued_bytes(self, tenant: str) -> int:
        """Queued bytes for ``tenant`` (the byte-quota check)."""
        return self._bytes.get(tenant, 0)

    def tenant_depths(self) -> Dict[str, int]:
        """{tenant: queued submissions} over nonempty tenants (the
        shed pass reads this once per pass)."""
        return {t: len(q) for t, q in self._q.items() if q}

    def push(self, tkt, weight: int) -> None:
        """Admit one ticket (its ``tenant``/``n_items``/``_nbytes``
        already set); stamps ``_vstart``/``_vfinish`` on the ticket."""
        t = tkt.tenant
        vstart = max(self._vtime, self._vfin_last.get(t, 0))
        cost = max(1, tkt.n_items) * WFQ_SCALE // max(1, weight)
        tkt._vstart = vstart
        tkt._vfinish = vstart + cost
        self._vfin_last[t] = tkt._vfinish
        self._q.setdefault(t, deque()).append(tkt)
        self._bytes[t] = self._bytes.get(t, 0) + tkt._nbytes
        self._len += 1

    def _best(self):
        """The head ticket with the smallest (vfinish, seq) — the
        WFQ decision. Dict iteration is insertion-ordered, itself a
        function of arrival order, and seq is globally unique, so the
        minimum (and thus the whole dispatch order) is replica-exact."""
        best = None
        for q in self._q.values():
            if not q:
                continue
            head = q[0]
            if best is None or \
                    (head._vfinish, head._seq) < (best._vfinish,
                                                  best._seq):
                best = head
        return best

    def peek(self):
        """The ticket :meth:`pop` would serve next (or None)."""
        return self._best()

    def pop(self, head=None):
        """Serve the WFQ winner: returns ``(ticket, decision)`` or
        ``None``. ``decision`` is the replay-testable record of this
        scheduling choice — the chosen tenant/seq, its virtual times,
        the lane virtual time it advanced, and the candidate window
        the choice was made over. Pass the ticket a preceding
        :meth:`peek` returned (with no intervening mutation) to skip
        re-running the winner scan — the collect loop peeks to check
        batch fit, and the scan is O(active tenants)."""
        if head is None:
            head = self._best()
        if head is None:
            return None
        t = head.tenant
        candidates = sum(1 for q in self._q.values() if q)
        self._q[t].popleft()
        self._len -= 1
        self._bytes[t] = max(0, self._bytes.get(t, 0) - head._nbytes)
        self._vtime = max(self._vtime, head._vstart)
        self._prune(t)
        decision = {"tenant": t, "seq": head._seq,
                    "vstart": head._vstart, "vfinish": head._vfinish,
                    "vtime": self._vtime, "candidates": candidates}
        return head, decision

    def _prune(self, tenant: str) -> None:
        """Drop idle per-tenant state once it can no longer influence
        a decision: an empty FIFO whose last vfinish is <= the lane
        virtual time would resolve to the same vstart either way, so
        forgetting it keeps memory proportional to ACTIVE tenants, not
        every tenant ever seen."""
        q = self._q.get(tenant)
        if q is not None and not q:
            del self._q[tenant]
            self._bytes.pop(tenant, None)
            if self._vfin_last.get(tenant, 0) <= self._vtime:
                self._vfin_last.pop(tenant, None)

    def oldest_seq(self) -> Optional[int]:
        """Smallest admission seq among tenant heads — what the
        service's sequence-based aging rule compares across lanes."""
        heads = [q[0]._seq for q in self._q.values() if q]
        return min(heads) if heads else None

    def drain_if(self, keep_fn) -> list:
        """Filter the whole lane in one deterministic sweep (the shed
        pass / abort path): ``keep_fn(ticket)`` decides per ticket;
        removed tickets are returned in iteration order (tenant
        insertion order, FIFO within tenant) with accounting updated.
        ``keep_fn=None`` removes everything."""
        removed = []
        for t in list(self._q):
            q = self._q[t]
            kept: deque = deque()
            while q:
                tkt = q.popleft()
                if keep_fn is not None and keep_fn(tkt):
                    kept.append(tkt)
                else:
                    removed.append(tkt)
                    self._len -= 1
                    self._bytes[t] = max(
                        0, self._bytes.get(t, 0) - tkt._nbytes)
            if kept:
                self._q[t] = kept
            else:
                self._prune(t)
        return removed


# ---------------- per-tenant SLO burn rates ----------------


class TenantSloMonitor:
    """Per-tenant error-budget accounting — the PR 10 ``SloMonitor``
    discipline (event-count sliding windows, no wall-clock buckets)
    keyed by tenant, with the metric-cardinality guard built in.

    Two objectives per tenant, same semantics as the lane monitor:

    * **latency** — fraction of completed items whose lane wait
      exceeded :data:`TENANT_P99_MS`, budgeted at
      ``1 - TENANT_LATENCY_TARGET``;
    * **completion** — fraction of terminal items that were
      shed/rejected/failed, budgeted at :data:`TENANT_SHED_BUDGET`.

    Cardinality: at most :data:`TENANT_TRACK_CAP` tenants carry
    individual windows (later arrivals fold into
    :data:`OTHER_TENANT`, counted in ``overflow_folded``), and the
    ONLY gauges ever minted are the rank-keyed
    ``crypto.verify.tenant.topk.<rank>.{burn_rate,shed_burn_rate,
    latency_burn_rate,id}`` set (K of them), the ``tenant.other.*``
    rollup, and two accounting gauges — a fixed series budget however
    many tenants exist or churn through the top-K."""

    def __init__(self, window: Optional[int] = None):
        self._lock = threading.Lock()
        self._window = TENANT_SLO_WINDOW if window is None \
            else max(8, int(window))
        # tenant -> {"lat": state, "comp": state}; state is the
        # SloMonitor shape: deque of 0/1 + running counters
        self._tenants: Dict[str, dict] = {}
        self._overflow_folded = 0
        self._events = 0
        # highest rank ever published: a shrunken top-K (fewer
        # tenants, or a lowered TENANT_TOPK push) must ZERO the ranks
        # it no longer writes — the registry has no delete, and a
        # frozen stale burn rate on a dashboard is worse than none
        self._published_ranks = 0

    # window-state machinery is the shared metrics helpers (ONE
    # implementation for the lane and tenant monitors)
    _fresh = staticmethod(fresh_burn_window)

    def configure(self, window: Optional[int] = None) -> None:
        if window is None:
            return
        with self._lock:
            self._window = max(8, int(window))
            for st in self._tenants.values():
                for obj in st.values():
                    self._trim_locked(obj)

    def _trim_locked(self, st: dict) -> None:
        trim_burn_window(st, self._window)

    def _state_locked(self, tenant: str) -> dict:
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= TENANT_TRACK_CAP and \
                    tenant != OTHER_TENANT:
                self._overflow_folded += 1
                return self._state_locked(OTHER_TENANT)
            st = self._tenants[tenant] = {"lat": self._fresh(),
                                          "comp": self._fresh()}
        return st

    def _push_locked(self, st: dict, bad: bool, n: int) -> None:
        push_burn_window(st, bad, n, self._window)

    def note_latency(self, tenant: str, wait_ms: float,
                     n: int = 1) -> None:
        """``n`` of ``tenant``'s items completed with this lane wait
        (the same allowlisted stamp the lane histograms consume — the
        monitor itself never reads a clock)."""
        bad = wait_ms > TENANT_P99_MS
        with self._lock:
            self._push_locked(self._state_locked(tenant)["lat"],
                              bad, n)
            publish = self._tick_locked(n)
        if publish:
            self.publish_topk()

    def note_completion(self, tenant: str, ok: bool,
                        n: int = 1) -> None:
        """``n`` of ``tenant``'s items reached a terminal state
        (``ok=False`` for shed / quota-rejected / failed)."""
        with self._lock:
            self._push_locked(self._state_locked(tenant)["comp"],
                              not ok, n)
            publish = self._tick_locked(n)
        if publish:
            self.publish_topk()

    def _tick_locked(self, n: int) -> bool:
        """Deterministic publish cadence: refresh the rank-keyed
        gauges every 512 recorded events (event-count, not clock)."""
        before = self._events
        self._events += n
        return (before // 512) != (self._events // 512)

    @staticmethod
    def _burns(st: dict) -> Tuple[float, float]:
        """(latency_burn, shed_burn) over the current windows."""
        out = []
        for key, budget in (("lat", max(1e-9,
                                        1.0 - TENANT_LATENCY_TARGET)),
                            ("comp", max(1e-9, TENANT_SHED_BUDGET))):
            obj = st[key]
            n = len(obj["events"])
            frac = (obj["bad"] / n) if n else 0.0
            out.append(round(frac / budget, 4))
        return out[0], out[1]

    def _ranked_locked(self) -> List[tuple]:
        """[(combined_burn, latency_burn, shed_burn, tenant)] sorted
        worst-first; ties break by tenant id so the ranking (and the
        published gauge set) is deterministic."""
        rows = []
        for t, st in self._tenants.items():
            lat, comp = self._burns(st)
            rows.append((max(lat, comp), lat, comp, t))
        rows.sort(key=lambda r: (-r[0], r[3]))
        return rows

    def publish_topk(self) -> List[dict]:
        """Refresh the rank-keyed burn gauges: top-K tenants by burn
        rate individually, everyone else aggregated into the
        ``tenant.other`` rollup. Returns the published top rows (the
        admin/telemetry payload)."""
        with self._lock:
            k = TENANT_TOPK
            ranked = self._ranked_locked()
            top = ranked[:k]
            rest = ranked[k:]
            # the rollup aggregates the REST's window counts, so its
            # burn is the population's, not an average of averages
            o_lat_bad = o_lat_n = o_comp_bad = o_comp_n = 0
            for _b, _l, _c, t in rest:
                st = self._tenants[t]
                o_lat_bad += st["lat"]["bad"]
                o_lat_n += len(st["lat"]["events"])
                o_comp_bad += st["comp"]["bad"]
                o_comp_n += len(st["comp"]["events"])
            tracked = len(self._tenants)
            overflow = self._overflow_folded
            stale_ranks = range(len(top), self._published_ranks)
            self._published_ranks = len(top)
        out = []
        for i in stale_ranks:
            base = f"crypto.verify.tenant.topk.{i}"
            registry.gauge(f"{base}.burn_rate").set(0.0)
            registry.gauge(f"{base}.latency_burn_rate").set(0.0)
            registry.gauge(f"{base}.shed_burn_rate").set(0.0)
            registry.gauge(f"{base}.id").set("")
        for i, (burn, lat, comp, t) in enumerate(top):
            base = f"crypto.verify.tenant.topk.{i}"
            registry.gauge(f"{base}.burn_rate").set(burn)
            registry.gauge(f"{base}.latency_burn_rate").set(lat)
            registry.gauge(f"{base}.shed_burn_rate").set(comp)
            registry.gauge(f"{base}.id").set(t)
            out.append({"rank": i, "tenant": t, "burn_rate": burn,
                        "latency_burn_rate": lat,
                        "shed_burn_rate": comp})
        lat_budget = max(1e-9, 1.0 - TENANT_LATENCY_TARGET)
        comp_budget = max(1e-9, TENANT_SHED_BUDGET)
        registry.gauge("crypto.verify.tenant.other.latency_burn_rate"
                       ).set(round((o_lat_bad / o_lat_n) / lat_budget,
                                   4) if o_lat_n else 0.0)
        registry.gauge("crypto.verify.tenant.other.shed_burn_rate"
                       ).set(round((o_comp_bad / o_comp_n)
                                   / comp_budget, 4)
                             if o_comp_n else 0.0)
        registry.gauge("crypto.verify.tenant.other.tenants").set(
            max(0, tracked - len(top)))
        registry.gauge("crypto.verify.tenant.tracked").set(tracked)
        registry.gauge("crypto.verify.tenant.overflow_folded").set(
            overflow)
        return out

    def burn_rates(self, tenant: str) -> Optional[dict]:
        """One tenant's current burn rates (None if untracked)."""
        with self._lock:
            st = self._tenants.get(tenant)
            if st is None:
                return None
            lat, comp = self._burns(st)
            return {"latency_burn_rate": lat, "shed_burn_rate": comp,
                    "latency_n": len(st["lat"]["events"]),
                    "completion_n": len(st["comp"]["events"])}

    def snapshot(self, top: Optional[int] = None) -> dict:
        """The ``tenant`` admin-route SLO payload: top rows (also
        refreshes the rank-keyed gauges), rollup accounting, window
        config."""
        rows = self.publish_topk()
        if top is not None:
            rows = rows[:max(0, int(top))]
        with self._lock:
            return {
                "window": self._window,
                "tracked": len(self._tenants),
                "track_cap": TENANT_TRACK_CAP,
                "overflow_folded": self._overflow_folded,
                "topk": TENANT_TOPK,
                "p99_ms": TENANT_P99_MS,
                "latency_target": TENANT_LATENCY_TARGET,
                "shed_budget": TENANT_SHED_BUDGET,
                "top": rows,
            }

    def _reset_for_testing(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._overflow_folded = 0
            self._events = 0


# process-wide monitor (every service instance feeds it, like the
# lane SloMonitor — one node per process in production)
tenant_slo = TenantSloMonitor()
