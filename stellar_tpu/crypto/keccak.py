"""Keccak-256 (original 0x01 padding, as used by Ethereum and by
soroban's ``compute_hash_keccak256`` host function — reference scope:
the env interface the vendored soroban-env-host exports to contracts;
this is the pre-NIST Keccak, NOT SHA3-256's 0x06 domain byte).

Pure-Python Keccak-f[1600] sponge. Contract-host use only (per-call
inputs are budget-capped); the TPU batch path for signatures stays on
the ed25519 kernels.
"""

from __future__ import annotations

__all__ = ["keccak256"]

_ROUND_CONSTANTS = [
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
]

# rotation offsets r[x][y] laid out by flat index x + 5*y
_ROTATIONS = [
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
]

_M64 = (1 << 64) - 1

_RATE = 136  # 1088-bit rate for 256-bit output


def _rol(v: int, s: int) -> int:
    return ((v << s) | (v >> (64 - s))) & _M64


def _keccak_f(a: list) -> None:
    """In-place Keccak-f[1600] permutation over 25 lanes."""
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20]
             for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rol(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(0, 25, 5):
                a[x + y] ^= d[x]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rol(
                    a[x + 5 * y], _ROTATIONS[x + 5 * y])
        # chi
        for x in range(5):
            for y in range(0, 25, 5):
                a[x + y] = b[x + y] ^ ((~b[(x + 1) % 5 + y]) &
                                       b[(x + 2) % 5 + y] & _M64)
        # iota
        a[0] ^= rc


def keccak256(data: bytes) -> bytes:
    state = [0] * 25
    # absorb with multi-rate padding, domain byte 0x01
    padded = data + b"\x01" + b"\x00" * (_RATE - 1 - len(data) % _RATE)
    padded = padded[:len(padded) - 1] + bytes([padded[-1] | 0x80])
    for off in range(0, len(padded), _RATE):
        block = padded[off:off + _RATE]
        for i in range(_RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i:8 * i + 8], "little")
        _keccak_f(state)
    # squeeze 32 bytes (single block: 32 < rate)
    return b"".join(state[i].to_bytes(8, "little") for i in range(4))
