"""BLS12-381 hash-to-curve (RFC 9380 hash_to_curve / SSWU + isogeny).

The isogeny constants in ``_h2c_constants.py`` are DERIVED AND VERIFIED
from first principles by ``tools/derive_h2c.py`` (Velu quotient by the
rational order-11 subgroup for G1, the Galois-stable 3-kernel for G2,
dual isogeny by linear solve against the multiplication-by-ell map).
The derivation independently reproduced the RFC's own published
parameters — G1 E' A' = 0x144698a3..., Z = 11; G2 B' = 1012(1+i),
Z = -(2+i); G2 h_eff = 3(z^2-1)·h2 — and an external RFC-test-vector
cross-check pinned the one freedom Velu cannot see (the Aut(E)
representative on the j=0 codomain, carried as ``post_x_mul`` /
``post_y_mul``). G1 is byte-exact against the RFC vectors.

Suites: BLS12381G1_XMD:SHA-256_SSWU_RO_ and
BLS12381G2_XMD:SHA-256_SSWU_RO_ (the ciphersuites the soroban host's
``bls12_381_hash_to_g1``/``_g2`` use; the DST is caller-supplied).
Reference boundary: the p22 soroban host's CAP-59 exports
(/root/reference/src/rust/Cargo.toml:51-80).
"""

import hashlib

from stellar_tpu.crypto import _h2c_constants as C
from stellar_tpu.crypto.bls12_381 import (
    _FP2_OPS, _FP_OPS, _f2_add, _f2_inv, _f2_mul, _f2_neg, _f2_sub,
    _pt_add, _pt_mul, P,
)

__all__ = ["hash_to_g1", "hash_to_g2", "map_fp_to_g1", "map_fp2_to_g2",
           "expand_message_xmd", "hash_to_field_fp", "hash_to_field_fp2"]

_L = 64  # ceil((381 + 128) / 8), both fields


# ---------------------------------------------------------------------------
# expand_message_xmd (RFC 9380 §5.3.1, SHA-256)
# ---------------------------------------------------------------------------

def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    h = hashlib.sha256
    b_in_bytes = 32
    s_in_bytes = 64
    ell = -(-len_in_bytes // b_in_bytes)
    if ell > 255 or len_in_bytes > 65535 or len(dst) > 255:
        raise ValueError("expand_message_xmd parameter overflow")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(s_in_bytes)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = h(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b1 = h(b0 + b"\x01" + dst_prime).digest()
    out = [b1]
    prev = b1
    for i in range(2, ell + 1):
        prev = h(bytes(x ^ y for x, y in zip(b0, prev)) +
                 bytes([i]) + dst_prime).digest()
        out.append(prev)
    return b"".join(out)[:len_in_bytes]


def hash_to_field_fp(msg: bytes, dst: bytes, count: int):
    uniform = expand_message_xmd(msg, dst, count * _L)
    return [int.from_bytes(uniform[i * _L:(i + 1) * _L], "big") % P
            for i in range(count)]


def hash_to_field_fp2(msg: bytes, dst: bytes, count: int):
    uniform = expand_message_xmd(msg, dst, count * 2 * _L)
    out = []
    for i in range(count):
        off = i * 2 * _L
        c0 = int.from_bytes(uniform[off:off + _L], "big") % P
        c1 = int.from_bytes(uniform[off + _L:off + 2 * _L], "big") % P
        out.append((c0, c1))
    return out


# ---------------------------------------------------------------------------
# sqrt / sgn0 (not provided by bls12_381's op bundles)
# ---------------------------------------------------------------------------

def _fp_sqrt(a):
    s = pow(a, (P + 1) // 4, P)  # P % 4 == 3
    return s if s * s % P == a % P else None


def _fp_is_square(a):
    return a % P == 0 or pow(a, (P - 1) // 2, P) == 1


def _fp2_is_square(a):
    if a[0] % P == 0 and a[1] % P == 0:
        return True
    n = (a[0] * a[0] + a[1] * a[1]) % P
    return pow(n, (P - 1) // 2, P) == 1


def _fp2_sqrt(a):
    a0, a1 = a[0] % P, a[1] % P
    if a1 == 0:
        s = _fp_sqrt(a0)
        if s is not None:
            return (s, 0)
        s = _fp_sqrt((-a0) % P)
        return None if s is None else (0, s)
    n = (a0 * a0 + a1 * a1) % P
    s = _fp_sqrt(n)
    if s is None:
        return None
    inv2 = (P + 1) // 2
    for sg in (s, (-s) % P):
        x0 = _fp_sqrt((a0 + sg) * inv2 % P)
        if not x0:
            continue
        x1 = a1 * pow(2 * x0 % P, P - 2, P) % P
        if _f2_mul((x0, x1), (x0, x1)) == (a0, a1):
            return (x0, x1)
    return None


def _sgn0_fp(x):
    return x % 2


def _sgn0_fp2(x):
    # RFC 9380 §4.1 sgn0 for m=2
    return x[0] % 2 if x[0] % P != 0 else x[1] % 2


class _FpExt:
    """SSWU-side field bundle for Fp (bls12_381's _Ops lacks
    sqrt/is_square/sgn0, and its point code is pinned to A = 0 — the
    isogenous curve E' has A != 0, so the SSWU internals stay here)."""
    add = staticmethod(lambda a, b: (a + b) % P)
    sub = staticmethod(lambda a, b: (a - b) % P)
    mul = staticmethod(lambda a, b: (a * b) % P)
    neg = staticmethod(lambda a: (-a) % P)
    inv = staticmethod(lambda a: pow(a, P - 2, P))
    is_zero = staticmethod(lambda a: a % P == 0)
    is_square = staticmethod(_fp_is_square)
    sqrt = staticmethod(_fp_sqrt)
    sgn0 = staticmethod(_sgn0_fp)
    one = 1


class _Fp2Ext:
    add = staticmethod(_f2_add)
    sub = staticmethod(_f2_sub)
    mul = staticmethod(_f2_mul)
    neg = staticmethod(_f2_neg)
    inv = staticmethod(_f2_inv)
    is_zero = staticmethod(lambda a: a[0] % P == 0 and a[1] % P == 0)
    is_square = staticmethod(_fp2_is_square)
    sqrt = staticmethod(_fp2_sqrt)
    sgn0 = staticmethod(_sgn0_fp2)
    one = (1, 0)


def _from_int(F, n):
    return n % P if F is _FpExt else (n % P, 0)


# ---------------------------------------------------------------------------
# simplified SWU + isogeny evaluation
# ---------------------------------------------------------------------------

def _sswu(F, A, B, Z, u, consts=None):
    """RFC 9380 §6.6.2 simplified SWU: u -> (x, y) on E': y^2 =
    x^3 + A x + B. ``consts`` optionally carries the precomputed
    per-curve inversions (-B/A and B/(Z*A))."""
    u2 = F.mul(u, u)
    zu2 = F.mul(Z, u2)
    tv = F.add(F.mul(zu2, zu2), zu2)          # Z^2 u^4 + Z u^2
    if consts is None:
        consts = (F.mul(F.neg(B), F.inv(A)),
                  F.mul(B, F.inv(F.mul(Z, A))))
    if F.is_zero(tv):
        x1 = consts[1]                        # exceptional case
    else:
        x1 = F.mul(consts[0], F.add(F.one, F.inv(tv)))

    def g(x):
        return F.add(F.add(F.mul(F.mul(x, x), x), F.mul(A, x)), B)

    gx1 = g(x1)
    if F.is_square(gx1):
        x, y = x1, F.sqrt(gx1)
    else:
        x2 = F.mul(zu2, x1)
        y = F.sqrt(g(x2))
        if y is None:  # cannot happen for valid Z; defensive
            raise ValueError("SSWU: neither branch square")
        x = x2
    if F.sgn0(u) != F.sgn0(y):
        y = F.neg(y)
    return x, y


def _iso_eval(F, cfg, x, y):
    """Evaluate the derived dual isogeny E' -> E at (x, y):
    X = N(x)/D(x), Y = y * (N'D - ND')(x) / (ell * D(x)^2), then the
    Aut(E) post-composition pinned by the RFC-vector cross-check."""
    num = cfg["iso_num"]
    den = cfg["iso_den"]

    def ev(poly, at):
        acc = None
        for c in reversed(poly):
            acc = c if acc is None else F.add(F.mul(acc, at), c)
        return acc

    def evd(poly, at):  # derivative eval
        acc = None
        for i in range(len(poly) - 1, 0, -1):
            term = F.mul(poly[i], _from_int(F, i))
            acc = term if acc is None else F.add(F.mul(acc, at), term)
        return acc

    d = ev(den, x)
    if F.is_zero(d):
        return None  # maps to infinity
    n_ = ev(num, x)
    dinv = F.inv(d)
    X = F.mul(n_, dinv)
    slope = F.sub(F.mul(evd(num, x), d), F.mul(n_, evd(den, x)))
    Y = F.mul(F.mul(y, F.mul(slope, F.mul(dinv, dinv))),
              cfg["_ell_inv"])
    return (F.mul(X, cfg["post_x_mul"]), F.mul(Y, cfg["post_y_mul"]))


def _prep_cfg(F, cfg):
    """Memoize the per-curve constant inversions on the config dict
    (they never change; inversions dominate the per-map field cost)."""
    if "_sswu_consts" not in cfg:
        A, B, Z = cfg["A2"], cfg["B2"], cfg["Z"]
        cfg["_sswu_consts"] = (F.mul(F.neg(B), F.inv(A)),
                              F.mul(B, F.inv(F.mul(Z, A))))
        cfg["_ell_inv"] = F.inv(_from_int(F, cfg["ell"]))
    return cfg


def _map_to_curve(F, cfg, u):
    """RFC 9380 map_to_curve: SSWU + isogeny, NO cofactor clearing —
    exactly the reference host's map_fp(2)_to_g1(2) semantics (arkworks
    WBMap); the output is on E but generally NOT in the r-subgroup."""
    _prep_cfg(F, cfg)
    x, y = _sswu(F, cfg["A2"], cfg["B2"], cfg["Z"], u,
                 cfg["_sswu_consts"])
    return _iso_eval(F, cfg, x, y)


# ---------------------------------------------------------------------------
# public maps (point arithmetic on E reuses bls12_381's shared code)
# ---------------------------------------------------------------------------

def map_fp_to_g1(u: int):
    """RFC 9380 map_to_curve for one Fp element: SSWU + isogeny, NO
    cofactor clearing (the reference host's map_fp_to_g1 returns the
    uncleared point — on-curve, generally outside the r-subgroup).
    Returns an affine (x, y) point on E or None (infinity)."""
    return _map_to_curve(_FpExt, C.G1, u % P)


def map_fp2_to_g2(u):
    return _map_to_curve(_Fp2Ext, C.G2, (u[0] % P, u[1] % P))


def hash_to_g1(msg: bytes, dst: bytes):
    """RFC 9380 hash_to_curve (random-oracle variant) into G1."""
    u0, u1 = hash_to_field_fp(msg, dst, 2)
    q0 = _map_to_curve(_FpExt, C.G1, u0)
    q1 = _map_to_curve(_FpExt, C.G1, u1)
    s = _pt_add(_FP_OPS, q0, q1)
    return _pt_mul(_FP_OPS, C.H_EFF_G1, s, reduce=False) \
        if s is not None else None


def hash_to_g2(msg: bytes, dst: bytes):
    u0, u1 = hash_to_field_fp2(msg, dst, 2)
    q0 = _map_to_curve(_Fp2Ext, C.G2, u0)
    q1 = _map_to_curve(_Fp2Ext, C.G2, u1)
    s = _pt_add(_FP2_OPS, q0, q1)
    return _pt_mul(_FP2_OPS, C.H_EFF_G2, s, reduce=False) \
        if s is not None else None
