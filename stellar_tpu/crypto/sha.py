"""SHA-256 / SHA-512, HMAC-SHA256, and HKDF.

Mirrors the reference's hashing surface (``src/crypto/SHA.h:60-63``:
``sha256``, ``SHA256`` incremental hasher, ``hmacSha256``,
``hmacSha256Verify``, ``hkdfExtract``, ``hkdfExpand``) on top of the
CPython built-ins (the reference wraps libsodium the same way). HKDF here
matches libsodium's crypto_kdf/RFC 5869 usage in ``PeerAuth``: extract =
HMAC(salt=0^32, ikm); expand = first 32 bytes of HMAC(prk, info || 0x01).
"""

from __future__ import annotations

import hashlib
import hmac as _hmac

__all__ = [
    "sha256", "sha512", "SHA256", "hmac_sha256", "hmac_sha256_verify",
    "hkdf_extract", "hkdf_expand",
]


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def sha512(data: bytes) -> bytes:
    return hashlib.sha512(data).digest()


class SHA256:
    """Incremental hasher with the reference's add/finish shape
    (``SHA.h`` ``SHA256::add``/``finish``; finish is single-shot)."""

    def __init__(self):
        self._h = hashlib.sha256()
        self._done = False

    def add(self, data: bytes) -> "SHA256":
        if self._done:
            raise RuntimeError("SHA256: add after finish")
        self._h.update(data)
        return self

    def finish(self) -> bytes:
        if self._done:
            raise RuntimeError("SHA256: finish twice")
        self._done = True
        return self._h.digest()

    def reset(self):
        self._h = hashlib.sha256()
        self._done = False


def hmac_sha256(key: bytes, data: bytes) -> bytes:
    # hmac.digest() rides CPython's one-shot C fast path (no HMAC
    # object construction) — overlay channels MAC every message twice
    return _hmac.digest(key, data, "sha256")


def hmac_sha256_verify(mac: bytes, key: bytes, data: bytes) -> bool:
    return _hmac.compare_digest(mac, hmac_sha256(key, data))


def hkdf_extract(ikm: bytes) -> bytes:
    """HKDF-Extract with a zero salt (reference ``SHA.cpp hkdfExtract``)."""
    return hmac_sha256(b"\x00" * 32, ikm)


def hkdf_expand(prk: bytes, info: bytes) -> bytes:
    """Single-block HKDF-Expand (reference ``SHA.cpp hkdfExpand``)."""
    return hmac_sha256(prk, info + b"\x01")
