"""X25519 ECDH for overlay channel auth (reference
``src/crypto/Curve25519.cpp`` wrapping libsodium crypto_scalarmult;
RFC 7748 semantics re-implemented on the same GF(2^255-19) the ed25519
oracle uses).

Host-side and tiny: one scalar mult per peer handshake — nowhere near
the batch-crypto hot path.
"""

from __future__ import annotations

import hmac as _hmac
import os

__all__ = ["scalarmult", "scalarmult_base", "random_secret",
           "public_from_secret", "hkdf_extract", "hkdf_expand",
           "hmac_sha256", "verify_hmac_sha256"]

P = 2 ** 255 - 19
A24 = 121665


def _clamp(k: bytes) -> int:
    n = bytearray(k)
    n[0] &= 248
    n[31] &= 127
    n[31] |= 64
    return int.from_bytes(bytes(n), "little")


try:  # OpenSSL X25519 (identical RFC 7748 clamping/semantics; the
    # pure-Python ladder below stays as the differential oracle —
    # test_crypto_host pins agreement incl. the small-order rejection)
    from cryptography.hazmat.primitives.asymmetric.x25519 import (
        X25519PrivateKey as _OsslX25519Priv,
        X25519PublicKey as _OsslX25519Pub,
    )
except ImportError:  # pragma: no cover
    _OsslX25519Priv = None


def scalarmult(secret: bytes, point: bytes) -> bytes:
    """X25519(secret, point) with libsodium's small-order rejection."""
    if len(secret) != 32 or len(point) != 32:
        raise ValueError("X25519 takes 32-byte scalar and point")
    if _OsslX25519Priv is not None:
        sk = _OsslX25519Priv.from_private_bytes(secret)
        pk = _OsslX25519Pub.from_public_bytes(point)
        try:
            return sk.exchange(pk)
        except ValueError as e:
            # OpenSSL rejects all-zero shared secrets like libsodium
            raise ValueError(
                "small-order X25519 point: all-zero shared secret"
            ) from e
    return _scalarmult_ladder(secret, point)


def _scalarmult_ladder(secret: bytes, point: bytes) -> bytes:
    """RFC 7748 Montgomery ladder (pure-Python oracle)."""
    k = _clamp(secret)
    u = int.from_bytes(point, "little") & ((1 << 255) - 1)
    x1 = u % P
    x2, z2 = 1, 0
    x3, z3 = x1, 1
    swap = 0
    for t in reversed(range(255)):
        kt = (k >> t) & 1
        swap ^= kt
        if swap:
            x2, x3 = x3, x2
            z2, z3 = z3, z2
        swap = kt
        a = (x2 + z2) % P
        aa = a * a % P
        b = (x2 - z2) % P
        bb = b * b % P
        e = (aa - bb) % P
        c = (x3 + z3) % P
        d = (x3 - z3) % P
        da = d * a % P
        cb = c * b % P
        x3 = (da + cb) % P
        x3 = x3 * x3 % P
        z3 = (da - cb) % P
        z3 = z3 * z3 % P
        z3 = z3 * x1 % P
        x2 = aa * bb % P
        z2 = e * (aa + A24 * e) % P
    if swap:
        x2, x3 = x3, x2
        z2, z3 = z3, z2
    out = x2 * pow(z2, P - 2, P) % P
    # libsodium's crypto_scalarmult fails on small-order peer points
    # (all-zero shared secret); without this a malicious peer could force
    # session keys derived from public data alone.
    if out == 0:
        raise ValueError("small-order X25519 point: all-zero shared secret")
    return out.to_bytes(32, "little")


BASE_POINT = (9).to_bytes(32, "little")


def scalarmult_base(secret: bytes) -> bytes:
    return scalarmult(secret, BASE_POINT)


def random_secret() -> bytes:
    return os.urandom(32)


def public_from_secret(secret: bytes) -> bytes:
    return scalarmult_base(secret)


# single KDF implementation lives in crypto/sha.py


from stellar_tpu.crypto.sha import (  # noqa: E402,F401
    hkdf_expand, hkdf_extract,
)


# one shared implementation (crypto/sha.py) — it MACs every overlay
# message twice (send + receive verify), so it rides hmac.digest()'s
# one-shot C fast path there
from stellar_tpu.crypto.sha import hmac_sha256  # noqa: E402,F401


def verify_hmac_sha256(key: bytes, msg: bytes, mac: bytes) -> bool:
    return _hmac.compare_digest(hmac_sha256(key, msg), mac)
