"""Streaming wire ingress (ISSUE 19): the zero-copy front door.

Every earlier gate fed the verify tier from in-process Python; this
module is the real network edge the ROADMAP promised — a
length-prefixed binary frame protocol (``stellar_tpu/utils/wire.py``)
over a local socket, terminating in the PR 17
:class:`~stellar_tpu.crypto.fleet.FleetRouter` (or a single
:class:`~stellar_tpu.crypto.verify_service.VerifyService`) as its
intended front door.

**Zero-copy path.** Each connection reader ``recv_into``\\ s frame
bodies directly into buffers leased from a
:class:`~stellar_tpu.parallel.hostbuf.HostBufferPool` and decodes
items in place: message bytes enter the service queues as
:class:`memoryview` slices of the lease (``pk``/``sig`` are 96 fixed
hashable bytes), and the lease is refcounted per frame — the buffer
is reused only after every ticket decoded from it reached a terminal
and its response left on the wire, so the donated-buffer dispatch
path reads wire bytes that were copied exactly once (kernel →
lease).

**Traces start on the wire.** The reader allocates a contiguous
trace block (``verify_service._alloc_trace_block``) the moment a
SUBMIT frame's preamble decodes — before admission — and emits an
``ingress.frame`` recorder event, so a ``trace?id=`` timeline begins
at the wire and survives refusal (the typed
:class:`~stellar_tpu.utils.resilience.Overloaded` is serialized back
as a canonical-JSON REFUSAL frame carrying
kind/lane/reason/tenant/replica/trace_lo) and fleet handoff
(``FleetRouter.submit(trace_lo=...)`` keeps the block through a
replica kill).

**Conservation extends to the wire.** Under the server's one
condition variable, at every snapshot, EXACTLY::

    frames_received == decoded_frames + malformed_frames
    items_decoded   == accepted + refused
    accepted        == resolved + shed + failed + pending

(the last sum feeds the service/fleet law: an accepted item is the
service's ``submitted``). ``snapshot()["conservation_gap"]`` is the
sum of the three residuals' magnitudes — 0 or the tier-1
``INGRESS_OK`` gate (``tools/ingress_selfcheck.py``) fails.

**No lock across any socket op.** Socket reads are exactly the
blocking calls the PR 18 lock-order prover hunts: every
``accept``/``recv_into``/``sendall`` here happens with NO lock held;
counters mutate under ``self._cv`` strictly after the I/O completes.
This module sits in both consensus lint scopes and the lockorder
graph with ZERO allowlist entries (pinned in
``tests/test_analysis.py``) — which also means it reads no clock:
read deadlines ride ``socket.settimeout`` plus event counts
(timeout-poll counts per frame, recv-call budgets), never
``time.monotonic``.

**A slow client cannot wedge the node.** The accept loop only ever
accepts; each connection gets its own reader + responder daemons.
Per-connection defenses: a mid-frame read deadline (a torn frame
must make progress every ``read_deadline_s``), a recv-call budget
per frame (a 1-byte-per-recv trickler is cut off after
``frame_recv_limit`` recvs), a total byte budget, and a frame-size
ceiling enforced on the DECLARED length, before any buffering. A
protocol violation gets a best-effort typed ERROR frame, then the
connection drops — a poisoned stream is never resynced.

**Zero-loss drain.** ``stop()`` closes the listener and stops
reading, but every already-admitted ticket is flushed: responders
keep draining until each pending ticket reaches a terminal (verdict,
typed refusal — including post-handoff outcomes after a fleet
``kill_replica`` — or a ticketed failure) and the response is sent,
before sockets close. No ticket ends unresolved.
"""

from __future__ import annotations

import concurrent.futures
import socket
import threading
from collections import deque
from typing import Dict, Optional, Sequence

import numpy as np

from stellar_tpu.crypto import batch_verifier
from stellar_tpu.crypto import verify_service as vs_mod
from stellar_tpu.parallel import hostbuf
from stellar_tpu.utils import faults
from stellar_tpu.utils import wire
from stellar_tpu.utils.metrics import registry
from stellar_tpu.utils.resilience import Overloaded

__all__ = ["IngressServer", "WireClient", "WireTicket",
           "ingress_health", "register_ingress_health",
           "READ_DEADLINE_S", "FRAME_RECV_LIMIT", "CONN_BYTE_BUDGET"]

# per-connection defense defaults (constructor overrides)
READ_DEADLINE_S = 5.0          # max wall time without mid-frame progress
FRAME_RECV_LIMIT = 8192        # max recv calls spent on ONE frame
CONN_BYTE_BUDGET = 1 << 30     # max bytes one connection may ever send
_POLL_S = 0.25                 # recv poll quantum (stop responsiveness)
_RESULT_TIMEOUT_S = 120.0      # max wait for one ticket's terminal

_MV = memoryview


# ---------------- admin-surface registration ----------------
# same last-started-instance policy as register_service_health /
# register_fleet_health: the telemetry report and admin routes read
# whatever server is currently serving

_health_lock = threading.Lock()
_health_provider = None
_server_ref = None


def register_ingress_health(provider) -> None:
    global _health_provider
    with _health_lock:
        _health_provider = provider


def running_server():
    """The last-started (still-running) server instance, or None —
    the journal collector (ISSUE 20) reads its wire totals through
    this, the same last-started-instance policy as the health
    surface."""
    with _health_lock:
        return _server_ref


def ingress_health() -> dict:
    """The active server's snapshot, or ``{"enabled": False}``."""
    with _health_lock:
        p = _health_provider
    if p is None:
        return {"enabled": False}
    snap = p()
    snap["enabled"] = True
    return snap


class IngressServer:
    """The wire front door over ``front`` (a FleetRouter or a
    VerifyService — anything with
    ``submit(items, lane=, tenant=, trace_lo=)``)."""

    def __init__(self, front, host: str = "127.0.0.1", port: int = 0,
                 *, max_frame_bytes: int = wire.MAX_FRAME_BYTES,
                 read_deadline_s: float = READ_DEADLINE_S,
                 frame_recv_limit: int = FRAME_RECV_LIMIT,
                 conn_byte_budget: int = CONN_BYTE_BUDGET,
                 result_timeout_s: float = _RESULT_TIMEOUT_S,
                 pool: Optional[hostbuf.HostBufferPool] = None):
        self._cv = threading.Condition()
        self._front = front
        self._host = host
        self._port = int(port)
        self.max_frame_bytes = int(max_frame_bytes)
        self.read_deadline_s = float(read_deadline_s)
        self.frame_recv_limit = int(frame_recv_limit)
        self.conn_byte_budget = int(conn_byte_budget)
        self.result_timeout_s = float(result_timeout_s)
        if pool is None:
            pool = hostbuf.HostBufferPool(
                buf_bytes=max(hostbuf.DEFAULT_BUF_BYTES,
                              self.max_frame_bytes))
        if pool.buf_bytes < self.max_frame_bytes:
            raise ValueError("pool buffers smaller than the frame "
                             "ceiling — a max-size frame must fit")
        self._pool = pool
        self._listener: Optional[socket.socket] = None
        self._accept_t: Optional[threading.Thread] = None
        self._running = False
        self._stopping = False
        self._conn_seq = 0
        self._conns: Dict[int, dict] = {}
        # the wire-extended conservation counters (module docstring) —
        # every one mutates ONLY under self._cv, strictly after the
        # socket op that justified it completed
        self._frames_received = 0
        self._decoded_frames = 0
        self._malformed_frames = 0
        self._items_decoded = 0
        self._accepted = 0
        self._refused = 0
        self._resolved = 0
        self._shed = 0
        self._failed = 0
        self._pending = 0
        self._bytes_in = 0
        self._bytes_out = 0
        self._conns_total = 0
        self._deadline_kills = 0
        self._budget_kills = 0
        self._send_failures = 0
        self._malformed_reasons: Dict[str, int] = {}

    # ---------------- lifecycle ----------------

    @property
    def port(self) -> int:
        with self._cv:
            return self._port

    def start(self) -> "IngressServer":
        with self._cv:
            if self._running:
                return self
            self._running = True
            self._stopping = False
        lst = socket.create_server((self._host, self._port))
        lst.settimeout(_POLL_S)
        t = threading.Thread(target=self._accept_loop, args=(lst,),
                             daemon=True, name="ingress-accept")
        with self._cv:
            self._listener = lst
            self._port = lst.getsockname()[1]
            self._accept_t = t
        t.start()
        register_ingress_health(self.snapshot)
        global _server_ref
        with _health_lock:
            _server_ref = self
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        """Zero-loss drain: stop accepting and reading, flush every
        admitted ticket's response, then close. ``timeout`` bounds
        each thread join (the responders themselves bound each
        ticket wait by ``result_timeout_s`` — a wedged terminal
        becomes a counted, ticketed failure, never silence)."""
        with self._cv:
            if not self._running:
                return
            self._stopping = True
            lst = self._listener
            self._listener = None
            accept_t = self._accept_t
            conns = list(self._conns.values())
            self._cv.notify_all()
        if lst is not None:
            lst.close()
        if accept_t is not None:
            accept_t.join(timeout or 30.0)
        for conn in conns:
            conn["reader_t"].join(timeout or 30.0)
        for conn in conns:
            conn["responder_t"].join(
                timeout or self.result_timeout_s + 30.0)
        with self._cv:
            self._running = False

    # ---------------- accept loop (never blocks on a client) ------

    def _accept_loop(self, lst: socket.socket) -> None:
        while True:
            with self._cv:
                if self._stopping:
                    return
            try:
                sock, _addr = lst.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.settimeout(_POLL_S)
            conn = {
                "sock": sock,
                "pending": deque(),   # FIFO of response entries
                "reader_done": False,
                "killed": False,
            }
            rt = threading.Thread(target=self._conn_reader,
                                  args=(conn,), daemon=True,
                                  name="ingress-read")
            st = threading.Thread(target=self._conn_responder,
                                  args=(conn,), daemon=True,
                                  name="ingress-respond")
            conn["reader_t"] = rt
            conn["responder_t"] = st
            with self._cv:
                cid = self._conn_seq
                self._conn_seq += 1
                conn["id"] = cid
                self._conns[cid] = conn
                self._conns_total += 1
            registry.gauge("crypto.verify.ingress.connections").set(
                len(self._conns))
            rt.start()
            st.start()

    # ---------------- per-connection reader ----------------

    def _read_exact(self, conn: dict, view, n: int,
                    mid_frame: bool) -> str:
        """Fill ``view[:n]`` from the connection. Returns ``"ok"``,
        ``"eof"`` (clean close between frames), ``"disconnect"``
        (close mid-frame), ``"deadline"`` (no mid-frame progress
        within the read deadline), ``"slow-frame"`` (recv-call
        budget for this frame exhausted), or ``"stopped"``. Clock
        discipline: the deadline is counted in ``_POLL_S`` timeout
        polls, never read from a clock."""
        sock = conn["sock"]
        got = 0
        idle_polls = 0
        max_polls = max(1, int(self.read_deadline_s / _POLL_S))
        while got < n:
            with self._cv:
                stopping = self._stopping
            if stopping and not mid_frame and got == 0:
                return "stopped"
            conn["frame_recvs"] += 1
            if conn["frame_recvs"] > self.frame_recv_limit:
                return "slow-frame"
            try:
                r = sock.recv_into(view[got:n])
            except socket.timeout:
                if mid_frame or got > 0:
                    idle_polls += 1
                    if idle_polls >= max_polls:
                        return "deadline"
                continue
            except OSError:
                return "disconnect" if (mid_frame or got) else "eof"
            if r == 0:
                return "disconnect" if (mid_frame or got) else "eof"
            idle_polls = 0
            got += r
            conn["bytes"] += r
        return "ok"

    def _conn_reader(self, conn: dict) -> None:
        lease = self._pool.lease()
        pos = 0
        header = bytearray(wire.HEADER_LEN)
        hview = _MV(header)
        try:
            while True:
                conn["frame_recvs"] = 0
                conn.setdefault("bytes", 0)
                status = self._read_exact(conn, hview,
                                          wire.HEADER_LEN,
                                          mid_frame=False)
                if status in ("eof", "stopped"):
                    return
                if status != "ok":
                    self._kill_conn(conn, status, frame=status in
                                    ("disconnect", "deadline",
                                     "slow-frame"))
                    return
                ftype, length = wire._HDR.unpack(header)
                if ftype not in (wire.SUBMIT,):
                    self._kill_conn(conn, "garbage", frame=True)
                    return
                if length > self.max_frame_bytes:
                    self._kill_conn(conn, "oversize", frame=True)
                    return
                if conn["bytes"] + length > self.conn_byte_budget:
                    self._kill_conn(conn, "byte-budget", frame=True)
                    return
                if pos + length > len(lease.buf):
                    # rotate to a fresh lease; the old buffer stays
                    # alive until its decoded frames' tickets finish
                    old = lease
                    lease = self._pool.lease()
                    pos = 0
                    self._pool.release(old)
                body = lease.mv[pos:pos + length]
                status = self._read_exact(conn, body, length,
                                          mid_frame=True)
                if status != "ok":
                    self._kill_conn(conn, status, frame=True)
                    return
                pos += length
                try:
                    req_id, lane, tenant, items = \
                        wire.decode_submit(body)
                except wire.MalformedFrame as e:
                    self._kill_conn(conn, e.reason, frame=True)
                    return
                self._admit(conn, lease, req_id, lane, tenant, items,
                            wire.HEADER_LEN + length)
        finally:
            self._pool.release(lease)
            with self._cv:
                conn["reader_done"] = True
                self._cv.notify_all()

    def _admit(self, conn: dict, lease, req_id: int, lane: str,
               tenant: Optional[str], items: list,
               frame_bytes: int) -> None:
        """One decoded SUBMIT frame → trace block, recorder event,
        admission, and EXACT counter movement (one locked section
        per outcome, after the submit attempt completed)."""
        n = len(items)
        trace_lo = vs_mod._alloc_trace_block(n)
        trange = [[trace_lo, trace_lo + n]] if n else []
        batch_verifier.note_trace_event(
            "ingress.frame", lane=lane, tenant=tenant, traces=trange,
            conn=conn["id"], req_id=req_id, items=n,
            nbytes=frame_bytes)
        entry = None
        refusal = None
        try:
            tkt = self._front.submit(items, lane=lane, tenant=tenant,
                                     trace_lo=trace_lo)
            entry = ("ticket", req_id, trace_lo, n, tkt, lease)
        except Overloaded as e:
            refusal = wire.encode_refusal(
                req_id, kind=e.kind, lane=e.lane, reason=e.reason,
                tenant=e.tenant, replica=e.replica,
                trace_lo=trace_lo, n=n, message=str(e))
        except ValueError as e:
            # semantic garbage (unknown lane / invalid tenant): a
            # typed refusal, not a dead connection — framing is fine
            refusal = wire.encode_refusal(
                req_id, kind="rejected", lane=lane,
                reason="invalid", tenant=None, replica=None,
                trace_lo=trace_lo, n=n, message=str(e))
        if entry is not None:
            # the lease must be retained BEFORE the entry becomes
            # visible to the responder (which releases it)
            self._pool.retain(lease)
        with self._cv:
            self._frames_received += 1
            self._decoded_frames += 1
            self._items_decoded += n
            self._bytes_in += frame_bytes
            if entry is not None:
                self._accepted += n
                self._pending += n
                conn["pending"].append(entry)
            else:
                self._refused += n
                conn["pending"].append(("raw", refusal))
            self._cv.notify_all()
        registry.meter("crypto.verify.ingress.frames").mark(1)
        registry.meter("crypto.verify.ingress.items").mark(n)
        registry.meter("crypto.verify.ingress.bytes_in").mark(
            frame_bytes)
        if entry is None:
            registry.meter("crypto.verify.ingress.refused").mark(n)

    def _kill_conn(self, conn: dict, reason: str,
                   frame: bool) -> None:
        """Protocol violation / budget exhaustion: best-effort typed
        ERROR frame, count (a malformed event counts as a received
        frame — the wire law stays exact), drop the read side. The
        responder still drains every already-admitted ticket."""
        try:
            conn["sock"].sendall(wire.encode_error(reason))
        except OSError:
            pass
        try:
            conn["sock"].shutdown(socket.SHUT_RD)
        except OSError:
            pass
        with self._cv:
            if frame:
                self._frames_received += 1
                self._malformed_frames += 1
                self._malformed_reasons[reason] = \
                    self._malformed_reasons.get(reason, 0) + 1
            if reason == "deadline":
                self._deadline_kills += 1
            elif reason == "byte-budget":
                self._budget_kills += 1
            conn["killed"] = True
        if frame:
            registry.meter("crypto.verify.ingress.malformed").mark(1)
        batch_verifier.note_trace_event(
            "ingress.malformed", conn=conn["id"], reason=reason)

    # ---------------- per-connection responder ----------------

    def _conn_responder(self, conn: dict) -> None:
        try:
            while True:
                entry = None
                with self._cv:
                    if conn["pending"]:
                        entry = conn["pending"].popleft()
                    elif conn["reader_done"]:
                        return
                    else:
                        self._cv.wait(0.05)
                        continue
                self._respond_one(conn, entry)
        finally:
            try:
                conn["sock"].close()
            except OSError:
                pass
            with self._cv:
                self._conns.pop(conn["id"], None)
                nconn = len(self._conns)
            registry.gauge(
                "crypto.verify.ingress.connections").set(nconn)

    def _respond_one(self, conn: dict, entry: tuple) -> None:
        if entry[0] == "raw":
            self._send_response(conn, entry[1])
            return
        # ("ticket", req_id, trace_lo, n, tkt, lease)
        _, req_id, trace_lo, n, tkt, lease = entry
        terminal = "resolved"
        try:
            out = np.asarray(
                tkt.result(timeout=self.result_timeout_s))
            fb = wire.encode_verdict(req_id, trace_lo, out.tolist())
        except Overloaded as e:
            # a typed post-admission verdict: a shed, or a refusal
            # from the survivor a fleet handoff re-homed us to —
            # either way the client gets the full typed story
            terminal = "shed"
            fb = wire.encode_refusal(
                req_id, kind=e.kind, lane=e.lane, reason=e.reason,
                tenant=e.tenant, replica=e.replica,
                trace_lo=trace_lo, n=n, message=str(e))
        except BaseException as e:  # ticketed failure, never silence
            terminal = "failed"
            fb = wire.encode_refusal(
                req_id, kind="failed", lane=None,
                reason="dispatch-error", tenant=None, replica=None,
                trace_lo=trace_lo, n=n, message=str(e))
        self._send_response(conn, fb)
        with self._cv:
            self._pending -= n
            if terminal == "resolved":
                self._resolved += n
            elif terminal == "shed":
                self._shed += n
            else:
                self._failed += n
        self._pool.release(lease)
        registry.meter(
            f"crypto.verify.ingress.{terminal}").mark(n)

    def _send_response(self, conn: dict, fb: bytes) -> None:
        sent = False
        try:
            conn["sock"].sendall(fb)
            sent = True
        except OSError:
            pass
        with self._cv:
            if sent:
                self._bytes_out += len(fb)
            else:
                self._send_failures += 1
        if sent:
            registry.meter(
                "crypto.verify.ingress.bytes_out").mark(len(fb))

    # ---------------- observability ----------------

    def journal_totals(self) -> dict:
        """Never-evicting wire totals for the unified journal (ISSUE
        20) — the ingress half of the completeness law
        (:func:`stellar_tpu.utils.journal.completeness` reconciles
        them against the fleet/service terminals). The wire counters
        depend on socket timing (how much a flooder got through), so
        the journal treats ingress as a NONDETERMINISTIC component:
        included in the completeness reconciliation, excluded from
        the bit-identity merge. No gauge side effects — journal
        collection must be a pure read (unlike :meth:`snapshot`)."""
        with self._cv:
            return {
                "frames_received": self._frames_received,
                "decoded_frames": self._decoded_frames,
                "malformed_frames": self._malformed_frames,
                "items_decoded": self._items_decoded,
                "accepted": self._accepted,
                "refused": self._refused,
                "resolved": self._resolved,
                "shed": self._shed,
                "failed": self._failed,
                "pending": self._pending,
            }

    def snapshot(self) -> dict:
        """The ingress surface: every wire counter plus the
        wire-extended conservation residual (must read 0 — the
        ``ingress.conservation_gap`` perf-sentinel row pins it at
        exactly zero in every bench record)."""
        with self._cv:
            wire_gap = self._frames_received - (
                self._decoded_frames + self._malformed_frames)
            admit_gap = self._items_decoded - (
                self._accepted + self._refused)
            term_gap = self._accepted - (
                self._resolved + self._shed + self._failed
                + self._pending)
            snap = {
                "running": self._running,
                "port": self._port,
                "connections": len(self._conns),
                "connections_total": self._conns_total,
                "frames_received": self._frames_received,
                "decoded_frames": self._decoded_frames,
                "malformed_frames": self._malformed_frames,
                "malformed_reasons": dict(self._malformed_reasons),
                "items_decoded": self._items_decoded,
                "accepted": self._accepted,
                "refused": self._refused,
                "resolved": self._resolved,
                "shed": self._shed,
                "failed": self._failed,
                "pending": self._pending,
                "bytes_in": self._bytes_in,
                "bytes_out": self._bytes_out,
                "deadline_kills": self._deadline_kills,
                "budget_kills": self._budget_kills,
                "send_failures": self._send_failures,
                "conservation_gap": (abs(wire_gap) + abs(admit_gap)
                                     + abs(term_gap)),
                "pool": self._pool.stats(),
            }
        registry.gauge("crypto.verify.ingress.pending").set(
            snap["pending"])
        registry.gauge(
            "crypto.verify.ingress.conservation_gap").set(
            snap["conservation_gap"])
        return snap


# ---------------- client ----------------

class WireTicket:
    """Client-side handle for one SUBMIT frame: quacks like a
    :class:`VerifyTicket` (``result``/``done``/``n_items``/``lane``/
    ``tenant``); ``trace_lo`` is learned from the response frame."""

    __slots__ = ("lane", "tenant", "n_items", "req_id", "trace_lo",
                 "_fut")

    def __init__(self, lane: str, tenant: Optional[str], n: int,
                 req_id: int):
        self.lane = lane
        self.tenant = tenant
        self.n_items = n
        self.req_id = req_id
        self.trace_lo: Optional[int] = None
        self._fut = concurrent.futures.Future()

    def done(self) -> bool:
        return self._fut.done()

    def result(self, timeout: Optional[float] = None):
        return self._fut.result(timeout)


class WireClient:
    """A well-behaved (or, with ``fault_point``, deliberately
    misbehaving — see ``faults.WIRE_MODES``/``faults.send_mangled``)
    wire client. Responses are correlated by ``req_id``, so they may
    arrive in any order; a reader daemon resolves tickets, rebuilding
    the typed :class:`Overloaded` from REFUSAL frames field by
    field."""

    def __init__(self, host: str, port: int, *,
                 timeout: float = 60.0,
                 fault_point: Optional[str] = None):
        self._lock = threading.Lock()
        sock = socket.create_connection((host, port),
                                        timeout=timeout)
        sock.settimeout(timeout)
        self._sock = sock
        self._fault_point = fault_point
        self._req_seq = 0
        self._pending: Dict[int, WireTicket] = {}
        self._closed = False
        self._dead = False
        self._reader = threading.Thread(
            target=self._read_loop, daemon=True, name="wire-client")
        self._reader.start()

    # -- two-step API so callers (tools/soak.py) can time the encode

    def reserve(self, lane: str, tenant: Optional[str],
                n: int) -> WireTicket:
        with self._lock:
            req_id = self._req_seq
            self._req_seq += 1
            tkt = WireTicket(lane, tenant, n, req_id)
            self._pending[req_id] = tkt
        return tkt

    def send_encoded(self, tkt: WireTicket, data: bytes) -> WireTicket:
        try:
            if self._fault_point:
                if not faults.send_mangled(self._sock, data,
                                           self._fault_point):
                    raise ConnectionError(
                        "wire fault closed the connection")
            else:
                self._sock.sendall(data)
        except OSError as e:
            self._fail_all(e)
            raise
        return tkt

    def submit(self, items: Sequence[tuple], lane: str = "bulk",
               tenant: Optional[str] = None) -> WireTicket:
        tkt = self.reserve(lane, tenant, len(items))
        data = wire.encode_submit(items, lane, tenant, tkt.req_id)
        return self.send_encoded(tkt, data)

    def verify(self, items: Sequence[tuple], lane: str = "bulk",
               tenant: Optional[str] = None,
               timeout: Optional[float] = None):
        return self.submit(items, lane, tenant).result(timeout)

    @property
    def alive(self) -> bool:
        """False once a wire fault, server kill, or close has failed
        the connection — misbehaving soak clients poll this to know
        when to reconnect."""
        with self._lock:
            return not (self._dead or self._closed)

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
        # shutdown first: it tears the connection down and wakes the
        # reader thread even while it is blocked in recv (a bare
        # close only drops this fd's reference — the kernel keeps the
        # connection alive under the blocked read, so the server
        # would never see the FIN)
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    # -- reader

    def _read_loop(self) -> None:
        dec = wire.FrameDecoder()
        try:
            while True:
                try:
                    data = self._sock.recv(65536)
                except socket.timeout:
                    with self._lock:
                        if self._closed:
                            return
                    continue
                except OSError:
                    break
                if not data:
                    break
                for ftype, decoded in dec.feed_decoded(data):
                    self._dispatch(ftype, decoded)
        except wire.MalformedFrame as e:
            self._fail_all(e)
            return
        self._fail_all(ConnectionError("ingress connection closed"))

    def _dispatch(self, ftype: int, decoded) -> None:
        if ftype == wire.VERDICT:
            req_id, trace_lo, verdicts = decoded
            tkt = self._take(req_id)
            if tkt is not None:
                tkt.trace_lo = trace_lo
                tkt._fut.set_result(np.asarray(verdicts, dtype=bool))
        elif ftype == wire.REFUSAL:
            d = decoded
            tkt = self._take(d.get("req_id"))
            if tkt is not None:
                tkt.trace_lo = d.get("trace_lo")
                n = int(d.get("n") or 0)
                lo = int(d.get("trace_lo") or 0)
                if d.get("kind") in ("rejected", "shed"):
                    tkt._fut.set_exception(Overloaded(
                        d.get("message") or "refused on the wire",
                        kind=d["kind"], lane=d.get("lane"),
                        reason=d.get("reason") or "",
                        tenant=d.get("tenant"),
                        trace_ids=range(lo, lo + n),
                        replica=d.get("replica")))
                else:
                    tkt._fut.set_exception(RuntimeError(
                        d.get("message") or "ingress failure"))
        # ERROR frames have no req_id: the server is about to close;
        # the closing recv loop fails every pending ticket

    def _take(self, req_id) -> Optional[WireTicket]:
        if req_id is None:
            return None
        with self._lock:
            return self._pending.pop(req_id, None)

    def _fail_all(self, err: BaseException) -> None:
        with self._lock:
            self._dead = True
            pend = list(self._pending.values())
            self._pending.clear()
        for tkt in pend:
            if not tkt._fut.done():
                tkt._fut.set_exception(err)
