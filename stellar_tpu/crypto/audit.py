"""Deterministic sampler for the result-integrity audit.

A corrupting accelerator returns WRONG BITS instead of hanging —
invisible to deadlines and breakers, which only see failures. Hardware
verify engines treat result cross-checking as mandatory for exactly
this reason (FPGA ECDSA verification engines re-verify on an
independent path); the dispatch layer therefore re-verifies a sampled
subset of every device-served chunk through the host oracle
(``docs/robustness.md`` "Sampled result-integrity audit").

The sample must be DETERMINISTIC IN THE BATCH CONTENT: consensus
replicas verifying the same txset must audit the same rows, or one
replica could quarantine its device (and change its serving backend)
on a batch where another did not — a latency divergence that is fine,
but it must never come from per-process randomness that the nondet
lint exists to ban. So indices are derived counter-mode from
SHA-256 of the chunk's raw bytes: same batch → same sample, on every
node, in every process. No clocks, no RNG state, no hash salts.
"""

from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

__all__ = ["sample_indices", "sample_rows", "verdict_record",
           "keep_under_shed"]


def sample_indices(material: bytes, n: int, rate: float) -> List[int]:
    """Indices in ``[0, n)`` to audit, derived deterministically from
    ``material`` (the chunk's raw bytes).

    ``rate <= 0`` disables the audit (empty sample). Otherwise the
    sample size is ``max(1, int(n * rate))`` — at least one row per
    chunk, so even a tiny rate cross-checks every dispatch. ``rate >=
    1`` audits every row (the chaos suite uses this to make a single
    corrupted sub-chunk a guaranteed catch).

    Collisions are resolved by drawing more counters, with a bounded
    budget — the sample may come up slightly short of ``k`` for
    mid-range rates, never over, and stays deterministic.
    """
    if n <= 0 or rate <= 0.0:
        return []
    k = min(n, max(1, int(n * rate + 1e-9)))
    if k >= n:
        return list(range(n))
    digest = hashlib.sha256(material).digest()
    picked: List[int] = []
    seen = set()
    ctr = 0
    budget = 4 * k + 16
    while len(picked) < k and ctr < budget:
        h = hashlib.sha256(digest + ctr.to_bytes(4, "little")).digest()
        idx = int.from_bytes(h[:8], "little") % n
        if idx not in seen:
            seen.add(idx)
            picked.append(idx)
        ctr += 1
    return picked


def sample_rows(material: bytes, eligible_rows: Sequence[int],
                rate: float) -> List[int]:
    """Sample among ELIGIBLE rows only — the rows whose device verdict
    actually decides the composed outcome (host policy gate passed).

    Rows the host policy gate already rejected compare ``False ==
    False`` against the oracle no matter what the device returned —
    sampling them would be vacuous, and since the sample is derived
    from the exact bytes the device holds, a corrupting chip could
    even predict such a blind spot. Restricting to eligible rows keeps
    every drawn sample a REAL cross-check; the eligibility mask is
    host-computed and deterministic, so replicas still agree.

    Returns row indices (in the caller's row numbering), possibly
    empty — a part with no eligible rows needs no audit, because no
    device bit in it can reach a verdict.
    """
    picks = sample_indices(material, len(eligible_rows), rate)
    return [eligible_rows[p] for p in picks]


def keep_under_shed(material: bytes, keep_fraction: float,
                    tenant: bytes = b"") -> bool:
    """Deterministic content-seeded keep/drop draw — the verify
    service's load-shed rule (``docs/robustness.md`` "Overload and
    load-shed"), same discipline as the audit sampler above: under
    identical overload pressure, replicas holding the same queued work
    shed IDENTICAL rows, because the draw is SHA-256 of the work's own
    bytes mapped uniformly into [0, 1) — no clocks, no RNG state, no
    hash salts. The draw itself never depends on queue composition (a
    submission's draw is fixed by its bytes), so survivors keep
    surviving as long as their effective keep fraction holds; only a
    pressure-level or tenant-pressure change in the FRACTION can shed
    a previous survivor.

    ``tenant`` (ISSUE 14) mixes the submitting tenant's key into the
    draw — length-prefixed, so distinct (tenant, material) splits can
    never alias — giving each tenant an independent shed stream: a
    per-tenant keep fraction then sheds a flooding tenant's own rows
    first while replicas still agree row-by-row. The empty key (the
    default/un-tenanted stream) preserves the historical draw bytes
    exactly.

    Returns True = KEEP (verify this work), False = SHED it. The
    boundary cases short-circuit without hashing: ``keep_fraction >=
    1`` keeps everything, ``<= 0`` sheds everything."""
    if keep_fraction >= 1.0:
        return True
    if keep_fraction <= 0.0:
        return False
    if tenant:
        material = (len(tenant).to_bytes(2, "little") + tenant
                    + material)
    h = hashlib.sha256(material).digest()
    draw = int.from_bytes(h[:8], "little") / float(1 << 64)
    return draw < keep_fraction


def verdict_record(device: Optional[int], lo: int, hi: int,
                   sampled: int, ok: bool) -> dict:
    """The evidence shape of one audit verdict, shared by the flight
    recorder's ``verify.audit.verdict`` events and the fault-domain
    payload of ``MULTICHIP_r*`` captures (``tools/multichip_bench.py``)
    — one definition so both streams stay comparable. Pure data: no
    clocks, no RNG (this module is in the nondet-lint scope; consumers
    that need timestamps stamp their own)."""
    return {"device": -1 if device is None else int(device),
            "rows": [int(lo), int(hi)],
            "sampled": int(sampled),
            "ok": bool(ok)}
