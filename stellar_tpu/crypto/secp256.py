"""Short-Weierstrass ECDSA for the soroban crypto host functions:
``recover_key_ecdsa_secp256k1`` and ``verify_sig_ecdsa_secp256r1``
(reference scope: the env interface soroban-env-host exposes; its
implementations are the k256/p256 RustCrypto crates).

Pure-Python Jacobian-coordinate scalar multiplication over the two
curves. Contract-host use only — per-call inputs are budget-capped and
these paths carry no ledger-close hot-loop traffic (that is ed25519,
which has the TPU batch kernels). Signatures are 64-byte ``r || s``
big-endian; public keys are 65-byte uncompressed SEC1 ``0x04 || X ||
Y`` exactly as the env functions take and return them.
"""

from __future__ import annotations

from typing import Optional, Tuple

__all__ = ["Curve", "SECP256K1", "SECP256R1", "EcdsaError",
           "verify_ecdsa", "recover_secp256k1"]


class EcdsaError(ValueError):
    pass


class Curve:
    """y^2 = x^3 + a*x + b over F_p, prime order n, generator G."""

    def __init__(self, name: str, p: int, a: int, b: int, n: int,
                 gx: int, gy: int):
        self.name = name
        self.p = p
        self.a = a
        self.b = b
        self.n = n
        self.g = (gx, gy)

    def on_curve(self, pt: Optional[Tuple[int, int]]) -> bool:
        if pt is None:
            return True
        x, y = pt
        return (y * y - (x * x * x + self.a * x + self.b)) % self.p == 0

    # ---- Jacobian arithmetic (None = point at infinity) ----

    def _double(self, pt):
        if pt is None:
            return None
        x, y, z = pt
        if y == 0:
            return None
        p = self.p
        ysq = y * y % p
        s = 4 * x * ysq % p
        m = (3 * x * x + self.a * z ** 4) % p
        nx = (m * m - 2 * s) % p
        ny = (m * (s - nx) - 8 * ysq * ysq) % p
        nz = 2 * y * z % p
        return (nx, ny, nz)

    def _add(self, p1, p2):
        if p1 is None:
            return p2
        if p2 is None:
            return p1
        p = self.p
        x1, y1, z1 = p1
        x2, y2, z2 = p2
        z1s, z2s = z1 * z1 % p, z2 * z2 % p
        u1 = x1 * z2s % p
        u2 = x2 * z1s % p
        s1 = y1 * z2s * z2 % p
        s2 = y2 * z1s * z1 % p
        if u1 == u2:
            if s1 != s2:
                return None
            return self._double(p1)
        h = (u2 - u1) % p
        r = (s2 - s1) % p
        hs = h * h % p
        hc = hs * h % p
        u1hs = u1 * hs % p
        nx = (r * r - hc - 2 * u1hs) % p
        ny = (r * (u1hs - nx) - s1 * hc) % p
        nz = h * z1 * z2 % p
        return (nx, ny, nz)

    def _to_affine(self, pt):
        if pt is None:
            return None
        x, y, z = pt
        zi = pow(z, self.p - 2, self.p)
        zis = zi * zi % self.p
        return (x * zis % self.p, y * zis * zi % self.p)

    def mul(self, k: int, pt: Optional[Tuple[int, int]]):
        """k * pt in affine coordinates (None = infinity)."""
        if pt is None or k % self.n == 0:
            return None
        acc = None
        add = (pt[0], pt[1], 1)
        k %= self.n
        while k:
            if k & 1:
                acc = self._add(acc, add)
            add = self._double(add)
            k >>= 1
        return self._to_affine(acc)

    def mul_add(self, k1: int, p1, k2: int, p2):
        """k1*p1 + k2*p2 (affine in/out) — ECDSA's hot combination."""
        j1 = self.mul(k1, p1)
        j2 = self.mul(k2, p2)
        if j1 is None:
            return j2
        if j2 is None:
            return j1
        r = self._add((j1[0], j1[1], 1), (j2[0], j2[1], 1))
        return self._to_affine(r)

    def lift_x(self, x: int, odd_y: bool) -> Tuple[int, int]:
        """Point with abscissa ``x`` and chosen y parity, or raise."""
        p = self.p
        rhs = (x * x * x + self.a * x + self.b) % p
        # both supported curves have p % 4 == 3
        y = pow(rhs, (p + 1) // 4, p)
        if y * y % p != rhs:
            raise EcdsaError("x is not on the curve")
        if (y & 1) != odd_y:
            y = p - y
        return (x, y)


SECP256K1 = Curve(
    "secp256k1",
    p=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEFFFFFC2F,
    a=0,
    b=7,
    n=0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141,
    gx=0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798,
    gy=0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8,
)

SECP256R1 = Curve(
    "secp256r1",
    p=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFF,
    a=0xFFFFFFFF00000001000000000000000000000000FFFFFFFFFFFFFFFFFFFFFFFC,
    b=0x5AC635D8AA3A93E7B3EBBD55769886BC651D06B0CC53B0F63BCE3C3E27D2604B,
    n=0xFFFFFFFF00000000FFFFFFFFFFFFFFFFBCE6FAADA7179E84F3B9CAC2FC632551,
    gx=0x6B17D1F2E12C4247F8BCE6E563A440F277037D812DEB33A0F4A13945D898C296,
    gy=0x4FE342E2FE1A7F9B8EE7EB4A7C0F9E162BCE33576B315ECECBB6406837BF51F5,
)


def _decode_point(curve: Curve, pk: bytes) -> Tuple[int, int]:
    if len(pk) != 65 or pk[0] != 0x04:
        raise EcdsaError("public key must be 65-byte uncompressed SEC1")
    x = int.from_bytes(pk[1:33], "big")
    y = int.from_bytes(pk[33:65], "big")
    if x >= curve.p or y >= curve.p:
        raise EcdsaError("public key coordinate out of range")
    pt = (x, y)
    if not curve.on_curve(pt):
        raise EcdsaError("public key not on curve")
    return pt


def _decode_sig(curve: Curve, sig: bytes) -> Tuple[int, int]:
    if len(sig) != 64:
        raise EcdsaError("signature must be 64 bytes r||s")
    r = int.from_bytes(sig[:32], "big")
    s = int.from_bytes(sig[32:], "big")
    if not (1 <= r < curve.n) or not (1 <= s < curve.n):
        raise EcdsaError("signature scalar out of range")
    return r, s


def verify_ecdsa(curve: Curve, pk: bytes, digest: bytes,
                 sig: bytes) -> bool:
    """ECDSA verify over a 32-byte message digest. Enforces low-S
    (s <= n/2), matching the soroban host's malleability rule."""
    q = _decode_point(curve, pk)
    r, s = _decode_sig(curve, sig)
    if s > curve.n // 2:
        raise EcdsaError("signature s is not normalized (high-S)")
    if len(digest) != 32:
        raise EcdsaError("digest must be 32 bytes")
    e = int.from_bytes(digest, "big") % curve.n
    si = pow(s, curve.n - 2, curve.n)
    u1 = e * si % curve.n
    u2 = r * si % curve.n
    pt = curve.mul_add(u1, curve.g, u2, q)
    if pt is None:
        return False
    return pt[0] % curve.n == r


def recover_secp256k1(digest: bytes, sig: bytes,
                      recovery_id: int) -> bytes:
    """Recover the uncompressed SEC1 public key from an ECDSA
    signature over secp256k1 (the soroban/Ethereum ecrecover shape:
    64-byte r||s plus recovery id 0-3)."""
    curve = SECP256K1
    if recovery_id not in (0, 1, 2, 3):
        raise EcdsaError("recovery id must be 0..3")
    if len(digest) != 32:
        raise EcdsaError("digest must be 32 bytes")
    r, s = _decode_sig(curve, sig)
    if s > curve.n // 2:
        raise EcdsaError("signature s is not normalized (high-S)")
    x = r
    if recovery_id >= 2:
        x += curve.n
        if x >= curve.p:
            raise EcdsaError("recovery x out of field range")
    rp = curve.lift_x(x, odd_y=bool(recovery_id & 1))
    e = int.from_bytes(digest, "big") % curve.n
    ri = pow(r, curve.n - 2, curve.n)
    # Q = r^-1 (s*R - e*G)
    neg_e = (-e) % curve.n
    sr = curve.mul_add(s, rp, neg_e, curve.g)
    if sr is None:
        raise EcdsaError("degenerate recovery")
    q = curve.mul(ri, sr)
    if q is None:
        raise EcdsaError("degenerate recovery")
    return b"\x04" + q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
