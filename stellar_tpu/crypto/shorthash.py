"""SipHash-2-4 short hashing for in-memory hash tables.

The reference seeds a process-global SipHash key at startup and routes
unordered-container hashing through it (``src/crypto/ShortHash.h:9-19``,
``shortHash::computeHash``, ``initialize``/``seedRecordingEnabled`` for
deterministic tests). Same surface here: ``initialize()`` draws a random
key, ``seed(k)`` pins it for deterministic tests, ``compute_hash`` is
SipHash-2-4 producing a 64-bit value.
"""

from __future__ import annotations

import os
import struct

__all__ = ["initialize", "seed", "compute_hash", "xdr_computed_hash"]

_MASK = 0xFFFFFFFFFFFFFFFF
_key = (0, 0)
_initialized = False


def initialize():
    global _key, _initialized
    if not _initialized:
        raw = os.urandom(16)
        _key = struct.unpack("<QQ", raw)
        _initialized = True


def seed(key16: bytes):
    """Pin the key (tests; reference BUILD_TESTS reseeding hooks)."""
    global _key, _initialized
    _key = struct.unpack("<QQ", key16)
    _initialized = True


def _rotl(x: int, b: int) -> int:
    return ((x << b) | (x >> (64 - b))) & _MASK


def _sipround(v0, v1, v2, v3):
    v0 = (v0 + v1) & _MASK
    v1 = _rotl(v1, 13) ^ v0
    v0 = _rotl(v0, 32)
    v2 = (v2 + v3) & _MASK
    v3 = _rotl(v3, 16) ^ v2
    v0 = (v0 + v3) & _MASK
    v3 = _rotl(v3, 21) ^ v0
    v2 = (v2 + v1) & _MASK
    v1 = _rotl(v1, 17) ^ v2
    v2 = _rotl(v2, 32)
    return v0, v1, v2, v3


def compute_hash(data: bytes) -> int:
    """SipHash-2-4 of ``data`` under the process key -> uint64."""
    if not _initialized:
        initialize()
    k0, k1 = _key
    v0 = k0 ^ 0x736F6D6570736575
    v1 = k1 ^ 0x646F72616E646F6D
    v2 = k0 ^ 0x6C7967656E657261
    v3 = k1 ^ 0x7465646279746573
    b = len(data) & 0xFF
    n_full = len(data) // 8
    for i in range(n_full):
        m = struct.unpack_from("<Q", data, i * 8)[0]
        v3 ^= m
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
        v0 ^= m
    tail = data[n_full * 8:]
    m = b << 56
    for i, ch in enumerate(tail):
        m |= ch << (8 * i)
    v3 ^= m
    v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    v0 ^= m
    v2 ^= 0xFF
    for _ in range(4):
        v0, v1, v2, v3 = _sipround(v0, v1, v2, v3)
    return (v0 ^ v1 ^ v2 ^ v3) & _MASK


def xdr_computed_hash(xdr_type, value) -> int:
    """Short hash of an XDR value's canonical encoding (reference
    ``shortHash::xdrComputeHash``)."""
    from stellar_tpu.xdr.runtime import to_bytes
    return compute_hash(to_bytes(xdr_type, value))
