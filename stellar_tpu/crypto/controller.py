"""Closed-loop control: the telemetry drives the service knobs.

PR 10 built per-lane SLO burn rates, pipeline-bubble attribution and
anomaly detection; PR 14 added per-tenant QoS with a bounded decision
log — and until now a HUMAN read that telemetry and turned
``VERIFY_SERVICE_MAX_BATCH`` by hand, so a mid-run load shift could
burn the scp lane's 0.001 completion budget before anyone reacted
(the committee-latency failure mode "Performance of EdDSA and BLS
Signatures in Committee-Based Consensus" measures). This module is
the deterministic feedback controller that closes the loop
(``docs/robustness.md`` "Closed-loop control"):

* **inputs** are EVENT-COUNT telemetry windows assembled by the
  service every ``CONTROL_EVERY`` collected batches: per-lane SLO
  burn rates from
  :data:`stellar_tpu.crypto.verify_service.slo_monitor`, queue-wait
  bubble dominance from the pipeline timeline, per-lane backlog
  gauges, the scp lane's head-of-line sequence age, and the shed
  pressure level — each window is a plain dict of numbers;
* **decisions** adapt three knobs within CLAMPED bounds:
  ``max_batch`` (multiplicative x2 / //2 inside
  ``[min_batch, batch_ceiling]``), ``pipeline_depth`` (+-1 inside
  ``[1, max_pipeline_depth]``) and the shed-ladder entry threshold
  ``shed_highwater_frac`` (+-1/8 inside
  ``[HIGHWATER_MIN, HIGHWATER_MAX]`` — exact binary steps, no float
  drift). The decision table: bulk burn high with queue-wait bubbles
  dominant (or backlog over the pressure band) and scp healthy ->
  GROW batches (amortize the per-dispatch floor, drain the backlog);
  scp latency/completion objective threatened -> SHRINK batches,
  RAISE pipeline depth (bound the head-of-line block in front of
  consensus work and keep dispatches flowing) and LOWER the shed
  highwater (the flood valve opens earlier); everything inside the
  relax band -> step each knob back toward its configured baseline;
* **hysteresis + cool-down** guard every move: a condition must hold
  for ``hysteresis`` CONSECUTIVE windows before it may act, and a
  knob that moved is frozen for ``cooldown`` further windows — a
  boundary-riding signal (burn oscillating 0.99/1.01) keeps
  resetting the streak and never flaps a knob, and the deadband
  between :data:`ACT_BURN` and :data:`RELAX_BURN` keeps grow/relax
  from ping-ponging;
* **zero clock reads in any decision** (same nondet discipline as the
  aging rule and the WFQ virtual time — this module sits in the
  nondet-lint scope with NO allowlist entry): :meth:`VerifyController.
  step` is a pure function of the window it is handed plus the
  controller's own bounded state, so two replicas fed the identical
  window sequence produce BIT-IDENTICAL knob trajectories — the
  replay surface ``tools/control_selfcheck.py`` gates (tier-1
  ``CONTROL_OK``).

Every step appends one compact tuple to the bounded
:meth:`VerifyController.control_log` (the bit-identity surface —
mirror of PR 14's scheduling ``decision_log``) and retains its full
input window (:meth:`VerifyController.windows`), and the service
emits each knob move as a ``service.control`` flight-recorder event
carrying the complete window it acted on — replay-testable like
every other scheduling surface: :meth:`VerifyController.replay` over
the retained windows reproduces the live log bit-for-bit.

Thread safety: all controller state mutates under ``self._lock``
(lock-lint scoped); the SERVICE applies the resulting knob values
under its own condition variable (the ``_locked`` application point
in ``verify_service``), so scheduling always reads a consistent knob
set.
"""

from __future__ import annotations

import copy
import os
import threading
from collections import deque
from typing import Dict, List, Optional

from stellar_tpu.utils.env import env_true as _env_true

__all__ = ["VerifyController", "configure_control", "CONTROL_ENABLED",
           "CONTROL_EVERY", "ACT_BURN", "RELAX_BURN",
           "QUEUE_WAIT_DOMINANT", "HIGHWATER_MIN", "HIGHWATER_MAX",
           "HIGHWATER_STEP", "BACKLOG_PRESSURE_OF_HIGHWATER"]


# ---------------- control policy knobs ----------------
# Env defaults let tools/tests set these without a Config; a node
# pushes its VERIFY_CONTROL_* Config knobs through configure_control()
# (same pattern as verify_service.configure_service). Disabled by
# default — closed-loop control is opt-in, exactly like the service.

CONTROL_ENABLED = _env_true("VERIFY_CONTROL_ENABLED")
# controller cadence: one window every N collected batches
# (event-count, never a timer)
CONTROL_EVERY = int(os.environ.get("VERIFY_CONTROL_EVERY", "8"))
# clamp bounds for the adapted knobs
CONTROL_MIN_BATCH = int(os.environ.get("VERIFY_CONTROL_MIN_BATCH",
                                       "32"))
CONTROL_MAX_BATCH = int(os.environ.get("VERIFY_CONTROL_MAX_BATCH",
                                       "8192"))
CONTROL_MAX_PIPELINE_DEPTH = int(os.environ.get(
    "VERIFY_CONTROL_MAX_PIPELINE_DEPTH", "8"))
# hysteresis: consecutive windows a condition must hold before acting
CONTROL_HYSTERESIS = int(os.environ.get("VERIFY_CONTROL_HYSTERESIS",
                                        "2"))
# cool-down: windows a knob stays frozen after it moved
CONTROL_COOLDOWN = int(os.environ.get("VERIFY_CONTROL_COOLDOWN", "4"))
# bounded control log / retained-window depth (the replay surface)
CONTROL_LOG = int(os.environ.get("VERIFY_CONTROL_LOG", "4096"))

# ---------------- decision bands (constants, not knobs) ----------------
# burn rate past which an objective counts as threatened (1.0 = the
# error budget is burning exactly as fast as the objective allows)
ACT_BURN = 1.0
# every signal under this counts as healthy — the deadband between
# ACT_BURN and RELAX_BURN is what keeps grow/relax from ping-ponging
RELAX_BURN = 0.5
# queue_wait share of attributed bubble time past which queue-wait
# counts as the dominant bubble class
QUEUE_WAIT_DOMINANT = 0.5
# shed-highwater clamp + step: exact eighths, so repeated +-steps are
# binary-exact and replicas never drift by a rounding order
HIGHWATER_MIN = 0.25
HIGHWATER_MAX = 0.875
HIGHWATER_STEP = 0.125
# bulk backlog over this fraction OF the shed highwater counts as
# queue pressure — the deterministic stand-in for queue-wait bubble
# dominance when no device timeline exists (host-only runs), and the
# early-warning band in live ones (sampling only at the highwater
# itself would race the shed pass that drains back under it)
BACKLOG_PRESSURE_OF_HIGHWATER = 0.5

_defaults_lock = threading.Lock()


def configure_control(enabled: Optional[bool] = None,
                      every: Optional[int] = None,
                      min_batch: Optional[int] = None,
                      max_batch: Optional[int] = None,
                      max_pipeline_depth: Optional[int] = None,
                      hysteresis: Optional[int] = None,
                      cooldown: Optional[int] = None,
                      log_cap: Optional[int] = None) -> None:
    """Push the control knobs (Config / tests); None keeps the current
    value. Instances read these at construction — push before the
    service is created (the Application does)."""
    global CONTROL_ENABLED, CONTROL_EVERY, CONTROL_MIN_BATCH
    global CONTROL_MAX_BATCH, CONTROL_MAX_PIPELINE_DEPTH
    global CONTROL_HYSTERESIS, CONTROL_COOLDOWN, CONTROL_LOG
    with _defaults_lock:
        if enabled is not None:
            CONTROL_ENABLED = bool(enabled)
        if every is not None:
            CONTROL_EVERY = max(1, int(every))
        if min_batch is not None:
            CONTROL_MIN_BATCH = max(1, int(min_batch))
        if max_batch is not None:
            CONTROL_MAX_BATCH = max(1, int(max_batch))
        if max_pipeline_depth is not None:
            CONTROL_MAX_PIPELINE_DEPTH = max(1, int(max_pipeline_depth))
        if hysteresis is not None:
            CONTROL_HYSTERESIS = max(1, int(hysteresis))
        if cooldown is not None:
            CONTROL_COOLDOWN = max(0, int(cooldown))
        if log_cap is not None:
            CONTROL_LOG = max(16, int(log_cap))


class VerifyController:
    """The deterministic feedback controller (module docstring). One
    instance belongs to one :class:`~stellar_tpu.crypto.
    verify_service.VerifyService`; construction captures the service's
    CONFIGURED knob values as the relax baseline. ``step(window)`` is
    the whole control surface: pure arithmetic of the window plus the
    controller's bounded state — no clocks, no RNG, no I/O."""

    def __init__(self, max_batch: int, pipeline_depth: int,
                 shed_highwater_frac: float, *,
                 min_batch: Optional[int] = None,
                 batch_ceiling: Optional[int] = None,
                 max_pipeline_depth: Optional[int] = None,
                 hysteresis: Optional[int] = None,
                 cooldown: Optional[int] = None,
                 log_cap: Optional[int] = None):
        self._lock = threading.Lock()
        self._min_batch = CONTROL_MIN_BATCH if min_batch is None \
            else max(1, int(min_batch))
        self._batch_ceiling = CONTROL_MAX_BATCH if batch_ceiling \
            is None else max(1, int(batch_ceiling))
        self._max_pd = CONTROL_MAX_PIPELINE_DEPTH \
            if max_pipeline_depth is None else max(1,
                                                   int(max_pipeline_depth))
        self._hysteresis = CONTROL_HYSTERESIS if hysteresis is None \
            else max(1, int(hysteresis))
        self._cooldown = CONTROL_COOLDOWN if cooldown is None \
            else max(0, int(cooldown))
        cap = CONTROL_LOG if log_cap is None else max(16, int(log_cap))
        # the baseline the relax band steps back toward — the
        # CONFIGURED values (sanitized, never re-shaped): an operator
        # knob outside the default clamp range WIDENS the clamp to
        # include it rather than being silently overridden — a
        # controller may never move a knob the operator set without a
        # logged decision
        base_mb = max(1, int(max_batch))
        base_pd = max(1, int(pipeline_depth))
        base_hw = min(1.0, max(0.01, float(shed_highwater_frac)))
        self._min_batch = min(self._min_batch, base_mb)
        self._batch_ceiling = max(self._batch_ceiling, base_mb)
        self._max_pd = max(self._max_pd, base_pd)
        self._hw_min = min(HIGHWATER_MIN, base_hw)
        self._hw_max = max(HIGHWATER_MAX, base_hw)
        self._base = {
            "max_batch": base_mb,
            "pipeline_depth": base_pd,
            "shed_highwater_frac": base_hw,
        }
        self._knobs = dict(self._base)
        self._seq = 0
        self._moves = 0
        self._streak = {"scp": 0, "bulk": 0, "healthy": 0}
        # knob -> first window seq at which it may move again
        self._frozen: Dict[str, int] = {}
        # compact per-step tuples: the bit-identity surface (mirror of
        # the service decision_log — deterministic fields ONLY)
        self._log: deque = deque(maxlen=cap)
        # full input windows, same depth: the replay surface
        self._windows: deque = deque(maxlen=cap)

    # clamp helpers read only the bound fields set above, so __init__
    # can use them while building _base (tests probe them directly)
    def _clamp_batch(self, v: int) -> int:
        return max(self._min_batch, min(self._batch_ceiling, int(v)))

    def _clamp_pd(self, v: int) -> int:
        return max(1, min(self._max_pd, int(v)))

    def _clamp_hw(self, v: float) -> float:
        return max(self._hw_min, min(self._hw_max, float(v)))

    # ---------------- public API ----------------

    def knobs(self) -> dict:
        """The controller's current knob values (the service applies
        these under its own lock after every step)."""
        with self._lock:
            return dict(self._knobs)

    def step(self, window: dict) -> List[dict]:
        """Evaluate ONE telemetry window; returns the list of applied
        knob moves (empty = hold). Appends one compact entry to the
        control log either way and retains the window for replay."""
        with self._lock:
            return self._step_locked(window)

    def control_log(self, limit: int = 0) -> list:
        """The bounded in-order control log: one
        ``(action, seq, max_batch, pipeline_depth, highwater_milli,
        reason)`` tuple per evaluated window (``action`` one of
        ``grow``/``shrink``/``relax``/``hold``). Two controllers fed
        the identical window sequence produce identical logs — the
        bit-identical surface ``tools/control_selfcheck.py`` gates.
        ``limit`` bounds the tail returned (0 = all retained)."""
        with self._lock:
            log = list(self._log)
        return log[-limit:] if limit else log

    def journal_log(self, limit: int = 0) -> list:
        """The control log rendered as unified-journal rows (ISSUE
        20): one dict per evaluated window, keyed by the window seq —
        already monotone and deterministic, so the journal merge can
        key control events by ``(component, seq)`` without a second
        counter. Same bit-identity contract as :meth:`control_log`."""
        return [
            {"seq": seq, "kind": "control", "action": action,
             "max_batch": mb, "pipeline_depth": pd,
             "highwater_milli": hw, "reason": reason}
            for action, seq, mb, pd, hw, reason
            in self.control_log(limit)]

    def windows(self, limit: int = 0) -> list:
        """The retained input windows, in step order (the replay
        input; bounded by the same cap as the log)."""
        with self._lock:
            out = [copy.deepcopy(w) for w in self._windows]
        return out[-limit:] if limit else out

    @property
    def moves(self) -> int:
        """Cumulative applied knob moves (the
        ``crypto.verify.control.decisions`` gauge)."""
        with self._lock:
            return self._moves

    def snapshot(self) -> dict:
        """The ``control`` admin-route payload: current/base knobs,
        clamp bounds, hysteresis state, accounting."""
        with self._lock:
            return {
                "windows": self._seq,
                "moves": self._moves,
                "knobs": dict(self._knobs),
                "base": dict(self._base),
                "clamps": {"min_batch": self._min_batch,
                           "batch_ceiling": self._batch_ceiling,
                           "max_pipeline_depth": self._max_pd,
                           "highwater_min": self._hw_min,
                           "highwater_max": self._hw_max},
                "hysteresis": self._hysteresis,
                "cooldown": self._cooldown,
                "streaks": dict(self._streak),
                "log_len": len(self._log),
            }

    def replay(self, windows) -> list:
        """Re-derive the knob trajectory from a window sequence: a
        FRESH controller with this one's configuration steps through
        ``windows`` and returns its control log. Replaying a live
        controller's own :meth:`windows` reproduces its
        :meth:`control_log` bit-for-bit WHILE the retained history is
        complete (first log entry still seq 1 — the log and window
        deques share one cap and evict in lockstep; past the cap,
        replay a captured prefix instead) — the replay procedure
        ``docs/robustness.md`` documents and ``CONTROL_OK`` gates."""
        with self._lock:
            twin = VerifyController(
                self._base["max_batch"], self._base["pipeline_depth"],
                self._base["shed_highwater_frac"],
                min_batch=self._min_batch,
                batch_ceiling=self._batch_ceiling,
                max_pipeline_depth=self._max_pd,
                hysteresis=self._hysteresis, cooldown=self._cooldown,
                log_cap=self._log.maxlen)
        for w in windows:
            twin.step(w)
        return twin.control_log()

    # ---------------- decision internals ----------------

    def _step_locked(self, window: dict) -> List[dict]:
        self._seq += 1
        seq = self._seq
        # DEEP copy on retention: the caller's window (with its nested
        # lane dicts) also rides the service.control recorder event —
        # a consumer mutating that event in place must never be able
        # to corrupt the retained replay surface
        self._windows.append(copy.deepcopy(window))
        lanes = window.get("lanes") or {}
        scp = lanes.get("scp") or {}
        bulk = lanes.get("bulk") or {}
        scp_burn = max(float(scp.get("latency_burn", 0.0)),
                       float(scp.get("shed_burn", 0.0)))
        bulk_burn = float(bulk.get("shed_burn", 0.0))
        lane_depth = max(1, int(window.get("lane_depth", 1)))
        backlog_frac = float(bulk.get("queued_submissions", 0)) \
            / lane_depth
        qw_frac = float(window.get("queue_wait_frac", 0.0))
        scp_queued = int(scp.get("queued_submissions", 0))
        hol_age = int(window.get("scp_hol_age", 0))
        pressure = int(window.get("pressure", 0))
        hw = self._knobs["shed_highwater_frac"]
        # backlog bands measure against the CONFIGURED baseline
        # highwater, never the adapted knob: measuring against the
        # adapted value is a self-reinforcing ratchet — a lowered
        # highwater lowers the pressure band, which keeps reporting
        # pressure, which keeps the healthy/relax branch unreachable
        # and pins the highwater at its floor forever
        band_hw = self._base["shed_highwater_frac"]
        # the three mutually-exclusive conditions; scp protection
        # wins. Beyond the (advisory, clock-derived) burn rate, two
        # DETERMINISTIC early signals threaten scp: the head-of-line
        # sequence age (a queued scp submission has watched a whole
        # lane-depth of newer admissions arrive while it waits — the
        # clock-free latency proxy) and dispatch-degraded pressure
        # with consensus work queued (capacity collapsed to the host
        # oracle: shrink the head-of-line block in front of scp NOW,
        # before the burn rate can show it)
        scp_threat = scp_burn > ACT_BURN or \
            (scp_queued > 0 and hol_age >= lane_depth) or \
            (scp_queued > 0 and pressure >= 2)
        backlog_pressure = backlog_frac >= \
            band_hw * BACKLOG_PRESSURE_OF_HIGHWATER
        bulk_pressure = (not scp_threat) and \
            (bulk_burn > ACT_BURN or backlog_pressure) and \
            (qw_frac >= QUEUE_WAIT_DOMINANT or backlog_pressure)
        healthy = (not scp_threat) and (not bulk_pressure) and \
            scp_burn < RELAX_BURN and bulk_burn < RELAX_BURN and \
            not backlog_pressure
        for cond, held in (("scp", scp_threat), ("bulk", bulk_pressure),
                           ("healthy", healthy)):
            self._streak[cond] = self._streak[cond] + 1 if held else 0
        # wants: (knob, target, action, reason) — applied only past
        # hysteresis and outside each knob's cool-down window
        wants: list = []
        if self._streak["scp"] >= self._hysteresis:
            action, reason = "shrink", "scp-threat"
            wants = [
                ("max_batch",
                 self._clamp_batch(self._knobs["max_batch"] // 2)),
                ("pipeline_depth",
                 self._clamp_pd(self._knobs["pipeline_depth"] + 1)),
                ("shed_highwater_frac",
                 self._clamp_hw(hw - HIGHWATER_STEP)),
            ]
        elif self._streak["bulk"] >= self._hysteresis:
            action = "grow"
            # the logged reason names EXACTLY the signals that fired
            # — an operator reading the control log must never see a
            # burn violation that did not happen
            sig = []
            if bulk_burn > ACT_BURN:
                sig.append("bulk-burn")
            if qw_frac >= QUEUE_WAIT_DOMINANT:
                sig.append("queue-wait")
            if backlog_pressure:
                sig.append("backlog")
            reason = "+".join(sig)
            wants = [
                ("max_batch",
                 self._clamp_batch(self._knobs["max_batch"] * 2)),
            ]
        elif self._streak["healthy"] >= self._hysteresis:
            action, reason = "relax", "healthy-relax"
            wants = [(k, self._toward_base_locked(k))
                     for k in self._knobs]
        else:
            action, reason = "hold", "no-condition"
        applied: List[dict] = []
        for knob, target in wants:
            if target == self._knobs[knob]:
                continue
            if seq < self._frozen.get(knob, 0):
                continue
            applied.append({"seq": seq, "action": action,
                            "knob": knob, "old": self._knobs[knob],
                            "new": target, "reason": reason})
            self._knobs[knob] = target
            self._frozen[knob] = seq + 1 + self._cooldown
            self._moves += 1
        if wants and not applied:
            # the condition held but every target was already at its
            # bound or frozen by a cool-down: the log says WHICH —
            # "at-base" (healthy, knobs steady at the configured
            # baseline) is a different operational state from
            # "at-bound" (a knob riding its clamp under sustained
            # pressure), and an operator must be able to tell them
            # apart from the log alone (replay reproduces either)
            frozen = any(t != self._knobs[k] and
                         seq < self._frozen.get(k, 0)
                         for k, t in wants)
            reason = "cooldown" if frozen else \
                ("at-base" if action == "relax" else "at-bound")
            action = "hold"
        self._log.append((
            action, seq, self._knobs["max_batch"],
            self._knobs["pipeline_depth"],
            int(round(self._knobs["shed_highwater_frac"] * 1000)),
            reason))
        return applied

    def _toward_base_locked(self, knob: str):
        """One relax step from the current value toward the
        configured baseline (never past it)."""
        cur, base = self._knobs[knob], self._base[knob]
        if cur == base:
            return cur
        if knob == "max_batch":
            return min(base, cur * 2) if cur < base \
                else max(base, cur // 2)
        if knob == "pipeline_depth":
            return cur + 1 if cur < base else cur - 1
        step = HIGHWATER_STEP
        return min(base, cur + step) if cur < base \
            else max(base, cur - step)
