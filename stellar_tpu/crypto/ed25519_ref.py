"""Pure-Python ed25519 reference implementation with libsodium-exact verify
semantics.

This is the consensus-critical oracle: the TPU batch verifier
(``stellar_tpu.ops.verify``) must agree bit-for-bit with this module's
accept/reject decisions, and this module mirrors libsodium's
``crypto_sign_verify_detached`` (the reference's verify path behind
``PubKeyUtils::verifySig``, reference ``src/crypto/SecretKey.cpp:435-468``):

  * reject if S is non-canonical (S >= L)                 [sc25519_is_canonical]
  * reject if R (sig[0:32]) encodes a small-order point   [ge25519_has_small_order]
  * reject if A (pk) is non-canonical (y >= p)            [ge25519_is_canonical]
  * reject if A encodes a small-order point
  * reject if A fails point decompression
  * compute h = SHA512(R || A || M) mod L
  * accept iff encode(s*B - h*A) == R  (bytewise, cofactorless)

The small-order check operates on raw encodings with the sign bit masked,
exactly like libsodium's blocklist comparison, so non-canonical encodings of
small-order points (y = p, y = p+1) are rejected too.

Performance is irrelevant here — this is for tests, key generation, and the
CPU fallback verifier. The hot path lives in ``stellar_tpu/ops``.
"""

from __future__ import annotations

import hashlib

__all__ = [
    "P",
    "L",
    "D",
    "verify",
    "verify_detailed",
    "sign",
    "secret_to_public",
    "scalarmult_base",
    "point_decompress",
    "point_compress",
    "point_add",
    "point_mul",
    "affine_table_rows",
    "IDENTITY",
    "BASE",
    "SMALL_ORDER_ENCODINGS",
]

# Field prime, group order, curve constant d = -121665/121666 mod p.
P = 2**255 - 19
L = 2**252 + 27742317777372353535851937790883648493
D = (-121665 * pow(121666, P - 2, P)) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)  # sqrt(-1) mod p


def _inv(x: int) -> int:
    return pow(x, P - 2, P)


# Points are extended homogeneous coordinates (X, Y, Z, T) with x = X/Z,
# y = Y/Z, x*y = T/Z.
IDENTITY = (0, 1, 1, 0)


def point_add(p1, p2):
    x1, y1, z1, t1 = p1
    x2, y2, z2, t2 = p2
    a = (y1 - x1) * (y2 - x2) % P
    b = (y1 + x1) * (y2 + x2) % P
    c = 2 * t1 * t2 * D % P
    dd = 2 * z1 * z2 % P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_double(p1):
    # dedicated doubling (RFC 8032 / ref10 ge25519_p2_dbl semantics)
    x1, y1, z1, _ = p1
    a = x1 * x1 % P
    b = y1 * y1 % P
    c = 2 * z1 * z1 % P
    h = (a + b) % P
    e = (h - (x1 + y1) * (x1 + y1)) % P
    g = (a - b) % P
    f = (c + g) % P
    return (e * f % P, g * h % P, f * g % P, e * h % P)


def point_mul(s: int, p1):
    q = IDENTITY
    while s > 0:
        if s & 1:
            q = point_add(q, p1)
        p1 = point_double(p1)
        s >>= 1
    return q


def affine_table_rows(p1, entries: int):
    """Affine cached rows ``(y+x, y-x, 2*d*x*y) mod P`` for the
    multiples ``v*p1``, v = 1..entries — the host half of every
    precomputed window table (the device layout packs these into limb
    vectors; see ``stellar_tpu.ops.edwards`` and
    ``stellar_tpu.parallel.signer_tables``).

    An incremental addition chain (entries-1 ``point_add``) keeps the
    cost linear, and the projective Z column is normalized by ONE
    Montgomery-batched inversion (prefix products + a single
    ``pow(.., P-2, P)`` + back-substitution) instead of ``entries``
    modexps — the same trick the device-side
    ``build_point_table_affine`` plays with ``fe.batch_inv``."""
    pts = []
    q = p1
    for _ in range(entries):
        pts.append(q)
        q = point_add(q, p1)
    prefix = []
    acc = 1
    for pt in pts:
        acc = acc * pt[2] % P
        prefix.append(acc)
    inv = _inv(acc)
    rows = [None] * entries
    for i in range(entries - 1, -1, -1):
        zinv = inv * (prefix[i - 1] if i else 1) % P
        inv = inv * pts[i][2] % P
        x = pts[i][0] * zinv % P
        y = pts[i][1] * zinv % P
        rows[i] = ((y + x) % P, (y - x) % P, 2 * D * x * y % P)
    return rows


def point_equal(p1, p2) -> bool:
    x1, y1, z1, _ = p1
    x2, y2, z2, _ = p2
    return (x1 * z2 - x2 * z1) % P == 0 and (y1 * z2 - y2 * z1) % P == 0


def point_compress(p1) -> bytes:
    x1, y1, z1, _ = p1
    zinv = _inv(z1)
    x = x1 * zinv % P
    y = y1 * zinv % P
    return ((y | ((x & 1) << 255)).to_bytes(32, "little"))


def _sqrt_ratio(u: int, v: int):
    """Return (ok, x) with x = sqrt(u/v) using the ref10 candidate-root
    method: x = u*v^3 * (u*v^7)^((p-5)/8), corrected by sqrt(-1)."""
    v3 = v * v % P * v % P
    v7 = v3 * v3 % P * v % P
    x = u * v3 % P * pow(u * v7 % P, (P - 5) // 8, P) % P
    vxx = x * x % P * v % P
    if vxx == u % P:
        return True, x
    if vxx == (-u) % P:
        return True, x * SQRT_M1 % P
    return False, 0


def point_decompress(s: bytes):
    """Decompress a 32-byte encoding; returns extended point or None.

    Mirrors libsodium ge25519_frombytes: the y coordinate is taken mod p
    implicitly (non-canonical y still decompresses here — callers that need
    libsodium verify semantics must apply the canonicity/small-order checks
    separately, as verify() does)."""
    if len(s) != 32:
        raise ValueError("bad encoding length")
    n = int.from_bytes(s, "little")
    sign = (n >> 255) & 1
    y = n & ((1 << 255) - 1)
    y %= P
    y2 = y * y % P
    u = (y2 - 1) % P
    v = (D * y2 + 1) % P
    ok, x = _sqrt_ratio(u, v)
    if not ok:
        return None
    if x == 0 and sign == 1:
        return None  # "negative zero" rejected (ref10 frombytes)
    if x & 1 != sign:
        x = P - x
    return (x, y, 1, x * y % P)


# Base point: y = 4/5 mod p, x positive-even per RFC 8032 (x is "even"? sign
# bit 0 encodes x with LSB 0 ... the standard base point has x with LSB 0).
_by = 4 * _inv(5) % P
_bp = point_decompress(_by.to_bytes(32, "little"))
assert _bp is not None
BASE = _bp


def _small_order_encodings():
    """All 32-byte encodings rejected by libsodium's ge25519_has_small_order:
    canonical encodings of the 8 small-order points plus the non-canonical
    aliases y=p, y=p+1 — compared with the sign bit masked off."""
    # Find a point of order exactly 8: take L*P for random-ish points P.
    y = 2
    q8 = None
    while q8 is None:
        pt = point_decompress((y).to_bytes(32, "little"))
        y += 1
        if pt is None:
            continue
        cand = point_mul(L, pt)
        if (not point_equal(cand, IDENTITY)
                and not point_equal(point_double(cand), IDENTITY)
                and not point_equal(point_double(point_double(cand)),
                                    IDENTITY)):
            q8 = cand
    encs = set()
    cur = IDENTITY
    for _ in range(8):
        enc = bytearray(point_compress(cur))
        enc[31] &= 0x7F  # sign bit masked in the comparison
        encs.add(bytes(enc))
        cur = point_add(cur, q8)
    # Non-canonical aliases of y=0 and y=1 (y = p, y = p + 1 fit in 255 bits).
    encs.add(P.to_bytes(32, "little"))
    encs.add((P + 1).to_bytes(32, "little"))
    return frozenset(encs)


SMALL_ORDER_ENCODINGS = _small_order_encodings()


def has_small_order(s: bytes) -> bool:
    masked = bytearray(s)
    masked[31] &= 0x7F
    return bytes(masked) in SMALL_ORDER_ENCODINGS


def is_canonical_point(s: bytes) -> bool:
    """libsodium ge25519_is_canonical: the 255-bit y must be < p."""
    y = int.from_bytes(s, "little") & ((1 << 255) - 1)
    return y < P


def is_canonical_scalar(s: bytes) -> bool:
    return int.from_bytes(s, "little") < L


def sha512_mod_l(*chunks: bytes) -> int:
    h = hashlib.sha512()
    for c in chunks:
        h.update(c)
    return int.from_bytes(h.digest(), "little") % L


def verify_detailed(pk: bytes, msg: bytes, sig: bytes) -> dict:
    """Verify with per-check breakdown (for differential tests vs the TPU
    path). Returns dict of named check booleans plus 'ok'."""
    out = {
        "s_canonical": False,
        "r_not_small": False,
        "a_canonical": False,
        "a_not_small": False,
        "a_decompressed": False,
        "r_match": False,
        "ok": False,
    }
    if len(pk) != 32 or len(sig) != 64:
        return out
    r_bytes, s_bytes = sig[:32], sig[32:]
    out["s_canonical"] = is_canonical_scalar(s_bytes)
    out["r_not_small"] = not has_small_order(r_bytes)
    out["a_canonical"] = is_canonical_point(pk)
    out["a_not_small"] = not has_small_order(pk)
    a = point_decompress(pk)
    out["a_decompressed"] = a is not None
    if a is None:
        return out
    out["r_match"] = _verify_equation_python(pk, msg, sig, a)
    out["ok"] = (out["s_canonical"] and out["r_not_small"]
                 and out["a_canonical"] and out["a_not_small"]
                 and out["a_decompressed"] and out["r_match"])
    return out


# Fast curve core: OpenSSL (the `cryptography` package) implements the
# same ref10-derived cofactorless equation check as libsodium; behind
# OUR policy gate (canonical s, small-order/canonical A and R — the
# checks libsodium performs that OpenSSL does not) its accept/reject
# matches the pure-Python oracle bit-for-bit. Differential + structured
# adversarial tests (tests/test_ed25519_ref.py,
# tests/test_batch_verifier.py) pin this equivalence; any load failure
# falls back to the pure-Python equation, never to a different answer.
try:
    from cryptography.exceptions import InvalidSignature as _OsslBadSig
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey as _OsslSK, Ed25519PublicKey as _OsslPK,
    )
    _HAVE_OSSL = True
except Exception:  # pragma: no cover - cryptography is baked in
    _HAVE_OSSL = False


def _verify_equation_python(pk: bytes, msg: bytes, sig: bytes,
                            a) -> bool:
    r_bytes, s_bytes = sig[:32], sig[32:]
    s = int.from_bytes(s_bytes, "little")
    h = sha512_mod_l(r_bytes, pk, msg)
    neg_a = (P - a[0], a[1], a[2], (P - a[3]) % P)
    rprime = point_add(point_mul(s % L, BASE), point_mul(h, neg_a))
    return point_compress(rprime) == r_bytes


def _policy_gate(pk: bytes, sig: bytes) -> bool:
    """The byte-level rejections libsodium performs that the bare
    curve-equation check does not: lengths, canonical s, small-order
    R/A, canonical A. The single source of truth for BOTH verify
    paths — edit here or nowhere."""
    if len(pk) != 32 or len(sig) != 64:
        return False
    r_bytes, s_bytes = sig[:32], sig[32:]
    if not is_canonical_scalar(s_bytes):
        return False
    if has_small_order(r_bytes) or has_small_order(pk):
        return False
    return is_canonical_point(pk)


def verify(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """libsodium-exact ``crypto_sign_verify_detached``."""
    if not _policy_gate(pk, sig):
        return False
    if _HAVE_OSSL:
        try:
            # OpenSSL's ref10 frombytes performs the same decompression
            # rejection as point_decompress, so no eager decompress here
            _OsslPK.from_public_bytes(pk).verify(sig, msg)
            return True
        except _OsslBadSig:
            return False
        except Exception:
            # OpenSSL wouldn't load a key our policy accepted: fall
            # back to the oracle equation rather than guess
            pass
    a = point_decompress(pk)
    if a is None:
        return False
    return _verify_equation_python(pk, msg, sig, a)


def verify_python(pk: bytes, msg: bytes, sig: bytes) -> bool:
    """The pure-Python oracle path (policy + equation), independent of
    OpenSSL — the differential-testing ground truth."""
    if not _policy_gate(pk, sig):
        return False
    a = point_decompress(pk)
    if a is None:
        return False
    return _verify_equation_python(pk, msg, sig, a)


def _clamp(k: bytes) -> int:
    a = bytearray(k)
    a[0] &= 248
    a[31] &= 127
    a[31] |= 64
    return int.from_bytes(bytes(a), "little")


def secret_to_public(seed: bytes) -> bytes:
    if len(seed) != 32:  # same contract on both paths
        raise ValueError("ed25519 seed must be 32 bytes")
    if _HAVE_OSSL:
        from cryptography.hazmat.primitives import serialization
        return _OsslSK.from_private_bytes(seed).public_key() \
            .public_bytes(serialization.Encoding.Raw,
                          serialization.PublicFormat.Raw)
    return secret_to_public_python(seed)


def secret_to_public_python(seed: bytes) -> bytes:
    """Pure-Python derivation (differential ground truth)."""
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    return point_compress(point_mul(a, BASE))


def scalarmult_base(s: int) -> bytes:
    return point_compress(point_mul(s, BASE))


def sign(seed: bytes, msg: bytes) -> bytes:
    """RFC 8032 ed25519 signing from a 32-byte seed. Deterministic, so
    the OpenSSL fast path produces byte-identical signatures to the
    pure-Python construction (pinned by test_differential_vs_openssl)."""
    if len(seed) != 32:  # same contract on both paths
        raise ValueError("ed25519 seed must be 32 bytes")
    if _HAVE_OSSL:
        return _OsslSK.from_private_bytes(seed).sign(msg)
    return sign_python(seed, msg)


def sign_python(seed: bytes, msg: bytes) -> bytes:
    h = hashlib.sha512(seed).digest()
    a = _clamp(h[:32])
    prefix = h[32:]
    pk = point_compress(point_mul(a, BASE))
    r = sha512_mod_l(prefix, msg)
    r_enc = point_compress(point_mul(r, BASE))
    k = sha512_mod_l(r_enc, pk, msg)
    s = (r + k * a) % L
    return r_enc + s.to_bytes(32, "little")
