"""BLS12-381 for the protocol-22 soroban host functions (CAP-59;
reference scope: the bls12_381_* env functions soroban-env-host p22
exports — its implementation is the blst-backed crate, absent from the
reference snapshot like the rest of the soroban trees).

Pure-Python tower-field pairing implementation, correctness-first:

- Fp / Fp2 / Fp6 / Fp12 arithmetic (u^2 = -1, v^3 = u+1, w^2 = v)
- G1 over E(Fp): y^2 = x^3 + 4; G2 over E'(Fp2): y^2 = x^3 + 4(u+1)
- subgroup checks by multiplying with the group order r
- optimal-ate Miller loop with the BLS parameter x = -0xd201000000010000
  and the standard final exponentiation
- Fr scalar-field arithmetic

Verified in-tree by algebraic properties (group laws, commutativity,
order-r annihilation, and pairing BILINEARITY e(aP, bQ) == e(abP, Q)
== e(P, abQ) across random scalars) plus the published generator
coordinates — no BLS library ships in this image to differentially
test against.

Serialization follows the ZCash/IETF format the reference host uses:
G1 = 96-byte uncompressed big-endian (x || y), G2 = 192 bytes
(x_c1 || x_c0 || y_c1 || y_c0), flag bits in the top three bits of the
first byte (compression=0 here; infinity flag honored).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

__all__ = ["P", "R", "G1_GEN", "G2_GEN", "BlsError",
           "g1_add", "g1_mul", "g1_msm", "g1_check",
           "g2_add", "g2_mul", "g2_msm", "g2_check",
           "pairing_check", "g1_encode", "g1_decode",
           "g2_encode", "g2_decode",
           "fr_add", "fr_sub", "fr_mul", "fr_pow", "fr_inv"]

# base field prime and subgroup order (standard BLS12-381 parameters)
P = 0x1A0111EA397FE69A4B1BA7B6434BACD764774B84F38512BF6730D2A0F6B0F6241EABFFFEB153FFFFB9FEFFFFFFFFAAAB
R = 0x73EDA753299D7D483339D80809A1D80553BDA402FFFE5BFEFFFFFFFF00000001

# |x| for the BLS parameter x = -0xd201000000010000 (x < 0)
BLS_X = 0xD201000000010000
BLS_X_IS_NEG = True

G1_GEN = (
    0x17F1D3A73197D7942695638C4FA9AC0FC3688C4F9774B905A14E3A3F171BAC586C55E83FF97A1AEFFB3AF00ADB22C6BB,
    0x08B3F481E3AAA0F1A09E30ED741D8AE4FCF5E095D5D00AF600DB18CB2C04B3EDD03CC744A2888AE40CAA232946C5E7E1,
)
G2_GEN = (
    (0x024AA2B2F08F0A91260805272DC51051C6E47AD4FA403B02B4510B647AE3D1770BAC0326A805BBEFD48056C8C121BDB8,
     0x13E02B6052719F607DACD3A088274F65596BD0D09920B61AB5DA61BBDC7F5049334CF11213945D57E5AC7D055D042B7E),
    (0x0CE5D527727D6E118CC9CDC6DA2E351AADFD9BAA8CBDD3A76D429A695160D12C923AC9CC3BACA289E193548608B82801,
     0x0606C4A02EA734CC32ACD2B02BC28B99CB3E287E85A763AF267492AB572E99AB3F370D275CEC1DA1AAA9075FF05F79BE),
)


class BlsError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Fp2 = Fp[u] / (u^2 + 1): elements as (c0, c1) meaning c0 + c1*u
# ---------------------------------------------------------------------------

def _f2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def _f2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def _f2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def _f2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) = a0b0 - a1b1 + (a0b1 + a1b0) u
    t0 = a[0] * b[0] % P
    t1 = a[1] * b[1] % P
    t2 = (a[0] + a[1]) * (b[0] + b[1]) % P
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def _f2_sqr(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    t0 = (a[0] + a[1]) * (a[0] - a[1]) % P
    t1 = 2 * a[0] * a[1] % P
    return (t0, t1)


def _f2_inv(a):
    # 1/(a0 + a1 u) = (a0 - a1 u) / (a0^2 + a1^2)
    d = (a[0] * a[0] + a[1] * a[1]) % P
    if d == 0:
        raise BlsError("Fp2 inversion of zero")
    di = pow(d, P - 2, P)
    return (a[0] * di % P, (-a[1]) * di % P)


def _f2_mul_scalar(a, k):
    return (a[0] * k % P, a[1] * k % P)


F2_ZERO = (0, 0)
F2_ONE = (1, 0)


# ---------------------------------------------------------------------------
# Fp6 = Fp2[v] / (v^3 - xi), xi = u + 1: elements (c0, c1, c2) of Fp2
# Fp12 = Fp6[w] / (w^2 - v):            elements (c0, c1) of Fp6
# ---------------------------------------------------------------------------

XI = (1, 1)  # u + 1


def _f2_mul_xi(a):
    # (a0 + a1 u)(1 + u) = a0 - a1 + (a0 + a1) u
    return ((a[0] - a[1]) % P, (a[0] + a[1]) % P)


def _f6_add(a, b):
    return tuple(_f2_add(x, y) for x, y in zip(a, b))


def _f6_sub(a, b):
    return tuple(_f2_sub(x, y) for x, y in zip(a, b))


def _f6_neg(a):
    return tuple(_f2_neg(x) for x in a)


def _f6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = _f2_mul(a0, b0)
    t1 = _f2_mul(a1, b1)
    t2 = _f2_mul(a2, b2)
    c0 = _f2_add(t0, _f2_mul_xi(_f2_sub(
        _f2_mul(_f2_add(a1, a2), _f2_add(b1, b2)), _f2_add(t1, t2))))
    c1 = _f2_add(_f2_sub(
        _f2_mul(_f2_add(a0, a1), _f2_add(b0, b1)), _f2_add(t0, t1)),
        _f2_mul_xi(t2))
    c2 = _f2_add(_f2_sub(
        _f2_mul(_f2_add(a0, a2), _f2_add(b0, b2)), _f2_add(t0, t2)),
        t1)
    return (c0, c1, c2)


def _f6_mul_by_v(a):
    # v * (c0 + c1 v + c2 v^2) = xi*c2 + c0 v + c1 v^2
    return (_f2_mul_xi(a[2]), a[0], a[1])


def _f6_inv(a):
    a0, a1, a2 = a
    t0 = _f2_sub(_f2_sqr(a0), _f2_mul_xi(_f2_mul(a1, a2)))
    t1 = _f2_sub(_f2_mul_xi(_f2_sqr(a2)), _f2_mul(a0, a1))
    t2 = _f2_sub(_f2_sqr(a1), _f2_mul(a0, a2))
    d = _f2_add(_f2_mul(a0, t0), _f2_mul_xi(
        _f2_add(_f2_mul(a2, t1), _f2_mul(a1, t2))))
    di = _f2_inv(d)
    return (_f2_mul(t0, di), _f2_mul(t1, di), _f2_mul(t2, di))


F6_ZERO = (F2_ZERO, F2_ZERO, F2_ZERO)
F6_ONE = (F2_ONE, F2_ZERO, F2_ZERO)


def _f12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = _f6_mul(a0, b0)
    t1 = _f6_mul(a1, b1)
    c0 = _f6_add(t0, _f6_mul_by_v(t1))
    c1 = _f6_sub(_f6_mul(_f6_add(a0, a1), _f6_add(b0, b1)),
                 _f6_add(t0, t1))
    return (c0, c1)


def _f12_sqr(a):
    return _f12_mul(a, a)


def _f12_inv(a):
    a0, a1 = a
    d = _f6_sub(_f6_mul(a0, a0), _f6_mul_by_v(_f6_mul(a1, a1)))
    di = _f6_inv(d)
    return (_f6_mul(a0, di), _f6_neg(_f6_mul(a1, di)))


def _f12_conj(a):
    return (a[0], _f6_neg(a[1]))


F12_ONE = (F6_ONE, F6_ZERO)


def _f12_pow(a, e: int):
    out = F12_ONE
    base = a
    while e:
        if e & 1:
            out = _f12_mul(out, base)
        base = _f12_sqr(base)
        e >>= 1
    return out


# Frobenius: gamma constants computed at import (xi^((p^k - 1)/6)
# powers), so no long literal tables are carried in source.

def _f2_pow(a, e: int):
    out = F2_ONE
    base = a
    while e:
        if e & 1:
            out = _f2_mul(out, base)
        base = _f2_sqr(base)
        e >>= 1
    return out


_FROB_GAMMA1 = [_f2_pow(XI, i * (P - 1) // 6) for i in range(6)]


def _f2_frob(a):
    """Conjugation: (a0 + a1 u)^p = a0 - a1 u since u^2 = -1."""
    return (a[0], (-a[1]) % P)


def _f6_frob(a):
    c0 = _f2_frob(a[0])
    c1 = _f2_mul(_f2_frob(a[1]), _FROB_GAMMA1[2])
    c2 = _f2_mul(_f2_frob(a[2]), _FROB_GAMMA1[4])
    return (c0, c1, c2)


def _f12_frob(a):
    a0, a1 = a
    c0 = _f6_frob(a0)
    t = _f6_frob(a1)
    c1 = tuple(_f2_mul(x, _FROB_GAMMA1[1]) for x in t)
    return (c0, c1)


# ---------------------------------------------------------------------------
# Curves (Jacobian coordinates over a generic field)
# ---------------------------------------------------------------------------

class _Ops:
    """Field ops bundle so G1 (Fp) and G2 (Fp2) share the point code."""

    def __init__(self, add, sub, neg, mul, sqr, inv, mul_small, zero,
                 one, b):
        self.add, self.sub, self.neg = add, sub, neg
        self.mul, self.sqr, self.inv = mul, sqr, inv
        self.mul_small = mul_small  # field elem x small int
        self.zero, self.one, self.b = zero, one, b


_FP_OPS = _Ops(
    add=lambda a, b: (a + b) % P, sub=lambda a, b: (a - b) % P,
    neg=lambda a: (-a) % P, mul=lambda a, b: a * b % P,
    sqr=lambda a: a * a % P,
    inv=lambda a: pow(a, P - 2, P) if a else (_ for _ in ()).throw(
        BlsError("Fp inversion of zero")),
    mul_small=lambda a, k: a * k % P,
    zero=0, one=1, b=4)

_FP2_OPS = _Ops(
    add=_f2_add, sub=_f2_sub, neg=_f2_neg, mul=_f2_mul, sqr=_f2_sqr,
    inv=_f2_inv, mul_small=_f2_mul_scalar,
    zero=F2_ZERO, one=F2_ONE, b=_f2_mul_xi((4, 0)))


def _on_curve(ops: _Ops, pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    lhs = ops.sqr(y)
    rhs = ops.add(ops.mul(ops.sqr(x), x), ops.b)
    return lhs == rhs


def _pt_add(ops: _Ops, p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 != y2 or y1 == ops.zero:
            return None
        # doubling: l = 3x^2 / 2y
        num = ops.mul_small(ops.sqr(x1), 3)
        den = ops.mul_small(y1, 2)
        lam = ops.mul(num, ops.inv(den))
    else:
        lam = ops.mul(ops.sub(y2, y1), ops.inv(ops.sub(x2, x1)))
    x3 = ops.sub(ops.sub(ops.sqr(lam), x1), x2)
    y3 = ops.sub(ops.mul(lam, ops.sub(x1, x3)), y1)
    return (x3, y3)


def _pt_neg(ops: _Ops, pt):
    if pt is None:
        return None
    return (pt[0], ops.neg(pt[1]))


def _pt_mul(ops: _Ops, k: int, pt, reduce: bool = True):
    """``reduce=False`` keeps the raw scalar — REQUIRED for the
    order-r subgroup test, where k=R must not collapse to 0."""
    if reduce:
        k %= R
    out = None
    add = pt
    while k:
        if k & 1:
            out = _pt_add(ops, out, add)
        add = _pt_add(ops, add, add)
        k >>= 1
    return out


# ---------------------------------------------------------------------------
# Public G1/G2 API (affine tuples; None = point at infinity)
# ---------------------------------------------------------------------------

def g1_check(pt, subgroup: bool = True):
    if not _on_curve(_FP_OPS, pt):
        raise BlsError("G1 point not on curve")
    if subgroup and pt is not None and \
            _pt_mul(_FP_OPS, R, pt, reduce=False) is not None:
        raise BlsError("G1 point not in the r-order subgroup")
    return pt


def g2_check(pt, subgroup: bool = True):
    if not _on_curve(_FP2_OPS, pt):
        raise BlsError("G2 point not on curve")
    if subgroup and pt is not None and \
            _pt_mul(_FP2_OPS, R, pt, reduce=False) is not None:
        raise BlsError("G2 point not in the r-order subgroup")
    return pt


def g1_add(a, b):
    return _pt_add(_FP_OPS, a, b)


def g1_mul(k: int, pt):
    return _pt_mul(_FP_OPS, k, pt)


def g1_msm(pairs: List[Tuple[int, object]]):
    out = None
    for k, pt in pairs:
        out = _pt_add(_FP_OPS, out, _pt_mul(_FP_OPS, k, pt))
    return out


def g2_add(a, b):
    return _pt_add(_FP2_OPS, a, b)


def g2_mul(k: int, pt):
    return _pt_mul(_FP2_OPS, k, pt)


def g2_msm(pairs: List[Tuple[int, object]]):
    out = None
    for k, pt in pairs:
        out = _pt_add(_FP2_OPS, out, _pt_mul(_FP2_OPS, k, pt))
    return out


# ---------------------------------------------------------------------------
# Pairing: optimal ate
# ---------------------------------------------------------------------------

def _emb_fp(a: int):
    """Fp -> Fp12."""
    return (((a % P, 0), F2_ZERO, F2_ZERO), F6_ZERO)


def _emb_f2_w2(a):
    """a * w^2 with a in Fp2: w^2 = v -> c0 slot 1 of the Fp6 c0."""
    return ((F2_ZERO, a, F2_ZERO), F6_ZERO)


def _emb_f2_w3(a):
    """a * w^3 = a * v * w -> c1 slot 1."""
    return (F6_ZERO, (F2_ZERO, a, F2_ZERO))


def _emb_f2(a):
    """Fp2 -> Fp12 (constant slot)."""
    return ((a, F2_ZERO, F2_ZERO), F6_ZERO)


# w^2 = v and w^3 = v*w as Fp12 elements, with their inverses
# precomputed once — the untwist divides by them
_W2 = ((F2_ZERO, F2_ONE, F2_ZERO), F6_ZERO)
_W3 = (F6_ZERO, (F2_ZERO, F2_ONE, F2_ZERO))
_W2_INV = _f12_inv(_W2)
_W3_INV = _f12_inv(_W3)


def _emb_g2(q):
    """G2 (twist) point -> E(Fp12): the untwist (x/w^2, y/w^3) — this
    direction verified on-curve (y^2 = x^3 + 4 over Fp12) for the
    published G2 generator."""
    x, y = q
    return (_f12_mul(_emb_f2(x), _W2_INV),
            _f12_mul(_emb_f2(y), _W3_INV))


def _f12_add(a, b):
    return (_f6_add(a[0], b[0]), _f6_add(a[1], b[1]))


def _f12_sub(a, b):
    return (_f6_sub(a[0], b[0]), _f6_sub(a[1], b[1]))


def _f12_is_zero(a):
    return a == (F6_ZERO, F6_ZERO)


def _line_f12(q1, q2, p):
    """Line through embedded G2 points q1, q2 evaluated at embedded
    G1 point p — all in Fp12 (slow, transparent)."""
    x1, y1 = q1
    x2, y2 = q2
    xp, yp = p
    if x1 == x2 and y1 == y2:
        num = _f12_mul(_f12_sqr(x1), _emb_fp(3))
        den = _f12_mul(y1, _emb_fp(2))
        lam = _f12_mul(num, _f12_inv(den))
    elif x1 == x2:
        return _f12_sub(xp, x1)
    else:
        lam = _f12_mul(_f12_sub(y2, y1), _f12_inv(_f12_sub(x2, x1)))
    return _f12_sub(_f12_mul(lam, _f12_sub(xp, x1)),
                    _f12_sub(yp, y1))


def _miller_loop(q, p) -> tuple:
    """f_{|x|, Q}(P) over the embedded points; inverted at the end for
    the negative BLS parameter."""
    if q is None or p is None:
        return F12_ONE
    qe = _emb_g2(q)
    pe = (_emb_fp(p[0]), _emb_fp(p[1]))
    t = qe
    f = F12_ONE
    for bit in bin(BLS_X)[3:]:
        f = _f12_mul(_f12_sqr(f), _line_f12(t, t, pe))
        t2 = _pt_add_f12(t, t)
        t = t2
        if bit == "1":
            f = _f12_mul(f, _line_f12(t, qe, pe))
            t = _pt_add_f12(t, qe)
    if BLS_X_IS_NEG:
        f = _f12_conj(f)  # unitary inverse after final exp's easy part
    return f


def _pt_add_f12(p1, p2):
    """Affine addition on E(Fp12): y^2 = x^3 + 4."""
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 != y2 or _f12_is_zero(y1):
            return None
        num = _f12_mul(_f12_sqr(x1), _emb_fp(3))
        den = _f12_mul(y1, _emb_fp(2))
        lam = _f12_mul(num, _f12_inv(den))
    else:
        lam = _f12_mul(_f12_sub(y2, y1), _f12_inv(_f12_sub(x2, x1)))
    x3 = _f12_sub(_f12_sub(_f12_sqr(lam), x1), x2)
    y3 = _f12_sub(_f12_mul(lam, _f12_sub(x1, x3)), y1)
    return (x3, y3)


def _final_exponentiation(f):
    """f^((p^12 - 1) / r) via the (p^6-1)(p^2+1) easy part and a plain
    big-exponent hard part (correctness over speed)."""
    # easy part
    f1 = _f12_mul(_f12_conj(f), _f12_inv(f))       # f^(p^6 - 1)
    f2 = _f12_mul(_f12_frob(_f12_frob(f1)), f1)    # ^(p^2 + 1)
    # hard part: (p^4 - p^2 + 1) / r
    e = (P ** 4 - P ** 2 + 1) // R
    return _f12_pow(f2, e)


def pairing_check(pairs: List[Tuple[object, object]]) -> bool:
    """prod e(P_i, Q_i) == 1 — the multi-pairing check the host
    exposes. P_i in G1, Q_i in G2 (affine or None)."""
    f = F12_ONE
    for p, q in pairs:
        if p is None or q is None:
            continue  # e(O, Q) = e(P, O) = 1
        f = _f12_mul(f, _miller_loop(q, p))
    return _final_exponentiation(f) == F12_ONE


# ---------------------------------------------------------------------------
# Serialization (ZCash format: 3 flag bits in the first byte)
# ---------------------------------------------------------------------------

_FLAG_COMPRESSED = 0x80
_FLAG_INFINITY = 0x40
_FLAG_SORT = 0x20


def g1_encode(pt) -> bytes:
    if pt is None:
        out = bytearray(96)
        out[0] = _FLAG_INFINITY
        return bytes(out)
    return pt[0].to_bytes(48, "big") + pt[1].to_bytes(48, "big")


def g1_decode(raw: bytes, subgroup_check: bool = True):
    if len(raw) != 96:
        raise BlsError("G1 uncompressed encoding must be 96 bytes")
    flags = raw[0] & 0xE0
    if flags & _FLAG_COMPRESSED:
        raise BlsError("compressed G1 encoding not accepted here")
    if flags & _FLAG_INFINITY:
        if any(raw[1:]) or raw[0] != _FLAG_INFINITY:
            raise BlsError("malformed G1 infinity encoding")
        return None
    x = int.from_bytes(raw[:48], "big")
    y = int.from_bytes(raw[48:], "big")
    if x >= P or y >= P:
        raise BlsError("G1 coordinate out of field range")
    return g1_check((x, y), subgroup=subgroup_check)


def g2_encode(pt) -> bytes:
    if pt is None:
        out = bytearray(192)
        out[0] = _FLAG_INFINITY
        return bytes(out)
    (x0, x1), (y0, y1) = pt
    return (x1.to_bytes(48, "big") + x0.to_bytes(48, "big") +
            y1.to_bytes(48, "big") + y0.to_bytes(48, "big"))


def g2_decode(raw: bytes, subgroup_check: bool = True):
    if len(raw) != 192:
        raise BlsError("G2 uncompressed encoding must be 192 bytes")
    flags = raw[0] & 0xE0
    if flags & _FLAG_COMPRESSED:
        raise BlsError("compressed G2 encoding not accepted here")
    if flags & _FLAG_INFINITY:
        if any(raw[1:]) or raw[0] != _FLAG_INFINITY:
            raise BlsError("malformed G2 infinity encoding")
        return None
    x1 = int.from_bytes(raw[0:48], "big")
    x0 = int.from_bytes(raw[48:96], "big")
    y1 = int.from_bytes(raw[96:144], "big")
    y0 = int.from_bytes(raw[144:192], "big")
    for c in (x0, x1, y0, y1):
        if c >= P:
            raise BlsError("G2 coordinate out of field range")
    return g2_check(((x0, x1), (y0, y1)), subgroup=subgroup_check)


# ---------------------------------------------------------------------------
# Fr scalar field
# ---------------------------------------------------------------------------

def fr_add(a: int, b: int) -> int:
    return (a + b) % R


def fr_sub(a: int, b: int) -> int:
    return (a - b) % R


def fr_mul(a: int, b: int) -> int:
    return a * b % R


def fr_pow(a: int, e: int) -> int:
    return pow(a % R, e, R)


def fr_inv(a: int) -> int:
    a %= R
    if a == 0:
        raise BlsError("Fr inversion of zero")
    return pow(a, R - 2, R)
