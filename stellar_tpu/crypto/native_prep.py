"""ctypes bridge to the C++ batch host-prep for ed25519 verification
(``native/ed25519_prep.cpp``): multithreaded SHA-512(R||A||M) mod L.

Mirrors the loader pattern of :mod:`stellar_tpu.utils.native`. Pure-Python
fallback (hashlib loop) keeps the framework functional without a
toolchain; differential tests pin the two together.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Sequence

import numpy as np

__all__ = ["available", "prep_batch", "sha512_batch"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "ed25519_prep.cpp")
_LIB = os.path.join(_REPO_ROOT, "build", "libed25519prep.so")

_lock = threading.Lock()
_lib = None
_tried = False

_L = 2**252 + 27742317777372353535851937790883648493


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or \
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_LIB), exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-pthread",
                     "-o", _LIB, _SRC],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB)
            u8p = ctypes.POINTER(ctypes.c_uint8)
            u64p = ctypes.POINTER(ctypes.c_uint64)
            lib.ed25519_prep_batch.argtypes = [
                u8p, u8p, u8p, u64p, u64p, ctypes.c_uint64, ctypes.c_int,
                u8p]
            lib.sha512_batch.argtypes = [u8p, u64p, u64p, ctypes.c_uint64,
                                         u8p]
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _u8(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _u64(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64))


def prep_batch(r: np.ndarray, a: np.ndarray, msgs: Sequence[bytes],
               nthreads: int = 0) -> np.ndarray:
    """h[i] = SHA512(r[i] || a[i] || msgs[i]) mod L as (n, 32) uint8 LE.

    r, a: (n, 32) uint8 C-contiguous arrays.
    """
    n = len(msgs)
    out = np.empty((n, 32), dtype=np.uint8)
    lib = _load()
    if lib is None:
        for i, m in enumerate(msgs):
            d = hashlib.sha512(r[i].tobytes() + a[i].tobytes() + m).digest()
            out[i] = np.frombuffer(
                (int.from_bytes(d, "little") % _L).to_bytes(32, "little"),
                dtype=np.uint8)
        return out
    blob = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.uint64, count=n)
    offs = np.zeros(n, dtype=np.uint64)
    np.cumsum(lens[:-1], out=offs[1:])
    if nthreads <= 0:
        nthreads = min(8, os.cpu_count() or 1)
    blob_arr = np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, dtype=np.uint8)
    r = np.ascontiguousarray(r)
    a = np.ascontiguousarray(a)
    lib.ed25519_prep_batch(_u8(r), _u8(a), _u8(blob_arr), _u64(offs),
                           _u64(lens), n, nthreads, _u8(out))
    return out


def sha512_batch(msgs: Sequence[bytes]) -> np.ndarray:
    """(n, 64) uint8 SHA-512 digests (test helper for the native hash)."""
    n = len(msgs)
    out = np.empty((n, 64), dtype=np.uint8)
    lib = _load()
    if lib is None:
        for i, m in enumerate(msgs):
            out[i] = np.frombuffer(hashlib.sha512(m).digest(), np.uint8)
        return out
    blob = b"".join(msgs)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.uint64, count=n)
    offs = np.zeros(n, dtype=np.uint64)
    np.cumsum(lens[:-1], out=offs[1:])
    blob_arr = np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, dtype=np.uint8)
    lib.sha512_batch(_u8(blob_arr), _u64(offs), _u64(lens), n, _u8(out))
    return out
