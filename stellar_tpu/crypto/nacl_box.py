"""libsodium ``crypto_box_seal`` construction: X25519 +
XSalsa20-Poly1305 (reference scope: ``SurveyManager`` encrypts survey
response bodies with libsodium sealed boxes,
``src/overlay/SurveyManager.h:20-38`` / ``src/crypto/Curve25519.cpp``).

Pure-Python Salsa20 core / HSalsa20 / Poly1305 assembled exactly per
the NaCl papers and the libsodium sealed-box layout:

    sealed = ephemeral_pk(32) || secretbox(m,
                 nonce = BLAKE2b-192(ephemeral_pk || recipient_pk),
                 key   = HSalsa20(X25519(ephemeral_sk, recipient_pk),
                                   0^16))

Verification in-tree (no libsodium/PyNaCl ships in this image): the
Salsa20 rounds are differential-tested against OpenSSL's scrypt
(hashlib.scrypt BlockMix runs Salsa20/8 over the same core), Poly1305
against the RFC 8439 vector, quarterround against the Salsa20 spec
examples, and X25519 against the ``cryptography`` package.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import struct

from stellar_tpu.crypto import curve25519 as c25519

__all__ = ["salsa20_core", "hsalsa20", "xsalsa20_xor", "poly1305",
           "secretbox", "secretbox_open", "box_beforenm",
           "seal", "seal_open", "BoxError"]

_M32 = 0xFFFFFFFF

# "expand 32-byte k"
_SIGMA = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)


class BoxError(ValueError):
    pass


def _rotl(v: int, s: int) -> int:
    return ((v << s) | (v >> (32 - s))) & _M32


def _quarterround(y0, y1, y2, y3):
    y1 ^= _rotl((y0 + y3) & _M32, 7)
    y2 ^= _rotl((y1 + y0) & _M32, 9)
    y3 ^= _rotl((y2 + y1) & _M32, 13)
    y0 ^= _rotl((y3 + y2) & _M32, 18)
    return y0, y1, y2, y3


def _rounds(x: list, rounds: int):
    """In-place double-rounds over a 16-word state."""
    for _ in range(0, rounds, 2):
        # columnround
        x[0], x[4], x[8], x[12] = _quarterround(x[0], x[4], x[8], x[12])
        x[5], x[9], x[13], x[1] = _quarterround(x[5], x[9], x[13], x[1])
        x[10], x[14], x[2], x[6] = _quarterround(x[10], x[14], x[2],
                                                 x[6])
        x[15], x[3], x[7], x[11] = _quarterround(x[15], x[3], x[7],
                                                 x[11])
        # rowround
        x[0], x[1], x[2], x[3] = _quarterround(x[0], x[1], x[2], x[3])
        x[5], x[6], x[7], x[4] = _quarterround(x[5], x[6], x[7], x[4])
        x[10], x[11], x[8], x[9] = _quarterround(x[10], x[11], x[8],
                                                 x[9])
        x[15], x[12], x[13], x[14] = _quarterround(x[15], x[12], x[13],
                                                   x[14])


def salsa20_core(block64: bytes, rounds: int = 20) -> bytes:
    """The Salsa20 hash: 16 LE words -> rounds -> feedforward add."""
    inp = list(struct.unpack("<16I", block64))
    x = list(inp)
    _rounds(x, rounds)
    return struct.pack("<16I",
                       *((a + b) & _M32 for a, b in zip(x, inp)))


def _key_state(key32: bytes, in16: bytes) -> list:
    k = struct.unpack("<8I", key32)
    n = struct.unpack("<4I", in16)
    return [_SIGMA[0], k[0], k[1], k[2], k[3], _SIGMA[1],
            n[0], n[1], n[2], n[3], _SIGMA[2],
            k[4], k[5], k[6], k[7], _SIGMA[3]]


def hsalsa20(key32: bytes, in16: bytes) -> bytes:
    """HSalsa20: rounds WITHOUT feedforward; output words
    0,5,10,15,6,7,8,9 (the nonce-extension PRF of XSalsa20)."""
    x = _key_state(key32, in16)
    _rounds(x, 20)
    return struct.pack("<8I", x[0], x[5], x[10], x[15],
                       x[6], x[7], x[8], x[9])


def xsalsa20_xor(data: bytes, nonce24: bytes, key32: bytes,
                 counter: int = 0) -> bytes:
    """XSalsa20 stream XOR: HSalsa20 subkey, then Salsa20 with the
    trailing 8 nonce bytes and a 64-bit LE block counter."""
    if len(nonce24) != 24 or len(key32) != 32:
        raise BoxError("bad nonce/key length")
    subkey = hsalsa20(key32, nonce24[:16])
    out = bytearray()
    n8 = nonce24[16:24]
    for i in range((len(data) + 63) // 64):
        block_in = n8 + struct.pack("<Q", counter + i)
        state = _key_state(subkey, block_in)
        ks = salsa20_core(struct.pack("<16I", *state))
        chunk = data[64 * i:64 * (i + 1)]
        out += bytes(a ^ b for a, b in zip(chunk, ks))
    return bytes(out)


def poly1305(msg: bytes, key32: bytes) -> bytes:
    """Poly1305 one-time MAC (NaCl/RFC 8439 — same function)."""
    r = int.from_bytes(key32[:16], "little")
    r &= 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(key32[16:32], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        chunk = msg[i:i + 16]
        n = int.from_bytes(chunk, "little") + (1 << (8 * len(chunk)))
        acc = ((acc + n) * r) % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def secretbox(m: bytes, nonce24: bytes, key32: bytes) -> bytes:
    """crypto_secretbox_xsalsa20poly1305 (detached layout folded to
    the combined tag||ciphertext wire form)."""
    first = xsalsa20_xor(b"\x00" * 32 + m, nonce24, key32)
    poly_key, c = first[:32], first[32:]
    return poly1305(c, poly_key) + c


def secretbox_open(boxed: bytes, nonce24: bytes, key32: bytes) -> bytes:
    if len(boxed) < 16:
        raise BoxError("box too short")
    tag, c = boxed[:16], boxed[16:]
    poly_key = xsalsa20_xor(b"\x00" * 32, nonce24, key32)
    if not _hmac.compare_digest(tag, poly1305(c, poly_key)):
        raise BoxError("bad box tag")
    return xsalsa20_xor(b"\x00" * 32 + c, nonce24, key32)[32:]


def box_beforenm(pk32: bytes, sk32: bytes) -> bytes:
    """crypto_box shared key: HSalsa20(X25519(sk, pk), 0^16)."""
    shared = c25519.scalarmult(sk32, pk32)
    return hsalsa20(shared, b"\x00" * 16)


def _seal_nonce(epk: bytes, rpk: bytes) -> bytes:
    return hashlib.blake2b(epk + rpk, digest_size=24).digest()


def seal(m: bytes, recipient_pk: bytes) -> bytes:
    """crypto_box_seal: anonymous sender, ephemeral key per message."""
    esk = c25519.random_secret()
    epk = c25519.public_from_secret(esk)
    k = box_beforenm(recipient_pk, esk)
    return epk + secretbox(m, _seal_nonce(epk, recipient_pk), k)


def seal_open(sealed: bytes, recipient_sk: bytes,
              recipient_pk: bytes) -> bytes:
    if len(sealed) < 48:
        raise BoxError("sealed box too short")
    epk = sealed[:32]
    k = box_beforenm(epk, recipient_sk)
    return secretbox_open(sealed[32:],
                          _seal_nonce(epk, recipient_pk), k)
