"""Host↔TPU bridge for batch ed25519 verification — workload #1 of the
generic batch-dispatch engine.

This is the TPU-native replacement for the reference's verify boundary
(``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``): callers
hand over (pubkey, message, signature) triples; they get back a bool per
triple with **bit-identical accept/reject decisions to libsodium's**
``crypto_sign_verify_detached``.

Division of labor (mirrors libsodium's own decomposition):

* host (cheap, byte-level): length checks, canonical-s (s < L),
  canonical-A (y < p), small-order blocklist for R and A — vectorized
  numpy; SHA-512 of R||A||M and reduction mod L — multithreaded C++
  (:mod:`stellar_tpu.crypto.native_prep`), ~12 ms → <1 ms for 2k sigs;
* device (the FLOPs): point decompression + 252-doubling Strauss-Shamir
  double-scalar multiplication + encode-compare, batched over the trailing
  lane axis (:mod:`stellar_tpu.ops.verify`). The device receives only raw
  32-byte A/R/s/h rows (256 KB per 2k sigs) and unpacks scalar digits
  itself.

Since ISSUE 7 the dispatch machinery itself — jit bucket cache,
per-device fault domains + degraded re-shard, circuit breakers,
watchdogged fetches, the sampled result-integrity audit, host-oracle
failover, and span instrumentation — lives in the workload-agnostic
:class:`stellar_tpu.parallel.batch_engine.BatchEngine`;
:class:`BatchVerifier` is the engine driven by the
:class:`Ed25519Workload` plugin, bit-identical in behavior to the
pre-refactor module (every chaos / device-domain / soak gate runs
against this composition). The second workload on the same substrate
is batched SHA-256 (:mod:`stellar_tpu.crypto.batch_hasher`).

``submit`` is the asynchronous half of the API: it dispatches the device
kernel without blocking and returns a resolver, so a caller draining a
queue (herder txset validation, catchup replay) can overlap host prep of
the next batch with device execution of the current one — the "two queue
classes" latency strategy from SURVEY §7.

The process-wide verify-result cache (the reference's 0xffff-entry
``RandomEvictionCache``, ``SecretKey.cpp:44-48,318-338``) lives in
``stellar_tpu.crypto.keys``; :meth:`BatchVerifier.install` wires this
verifier in behind it. Fault tolerance and the result-integrity story
are the engine's (``docs/robustness.md``): degraded mode changes
latency, never decisions, and a corrupting accelerator never decides
signature validity.

For compatibility (tests, tools, the admin surface) this module
re-exports the engine's process-wide dispatch state and functions under
their historical names — ``configure_dispatch``, ``dispatch_health``,
``device_available``, the breaker, the probe state, the knobs.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from stellar_tpu.crypto import ed25519_ref as ref
from stellar_tpu.crypto import native_prep
from stellar_tpu.parallel import batch_engine, signer_tables
from stellar_tpu.parallel.batch_engine import (  # noqa: F401 (re-exports)
    DEFAULT_BUCKET_SIZES, RESOLVE_PHASES, RESOLVE_ROOT, BatchEngine,
    Workload, _auto_mesh, _breaker, _enter_host_only, _note_device_failure,
    _reset_dispatch_state_for_testing, configure_dispatch, device_available,
    dispatch_attribution, dispatch_degraded, dispatch_health,
    fleet_health_snapshot, host_only_mode, note_shed_onset,
    note_trace_event, register_fleet_health, register_service_health,
    served_counts, service_health_snapshot, start_device_probe,
    trace_ranges,
)
from stellar_tpu.utils import resilience, tracing
from stellar_tpu.utils.metrics import registry

__all__ = ["BatchVerifier", "Ed25519Workload", "Ed25519HotWorkload",
           "default_verifier",
           "device_available", "dispatch_health", "configure_dispatch",
           "dispatch_attribution", "dispatch_degraded",
           "note_shed_onset", "note_trace_event", "trace_ranges",
           "register_service_health", "register_fleet_health",
           "fleet_health_snapshot",
           "RESOLVE_PHASES", "RESOLVE_ROOT"]

_L = ref.L
_P = ref.P

# libsodium's blocklist, as a (14, 32) uint8 matrix for vectorized compare.
_SMALL_ORDER = np.stack([np.frombuffer(e, dtype=np.uint8)
                         for e in sorted(ref.SMALL_ORDER_ENCODINGS)])

_L_BYTES = np.frombuffer(_L.to_bytes(32, "little"), dtype=np.uint8)
_P_BYTES = np.frombuffer(_P.to_bytes(32, "little"), dtype=np.uint8)

# Mutable process-wide dispatch state lives in batch_engine (it is
# shared by every workload); module __getattr__ below forwards reads of
# the historical names (bv.DEADLINE_MS, bv._device_state, bv._probe,
# ...) so existing tests and tools keep working against the live
# values, not stale copies.
_ENGINE_STATE = ("DEADLINE_MS", "DISPATCH_RETRIES", "AUDIT_RATE",
                 "_device_state", "_probe", "_host_only")


def __getattr__(name: str):
    if name in _ENGINE_STATE:
        return getattr(batch_engine, name)
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")


def _host_verify_items(items: Sequence[tuple]) -> np.ndarray:
    """Bit-identical host re-verification of (pk, msg, sig) triples —
    the failover path. Libsodium's policy gate stays the single source
    of truth (``ed25519_ref._policy_gate``); curve equations ride the
    threaded native batch when it built, else the pure oracle."""
    from stellar_tpu.crypto import keys
    out = np.zeros(len(items), dtype=bool)
    good = [i for i, (pk, _m, sg) in enumerate(items)
            if len(pk) == 32 and len(sg) == 64]
    if good:
        res = keys._host_oracle_batch(
            [(None,) + tuple(items[i]) for i in good])
        for i, okv in zip(good, res):
            out[i] = bool(okv)
    return out


def _lt_le_bytes(vals: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """Per-row little-endian comparison vals < bound (vals (B,32) uint8)."""
    # compare from most significant byte down
    v = vals[:, ::-1].astype(np.int16)
    b = bound[::-1].astype(np.int16)
    diff = v - b[None, :]
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    any_nz = nz.any(axis=1)
    picked = diff[np.arange(len(vals)), first]
    return np.where(any_nz, picked < 0, False)  # equal -> not less


def _small_order_mask(enc: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 -> bool (B,) True where encoding is small-order,
    sign bit masked (libsodium ge25519_has_small_order)."""
    masked = enc.copy()
    masked[:, 31] &= 0x7F
    return (masked[:, None, :] == _SMALL_ORDER[None, :, :]).all(-1).any(-1)


class Ed25519Workload(Workload):
    """The ed25519 verify workload: host policy gates + SHA-512 prep in
    ``encode``, the signed-window Strauss-Shamir kernel on device, the
    libsodium-exact host oracle for failover and audit. The gate mask
    is the host policy verdict: a gate-rejected row is False regardless
    of device bits (``finalize`` ANDs it in), exactly libsodium's
    composed decision."""

    metrics_ns = "crypto.verify"
    span_ns = "verify"

    def encode(self, items: Sequence[tuple]
               ) -> Tuple[np.ndarray, tuple]:
        n = len(items)
        ok = np.ones(n, dtype=bool)
        # one frombuffer over joined bytes instead of three numpy row
        # writes per item — the per-item version was the single
        # biggest host-prep cost at 2k-signature batches
        msgs = []
        pk_parts = []
        sig_parts = []
        z32, z64 = bytes(32), bytes(64)
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                ok[i] = False
                pk_parts.append(z32)
                sig_parts.append(z64)
                msgs.append(b"")
            else:
                pk_parts.append(pk)
                sig_parts.append(sig)
                msgs.append(msg)
        a = np.frombuffer(b"".join(pk_parts),
                          dtype=np.uint8).reshape(n, 32)
        sig_mat = np.frombuffer(b"".join(sig_parts),
                                dtype=np.uint8).reshape(n, 64)
        r = np.ascontiguousarray(sig_mat[:, :32])
        s = np.ascontiguousarray(sig_mat[:, 32:])
        # h = SHA512(R||A||M) mod L — native multithreaded C++
        h = native_prep.prep_batch(r, a, msgs)
        # host policy checks (libsodium order: s canonical, small-order R/A,
        # canonical A)
        ok &= _lt_le_bytes(s, _L_BYTES)
        ok &= ~_small_order_mask(r)
        ok &= ~_small_order_mask(a)
        a_masked = a.copy()
        a_masked[:, 31] &= 0x7F
        ok &= _lt_le_bytes(a_masked, _P_BYTES)
        return ok, (a, r, s, h)

    def pad_rows(self) -> tuple:
        return (_PAD_A, _PAD_R, _PAD_S, _PAD_H)

    def kernel_fn(self):
        from stellar_tpu.ops import verify as vk
        return vk.verify_kernel

    def empty_result(self, n: int) -> np.ndarray:
        return np.zeros(n, dtype=bool)

    def host_result(self, items: Sequence[tuple]) -> np.ndarray:
        return _host_verify_items(items)

    def finalize(self, gate: np.ndarray, out: np.ndarray,
                 items: Sequence[tuple]) -> np.ndarray:
        return gate & out


class Ed25519HotWorkload(Ed25519Workload):
    """The HOT-SIGNER variant of the verify workload (ISSUE 16): rows
    whose pubkey already has a cached 128-entry affine A-table skip the
    in-kernel decompression + table build and run the byte-aligned
    radix-256 kernel (:func:`stellar_tpu.ops.verify.verify_kernel_hot`)
    with the table as a plain operand — ~24% fewer executed dsm MACs
    per lane than the cold radix-32 path (``tools/kernel_cost.py``
    ``dsm.hot`` vs ``dsm.cold``; docs/kernel_design.md §5).

    Items are ``((pk, msg, sig), table)`` pairs — the triple plus the
    cache entry the partitioning :meth:`BatchVerifier.submit` looked
    up for it. ``encode`` runs the UNCHANGED host policy gates over
    the triples (canonical s/A, small-order, lengths — the gate ANDs
    into the verdict exactly like the cold path), then replaces the
    pubkey operand with the stacked per-row tables. ``host_result``
    and the audit oracle see only the triples, so hot-served rows are
    audited against the very same libsodium-exact oracle as cold ones.

    ``variant_name`` keys this plugin's jit wrappers into the engine's
    per-variant cache: the pinned primary bucket shapes never grow.
    """

    variant_name = "hot"

    def encode(self, items: Sequence[tuple]
               ) -> Tuple[np.ndarray, tuple]:
        ok, (_a, r, s, h) = super().encode([it for it, _t in items])
        tables = np.stack([t for _it, t in items])
        return ok, (tables, r, s, h)

    def pad_rows(self) -> tuple:
        return (_PAD_TABLE, _PAD_R, _PAD_S, _PAD_H)

    def kernel_fn(self):
        from stellar_tpu.ops import verify as vk
        return vk.verify_kernel_hot

    def host_result(self, items: Sequence[tuple]) -> np.ndarray:
        return _host_verify_items([it for it, _t in items])

    def on_audit_conviction(self, items: Sequence[tuple]) -> None:
        # a corrupt-device conviction over a hot-served part evicts
        # every table that served it: a poisoned resident table must
        # never outlive the audit that caught it (the next sight
        # rebuilds from the pubkey bytes)
        for (pk, _m, _s), _t in items:
            signer_tables.signer_table_cache.evict(pk)


class BatchVerifier(BatchEngine):
    """Batched libsodium-exact ed25519 verifier with a jit bucket cache
    — the :class:`Ed25519Workload` riding the generic engine.

    Args:
      mesh: optional 1-D ``jax.sharding.Mesh``; if given (and it spans
        >= 2 devices), buckets divisible by the device count are split
        into per-device SUB-CHUNKS of the plain kernel — one
        attributable dispatch per device, quarantine/re-shard per
        ``stellar_tpu.parallel.device_health`` — instead of one
        ``shard_map`` call. Non-divisible buckets (and mesh=None) use
        a single whole-bucket dispatch under the global breaker.
      bucket_sizes: padded batch sizes, ascending; each dispatch shape
        compiles once (per serving device on the mesh path).
    """

    def __init__(self, mesh=None, bucket_sizes=(128, 512, 2048)):
        super().__init__(Ed25519Workload(), mesh=mesh,
                         bucket_sizes=bucket_sizes)
        self._hot = Ed25519HotWorkload()

    def submit(self, items: Sequence[tuple], trace_ids=None,
               variant=None) -> Callable[[], np.ndarray]:
        """Partitioning submit (ISSUE 16): rows whose signer already
        has a cached A-table ride the hot radix-256 kernel variant;
        the rest ride the unchanged cold path — which populates the
        cache, so a signer's FIRST sight is cold and every repeat is
        hot. The partition is decided per row at encode time from the
        cache alone (content-keyed, deterministic — two replicas fed
        the same traffic split identically); verdicts are bit-identical
        either way, so the split can never change a decision. With the
        cache disabled (``VERIFY_SIGNER_TABLE_ENABLED=0`` /
        ``configure_dispatch(signer_table_enabled=False)``) every row
        rides cold and this is exactly the pre-16 engine submit."""
        cache = signer_tables.signer_table_cache
        if variant is not None or not cache.enabled or not len(items):
            return super().submit(items, trace_ids=trace_ids,
                                  variant=variant)
        hot_idx, hot_items = [], []
        cold_idx, cold_items = [], []
        # the partition (cache traffic + first-sight table builds) is
        # host PREP work: it rides the prep phase span so the blocking
        # root's attribution stays >= 95% covered (METRICS_EXPORT_OK)
        with tracing.span(f"{self._span_ns}.prep"):
            for i, it in enumerate(items):
                pk = it[0]
                tab = cache.lookup(pk) if len(pk) == 32 else None
                if tab is not None:
                    hot_idx.append(i)
                    hot_items.append((it, tab))
                    continue
                cold_idx.append(i)
                cold_items.append(it)
                if len(pk) == 32:
                    # first sight: build + install NOW (one
                    # incremental chain + one batched inversion,
                    # ~1 ms) so the next occurrence — even later in
                    # this very batch — hits; THIS row still rides
                    # cold (its verdict needs the full decompress
                    # gate the cold kernel carries)
                    built = signer_tables.build_signer_table(pk)
                    if built is not None:
                        cache.install(pk, built)
        if not hot_items:
            return super().submit(items, trace_ids=trace_ids)
        registry.meter(
            "crypto.verify.signer_table.hot_rows").mark(len(hot_items))
        registry.meter(
            "crypto.verify.signer_table.cold_rows").mark(len(cold_items))
        hot_tr = [trace_ids[i] for i in hot_idx] if trace_ids else None
        cold_tr = [trace_ids[i] for i in cold_idx] if trace_ids \
            else None
        resolve_hot = super().submit(hot_items, trace_ids=hot_tr,
                                     variant=self._hot)
        resolve_cold = super().submit(cold_items, trace_ids=cold_tr) \
            if cold_items else None
        hot_ix = np.asarray(hot_idx, dtype=np.intp)
        cold_ix = np.asarray(cold_idx, dtype=np.intp)
        n = len(items)

        def resolve() -> np.ndarray:
            out = np.zeros(n, dtype=bool)
            out[hot_ix] = resolve_hot()
            if resolve_cold is not None:
                out[cold_ix] = resolve_cold()
            return out

        return resolve

    def verify_batch(self, items: Sequence[tuple]) -> np.ndarray:
        """items: sequence of (pk: bytes, msg: bytes, sig: bytes).
        Returns bool array, libsodium-identical per item. The root
        span covers the whole blocking call, so the per-phase spans
        under it attribute the blocking headline
        (:func:`dispatch_attribution`)."""
        return self.compute_batch(items)

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        """Single verify (uncached — the process-wide result cache lives
        in ``stellar_tpu.crypto.keys.verify_sig``; wire this verifier in
        behind it with :meth:`install`)."""
        return bool(self.verify_batch([(pk, msg, sig)])[0])

    def install(self, trickle_window_ms: Optional[float] = None
                ) -> "BatchVerifier":
        """Make this verifier the backend for ``keys.verify_sig`` so all
        single-sig call sites hit the shared cache first, then the TPU.

        ``trickle_window_ms`` wires a :class:`TrickleBatcher` in front:
        worth it when verify callers are CONCURRENT (overlay auth,
        threaded replay); in a purely single-threaded crank it only
        adds the window to each miss, so it stays opt-in."""
        from stellar_tpu.crypto import keys
        if trickle_window_ms is not None:
            batcher = TrickleBatcher(self, window_ms=trickle_window_ms)
            keys.set_verifier_backend(batcher.verify_sig)
        else:
            keys.set_verifier_backend(self.verify_sig)
        return self


class TrickleBatcher:
    """Micro-batch window for single-signature verify misses — the
    "trickle queue class" of SURVEY §7: bulk paths batch explicitly,
    but lone verifies (overlay auth handshakes, single SCP envelopes)
    would each pay a full solo device dispatch. Concurrent arrivals
    collect for up to ``window_ms`` (or ``max_batch``) and ride ONE
    dispatch; the synchronous bool API is preserved by parking callers
    on futures. The first caller of a window is the leader: it waits
    the window out, dispatches everything queued, and resolves every
    future; followers just block on theirs.

    The internal queue is BOUNDED (``max_pending``): a caller arriving
    when it is full gets a typed :class:`resilience.Overloaded` at
    ingress instead of growing the pending list without limit while a
    leader is stuck behind a slow dispatch — the same
    admission-control discipline as the resident verify service
    (``docs/robustness.md`` "Overload and load-shed")."""

    def __init__(self, verifier: BatchVerifier, window_ms: float = 1.0,
                 max_batch: int = 64, max_pending: int = 4096):
        self._verifier = verifier
        self._window = window_ms / 1000.0
        self._max = max_batch
        self._max_pending = max(1, int(max_pending))
        self._cv = threading.Condition()
        self._pending: list = []  # ((pk, msg, sig), Future)
        self._leader_active = False
        self._flush_asap = False
        self.dispatches = 0  # instrumentation (bench / tests)
        self.rejected = 0    # ingress Overloaded count

    def _dispatch_batch(self, batch: list) -> None:
        """Resolve one claimed batch through the verifier, fanning a
        leader-side failure out to every parked future (nobody hangs)."""
        try:
            results = self._verifier.verify_batch(
                [item for item, _f in batch])
        except BaseException as e:
            for _item, f in batch:
                f.set_exception(e)
            raise
        for (_item, f), ok in zip(batch, results):
            f.set_result(bool(ok))

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        from concurrent.futures import Future
        import time
        fut: Future = Future()
        with self._cv:
            if len(self._pending) >= self._max_pending:
                # bounded queue: reject at ingress, typed — the caller
                # decides whether to retry, shed, or fail its request
                self.rejected += 1
                registry.counter("crypto.verify.trickle.rejected").inc()
                raise resilience.Overloaded(
                    f"trickle window full ({self._max_pending} pending)",
                    kind="rejected", lane="trickle",
                    reason="queue-depth")
            self._pending.append(((pk, msg, sig), fut))
            if self._leader_active:
                if len(self._pending) >= self._max:
                    self._cv.notify_all()  # wake the leader early
                lead = False
            else:
                self._leader_active = True
                lead = True
        if lead:
            deadline = time.perf_counter() + self._window
            with self._cv:
                while len(self._pending) < self._max and \
                        not self._flush_asap:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = self._pending
                self._pending = []
                self._leader_active = False
                self._flush_asap = False
                # counted under the lock: the next window's leader can
                # already be running by the time this one dispatches
                self.dispatches += 1
            self._dispatch_batch(batch)
        return fut.result()

    def flush(self) -> int:
        """Dispatch everything queued RIGHT NOW instead of waiting the
        window out (service drain / shutdown path). Tolerant of
        enqueues racing a window close: all queue/leader transitions
        happen under the window lock, so an item is owned by exactly
        one dispatcher — if a leader is active it OWNS the pending
        list (flush just wakes it early and returns 0); otherwise
        flush claims the batch itself, and an enqueue arriving after
        the claim simply elects itself the next leader. Returns how
        many items THIS call dispatched."""
        with self._cv:
            if self._leader_active:
                self._flush_asap = True
                self._cv.notify_all()
                return 0
            batch = self._pending
            self._pending = []
            if batch:
                self.dispatches += 1
        if not batch:
            return 0
        self._dispatch_batch(batch)
        return len(batch)


# Padding rows: any syntactically valid inputs work (results are sliced
# off); use the base point with zero scalars so padded lanes stay cheap
# and never hit the decompress-failure path. Under the signed-window
# kernels zero scalars recode to all-zero digit streams, so every
# padded window select rides the identity patch of
# ops.edwards.table_select_affine (radix-32, PR 13) /
# ops.edwards.table_select (radix-16) — still valid, still cheap, and
# R' stays the identity, matching _PAD_R (pinned by
# tests/test_signed_recode.py::test_padding_rows_recode_to_identity_digits
# and its radix-32 sibling test_recode32_padding_rows_are_identity).
_PAD_A = np.frombuffer(ref.point_compress(ref.BASE), np.uint8).copy()[None]
_PAD_R = np.frombuffer(ref.point_compress(ref.IDENTITY), np.uint8).copy()[None]
_PAD_S = np.zeros((1, 32), dtype=np.uint8)
_PAD_H = np.zeros((1, 32), dtype=np.uint8)
# Hot-path padding table: the base point's cached A-table (any valid
# table works — padded lanes' zero scalars select the identity patch of
# table_select_affine and the results are sliced off). Built once at
# import by the same host builder that fills the signer cache.
_PAD_TABLE = signer_tables.build_signer_table(_PAD_A.tobytes())[None]


_default: Optional[BatchVerifier] = None
_default_lock = threading.Lock()


def default_verifier() -> BatchVerifier:
    """Process-wide verifier. Multi-chip hosts shard with ZERO config:
    the default mesh spans every local device and the standard bucket
    sizes divide any power-of-two chip count, so the v5e-8 target uses
    all chips out of the box (single-chip and CPU hosts are unchanged:
    the mesh is None)."""
    global _default
    with _default_lock:
        if _default is None:
            # the large buckets exist for COALESCED dispatches (catchup
            # replay fusing a whole checkpoint's signatures into one
            # round trip — the tunnel pays ~70ms per dispatch, so
            # chunking a 16k batch into 8x2048 would cost 8 round trips
            # for 8x less kernel work); small batches bucket as before
            _default = BatchVerifier(
                mesh=_auto_mesh(),
                bucket_sizes=DEFAULT_BUCKET_SIZES)
        return _default
