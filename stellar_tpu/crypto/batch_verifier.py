"""Host↔TPU bridge for batch ed25519 verification.

This is the TPU-native replacement for the reference's verify boundary
(``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``): callers
hand over (pubkey, message, signature) triples; they get back a bool per
triple with **bit-identical accept/reject decisions to libsodium's**
``crypto_sign_verify_detached``.

Division of labor (mirrors libsodium's own decomposition):

* host (cheap, byte-level): length checks, canonical-s (s < L),
  canonical-A (y < p), small-order blocklist for R and A — vectorized
  numpy; SHA-512 of R||A||M and reduction mod L — multithreaded C++
  (:mod:`stellar_tpu.crypto.native_prep`), ~12 ms → <1 ms for 2k sigs;
* device (the FLOPs): point decompression + 252-doubling Strauss-Shamir
  double-scalar multiplication + encode-compare, batched over the trailing
  lane axis (:mod:`stellar_tpu.ops.verify`). The device receives only raw
  32-byte A/R/s/h rows (256 KB per 2k sigs) and unpacks scalar digits
  itself.

Batches are padded to a small set of bucket sizes so each size
jit-compiles exactly once; oversize batches are chunked. A 1-D
``jax.sharding.Mesh`` shards the batch across chips with ``shard_map``
(no collectives — verify is data-parallel).

``submit`` is the asynchronous half of the API: it dispatches the device
kernel without blocking and returns a resolver, so a caller draining a
queue (herder txset validation, catchup replay) can overlap host prep of
the next batch with device execution of the current one — the "two queue
classes" latency strategy from SURVEY §7.

The process-wide verify-result cache (the reference's 0xffff-entry
``RandomEvictionCache``, ``SecretKey.cpp:44-48,318-338``) lives in
``stellar_tpu.crypto.keys``; :meth:`BatchVerifier.install` wires this
verifier in behind it.

Fault tolerance (``docs/robustness.md``): the tunnel's observed failure
mode is a HANG, not an exception — a mid-flight death would park
``resolve`` in ``np.asarray`` forever. Every device interaction is
therefore (a) deadline-guarded (``VERIFY_DEVICE_DEADLINE_MS``), (b)
accounted to a process-wide circuit breaker, and (c) backed by host
re-verification of the affected chunk through the same oracle stack
(`ed25519_ref`/`native_verify`) — degraded mode changes latency, never
decisions. The breaker also paces ``device_available`` re-probes so a
recovered tunnel is picked up (half-open) instead of being ignored for
the life of the process.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from stellar_tpu.crypto import ed25519_ref as ref
from stellar_tpu.crypto import native_prep
from stellar_tpu.utils import faults, resilience
from stellar_tpu.utils.metrics import registry

__all__ = ["BatchVerifier", "default_verifier", "device_available",
           "dispatch_health", "configure_dispatch"]

_L = ref.L
_P = ref.P

# libsodium's blocklist, as a (14, 32) uint8 matrix for vectorized compare.
_SMALL_ORDER = np.stack([np.frombuffer(e, dtype=np.uint8)
                         for e in sorted(ref.SMALL_ORDER_ENCODINGS)])

_L_BYTES = np.frombuffer(_L.to_bytes(32, "little"), dtype=np.uint8)
_P_BYTES = np.frombuffer(_P.to_bytes(32, "little"), dtype=np.uint8)


# ---------------- dispatch resilience policy ----------------
# Env defaults let tools/bench set these without a Config; a node pushes
# its Config knobs through configure_dispatch() at setup.

DEADLINE_MS = float(os.environ.get("VERIFY_DEVICE_DEADLINE_MS", "8000"))
DISPATCH_RETRIES = int(os.environ.get("VERIFY_DISPATCH_RETRIES", "1"))

# The production jit bucket ladder (default_verifier). Also the shape
# set the static overflow prover must cover — stellar_tpu.analysis.
# overflow proves the kernel at exactly these sizes (tools/analyze.py).
DEFAULT_BUCKET_SIZES = (128, 512, 2048, 4096, 8192, 16384)

_log = logging.getLogger("stellar_tpu.crypto")


def _on_breaker_transition(old: str, new: str) -> None:
    registry.counter("crypto.verify.breaker.transitions").inc()
    registry.gauge("crypto.verify.breaker.state").set(new)
    _log.warning("verify-device breaker %s -> %s", old, new)


_breaker = resilience.CircuitBreaker(
    name="verify-device",
    failure_threshold=int(os.environ.get(
        "VERIFY_BREAKER_FAILURE_THRESHOLD", "3")),
    backoff_min_s=float(os.environ.get(
        "VERIFY_BREAKER_BACKOFF_MIN_S", "1")),
    backoff_max_s=float(os.environ.get(
        "VERIFY_BREAKER_BACKOFF_MAX_S", "120")),
    on_transition=_on_breaker_transition)


def configure_dispatch(deadline_ms: Optional[float] = None,
                       dispatch_retries: Optional[int] = None,
                       failure_threshold: Optional[int] = None,
                       backoff_min_s: Optional[float] = None,
                       backoff_max_s: Optional[float] = None) -> None:
    """Push dispatch-resilience knobs (Config / tests); None keeps the
    current value. ``deadline_ms <= 0`` disables the resolve watchdog."""
    global DEADLINE_MS, DISPATCH_RETRIES
    if deadline_ms is not None:
        DEADLINE_MS = float(deadline_ms)
    if dispatch_retries is not None:
        DISPATCH_RETRIES = max(0, int(dispatch_retries))
    _breaker.configure(failure_threshold=failure_threshold,
                       backoff_min_s=backoff_min_s,
                       backoff_max_s=backoff_max_s)


def served_counts() -> dict:
    """Process-wide items-served tally by backend — the attribution
    bench.py records so a silent fallback can never be reported as a
    device number."""
    return {
        "device": registry.meter("crypto.verify.serve.device").count,
        "host_fallback": registry.meter(
            "crypto.verify.serve.host_fallback").count,
    }


def dispatch_health() -> dict:
    """Degradation observability (info endpoint / `dispatch` admin
    route): breaker state, backend attribution, fallback/retry/deadline
    counters, active knobs."""
    return {
        "device_state": _device_state or "unprobed",
        "breaker": _breaker.snapshot(),
        "deadline_ms": DEADLINE_MS,
        "dispatch_retries": DISPATCH_RETRIES,
        "served": served_counts(),
        "fallback_chunks": registry.meter(
            "crypto.verify.dispatch.fallback").count,
        "deadline_misses": registry.counter(
            "crypto.verify.dispatch.deadline_miss").count,
        "retries": registry.counter("crypto.verify.dispatch.retry").count,
        "short_circuits": registry.counter(
            "crypto.verify.dispatch.short_circuit").count,
    }


def _note_device_failure(stage: str, exc: BaseException) -> None:
    """One failing device interaction: breaker accounting + metrics.
    The caller re-verifies the affected chunk on the host."""
    registry.meter("crypto.verify.dispatch.fallback").mark()
    _breaker.record_failure()
    _log.warning(
        "device %s failed (%s: %s) — affected chunk re-verified on the "
        "host oracle", stage, type(exc).__name__, exc)


def _resolve_budget_s() -> Optional[float]:
    """Watchdog budget for one device-array fetch, or None (unguarded).
    Guarded whenever a real accelerator answered the probe (hangs are
    its observed failure mode) or a chaos fault is armed; UNGUARDED on
    jax-CPU/unprobed processes — XLA-on-CPU test executions are slow
    but cannot tunnel-hang, and a false deadline trip there would
    silently reroute differential tests to the host oracle."""
    if DEADLINE_MS <= 0:
        return None
    if faults.is_active(faults.RESOLVE) or faults.is_active(faults.DISPATCH):
        return DEADLINE_MS / 1000.0
    if _device_state in (None, "cpu"):
        return None
    return DEADLINE_MS / 1000.0


def _fetch(dev) -> np.ndarray:
    """The blocking half of a dispatch (runs under the watchdog)."""
    faults.inject(faults.RESOLVE)
    return np.asarray(dev)


def _host_verify_items(items: Sequence[tuple]) -> np.ndarray:
    """Bit-identical host re-verification of (pk, msg, sig) triples —
    the failover path. Libsodium's policy gate stays the single source
    of truth (``ed25519_ref._policy_gate``); curve equations ride the
    threaded native batch when it built, else the pure oracle."""
    from stellar_tpu.crypto import keys
    out = np.zeros(len(items), dtype=bool)
    good = [i for i, (pk, _m, sg) in enumerate(items)
            if len(pk) == 32 and len(sg) == 64]
    if good:
        res = keys._host_oracle_batch(
            [(None,) + tuple(items[i]) for i in good])
        for i, okv in zip(good, res):
            out[i] = bool(okv)
    return out


def _lt_le_bytes(vals: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """Per-row little-endian comparison vals < bound (vals (B,32) uint8)."""
    # compare from most significant byte down
    v = vals[:, ::-1].astype(np.int16)
    b = bound[::-1].astype(np.int16)
    diff = v - b[None, :]
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    any_nz = nz.any(axis=1)
    picked = diff[np.arange(len(vals)), first]
    return np.where(any_nz, picked < 0, False)  # equal -> not less


def _small_order_mask(enc: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 -> bool (B,) True where encoding is small-order,
    sign bit masked (libsodium ge25519_has_small_order)."""
    masked = enc.copy()
    masked[:, 31] &= 0x7F
    return (masked[:, None, :] == _SMALL_ORDER[None, :, :]).all(-1).any(-1)


class BatchVerifier:
    """Batched libsodium-exact ed25519 verifier with a jit bucket cache.

    Args:
      mesh: optional 1-D ``jax.sharding.Mesh``; if given, buckets divisible
        by the mesh size run under shard_map across its devices.
      bucket_sizes: padded batch sizes, ascending; each compiles once.
    """

    def __init__(self, mesh=None, bucket_sizes=(128, 512, 2048)):
        self._mesh = mesh
        self._buckets = tuple(sorted(bucket_sizes))
        # jit-wrapper cache: written from any thread that dispatches
        # (trickle leaders, chaos tests, the close path) — guarded, the
        # wrapper itself is built outside the lock (cheap; the compile
        # happens lazily at first call)
        self._kernels = {}
        self._kernels_lock = threading.Lock()
        # per-instance backend attribution (items served), mirrored into
        # the process-wide meters: bench and the chaos tests read these
        self._stats_lock = threading.Lock()
        self.served = {"device": 0, "host-fallback": 0}
        self.deadline_misses = 0
        self.retries = 0

    def _mark_served(self, kind: str, n: int) -> None:
        with self._stats_lock:
            self.served[kind] += n
        registry.meter("crypto.verify.serve." +
                       ("device" if kind == "device" else
                        "host_fallback")).mark(n)

    # ---------------- device dispatch ----------------

    def _kernel_for(self, n: int):
        with self._kernels_lock:
            kernel = self._kernels.get(n)
        if kernel is None:
            import jax
            from stellar_tpu.ops import verify as vk
            if self._mesh is not None and n % self._mesh.size == 0:
                built = vk.verify_kernel_sharded(self._mesh)
            else:
                built = jax.jit(vk.verify_kernel)
            with self._kernels_lock:
                # setdefault: a racing builder's wrapper wins once —
                # both wrappers trace identically, so the loser is
                # just garbage, never a different kernel
                kernel = self._kernels.setdefault(n, built)
        return kernel

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _dispatch_device(self, a: np.ndarray, r: np.ndarray, s: np.ndarray,
                         h: np.ndarray):
        """Dispatch padded/chunked batches to the jitted kernel without
        blocking; returns a list of (slice, chunk_len, device_array).
        A chunk whose dispatch raises (or that the open breaker refuses)
        carries ``None`` and is re-verified on the host at resolve time;
        transient dispatch exceptions get ``DISPATCH_RETRIES`` fresh
        attempts first."""
        n = a.shape[0]
        top = self._buckets[-1]
        pending = []
        start = 0
        while start < n:
            chunk = min(top, n - start)
            b = self._bucket(chunk)
            pad = b - chunk
            sl = slice(start, start + chunk)
            aa = np.concatenate([a[sl], np.repeat(_PAD_A, pad, 0)])
            rr = np.concatenate([r[sl], np.repeat(_PAD_R, pad, 0)])
            ss = np.concatenate([s[sl], np.repeat(_PAD_S, pad, 0)])
            hh = np.concatenate([h[sl], np.repeat(_PAD_H, pad, 0)])
            dev = None
            if _breaker.allow():
                attempts = 1 + DISPATCH_RETRIES
                for attempt in range(attempts):
                    try:
                        faults.inject(faults.DISPATCH)
                        dev = self._kernel_for(b)(aa, rr, ss, hh)
                        break
                    except Exception as e:
                        dev = None
                        if attempt + 1 < attempts:
                            registry.counter(
                                "crypto.verify.dispatch.retry").inc()
                            with self._stats_lock:
                                self.retries += 1
                        else:
                            _note_device_failure("dispatch", e)
            else:
                registry.counter(
                    "crypto.verify.dispatch.short_circuit").inc()
            pending.append((sl, chunk, dev))
            start += chunk
        return pending

    # ---------------- public API ----------------

    def _prep(self, items: Sequence[tuple]):
        from stellar_tpu.utils.tracing import zone
        with zone("crypto.prep"):
            return self._prep_inner(items)

    def _prep_inner(self, items: Sequence[tuple]):
        n = len(items)
        ok = np.ones(n, dtype=bool)
        # one frombuffer over joined bytes instead of three numpy row
        # writes per item — the per-item version was the single
        # biggest host-prep cost at 2k-signature batches
        msgs = []
        pk_parts = []
        sig_parts = []
        z32, z64 = bytes(32), bytes(64)
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                ok[i] = False
                pk_parts.append(z32)
                sig_parts.append(z64)
                msgs.append(b"")
            else:
                pk_parts.append(pk)
                sig_parts.append(sig)
                msgs.append(msg)
        a = np.frombuffer(b"".join(pk_parts),
                          dtype=np.uint8).reshape(n, 32)
        sig_mat = np.frombuffer(b"".join(sig_parts),
                                dtype=np.uint8).reshape(n, 64)
        r = np.ascontiguousarray(sig_mat[:, :32])
        s = np.ascontiguousarray(sig_mat[:, 32:])
        # h = SHA512(R||A||M) mod L — native multithreaded C++
        h = native_prep.prep_batch(r, a, msgs)
        # host policy checks (libsodium order: s canonical, small-order R/A,
        # canonical A)
        ok &= _lt_le_bytes(s, _L_BYTES)
        ok &= ~_small_order_mask(r)
        ok &= ~_small_order_mask(a)
        a_masked = a.copy()
        a_masked[:, 31] &= 0x7F
        ok &= _lt_le_bytes(a_masked, _P_BYTES)
        return ok, a, r, s, h

    def submit(self, items: Sequence[tuple]) -> Callable[[], np.ndarray]:
        """Asynchronous verify: host prep + non-blocking device dispatch.

        Returns a zero-arg resolver; calling it blocks on the device result
        and returns the per-item bool array. Multiple submitted batches
        pipeline on device (jax async dispatch), overlapping transfer and
        compute across batches.
        """
        n = len(items)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        ok, a, r, s, h = self._prep(items)
        if not ok.any():
            return lambda: ok
        pending = self._dispatch_device(a, r, s, h)
        items = list(items)  # pinned for possible host re-verification

        def resolve() -> np.ndarray:
            out = np.zeros(n, dtype=bool)
            for sl, chunk, dev in pending:
                got = None
                if dev is not None:
                    # an OPEN breaker short-circuits remaining chunks so
                    # one outage costs threshold x deadline, not chunks
                    # x deadline; state (not allow()) is checked because
                    # a half-open chunk already holds its grant from
                    # dispatch time and must be fetched, not refused
                    if _breaker.state != resilience.OPEN:
                        try:
                            got = resilience.call_with_deadline(
                                lambda d=dev: _fetch(d),
                                _resolve_budget_s(),
                                name="verify-resolve")
                        except resilience.DeadlineExceeded as e:
                            registry.counter(
                                "crypto.verify.dispatch.deadline_miss"
                            ).inc()
                            with self._stats_lock:
                                self.deadline_misses += 1
                            _note_device_failure("resolve-deadline", e)
                        except Exception as e:
                            _note_device_failure("resolve", e)
                    else:
                        registry.counter(
                            "crypto.verify.dispatch.short_circuit").inc()
                if got is not None:
                    out[sl] = np.asarray(got)[:chunk]
                    _breaker.record_success()
                    self._mark_served("device", chunk)
                else:
                    # failover: bit-identical host re-verification of
                    # the affected chunk (latency changes, decisions
                    # never do)
                    out[sl] = _host_verify_items(items[sl])
                    self._mark_served("host-fallback", chunk)
            return ok & out

        return resolve

    def verify_batch(self, items: Sequence[tuple]) -> np.ndarray:
        """items: sequence of (pk: bytes, msg: bytes, sig: bytes).
        Returns bool array, libsodium-identical per item."""
        return self.submit(items)()

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        """Single verify (uncached — the process-wide result cache lives
        in ``stellar_tpu.crypto.keys.verify_sig``; wire this verifier in
        behind it with :meth:`install`)."""
        return bool(self.verify_batch([(pk, msg, sig)])[0])

    def install(self, trickle_window_ms: Optional[float] = None
                ) -> "BatchVerifier":
        """Make this verifier the backend for ``keys.verify_sig`` so all
        single-sig call sites hit the shared cache first, then the TPU.

        ``trickle_window_ms`` wires a :class:`TrickleBatcher` in front:
        worth it when verify callers are CONCURRENT (overlay auth,
        threaded replay); in a purely single-threaded crank it only
        adds the window to each miss, so it stays opt-in."""
        from stellar_tpu.crypto import keys
        if trickle_window_ms is not None:
            batcher = TrickleBatcher(self, window_ms=trickle_window_ms)
            keys.set_verifier_backend(batcher.verify_sig)
        else:
            keys.set_verifier_backend(self.verify_sig)
        return self


class TrickleBatcher:
    """Micro-batch window for single-signature verify misses — the
    "trickle queue class" of SURVEY §7: bulk paths batch explicitly,
    but lone verifies (overlay auth handshakes, single SCP envelopes)
    would each pay a full solo device dispatch. Concurrent arrivals
    collect for up to ``window_ms`` (or ``max_batch``) and ride ONE
    dispatch; the synchronous bool API is preserved by parking callers
    on futures. The first caller of a window is the leader: it waits
    the window out, dispatches everything queued, and resolves every
    future; followers just block on theirs."""

    def __init__(self, verifier: BatchVerifier, window_ms: float = 1.0,
                 max_batch: int = 64):
        self._verifier = verifier
        self._window = window_ms / 1000.0
        self._max = max_batch
        self._cv = threading.Condition()
        self._pending: list = []  # ((pk, msg, sig), Future)
        self._leader_active = False
        self.dispatches = 0  # instrumentation (bench / tests)

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        from concurrent.futures import Future
        import time
        fut: Future = Future()
        with self._cv:
            self._pending.append(((pk, msg, sig), fut))
            if self._leader_active:
                if len(self._pending) >= self._max:
                    self._cv.notify_all()  # wake the leader early
                lead = False
            else:
                self._leader_active = True
                lead = True
        if lead:
            deadline = time.perf_counter() + self._window
            with self._cv:
                while len(self._pending) < self._max:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = self._pending
                self._pending = []
                self._leader_active = False
                # counted under the lock: the next window's leader can
                # already be running by the time this one dispatches
                self.dispatches += 1
            try:
                results = self._verifier.verify_batch(
                    [item for item, _f in batch])
            except BaseException as e:
                for _item, f in batch:
                    f.set_exception(e)
                raise
            for (_item, f), ok in zip(batch, results):
                f.set_result(bool(ok))
        return fut.result()


# Padding rows: any syntactically valid inputs work (results are sliced
# off); use the base point with zero scalars so padded lanes stay cheap
# and never hit the decompress-failure path. Under the signed-window
# kernel (PR 1) zero scalars recode to all-zero digit streams, so every
# padded window select rides the identity fixup of
# ops.edwards.table_select — still valid, still cheap, and R' stays the
# identity, matching _PAD_R (pinned by
# tests/test_signed_recode.py::test_padding_rows_recode_to_identity_digits).
_PAD_A = np.frombuffer(ref.point_compress(ref.BASE), np.uint8).copy()[None]
_PAD_R = np.frombuffer(ref.point_compress(ref.IDENTITY), np.uint8).copy()[None]
_PAD_S = np.zeros((1, 32), dtype=np.uint8)
_PAD_H = np.zeros((1, 32), dtype=np.uint8)


_default: Optional[BatchVerifier] = None
_default_lock = threading.Lock()

_device_state: Optional[str] = None  # None=unprobed, else platform|"dead"
_device_probe_lock = threading.Lock()
# current probe attempt: {"thread", "box", "started", "accounted"}.
# Unlike the pre-breaker design this is RE-ARMABLE: a "dead" verdict is
# re-probed when the breaker's backoff window expires, so a recovered
# tunnel is picked up instead of being ignored for the process lifetime.
_probe: Optional[dict] = None


def _launch_probe_locked() -> dict:
    """Spawn a fresh probe attempt (call with _device_probe_lock held).
    A probe on a wedged tunnel hangs; its daemon thread is abandoned
    when accounted — backoff growth bounds the leak to one thread per
    half-open window."""
    global _probe

    box: dict = {}

    def probe():
        try:
            faults.inject(faults.PROBE)
            import jax
            platform = jax.devices()[0].platform
            if platform != "cpu":
                # jax.devices() answers from the in-process cache once
                # the backend has initialized, so on an accelerator only
                # a REAL tiny dispatch proves the tunnel: a vacuous
                # success here would re-close a dispatch-opened breaker
                # (and reset its backoff) while the device is still
                # dead. On a dead tunnel this hangs — exactly what the
                # caller's watchdog + breaker accounting expect.
                np.asarray(jax.jit(lambda x: x + 1)(
                    np.zeros(2, np.int32)))
            box["platform"] = platform
        except Exception as e:  # no backend at all
            box["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True, name="device-probe")
    _probe = {"thread": t, "box": box, "started": time.monotonic(),
              "accounted": False}
    t.start()
    return _probe


def _account_probe_locked(cur: dict, hung: bool, timeout_s: float) -> None:
    """Turn a finished/overdue probe attempt into device state + breaker
    accounting (call with _device_probe_lock held; idempotent)."""
    global _device_state
    if cur["accounted"]:
        return
    cur["accounted"] = True
    box = cur["box"]
    if hung:
        _device_state = "dead"
        _breaker.record_failure()
        _log.warning(
            "device probe hung > %ss — signature verification falls "
            "back to the host oracle (breaker: %s)",
            timeout_s, _breaker.state)
    elif "platform" in box:
        _device_state = box["platform"]
        _breaker.record_success()
    else:
        _device_state = "dead"
        _breaker.record_failure()
        _log.warning(
            "device probe failed (%s) — signature verification falls "
            "back to the host oracle (breaker: %s)",
            box.get("error", "no backend"), _breaker.state)


def start_device_probe() -> None:
    """Fire the device probe WITHOUT waiting for it (idempotent).
    Called from LedgerManager/Application construction so the jax
    import + ``jax.devices()`` cost (seconds, or a hang on a dead
    tunnel) is paid during startup, never inside the first ledger
    close (the reference initializes its crypto stack at app start,
    not in ``closeLedger``)."""
    with _device_probe_lock:
        if _probe is None and _device_state is None:
            _launch_probe_locked()


def device_available(timeout_s: float = 30.0,
                     block: bool = True) -> bool:
    """True when a REAL accelerator is reachable AND the dispatch
    breaker is closed. Probes run in watchdogged threads: with the axon
    tunnel down, ``jax.devices()`` hangs forever rather than raising,
    and a node must fall back to the host oracle instead of hanging the
    close path (failure detection, not configuration). jax-CPU reports
    False permanently: batching bignum kernels through XLA-on-CPU is
    strictly slower than the host oracle, so auto mode only engages the
    device path on tpu-class hardware — that is configuration, and is
    never re-probed.

    A "dead" verdict, by contrast, is a FAILURE and heals: the circuit
    breaker re-probes (half-open) once its exponential-backoff window
    expires, so a tunnel that comes back is picked up without hammering
    one that stays down.

    ``block=False`` never waits: a still-pending probe answers False
    for now WITHOUT caching a verdict, so latency-critical callers
    (the close path) fall back to the host oracle this round and pick
    up the device once the probe resolves. A pending probe older than
    ``timeout_s`` is accounted hung even for non-blocking callers, so
    breaker-paced recovery works on a node that only ever asks
    non-blockingly."""
    start_device_probe()
    with _device_probe_lock:
        cur = _probe
        if cur is None or cur["accounted"]:
            if _device_state == "cpu":
                return False  # configuration, not a fault
            if _device_state not in (None, "dead") and \
                    _breaker.state == resilience.CLOSED:
                return True
            # dead (or breaker tripped by dispatch failures): re-probe
            # only when the backoff window has expired
            if _breaker.allow():
                cur = _launch_probe_locked()
            else:
                return False
    t = cur["thread"]
    if block:
        # join OUTSIDE the lock: a blocking waiter must never make a
        # concurrent block=False caller (the close path) wait on the
        # lock for up to timeout_s
        t.join(timeout_s)
    with _device_probe_lock:
        if not cur["accounted"]:
            if not t.is_alive():
                _account_probe_locked(cur, hung=False, timeout_s=timeout_s)
            elif block or \
                    time.monotonic() - cur["started"] > timeout_s:
                _account_probe_locked(cur, hung=True, timeout_s=timeout_s)
            else:
                return False  # pending — ask again later, don't cache
        return _device_state not in (None, "dead", "cpu") and \
            _breaker.state == resilience.CLOSED


def _reset_dispatch_state_for_testing() -> None:
    """Fresh probe/breaker state (chaos tests): equivalent to process
    start for the dispatch layer. Cumulative metrics are untouched."""
    global _device_state, _probe
    with _device_probe_lock:
        _device_state = None
        _probe = None
    _breaker.record_success()  # closed, zero failures, backoff reset


def _auto_mesh():
    """1-D mesh over every local device, or None when single-device.
    Buckets not divisible by the mesh size fall back to the unsharded
    kernel, so odd device counts degrade gracefully."""
    try:
        import jax
        devs = jax.devices()
    except Exception:
        return None
    if len(devs) < 2:
        return None
    from jax.sharding import Mesh
    return Mesh(np.array(devs), ("batch",))


def default_verifier() -> BatchVerifier:
    """Process-wide verifier. Multi-chip hosts shard with ZERO config:
    the default mesh spans every local device and the standard bucket
    sizes divide any power-of-two chip count, so the v5e-8 target uses
    all chips out of the box (single-chip and CPU hosts are unchanged:
    the mesh is None)."""
    global _default
    with _default_lock:
        if _default is None:
            # the large buckets exist for COALESCED dispatches (catchup
            # replay fusing a whole checkpoint's signatures into one
            # round trip — the tunnel pays ~70ms per dispatch, so
            # chunking a 16k batch into 8x2048 would cost 8 round trips
            # for 8x less kernel work); small batches bucket as before
            _default = BatchVerifier(
                mesh=_auto_mesh(),
                bucket_sizes=DEFAULT_BUCKET_SIZES)
        return _default
