"""Host↔TPU bridge for batch ed25519 verification.

This is the TPU-native replacement for the reference's verify boundary
(``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``): callers
hand over (pubkey, message, signature) triples; they get back a bool per
triple with **bit-identical accept/reject decisions to libsodium's**
``crypto_sign_verify_detached``.

Division of labor (mirrors libsodium's own decomposition):

* host (cheap, byte-level): length checks, canonical-s (s < L),
  canonical-A (y < p), small-order blocklist for R and A — vectorized
  numpy; SHA-512 of R||A||M and reduction mod L — multithreaded C++
  (:mod:`stellar_tpu.crypto.native_prep`), ~12 ms → <1 ms for 2k sigs;
* device (the FLOPs): point decompression + 252-doubling Strauss-Shamir
  double-scalar multiplication + encode-compare, batched over the trailing
  lane axis (:mod:`stellar_tpu.ops.verify`). The device receives only raw
  32-byte A/R/s/h rows (256 KB per 2k sigs) and unpacks scalar digits
  itself.

Batches are padded to a small set of bucket sizes so each size
jit-compiles exactly once; oversize batches are chunked. On a
multi-chip host each padded bucket is split into per-device SUB-CHUNKS
(bucket // n_devices rows each) dispatched independently to the
devices of a 1-D mesh — pure data parallelism, no collectives, same
math as the former ``shard_map`` dispatch, but every device interaction
is now ATTRIBUTABLE to one chip. That attribution is the fault-domain
boundary (``docs/robustness.md``): a failing device opens only its own
breaker (``stellar_tpu.parallel.device_health``), its share of the
batch re-shards over the surviving devices at unchanged sub-chunk
shapes (so degradation never pays a fresh XLA compile), and a
half-open re-probe regrows it into the rotation.

``submit`` is the asynchronous half of the API: it dispatches the device
kernel without blocking and returns a resolver, so a caller draining a
queue (herder txset validation, catchup replay) can overlap host prep of
the next batch with device execution of the current one — the "two queue
classes" latency strategy from SURVEY §7.

The process-wide verify-result cache (the reference's 0xffff-entry
``RandomEvictionCache``, ``SecretKey.cpp:44-48,318-338``) lives in
``stellar_tpu.crypto.keys``; :meth:`BatchVerifier.install` wires this
verifier in behind it.

Fault tolerance (``docs/robustness.md``): the tunnel's observed failure
mode is a HANG, not an exception — a mid-flight death would park
``resolve`` in ``np.asarray`` forever. Every device interaction is
therefore (a) deadline-guarded (``VERIFY_DEVICE_DEADLINE_MS``), (b)
accounted to a circuit breaker — the PER-DEVICE one when the failure is
attributable to a mesh device, the process-wide one otherwise — and
(c) backed by host re-verification of the affected rows through the
same oracle stack (`ed25519_ref`/`native_verify`) — degraded mode
changes latency, never decisions. The breaker also paces
``device_available`` re-probes so a recovered tunnel is picked up
(half-open) instead of being ignored for the life of the process.

A chip that returns WRONG BITS instead of hanging defeats all of the
above, so every resolve additionally re-verifies a deterministic
content-seeded sample of device verdicts through the host oracle
(``VERIFY_AUDIT_RATE``, :mod:`stellar_tpu.crypto.audit`); a mismatch
hard-quarantines the device, flips the process into HOST-ONLY mode,
and re-verifies the affected rows — a corrupting accelerator never
decides signature validity.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Optional, Sequence

import numpy as np

from stellar_tpu.crypto import audit as audit_mod
from stellar_tpu.crypto import ed25519_ref as ref
from stellar_tpu.crypto import native_prep
from stellar_tpu.parallel import device_health
from stellar_tpu.utils import faults, resilience, tracing
from stellar_tpu.utils.metrics import registry

__all__ = ["BatchVerifier", "default_verifier", "device_available",
           "dispatch_health", "configure_dispatch",
           "dispatch_attribution", "dispatch_degraded",
           "note_shed_onset", "register_service_health",
           "RESOLVE_PHASES", "RESOLVE_ROOT"]

_L = ref.L
_P = ref.P

# libsodium's blocklist, as a (14, 32) uint8 matrix for vectorized compare.
_SMALL_ORDER = np.stack([np.frombuffer(e, dtype=np.uint8)
                         for e in sorted(ref.SMALL_ORDER_ENCODINGS)])

_L_BYTES = np.frombuffer(_L.to_bytes(32, "little"), dtype=np.uint8)
_P_BYTES = np.frombuffer(_P.to_bytes(32, "little"), dtype=np.uint8)


# ---------------- dispatch resilience policy ----------------
# Env defaults let tools/bench set these without a Config; a node pushes
# its Config knobs through configure_dispatch() at setup.

DEADLINE_MS = float(os.environ.get("VERIFY_DEVICE_DEADLINE_MS", "8000"))
DISPATCH_RETRIES = int(os.environ.get("VERIFY_DISPATCH_RETRIES", "1"))
# Result-integrity audit: fraction of each device-served part re-checked
# through the host oracle (min 1 row per part; <= 0 disables). The
# sample is derived from the batch CONTENT (crypto/audit.py) so
# consensus replicas audit identical rows.
AUDIT_RATE = float(os.environ.get("VERIFY_AUDIT_RATE", "0.02"))

# The production jit bucket ladder (default_verifier). Also the shape
# set the static overflow prover must cover — stellar_tpu.analysis.
# overflow proves the kernel at exactly these sizes (tools/analyze.py).
DEFAULT_BUCKET_SIZES = (128, 512, 2048, 4096, 8192, 16384)

_log = logging.getLogger("stellar_tpu.crypto")


# ---------------- resolve flight-recorder phases (ISSUE 5) ----------------
# Every phase of a blocking verify is a span; the phases are DISJOINT
# wall-time intervals under the RESOLVE_ROOT span, so summing their
# timer deltas attributes the blocking headline ("relay = X ms, device
# compute = Y ms, fetch = Z ms" — docs/observability.md). The next
# dispatch-floor PR starts from this breakdown, not one opaque number.
RESOLVE_PHASES = ("verify.prep", "verify.bucket", "verify.dispatch",
                  "verify.fetch", "verify.audit", "verify.host_fallback")
RESOLVE_ROOT = "verify.blocking"


def dispatch_attribution(before: dict, after: dict, reps: int = 1) -> dict:
    """Per-phase dispatch attribution from span-timer deltas.

    ``before``/``after`` are :func:`stellar_tpu.utils.tracing.
    span_totals` snapshots taken around the measured resolves. EVERY
    phase is reported (zero-count phases included), so a dead-tunnel
    record still carries the complete breakdown; ``coverage`` is the
    phase-sum over the blocking root span's time — the reconciliation
    the bench record asserts (>= 0.95 means the breakdown explains the
    headline, not a fraction of it)."""
    def delta(name):
        key = f"span.{name}"
        b = before.get(key, {"count": 0, "sum_ms": 0.0})
        a = after.get(key, {"count": 0, "sum_ms": 0.0})
        return a["count"] - b["count"], a["sum_ms"] - b["sum_ms"]

    reps = max(1, int(reps))
    phases = {}
    phase_sum = 0.0
    for name in RESOLVE_PHASES:
        c, s = delta(name)
        phases[name] = {"count": c, "total_ms": round(s, 3),
                        "per_rep_ms": round(s / reps, 4)}
        phase_sum += s
    root_count, root_sum = delta(RESOLVE_ROOT)
    coverage = (phase_sum / root_sum) if root_sum > 0 else None
    return {
        "phases": phases,
        "span_sum_per_rep_ms": round(phase_sum / reps, 4),
        "blocking_span_per_rep_ms": round(root_sum / reps, 4),
        "blocking_span_count": root_count,
        "coverage": round(coverage, 4) if coverage is not None else None,
        "reps": reps,
    }


def _on_breaker_transition(old: str, new: str) -> None:
    registry.counter("crypto.verify.breaker.transitions").inc()
    registry.gauge("crypto.verify.breaker.state").set(new)
    _log.warning("verify-device breaker %s -> %s", old, new)
    if new == resilience.OPEN:
        # flight-recorder trigger: the spans leading into the trip
        # must survive to be read (docs/observability.md)
        tracing.flight_recorder.dump("breaker-open:verify-device")


_breaker = resilience.CircuitBreaker(
    name="verify-device",
    failure_threshold=int(os.environ.get(
        "VERIFY_BREAKER_FAILURE_THRESHOLD", "3")),
    backoff_min_s=float(os.environ.get(
        "VERIFY_BREAKER_BACKOFF_MIN_S", "1")),
    backoff_max_s=float(os.environ.get(
        "VERIFY_BREAKER_BACKOFF_MAX_S", "120")),
    on_transition=_on_breaker_transition)


def configure_dispatch(deadline_ms: Optional[float] = None,
                       dispatch_retries: Optional[int] = None,
                       failure_threshold: Optional[int] = None,
                       backoff_min_s: Optional[float] = None,
                       backoff_max_s: Optional[float] = None,
                       audit_rate: Optional[float] = None,
                       device_failure_threshold: Optional[int] = None,
                       device_backoff_min_s: Optional[float] = None,
                       device_backoff_max_s: Optional[float] = None
                       ) -> None:
    """Push dispatch-resilience knobs (Config / tests); None keeps the
    current value. ``deadline_ms <= 0`` disables the resolve watchdog;
    ``audit_rate <= 0`` disables the result-integrity audit; the
    ``device_*`` knobs shape the per-device quarantine breakers."""
    global DEADLINE_MS, DISPATCH_RETRIES, AUDIT_RATE
    if deadline_ms is not None:
        DEADLINE_MS = float(deadline_ms)
    if dispatch_retries is not None:
        DISPATCH_RETRIES = max(0, int(dispatch_retries))
    if audit_rate is not None:
        AUDIT_RATE = float(audit_rate)
    _breaker.configure(failure_threshold=failure_threshold,
                       backoff_min_s=backoff_min_s,
                       backoff_max_s=backoff_max_s)
    device_health.get().configure(
        failure_threshold=device_failure_threshold,
        backoff_min_s=device_backoff_min_s,
        backoff_max_s=device_backoff_max_s)


# ---------------- host-only mode (result-integrity posture) ----------------
# Once ANY device is caught returning wrong verdict bits, the process
# stops trusting the accelerator path entirely: quarantining the one
# chip bounds the blast radius, but a machine that corrupted once has
# forfeited the benefit of the doubt for consensus decisions. Sticky
# for the process lifetime (operators restart after replacing the
# part); tests reset via _reset_dispatch_state_for_testing.

_host_only = False
_host_only_lock = threading.Lock()


def _enter_host_only(reason: str) -> None:
    global _host_only
    with _host_only_lock:
        already = _host_only
        _host_only = True
    if not already:
        registry.gauge("crypto.verify.host_only").set(True)
        _log.error(
            "verify dispatch entering HOST-ONLY mode (%s): device "
            "verdicts are no longer trusted for consensus decisions",
            reason)


def host_only_mode() -> bool:
    return _host_only


def dispatch_degraded() -> bool:
    """True when the accelerator path is unavailable to new work — the
    global breaker is OPEN or the process flipped host-only. This is
    the verify service's shed-ladder pressure input
    (:mod:`stellar_tpu.crypto.verify_service`): with effective
    capacity collapsed to the host oracle, the service sheds
    lowest-priority backlog instead of queueing to death."""
    return _host_only or _breaker.state == resilience.OPEN


# ---------------- resident verify service hooks ----------------
# verify_service.py sits ON TOP of this module and is inside the
# consensus nondet-lint scope, so it may not import the clock-bearing
# tracing layer directly; its flight-recorder trigger and health
# surface route through here instead.

_service_lock = threading.Lock()
_service_health_provider: Optional[Callable[[], dict]] = None


def register_service_health(provider: Optional[Callable[[], dict]]
                            ) -> None:
    """Install the resident verify service's snapshot callable so
    ``dispatch_health()`` (and the ``dispatch`` admin route) carries
    queue depths and shed/reject accounting next to the breaker state.
    ``None`` unregisters (tests)."""
    global _service_health_provider
    with _service_lock:
        _service_health_provider = provider


def service_health_snapshot() -> dict:
    """The registered service's snapshot, or ``{"running": False}``
    when no service ever started — shared by ``dispatch_health()``
    and the ``service`` admin route."""
    provider = _service_health_provider
    return provider() if provider is not None else {"running": False}


def note_shed_onset(reason: str) -> None:
    """First-onset load-shed trigger: dump the flight recorder so the
    spans and queue events leading INTO the overload survive to be
    read (same policy as breaker trips and audit mismatches —
    docs/observability.md)."""
    registry.counter("crypto.verify.service.shed_onsets").inc()
    tracing.flight_recorder.dump(f"service-shed:{reason}")


def served_counts() -> dict:
    """Process-wide items-served tally by backend — the attribution
    bench.py records so a silent fallback can never be reported as a
    device number."""
    return {
        "device": registry.meter("crypto.verify.serve.device").count,
        "host_fallback": registry.meter(
            "crypto.verify.serve.host_fallback").count,
    }


def dispatch_health() -> dict:
    """Degradation observability (info endpoint / `dispatch` admin
    route): breaker state, backend attribution, fallback/retry/deadline
    counters, active knobs."""
    return {
        "device_state": _device_state or "unprobed",
        "breaker": _breaker.snapshot(),
        "deadline_ms": DEADLINE_MS,
        "dispatch_retries": DISPATCH_RETRIES,
        "served": served_counts(),
        "fallback_chunks": registry.meter(
            "crypto.verify.dispatch.fallback").count,
        "deadline_misses": registry.counter(
            "crypto.verify.dispatch.deadline_miss").count,
        "retries": registry.counter("crypto.verify.dispatch.retry").count,
        "short_circuits": registry.counter(
            "crypto.verify.dispatch.short_circuit").count,
        "host_only": _host_only,
        "audit": {
            "rate": AUDIT_RATE,
            "sampled": registry.counter(
                "crypto.verify.audit.sampled").count,
            "mismatches": registry.counter(
                "crypto.verify.audit.mismatch").count,
        },
        "device_health": device_health.get().snapshot(),
        "watchdog": resilience.watchdog_stats(),
        "flight_recorder": tracing.flight_recorder.stats(),
        "service": service_health_snapshot(),
    }


def _note_device_failure(stage: str, exc: BaseException,
                         dev_idx: Optional[int] = None) -> None:
    """One failing device interaction: breaker accounting + metrics.
    ``dev_idx`` attributes the failure to ONE mesh device (only its
    breaker opens — the fault-domain boundary); None means the failure
    is not attributable (single-device dispatch) and feeds the
    process-wide breaker. The caller re-verifies the affected rows on
    the host."""
    registry.meter("crypto.verify.dispatch.fallback").mark()
    if dev_idx is None:
        _breaker.record_failure()
    elif device_health.get().record_failure(dev_idx):
        # correlated-outage escalation: each quarantine ONSET counts
        # one failure against the global breaker. A single sick chip
        # (one quarantine, then healthy traffic resets the streak)
        # leaves the mesh serving; a whole-tunnel death quarantines
        # device after device with no intervening success, reaches the
        # global threshold, and short-circuits the remaining chunks —
        # bounding the outage at global_threshold quarantines instead
        # of n_devices independent ones
        tracing.flight_recorder.dump(f"quarantine:device{dev_idx}")
        _breaker.record_failure()
    _log.warning(
        "device%s %s failed (%s: %s) — affected rows re-verified on "
        "the host oracle",
        "" if dev_idx is None else f" {dev_idx}",
        stage, type(exc).__name__, exc)


def _resolve_budget_s() -> Optional[float]:
    """Watchdog budget for one device-array fetch, or None (unguarded).
    Guarded whenever a real accelerator answered the probe (hangs are
    its observed failure mode) or a chaos fault is armed; UNGUARDED on
    jax-CPU/unprobed processes — XLA-on-CPU test executions are slow
    but cannot tunnel-hang, and a false deadline trip there would
    silently reroute differential tests to the host oracle."""
    if DEADLINE_MS <= 0:
        return None
    if faults.is_active(faults.RESOLVE) or faults.is_active(faults.DISPATCH):
        return DEADLINE_MS / 1000.0
    if _device_state in (None, "cpu"):
        return None
    return DEADLINE_MS / 1000.0


def _fetch(dev, dev_idx: Optional[int] = None) -> np.ndarray:
    """The blocking half of a dispatch (runs under the watchdog).
    ``dev_idx`` attributes the fetch to one mesh device for per-device
    chaos faults — including verdict corruption, applied here so the
    wrong bits flow through exactly the path real corruption would.
    The span opens on the POOL WORKER with the submitter's propagated
    context, so a fetch that hangs appears OPEN in a flight-recorder
    dump, parent-linked to the resolve that dispatched it."""
    with tracing.span("verify.fetch.device", device=dev_idx):
        faults.inject(faults.RESOLVE, device=dev_idx)
        arr = np.asarray(dev)
        return faults.corrupt_verdicts(faults.RESOLVE, dev_idx, arr)


def _host_verify_items(items: Sequence[tuple]) -> np.ndarray:
    """Bit-identical host re-verification of (pk, msg, sig) triples —
    the failover path. Libsodium's policy gate stays the single source
    of truth (``ed25519_ref._policy_gate``); curve equations ride the
    threaded native batch when it built, else the pure oracle."""
    from stellar_tpu.crypto import keys
    out = np.zeros(len(items), dtype=bool)
    good = [i for i, (pk, _m, sg) in enumerate(items)
            if len(pk) == 32 and len(sg) == 64]
    if good:
        res = keys._host_oracle_batch(
            [(None,) + tuple(items[i]) for i in good])
        for i, okv in zip(good, res):
            out[i] = bool(okv)
    return out


def _lt_le_bytes(vals: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """Per-row little-endian comparison vals < bound (vals (B,32) uint8)."""
    # compare from most significant byte down
    v = vals[:, ::-1].astype(np.int16)
    b = bound[::-1].astype(np.int16)
    diff = v - b[None, :]
    nz = diff != 0
    first = np.argmax(nz, axis=1)
    any_nz = nz.any(axis=1)
    picked = diff[np.arange(len(vals)), first]
    return np.where(any_nz, picked < 0, False)  # equal -> not less


def _small_order_mask(enc: np.ndarray) -> np.ndarray:
    """(B, 32) uint8 -> bool (B,) True where encoding is small-order,
    sign bit masked (libsodium ge25519_has_small_order)."""
    masked = enc.copy()
    masked[:, 31] &= 0x7F
    return (masked[:, None, :] == _SMALL_ORDER[None, :, :]).all(-1).any(-1)


class BatchVerifier:
    """Batched libsodium-exact ed25519 verifier with a jit bucket cache.

    Args:
      mesh: optional 1-D ``jax.sharding.Mesh``; if given (and it spans
        >= 2 devices), buckets divisible by the device count are split
        into per-device SUB-CHUNKS of the plain kernel — one
        attributable dispatch per device, quarantine/re-shard per
        ``stellar_tpu.parallel.device_health`` — instead of one
        ``shard_map`` call. Non-divisible buckets (and mesh=None) use
        a single whole-bucket dispatch under the global breaker.
      bucket_sizes: padded batch sizes, ascending; each dispatch shape
        compiles once (per serving device on the mesh path).
    """

    def __init__(self, mesh=None, bucket_sizes=(128, 512, 2048)):
        self._mesh = mesh
        self._devices = None
        if mesh is not None:
            from stellar_tpu.parallel.mesh import mesh_devices
            devs = mesh_devices(mesh)
            if len(devs) >= 2:
                self._devices = devs
        self._buckets = tuple(sorted(bucket_sizes))
        # jit-wrapper cache keyed by DISPATCH SHAPE (rows per kernel
        # call: the bucket on single-device hosts, bucket // n_devices
        # on a mesh): written from any thread that dispatches (trickle
        # leaders, chaos tests, the close path) — guarded, the wrapper
        # itself is built outside the lock (cheap; the compile happens
        # lazily at first call)
        self._kernels = {}
        self._kernels_lock = threading.Lock()
        # per-instance backend attribution (items served), mirrored into
        # the process-wide meters: bench and the chaos tests read these
        self._stats_lock = threading.Lock()
        self.served = {"device": 0, "host-fallback": 0}
        self.device_served = {}  # mesh device index -> items served
        self.deadline_misses = 0
        self.retries = 0
        self.audit_mismatches = 0

    def _mark_served(self, kind: str, n: int,
                     dev_idx: Optional[int] = None) -> None:
        with self._stats_lock:
            self.served[kind] += n
            if dev_idx is not None:
                self.device_served[dev_idx] = \
                    self.device_served.get(dev_idx, 0) + n
        registry.meter("crypto.verify.serve." +
                       ("device" if kind == "device" else
                        "host_fallback")).mark(n)

    # ---------------- device dispatch ----------------

    def _kernel_for(self, n: int):
        with self._kernels_lock:
            kernel = self._kernels.get(n)
        if kernel is None:
            import jax
            from stellar_tpu.ops import verify as vk
            # one plain jit wrapper per dispatch shape; on the mesh
            # path placement follows the committed inputs, so the SAME
            # wrapper serves every device (jax caches one executable
            # per (shape, device) underneath)
            built = jax.jit(vk.verify_kernel)
            with self._kernels_lock:
                # setdefault: a racing builder's wrapper wins once —
                # both wrappers trace identically, so the loser is
                # just garbage, never a different kernel
                kernel = self._kernels.setdefault(n, built)
        return kernel

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _dispatch_one(self, aa, rr, ss, hh, bsize: int,
                      dev_idx: Optional[int]):
        """One kernel call (whole padded bucket, or one per-device
        sub-chunk): inject-point + retry + failure attribution. Returns
        the in-flight device array, or None (host fallback)."""
        attempts = 1 + DISPATCH_RETRIES
        for attempt in range(attempts):
            try:
                faults.inject(faults.DISPATCH, device=dev_idx)
                return self._kernel_for(bsize)(aa, rr, ss, hh)
            except Exception as e:
                if attempt + 1 < attempts:
                    registry.counter(
                        "crypto.verify.dispatch.retry").inc()
                    with self._stats_lock:
                        self.retries += 1
                else:
                    _note_device_failure("dispatch", e, dev_idx)
        return None

    def _dispatch_parts(self, aa, rr, ss, hh, b: int, chunk: int):
        """Split one padded bucket into per-device sub-chunks over the
        CURRENTLY HEALTHY devices — the degraded-mesh re-shard.

        The sub-chunk shape is fixed at ``b // n_devices`` for the FULL
        mesh size, independent of how many devices survive: quarantine
        only changes which healthy device serves how many sub-chunks
        (round-robin over the survivors), never the shapes — and every
        survivor already compiled its sub-chunk executable when it
        served its own share, so degradation and regrowth never pay a
        fresh XLA compile (the invariant `docs/robustness.md` pins).

        A half-open device's breaker grants exactly one sub-chunk per
        backoff window — probation traffic IS the re-probe; success
        regrows the device into the rotation.

        Returns part records ``[lo, hi, dev_idx, arr]``: valid rows
        ``lo:hi`` of the chunk, serving device, in-flight array (None =
        host fallback). All-padding tail sub-chunks are skipped."""
        import jax
        n_dev = len(self._devices)
        sub = b // n_dev
        # sub-chunks that carry real rows (pure-padding tails are
        # never dispatched)
        n_parts = min(n_dev, -(-chunk // sub))
        assignment = device_health.get().assign_parts(n_dev, n_parts)
        if assignment != list(range(n_parts)):
            # degraded-mesh re-shard decision: record WHO serves WHAT
            # (or None = host fallback) so a dump of a degraded window
            # shows the assignment that produced its latencies
            tracing.flight_recorder.note(
                "verify.reshard", assignment=list(assignment),
                parts=n_parts, devices=n_dev)
        parts = []
        for j, di in enumerate(assignment):
            lo = j * sub
            hi = min(lo + sub, chunk)
            if di is None:
                # zero survivors and no probation grants: the whole
                # mesh is quarantined — only now does the verifier
                # fall back to the host oracle
                registry.counter(
                    "crypto.verify.dispatch.short_circuit").inc()
                parts.append([lo, hi, None, None])
                continue
            placed = tuple(
                jax.device_put(x[lo:lo + sub], self._devices[di])
                for x in (aa, rr, ss, hh))
            arr = self._dispatch_one(*placed, bsize=sub, dev_idx=di)
            parts.append([lo, hi, di, arr])
        return parts

    def _dispatch_device(self, a: np.ndarray, r: np.ndarray, s: np.ndarray,
                         h: np.ndarray):
        """Dispatch padded/chunked batches to the jitted kernel without
        blocking; returns a list of (slice, chunk_len, parts) where
        parts are per-device sub-chunk records (single-device hosts get
        one whole-bucket part). A part whose dispatch raises (or that
        an open breaker refuses, or host-only mode) carries ``None``
        and is re-verified on the host at resolve time; transient
        dispatch exceptions get ``DISPATCH_RETRIES`` fresh attempts
        first."""
        n = a.shape[0]
        top = self._buckets[-1]
        pending = []
        start = 0
        host_only = _host_only
        while start < n:
            chunk = min(top, n - start)
            b = self._bucket(chunk)
            pad = b - chunk
            sl = slice(start, start + chunk)

            def _padded_inputs():
                # built ONLY for chunks that will actually dispatch:
                # a host-only or breaker-refused chunk must not pay
                # 4x bucket-sized copies it never reads (nor charge
                # them to the bucket phase of the attribution)
                with tracing.span("verify.bucket"):
                    return (
                        np.concatenate([a[sl],
                                        np.repeat(_PAD_A, pad, 0)]),
                        np.concatenate([r[sl],
                                        np.repeat(_PAD_R, pad, 0)]),
                        np.concatenate([s[sl],
                                        np.repeat(_PAD_S, pad, 0)]),
                        np.concatenate([h[sl],
                                        np.repeat(_PAD_H, pad, 0)]))

            if host_only:
                # integrity posture: no device dispatch at all
                parts = [[0, chunk, None, None]]
            elif self._devices is not None and \
                    b % len(self._devices) == 0:
                # the global breaker gates the mesh path too: a
                # correlated outage (escalated quarantines) opens it
                # and short-circuits whole chunks; its half-open grant
                # admits one chunk as the recovery probe
                if _breaker.allow():
                    aa, rr, ss, hh = _padded_inputs()
                    with tracing.span("verify.dispatch", devices=True):
                        parts = self._dispatch_parts(aa, rr, ss, hh, b,
                                                     chunk)
                else:
                    registry.counter(
                        "crypto.verify.dispatch.short_circuit").inc()
                    parts = [[0, chunk, None, None]]
            elif _breaker.allow():
                aa, rr, ss, hh = _padded_inputs()
                with tracing.span("verify.dispatch"):
                    arr = self._dispatch_one(aa, rr, ss, hh, b, None)
                parts = [[0, chunk, None, arr]]
            else:
                registry.counter(
                    "crypto.verify.dispatch.short_circuit").inc()
                parts = [[0, chunk, None, None]]
            pending.append((sl, chunk, parts))
            start += chunk
        return pending

    # ---------------- public API ----------------

    def _prep(self, items: Sequence[tuple]):
        # host-side prep phase: byte recode into the on-wire matrices,
        # SHA-512(R||A||M) mod L, and the policy gates
        with tracing.span("verify.prep"):
            return self._prep_inner(items)

    def _prep_inner(self, items: Sequence[tuple]):
        n = len(items)
        ok = np.ones(n, dtype=bool)
        # one frombuffer over joined bytes instead of three numpy row
        # writes per item — the per-item version was the single
        # biggest host-prep cost at 2k-signature batches
        msgs = []
        pk_parts = []
        sig_parts = []
        z32, z64 = bytes(32), bytes(64)
        for i, (pk, msg, sig) in enumerate(items):
            if len(pk) != 32 or len(sig) != 64:
                ok[i] = False
                pk_parts.append(z32)
                sig_parts.append(z64)
                msgs.append(b"")
            else:
                pk_parts.append(pk)
                sig_parts.append(sig)
                msgs.append(msg)
        a = np.frombuffer(b"".join(pk_parts),
                          dtype=np.uint8).reshape(n, 32)
        sig_mat = np.frombuffer(b"".join(sig_parts),
                                dtype=np.uint8).reshape(n, 64)
        r = np.ascontiguousarray(sig_mat[:, :32])
        s = np.ascontiguousarray(sig_mat[:, 32:])
        # h = SHA512(R||A||M) mod L — native multithreaded C++
        h = native_prep.prep_batch(r, a, msgs)
        # host policy checks (libsodium order: s canonical, small-order R/A,
        # canonical A)
        ok &= _lt_le_bytes(s, _L_BYTES)
        ok &= ~_small_order_mask(r)
        ok &= ~_small_order_mask(a)
        a_masked = a.copy()
        a_masked[:, 31] &= 0x7F
        ok &= _lt_le_bytes(a_masked, _P_BYTES)
        return ok, a, r, s, h

    def submit(self, items: Sequence[tuple]) -> Callable[[], np.ndarray]:
        """Asynchronous verify: host prep + non-blocking device dispatch.

        Returns a zero-arg resolver; calling it blocks on the device result
        and returns the per-item bool array. Multiple submitted batches
        pipeline on device (jax async dispatch), overlapping transfer and
        compute across batches.
        """
        n = len(items)
        if n == 0:
            return lambda: np.zeros(0, dtype=bool)
        ok, a, r, s, h = self._prep(items)
        if not ok.any():
            return lambda: ok
        pending = self._dispatch_device(a, r, s, h)
        items = list(items)  # pinned for possible host re-verification

        def _audit_part(vals: np.ndarray, gl: int, gh: int,
                        di: Optional[int]) -> bool:
            """Sampled result-integrity audit of one device-served
            part (global rows ``gl:gh``): re-verify a content-seeded
            sample through the host oracle and compare against the
            COMPOSED decision (host policy gate AND device verdict) —
            the quantity that is pinned bit-identical to libsodium.
            Only rows that PASSED the host policy gate are sampled:
            a gate-rejected row is False regardless of device bits, so
            auditing it would be vacuous (and a predictable blind
            spot). True = clean (or nothing to audit)."""
            with tracing.span("verify.audit", device=di):
                material = (a[gl:gh].tobytes() + r[gl:gh].tobytes() +
                            s[gl:gh].tobytes() + h[gl:gh].tobytes())
                eligible = [i for i in range(gh - gl) if ok[gl + i]]
                idxs = audit_mod.sample_rows(material, eligible,
                                             AUDIT_RATE)
                if not idxs:
                    return True
                registry.counter("crypto.verify.audit.sampled").inc(
                    len(idxs))
                want = _host_verify_items([items[gl + i] for i in idxs])
                got_comp = np.array([bool(vals[i]) for i in idxs])
                clean = bool((want == got_comp).all())
            # verdict lands in both evidence streams: the per-device
            # health registry (MULTICHIP fault-domain evidence) and
            # the flight recorder (visible in dumps near the spans)
            device_health.get().note_audit(di, ok=clean,
                                           sampled=len(idxs))
            tracing.flight_recorder.note(
                "verify.audit.verdict",
                **audit_mod.verdict_record(di, gl, gh, len(idxs),
                                           clean))
            return clean

        def _resolve_impl() -> np.ndarray:
            out = np.zeros(n, dtype=bool)
            for sl, chunk, parts in pending:
                for lo, hi, di, arr in parts:
                    got = None
                    # _host_only is re-read PER PART: once any part's
                    # audit proves corruption, the remaining
                    # already-dispatched parts of this very batch are
                    # host re-verified too — the batch that convicted
                    # the machine must not let device bits decide its
                    # other rows
                    if arr is not None and not _host_only:
                        # an OPEN breaker short-circuits this fault
                        # domain's remaining parts so one outage costs
                        # threshold x deadline, not parts x deadline;
                        # state (not allow()) is checked because a
                        # half-open part already holds its grant from
                        # dispatch time and must be fetched, not
                        # refused
                        gate = _breaker if di is None else \
                            device_health.get().breaker(di)
                        if gate.state != resilience.OPEN:
                            # the fetch span covers the whole
                            # fetch/deadline race; a trip dumps while
                            # it (and the worker-side device span) are
                            # still open, so the dump shows exactly
                            # where the hang is parked
                            with tracing.span("verify.fetch",
                                              device=di):
                                try:
                                    got = resilience.call_with_deadline(
                                        lambda d=arr, i=di:
                                        _fetch(d, i),
                                        _resolve_budget_s(),
                                        name="verify-resolve")
                                except resilience.DeadlineExceeded as e:
                                    registry.counter(
                                        "crypto.verify.dispatch."
                                        "deadline_miss").inc()
                                    with self._stats_lock:
                                        self.deadline_misses += 1
                                    _note_device_failure(
                                        "resolve-deadline", e, di)
                                    tracing.flight_recorder.dump(
                                        "watchdog-timeout:device"
                                        f"{'-global' if di is None else di}")
                                except Exception as e:
                                    _note_device_failure(
                                        "resolve", e, di)
                        else:
                            registry.counter(
                                "crypto.verify.dispatch."
                                "short_circuit").inc()
                    gl, gh = sl.start + lo, sl.start + hi
                    if got is not None:
                        vals = np.asarray(got)[:hi - lo]
                        if not _audit_part(vals, gl, gh, di):
                            # wrong bits: hard-quarantine the chip,
                            # stop trusting the accelerator path, and
                            # re-verify the whole part on the host —
                            # the corrupted verdicts never surface
                            registry.counter(
                                "crypto.verify.audit.mismatch").inc()
                            with self._stats_lock:
                                self.audit_mismatches += 1
                            if di is not None:
                                device_health.get().quarantine(
                                    di, reason="audit-mismatch")
                            else:
                                _breaker.trip()
                            tracing.flight_recorder.dump(
                                f"audit-mismatch:device{di}")
                            _enter_host_only(
                                "result-integrity audit mismatch on "
                                f"device {di}")
                            _log.error(
                                "audit mismatch: device %s returned "
                                "wrong verdict bits for rows %d:%d",
                                di, gl, gh)
                            got = None
                        else:
                            out[gl:gh] = vals
                            if di is None:
                                _breaker.record_success()
                            else:
                                device_health.get().record_success(di)
                                # healthy traffic also resets the
                                # global breaker's quarantine streak,
                                # so isolated quarantines accumulated
                                # over hours never masquerade as a
                                # correlated outage (and a real one —
                                # zero successes — still escalates)
                                _breaker.record_success()
                            self._mark_served("device", hi - lo, di)
                    if got is None:
                        # failover: bit-identical host re-verification
                        # of the affected rows (latency changes,
                        # decisions never do)
                        with tracing.span("verify.host_fallback",
                                          device=di):
                            out[gl:gh] = _host_verify_items(
                                items[gl:gh])
                        self._mark_served("host-fallback", hi - lo)
            return ok & out

        def resolve() -> np.ndarray:
            with tracing.span("verify.resolve"):
                return _resolve_impl()

        return resolve

    def verify_batch(self, items: Sequence[tuple]) -> np.ndarray:
        """items: sequence of (pk: bytes, msg: bytes, sig: bytes).
        Returns bool array, libsodium-identical per item. The root
        span covers the whole blocking call, so the per-phase spans
        under it attribute the blocking headline
        (:func:`dispatch_attribution`)."""
        with tracing.span(RESOLVE_ROOT):
            return self.submit(items)()

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        """Single verify (uncached — the process-wide result cache lives
        in ``stellar_tpu.crypto.keys.verify_sig``; wire this verifier in
        behind it with :meth:`install`)."""
        return bool(self.verify_batch([(pk, msg, sig)])[0])

    def install(self, trickle_window_ms: Optional[float] = None
                ) -> "BatchVerifier":
        """Make this verifier the backend for ``keys.verify_sig`` so all
        single-sig call sites hit the shared cache first, then the TPU.

        ``trickle_window_ms`` wires a :class:`TrickleBatcher` in front:
        worth it when verify callers are CONCURRENT (overlay auth,
        threaded replay); in a purely single-threaded crank it only
        adds the window to each miss, so it stays opt-in."""
        from stellar_tpu.crypto import keys
        if trickle_window_ms is not None:
            batcher = TrickleBatcher(self, window_ms=trickle_window_ms)
            keys.set_verifier_backend(batcher.verify_sig)
        else:
            keys.set_verifier_backend(self.verify_sig)
        return self


class TrickleBatcher:
    """Micro-batch window for single-signature verify misses — the
    "trickle queue class" of SURVEY §7: bulk paths batch explicitly,
    but lone verifies (overlay auth handshakes, single SCP envelopes)
    would each pay a full solo device dispatch. Concurrent arrivals
    collect for up to ``window_ms`` (or ``max_batch``) and ride ONE
    dispatch; the synchronous bool API is preserved by parking callers
    on futures. The first caller of a window is the leader: it waits
    the window out, dispatches everything queued, and resolves every
    future; followers just block on theirs.

    The internal queue is BOUNDED (``max_pending``): a caller arriving
    when it is full gets a typed :class:`resilience.Overloaded` at
    ingress instead of growing the pending list without limit while a
    leader is stuck behind a slow dispatch — the same
    admission-control discipline as the resident verify service
    (``docs/robustness.md`` "Overload and load-shed")."""

    def __init__(self, verifier: BatchVerifier, window_ms: float = 1.0,
                 max_batch: int = 64, max_pending: int = 4096):
        self._verifier = verifier
        self._window = window_ms / 1000.0
        self._max = max_batch
        self._max_pending = max(1, int(max_pending))
        self._cv = threading.Condition()
        self._pending: list = []  # ((pk, msg, sig), Future)
        self._leader_active = False
        self._flush_asap = False
        self.dispatches = 0  # instrumentation (bench / tests)
        self.rejected = 0    # ingress Overloaded count

    def _dispatch_batch(self, batch: list) -> None:
        """Resolve one claimed batch through the verifier, fanning a
        leader-side failure out to every parked future (nobody hangs)."""
        try:
            results = self._verifier.verify_batch(
                [item for item, _f in batch])
        except BaseException as e:
            for _item, f in batch:
                f.set_exception(e)
            raise
        for (_item, f), ok in zip(batch, results):
            f.set_result(bool(ok))

    def verify_sig(self, pk: bytes, msg: bytes, sig: bytes) -> bool:
        from concurrent.futures import Future
        import time
        fut: Future = Future()
        with self._cv:
            if len(self._pending) >= self._max_pending:
                # bounded queue: reject at ingress, typed — the caller
                # decides whether to retry, shed, or fail its request
                self.rejected += 1
                registry.counter("crypto.verify.trickle.rejected").inc()
                raise resilience.Overloaded(
                    f"trickle window full ({self._max_pending} pending)",
                    kind="rejected", lane="trickle",
                    reason="queue-depth")
            self._pending.append(((pk, msg, sig), fut))
            if self._leader_active:
                if len(self._pending) >= self._max:
                    self._cv.notify_all()  # wake the leader early
                lead = False
            else:
                self._leader_active = True
                lead = True
        if lead:
            deadline = time.perf_counter() + self._window
            with self._cv:
                while len(self._pending) < self._max and \
                        not self._flush_asap:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._cv.wait(left)
                batch = self._pending
                self._pending = []
                self._leader_active = False
                self._flush_asap = False
                # counted under the lock: the next window's leader can
                # already be running by the time this one dispatches
                self.dispatches += 1
            self._dispatch_batch(batch)
        return fut.result()

    def flush(self) -> int:
        """Dispatch everything queued RIGHT NOW instead of waiting the
        window out (service drain / shutdown path). Tolerant of
        enqueues racing a window close: all queue/leader transitions
        happen under the window lock, so an item is owned by exactly
        one dispatcher — if a leader is active it OWNS the pending
        list (flush just wakes it early and returns 0); otherwise
        flush claims the batch itself, and an enqueue arriving after
        the claim simply elects itself the next leader. Returns how
        many items THIS call dispatched."""
        with self._cv:
            if self._leader_active:
                self._flush_asap = True
                self._cv.notify_all()
                return 0
            batch = self._pending
            self._pending = []
            if batch:
                self.dispatches += 1
        if not batch:
            return 0
        self._dispatch_batch(batch)
        return len(batch)


# Padding rows: any syntactically valid inputs work (results are sliced
# off); use the base point with zero scalars so padded lanes stay cheap
# and never hit the decompress-failure path. Under the signed-window
# kernel (PR 1) zero scalars recode to all-zero digit streams, so every
# padded window select rides the identity fixup of
# ops.edwards.table_select — still valid, still cheap, and R' stays the
# identity, matching _PAD_R (pinned by
# tests/test_signed_recode.py::test_padding_rows_recode_to_identity_digits).
_PAD_A = np.frombuffer(ref.point_compress(ref.BASE), np.uint8).copy()[None]
_PAD_R = np.frombuffer(ref.point_compress(ref.IDENTITY), np.uint8).copy()[None]
_PAD_S = np.zeros((1, 32), dtype=np.uint8)
_PAD_H = np.zeros((1, 32), dtype=np.uint8)


_default: Optional[BatchVerifier] = None
_default_lock = threading.Lock()

_device_state: Optional[str] = None  # None=unprobed, else platform|"dead"
_device_probe_lock = threading.Lock()
# current probe attempt: {"thread", "box", "started", "accounted"}.
# Unlike the pre-breaker design this is RE-ARMABLE: a "dead" verdict is
# re-probed when the breaker's backoff window expires, so a recovered
# tunnel is picked up instead of being ignored for the process lifetime.
_probe: Optional[dict] = None


def _launch_probe_locked() -> dict:
    """Spawn a fresh probe attempt (call with _device_probe_lock held).
    A probe on a wedged tunnel hangs; its daemon thread is abandoned
    when accounted — backoff growth bounds the leak to one thread per
    half-open window."""
    global _probe

    box: dict = {}

    def probe():
        try:
            faults.inject(faults.PROBE)
            import jax
            platform = jax.devices()[0].platform
            if platform != "cpu":
                # jax.devices() answers from the in-process cache once
                # the backend has initialized, so on an accelerator only
                # a REAL tiny dispatch proves the tunnel: a vacuous
                # success here would re-close a dispatch-opened breaker
                # (and reset its backoff) while the device is still
                # dead. On a dead tunnel this hangs — exactly what the
                # caller's watchdog + breaker accounting expect.
                np.asarray(jax.jit(lambda x: x + 1)(
                    np.zeros(2, np.int32)))
            box["platform"] = platform
        except Exception as e:  # no backend at all
            box["error"] = str(e)

    t = threading.Thread(target=probe, daemon=True, name="device-probe")
    _probe = {"thread": t, "box": box, "started": time.monotonic(),
              "accounted": False}
    t.start()
    return _probe


def _account_probe_locked(cur: dict, hung: bool, timeout_s: float) -> None:
    """Turn a finished/overdue probe attempt into device state + breaker
    accounting (call with _device_probe_lock held; idempotent)."""
    global _device_state
    if cur["accounted"]:
        return
    cur["accounted"] = True
    box = cur["box"]
    if hung:
        _device_state = "dead"
        _breaker.record_failure()
        _log.warning(
            "device probe hung > %ss — signature verification falls "
            "back to the host oracle (breaker: %s)",
            timeout_s, _breaker.state)
    elif "platform" in box:
        _device_state = box["platform"]
        _breaker.record_success()
    else:
        _device_state = "dead"
        _breaker.record_failure()
        _log.warning(
            "device probe failed (%s) — signature verification falls "
            "back to the host oracle (breaker: %s)",
            box.get("error", "no backend"), _breaker.state)


def start_device_probe() -> None:
    """Fire the device probe WITHOUT waiting for it (idempotent).
    Called from LedgerManager/Application construction so the jax
    import + ``jax.devices()`` cost (seconds, or a hang on a dead
    tunnel) is paid during startup, never inside the first ledger
    close (the reference initializes its crypto stack at app start,
    not in ``closeLedger``)."""
    with _device_probe_lock:
        if _probe is None and _device_state is None:
            _launch_probe_locked()


def device_available(timeout_s: float = 30.0,
                     block: bool = True) -> bool:
    """True when a REAL accelerator is reachable AND the dispatch
    breaker is closed. Probes run in watchdogged threads: with the axon
    tunnel down, ``jax.devices()`` hangs forever rather than raising,
    and a node must fall back to the host oracle instead of hanging the
    close path (failure detection, not configuration). jax-CPU reports
    False permanently: batching bignum kernels through XLA-on-CPU is
    strictly slower than the host oracle, so auto mode only engages the
    device path on tpu-class hardware — that is configuration, and is
    never re-probed.

    A "dead" verdict, by contrast, is a FAILURE and heals: the circuit
    breaker re-probes (half-open) once its exponential-backoff window
    expires, so a tunnel that comes back is picked up without hammering
    one that stays down.

    ``block=False`` never waits: a still-pending probe answers False
    for now WITHOUT caching a verdict, so latency-critical callers
    (the close path) fall back to the host oracle this round and pick
    up the device once the probe resolves. A pending probe older than
    ``timeout_s`` is accounted hung even for non-blocking callers, so
    breaker-paced recovery works on a node that only ever asks
    non-blockingly."""
    start_device_probe()
    with _device_probe_lock:
        cur = _probe
        if cur is None or cur["accounted"]:
            if _device_state == "cpu":
                return False  # configuration, not a fault
            if _device_state not in (None, "dead") and \
                    _breaker.state == resilience.CLOSED:
                return True
            # dead (or breaker tripped by dispatch failures): re-probe
            # only when the backoff window has expired
            if _breaker.allow():
                cur = _launch_probe_locked()
            else:
                return False
    t = cur["thread"]
    if block:
        # join OUTSIDE the lock: a blocking waiter must never make a
        # concurrent block=False caller (the close path) wait on the
        # lock for up to timeout_s
        t.join(timeout_s)
    with _device_probe_lock:
        if not cur["accounted"]:
            if not t.is_alive():
                _account_probe_locked(cur, hung=False, timeout_s=timeout_s)
            elif block or \
                    time.monotonic() - cur["started"] > timeout_s:
                _account_probe_locked(cur, hung=True, timeout_s=timeout_s)
            else:
                return False  # pending — ask again later, don't cache
        return _device_state not in (None, "dead", "cpu") and \
            _breaker.state == resilience.CLOSED


def _reset_dispatch_state_for_testing() -> None:
    """Fresh probe/breaker state (chaos tests): equivalent to process
    start for the dispatch layer. Cumulative metrics are untouched."""
    global _device_state, _probe, _host_only
    with _device_probe_lock:
        _device_state = None
        _probe = None
    with _host_only_lock:
        _host_only = False
    _breaker.record_success()  # closed, zero failures, backoff reset
    device_health.get()._reset_for_testing()


def _auto_mesh():
    """1-D mesh over every local device, or None when single-device.
    Buckets not divisible by the mesh size fall back to the unsharded
    kernel, so odd device counts degrade gracefully."""
    try:
        import jax
        devs = jax.devices()
    except Exception:
        return None
    if len(devs) < 2:
        return None
    from jax.sharding import Mesh
    return Mesh(np.array(devs), ("batch",))


def default_verifier() -> BatchVerifier:
    """Process-wide verifier. Multi-chip hosts shard with ZERO config:
    the default mesh spans every local device and the standard bucket
    sizes divide any power-of-two chip count, so the v5e-8 target uses
    all chips out of the box (single-chip and CPU hosts are unchanged:
    the mesh is None)."""
    global _default
    with _default_lock:
        if _default is None:
            # the large buckets exist for COALESCED dispatches (catchup
            # replay fusing a whole checkpoint's signatures into one
            # round trip — the tunnel pays ~70ms per dispatch, so
            # chunking a 16k batch into 8x2048 would cost 8 round trips
            # for 8x less kernel work); small batches bucket as before
            _default = BatchVerifier(
                mesh=_auto_mesh(),
                bucket_sizes=DEFAULT_BUCKET_SIZES)
        return _default
