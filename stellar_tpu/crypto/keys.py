"""SecretKey / PubKeyUtils: the framework's signing identity layer.

Mirrors the reference's ``src/crypto/SecretKey.h`` surface: seed-based
ed25519 keys, StrKey round-trips, deterministic test keys
(``pseudoRandomForTesting``), and — the north-star boundary —
``verify_sig`` with a 0xffff-entry random-eviction result cache in front
of a *pluggable* verifier backend (``crypto/SecretKey.cpp:44-48,435-468``).

Backends:
  * the pure-Python libsodium-exact oracle (default; always available)
  * the TPU ``BatchVerifier`` (``stellar_tpu.crypto.batch_verifier``) —
    installed via ``set_verifier_backend`` for bulk paths; single-sig
    calls still hit the cache first.
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Optional

from stellar_tpu.crypto import ed25519_ref as _ref
from stellar_tpu.crypto import strkey as _strkey
from stellar_tpu.crypto.sha import sha256
from stellar_tpu.utils.cache import RandomEvictionCache

__all__ = [
    "SecretKey", "PublicKey", "verify_sig", "cached_verify_sig",
    "seed_verify_cache", "set_verifier_backend",
    "get_verifier_backend_name",
    "get_verify_cache_stats", "flush_verify_cache",
    "sign_ops_per_second", "verify_ops_per_second",
]

VERIFY_CACHE_SIZE = 0xFFFF
# below this, batch_verify_into_cache uses the host oracle directly
MIN_DEVICE_BATCH = 32

_cache_lock = threading.Lock()
_verify_cache: RandomEvictionCache = RandomEvictionCache(VERIFY_CACHE_SIZE)
_backend: Optional[Callable[[bytes, bytes, bytes], bool]] = None


class PublicKey:
    """32-byte ed25519 public key with StrKey + XDR conveniences."""

    __slots__ = ("raw",)

    def __init__(self, raw: bytes):
        if len(raw) != 32:
            raise ValueError("public key must be 32 bytes")
        self.raw = bytes(raw)

    @classmethod
    def from_strkey(cls, s: str) -> "PublicKey":
        return cls(_strkey.decode_account(s))

    def to_strkey(self) -> str:
        return _strkey.encode_account(self.raw)

    def to_xdr(self):
        from stellar_tpu.xdr.types import account_id
        return account_id(self.raw)

    @classmethod
    def from_xdr(cls, v) -> "PublicKey":
        return cls(v.value)

    def hint(self) -> bytes:
        """Signature hint: last 4 bytes of the key (reference
        ``SignatureUtils::getHint``)."""
        return self.raw[-4:]

    def __eq__(self, other):
        return isinstance(other, PublicKey) and self.raw == other.raw

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self):
        return f"PublicKey({self.to_strkey()})"


class SecretKey:
    """Seed-based ed25519 secret key (reference ``SecretKey.h:22``)."""

    __slots__ = ("seed", "_pk")

    def __init__(self, seed: bytes):
        if len(seed) != 32:
            raise ValueError("seed must be 32 bytes")
        self.seed = bytes(seed)
        self._pk: Optional[PublicKey] = None

    @classmethod
    def random(cls) -> "SecretKey":
        return cls(os.urandom(32))

    @classmethod
    def from_strkey_seed(cls, s: str) -> "SecretKey":
        return cls(_strkey.decode_seed(s))

    @classmethod
    def pseudo_random_for_testing(cls) -> "SecretKey":
        """Non-CSPRNG key for tests (reference ``SecretKey.h:66-77``)."""
        import random
        return cls(bytes(random.getrandbits(8) for _ in range(32)))

    @classmethod
    def from_seed_str(cls, s: str) -> "SecretKey":
        """Deterministic key from an arbitrary string: seed = SHA256(s)
        (reference tests' getAccount pattern)."""
        return cls(sha256(s.encode() if isinstance(s, str) else s))

    def to_strkey_seed(self) -> str:
        return _strkey.encode_seed(self.seed)

    @property
    def public_key(self) -> PublicKey:
        if self._pk is None:
            self._pk = PublicKey(_ref.secret_to_public(self.seed))
        return self._pk

    def get_public_key(self) -> PublicKey:
        return self.public_key

    def sign(self, msg: bytes) -> bytes:
        return _ref.sign(self.seed, msg)

    def sign_decorated(self, msg: bytes):
        from stellar_tpu.xdr.tx import DecoratedSignature
        return DecoratedSignature(hint=self.public_key.hint(),
                                  signature=self.sign(msg))

    def __eq__(self, other):
        return isinstance(other, SecretKey) and self.seed == other.seed

    def __hash__(self):
        return hash(self.seed)

    def __repr__(self):
        return f"SecretKey({self.public_key.to_strkey()})"


def set_verifier_backend(fn: Optional[Callable[[bytes, bytes, bytes], bool]]):
    """Install a verify backend (pk, msg, sig) -> bool; None restores the
    pure-Python oracle. The result cache stays in front either way."""
    global _backend
    with _cache_lock:
        _backend = fn


def accelerated_verify_available() -> bool:
    """True when bulk verification is worth collecting for: an explicit
    backend is installed, or the device probe says an accelerator is
    live. The shared gate for every prefetch-then-apply path (ledger
    close seeding, catchup checkpoint prefetch) — on the host-oracle
    fallback a prefetch is the same sequential work plus collection
    overhead, so those paths verify lazily instead."""
    if _backend is not None:
        return True
    from stellar_tpu.crypto import batch_verifier
    return batch_verifier.device_available(block=False)


def get_verifier_backend_name() -> str:
    """Which backend serves verification right now — recorded into
    every published benchmark row so numbers are attributable."""
    if _backend is None:
        from stellar_tpu.crypto import batch_verifier
        state = batch_verifier._device_state  # no probe side effect
        if state in ("dead", "cpu"):
            return f"host-oracle(auto; device={state})"
        return f"auto(host<{MIN_DEVICE_BATCH},device-batch>=" \
            f"{MIN_DEVICE_BATCH},device={state or 'unprobed'})"
    self_obj = getattr(_backend, "__self__", None)
    if self_obj is not None:
        name = type(self_obj).__name__
        if name == "TrickleBatcher":
            return "device-batch+trickle"
        if hasattr(self_obj, "verify_batch"):
            return "device-batch"
        return name
    mod = getattr(_backend, "__module__", "")
    if "ed25519_ref" in mod:
        return "host-oracle"
    return getattr(_backend, "__qualname__", "custom")


def _cache_key(pk: bytes, msg: bytes, sig: bytes) -> bytes:
    # Identity of the (key, sig, msg) triple. pk and sig are validated
    # fixed-length (32/64) before this is called, so the concatenation
    # has unambiguous field boundaries.
    return sha256(pk + sig + msg)


def verify_sig(pk, msg: bytes, sig: bytes) -> bool:
    """The ``PubKeyUtils::verifySig`` equivalent — all single-signature
    verification funnels through here."""
    raw = pk.raw if isinstance(pk, PublicKey) else bytes(pk)
    if len(sig) != 64 or len(raw) != 32:
        return False
    key = _cache_key(raw, msg, sig)
    with _cache_lock:
        got = _verify_cache.maybe_get(key)
    if got is not None:
        return got
    fn = _backend or _ref.verify
    ok = bool(fn(raw, msg, sig))
    with _cache_lock:
        _verify_cache.put(key, ok)
    return ok


def cached_verify_sig(pk, msg: bytes, sig: bytes) -> Optional[bool]:
    """Cache-only lookup of a prior ``verify_sig`` answer (``None`` on
    miss) — lets adoption call sites (herder SCP envelopes) honor a
    ``batch_verify_into_cache`` prefetch before paying a verify-service
    round trip for one row. Malformed lengths answer ``False`` exactly
    as ``verify_sig`` would."""
    raw = pk.raw if isinstance(pk, PublicKey) else bytes(pk)
    if len(sig) != 64 or len(raw) != 32:
        return False
    with _cache_lock:
        return _verify_cache.maybe_get(_cache_key(raw, msg, sig))


def seed_verify_cache(results) -> None:
    """Seed the ``verify_sig`` result cache with already-decided
    ``(pk, msg, sig, ok)`` quadruples — how a verify-service verdict
    keeps the flood-dedup cache consistent with the direct path (the
    service's answers are pinned bit-identical to the host oracle, so
    seeding can never teach the cache a different decision)."""
    keyed = [(_cache_key(pk, msg, sig), bool(ok))
             for pk, msg, sig, ok in results
             if len(pk) == 32 and len(sig) == 64]
    with _cache_lock:
        for k, ok in keyed:
            _verify_cache.put(k, ok)


def _host_oracle_batch(todo) -> list:
    """Host verification of (key, pk, msg, sig) tuples: libsodium's
    policy gate in Python (the single source of truth,
    ed25519_ref._policy_gate), curve equations through the threaded
    native libcrypto batch when it built, else the per-call oracle."""
    from stellar_tpu.crypto import native_verify
    if not native_verify.available():
        return [_ref.verify(pk, msg, sig) for _, pk, msg, sig in todo]
    gate = [_ref._policy_gate(pk, sig) for _, pk, msg, sig in todo]
    # compact to gate-passing rows (a flood of malformed sigs must not
    # pay full curve verifications for discarded results), then
    # scatter the equation results back
    idx = [i for i, g in enumerate(gate) if g]
    if not idx:
        return [False] * len(todo)
    eq = native_verify.verify_eq_batch(
        [todo[i][1] for i in idx], [todo[i][2] for i in idx],
        [todo[i][3] for i in idx])
    out = [False] * len(todo)
    for i, e in zip(idx, eq):
        out[i] = bool(e)
    return out


def batch_verify_into_cache(items) -> None:
    """Verify (pk, msg, sig) triples in one device batch and seed the
    result cache, so subsequent ``verify_sig`` calls for the same
    triples are O(1) lookups. This is how bulk validation paths (txset
    checkValid, SCP envelope floods, catchup replay) ride the TPU: they
    prefetch, then the per-signer logic runs unchanged
    (reference boundary: ``PubKeyUtils::verifySig`` cache,
    ``SecretKey.cpp:318-338``)."""
    # hash outside the lock; keep the key alongside the triple
    keyed = [(_cache_key(pk, msg, sig), pk, msg, sig)
             for pk, msg, sig in items
             if len(pk) == 32 and len(sig) == 64]
    with _cache_lock:
        todo = [(k, pk, msg, sig) for k, pk, msg, sig in keyed
                if _verify_cache.maybe_get(k) is None]
    if not todo:
        return
    if len(todo) < MIN_DEVICE_BATCH:
        # tiny batches aren't worth a device round trip; use exactly
        # what verify_sig would (installed backend or host oracle) so
        # both paths cache consistent answers
        fn = _backend or _ref.verify
        results = [fn(pk, msg, sig) for _, pk, msg, sig in todo]
    elif _backend is not None:
        if hasattr(_backend, "__self__") and \
                hasattr(_backend.__self__, "verify_batch"):
            results = _backend.__self__.verify_batch(
                [(pk, msg, sig) for _, pk, msg, sig in todo])
        else:
            # custom scalar backend: stay consistent with verify_sig
            results = [_backend(pk, msg, sig) for _, pk, msg, sig in todo]
    else:
        from stellar_tpu.crypto import batch_verifier
        if batch_verifier.device_available(block=False):
            results = batch_verifier.default_verifier().verify_batch(
                [(pk, msg, sig) for _, pk, msg, sig in todo])
        else:
            # no accelerator (cpu-only jax, or a dead tunnel): the
            # host oracle beats XLA-on-CPU for bignum verify; the
            # threaded native batch (same libcrypto, same EVP call,
            # policy gate in Python as always) spreads the equation
            # checks across cores where the host has them
            results = _host_oracle_batch(todo)
    with _cache_lock:
        for (k, _, _, _), ok in zip(todo, results):
            _verify_cache.put(k, bool(ok))


def seed_cache_assume_valid(items) -> int:
    """Mark (pk, msg, sig) triples VALID in the cache without
    verifying. ONLY for replaying history whose results are already
    trusted (reference CATCHUP_SKIP_KNOWN_RESULTS_FOR_TESTING) — the
    outcome of every signature in an archived, hash-verified ledger is
    fixed by its recorded results."""
    keyed = [_cache_key(pk, msg, sig) for pk, msg, sig in items
             if len(pk) == 32 and len(sig) == 64]
    with _cache_lock:
        for k in keyed:
            _verify_cache.put(k, True)
    return len(keyed)


def flush_verify_cache():
    with _cache_lock:
        _verify_cache.clear()
        _verify_cache.hits = 0
        _verify_cache.misses = 0


def get_verify_cache_stats() -> dict:
    with _cache_lock:
        return {"hits": _verify_cache.hits, "misses": _verify_cache.misses,
                "size": len(_verify_cache)}


def sign_ops_per_second(iterations: int = 200) -> float:
    """Reference ``SecretKey::benchmarkOpsPerSecond`` (sign half)."""
    import time
    sk = SecretKey.random()
    msg = b"benchmark-payload" * 4
    t0 = time.perf_counter()
    for _ in range(iterations):
        sk.sign(msg)
    return iterations / (time.perf_counter() - t0)


def verify_ops_per_second(iterations: int = 200) -> float:
    import time
    sk = SecretKey.random()
    msg = b"benchmark-payload" * 4
    sig = sk.sign(msg)
    pk = sk.public_key
    t0 = time.perf_counter()
    for _ in range(iterations):
        flush_verify_cache()
        verify_sig(pk, msg, sig)
    return iterations / (time.perf_counter() - t0)
