"""ctypes bridge to the threaded native batch ed25519 verifier
(``native/ed25519_batch_verify.cpp``): the host-side fallback when no
accelerator is reachable. The system libcrypto's EVP one-shot runs the
same ref10-derived cofactorless equation as the per-call oracle, and
the libsodium policy gate stays in Python
(:func:`stellar_tpu.crypto.ed25519_ref._policy_gate`) exactly as for
the per-call path; agreement is PINNED by the differential test
(tests/test_batch_verifier.py) rather than assumed — the
``cryptography`` wheel may embed its own OpenSSL build (reference
boundary: ``PubKeyUtils::verifySig``, ``src/crypto/SecretKey.cpp:435-468``).
"""

from __future__ import annotations

import ctypes
import os
import threading
from typing import Sequence

import numpy as np

__all__ = ["available", "verify_eq_batch"]

_HERE = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_HERE, "native", "ed25519_batch_verify.cpp")
_LIB = os.path.join(_HERE, "build", "libed25519verify.so")

_lock = threading.Lock()
_lib = None
_tried = False

_u8p = ctypes.POINTER(ctypes.c_uint8)
_u64p = ctypes.POINTER(ctypes.c_uint64)


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            from stellar_tpu.soroban.native_wasm import _build_lib
            _build_lib([_SRC], _LIB, extra_flags=["-ldl"], timeout=120)
            lib = ctypes.CDLL(_LIB)
            lib.ed25519_verify_available.restype = ctypes.c_int
            lib.ed25519_verify_batch.argtypes = [
                _u8p, _u8p, _u8p, _u64p, _u64p, ctypes.c_uint64,
                ctypes.c_int, _u8p]
            lib.ed25519_verify_batch.restype = ctypes.c_int
            if lib.ed25519_verify_available() != 1:
                lib = None
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def _u8(a: np.ndarray):
    return a.ctypes.data_as(_u8p)


def verify_eq_batch(pks: Sequence[bytes], msgs: Sequence[bytes],
                    sigs: Sequence[bytes],
                    nthreads: int = 0) -> np.ndarray:
    """Curve-equation verification for n well-formed (32B pk, msg,
    64B sig) items, threaded. Callers apply the libsodium policy gate
    separately (same split as every other verify path)."""
    n = len(pks)
    out = np.zeros(n, dtype=np.uint8)
    if n == 0:
        return out.astype(bool)
    lib = _load()
    assert lib is not None, "native verifier unavailable"
    pk_blob = np.frombuffer(b"".join(pks), dtype=np.uint8)
    sig_blob = np.frombuffer(b"".join(sigs), dtype=np.uint8)
    blob = b"".join(msgs)
    msg_blob = np.frombuffer(blob, dtype=np.uint8) if blob else \
        np.zeros(1, dtype=np.uint8)
    lens = np.fromiter((len(m) for m in msgs), dtype=np.uint64, count=n)
    offs = np.zeros(n, dtype=np.uint64)
    np.cumsum(lens[:-1], out=offs[1:])
    if nthreads <= 0:
        nthreads = min(8, os.cpu_count() or 1)
    rc = lib.ed25519_verify_batch(
        _u8(pk_blob), _u8(sig_blob), _u8(msg_blob),
        offs.ctypes.data_as(_u64p), lens.ctypes.data_as(_u64p),
        n, nthreads, _u8(out))
    assert rc == 0
    return out.astype(bool)
