"""Replicated verify fleet (ISSUE 17): N active-active
:class:`~stellar_tpu.crypto.verify_service.VerifyService` replicas
behind a deterministic front-end router.

One resident service is both a throughput ceiling and a single point
of failure for the millions-of-users north star. PRs 14/15 made every
scheduling, shed and control decision a pure function of event-count
state — bit-identical across replicas (tier-1 ``TENANT_QOS_OK``,
``CONTROL_OK``). This module SPENDS that determinism:

**Routing** is rendezvous (highest-random-weight) hashing over the
``(lane, tenant)`` key with the same content-seeded SHA-256 draw
discipline as :func:`stellar_tpu.crypto.audit.keep_under_shed`: per
candidate replica ``i`` the score is the first 8 little-endian bytes
of ``sha256(len(key) || key || i)``, highest score wins (ties break
to the smaller index). Zero clocks, zero RNG — two independently
constructed routers given the same submission stream route
identically (tier-1 ``FLEET_OK`` pins this), and a replica's loss
moves ONLY that replica's keys (re-hashed across survivors); its
return moves them back exactly.

**Conservation** lifts the service's per-lane law to the fleet:

    fleet submitted == Σ per-replica (verified + rejected + shed
                       + failed + pending) + router_refused

with residual exactly 0 at all times (``snapshot()
["conservation_gap"]``, the ``fleet`` admin route, and
``dispatch_health()["fleet"]``). A drained replica's queued items
move to its ``handoff`` terminal — excluded from the sum above and
counted exactly once more at the survivor that re-admits them, so the
law holds THROUGH a kill (``router_refused`` counts items the router
itself refused because no replica was admissible; they reached no
replica's counters).

**Divergence conviction** lifts the PR 4 sampled-audit discipline (a
corrupting chip is convicted from evidence, never trusted) from chip
to replica granularity: the router keeps a bounded per-replica ledger
of what it submitted (``seq -> (lane, tenant)``) and, every
``DIVERGENCE_EVERY`` routes, re-reads each live replica's bounded
``decision_log()`` / ``control_log()`` and checks every retained
tuple against the ledger and the tuples' own invariants (shape, kind,
lane, replica stamp, integer domains). An honest replica can NEVER
fail the check — its log is produced by the very code path that fed
the ledger — so there are no false positives; a corrupted or
Byzantine replica is convicted from its own log, its per-replica
:class:`~stellar_tpu.utils.resilience.CircuitBreaker` hard-trips
(the :mod:`~stellar_tpu.parallel.device_health` style), and its key
range re-hashes across survivors. Re-admission is by probation: after
``PROBATION`` further routes (event-count, not a clock — routing must
stay deterministic) the replica re-enters the candidate set as
``probation`` and is promoted back to ``active`` only by surviving
the next divergence check.

**Drain/handoff** (:meth:`FleetRouter.kill_replica`): a replica can
be killed mid-soak with zero lost tickets — its queued submissions
are extracted (:meth:`VerifyService.drain_handoff`), re-submitted
through the router to survivors WITH their original trace IDs
(``submit(trace_lo=...)``), and each original ticket's future is
chained to its re-submission, so callers never observe the move.
In-flight work finishes during the drain stop. A survivor's refusal
is a typed :class:`Overloaded` naming the refusing replica — never
silence.

This module sits inside both consensus lint scopes
(``analysis/nondet.py`` HOST_ORACLE_FILES with NO allowlist entries,
``analysis/locks.py`` SCOPE): the router reads no clock and draws no
RNG anywhere — the per-replica breakers keep their own clocks inside
:mod:`~stellar_tpu.utils.resilience`, but they are a health/metric
surface only, never a routing input.
"""

from __future__ import annotations

import hashlib
import inspect
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

from stellar_tpu.crypto import batch_verifier
from stellar_tpu.crypto import tenant as tenant_mod
from stellar_tpu.crypto import verify_service as vs_mod
from stellar_tpu.utils import resilience
from stellar_tpu.utils.metrics import registry

__all__ = ["FleetRouter", "SharedVerifier", "Overloaded",
           "configure_fleet", "default_fleet", "running_fleet",
           "fleet_health", "route_key", "route_score"]

# re-export: the typed admission verdict (same policy as
# verify_service — callers catch one type at every boundary)
Overloaded = resilience.Overloaded


def _env_true(name: str) -> bool:
    return os.environ.get(name, "").strip().lower() in (
        "1", "true", "yes", "on")


# ---------------- knob defaults (Config push / env) ----------------

FLEET_ENABLED = _env_true("VERIFY_FLEET_ENABLED")
FLEET_REPLICAS = int(os.environ.get("VERIFY_FLEET_REPLICAS", "3"))
# divergence-audit cadence: one full log re-check every N router
# submissions (event-count, never a timer)
DIVERGENCE_EVERY = int(os.environ.get(
    "VERIFY_FLEET_DIVERGENCE_EVERY", "64"))
# probation delay after a conviction, in ROUTES (event-count — a
# clock here would make two routers' candidate sets diverge)
PROBATION = int(os.environ.get("VERIFY_FLEET_PROBATION", "256"))
# per-replica submission-ledger cap (seq -> (lane, tenant)); evicted
# entries degrade the divergence check to structural-only for those
# seqs, never to silence
LEDGER = int(os.environ.get("VERIFY_FLEET_LEDGER", "8192"))
# metric-cardinality guard (the PR 14 discipline): per-replica gauge
# series only for the first N replicas, the rest fold into the
# reserved `~other` rollup — fleet growth can never blow the
# TimeSeriesRing series cap
METRIC_REPLICAS = int(os.environ.get(
    "VERIFY_FLEET_METRIC_REPLICAS", "8"))

_defaults_lock = threading.Lock()


def configure_fleet(enabled: Optional[bool] = None,
                    replicas: Optional[int] = None,
                    divergence_every: Optional[int] = None,
                    probation: Optional[int] = None,
                    ledger: Optional[int] = None,
                    metric_replicas: Optional[int] = None) -> None:
    """Push fleet-policy knobs (Config / tests); None keeps the
    current value. Instances read these at construction — push before
    :func:`default_fleet` (the Application does)."""
    global FLEET_ENABLED, FLEET_REPLICAS, DIVERGENCE_EVERY, \
        PROBATION, LEDGER, METRIC_REPLICAS
    with _defaults_lock:
        if enabled is not None:
            FLEET_ENABLED = bool(enabled)
        if replicas is not None:
            FLEET_REPLICAS = max(1, int(replicas))
        if divergence_every is not None:
            DIVERGENCE_EVERY = max(1, int(divergence_every))
        if probation is not None:
            PROBATION = max(1, int(probation))
        if ledger is not None:
            LEDGER = max(16, int(ledger))
        if metric_replicas is not None:
            METRIC_REPLICAS = max(1, int(metric_replicas))


# ---------------- the deterministic draw ----------------

def route_key(lane: str, tenant: str) -> bytes:
    """Length-prefixed ``(lane, tenant)`` key material — the same
    ambiguity-free framing as :func:`audit.keep_under_shed`'s tenant
    mixing, so distinct (lane, tenant) pairs can never collide by
    concatenation."""
    lb, tb = lane.encode("utf-8"), tenant.encode("utf-8")
    return (len(lb).to_bytes(2, "little") + lb
            + len(tb).to_bytes(2, "little") + tb)


def route_score(key: bytes, replica: int) -> int:
    """Rendezvous score of one replica for one key: the first 8
    little-endian bytes of ``sha256(len(key) || key || replica)``.
    Pure content arithmetic — every router computes the same score."""
    material = (len(key).to_bytes(2, "little") + key
                + int(replica).to_bytes(8, "little"))
    return int.from_bytes(
        hashlib.sha256(material).digest()[:8], "little")


def _pick(candidates: Sequence[int], key: bytes) -> Optional[int]:
    """Highest rendezvous score among ``candidates`` (ties break to
    the smaller index — candidates iterate ascending and only a
    strictly greater score displaces the incumbent)."""
    best, best_score = None, -1
    for i in candidates:
        s = route_score(key, i)
        if s > best_score:
            best, best_score = i, s
    return best


# ---------------- shared-engine adapter ----------------

class SharedVerifier:
    """Serialize ``submit`` calls of N replica dispatcher threads on
    ONE underlying engine. :class:`~stellar_tpu.crypto.batch_verifier.
    BatchVerifier.submit` mutates engine state (jit caches, pinned
    buffers, ledger tokens) and is only ever entered by a single
    dispatcher in the one-service deployment; the fleet keeps that
    invariant with a lock. Resolvers are returned as-is — the resolve
    path guards its shared registries itself, so in-flight batches of
    different replicas still overlap on device."""

    def __init__(self, verifier):
        self._verifier = verifier
        self._lock = threading.Lock()
        # trace-ID propagation rides inner verifiers that accept it
        # (same duck-typing as VerifyService.start)
        try:
            self._traceful = "trace_ids" in inspect.signature(
                verifier.submit).parameters
        except (TypeError, ValueError):
            self._traceful = False

    def submit(self, items, trace_ids=None):
        with self._lock:
            if self._traceful:
                return self._verifier.submit(items,
                                             trace_ids=trace_ids)
            return self._verifier.submit(items)


def _chain_tickets(new_tkt, old_tkt) -> None:
    """Complete a handed-off ticket's future from its re-submission:
    result, shed/reject Overloaded, or the batch's own failure — the
    original caller sees exactly what a direct submitter would."""
    def _done(f):
        e = f.exception()
        if e is not None:
            old_tkt._fut.set_exception(e)
        else:
            old_tkt._fut.set_result(f.result())
    new_tkt._fut.add_done_callback(_done)


# replica lifecycle states. active/probation are routable;
# quarantined is convicted and waiting out its event-count probation;
# dead is drained and stopped (kill_replica), never routable again.
_ROUTABLE = ("active", "probation")


class FleetRouter:
    """The active-active fleet front end (module docstring). Built
    either over explicit ``services`` (tests / the soak, each already
    carrying ``replica=i``) or lazily at :meth:`start` as
    ``replicas`` fresh :class:`VerifyService` instances sharing one
    engine through :class:`SharedVerifier`."""

    def __init__(self, services: Optional[Sequence] = None,
                 verifier=None,
                 replicas: Optional[int] = None,
                 divergence_every: Optional[int] = None,
                 probation: Optional[int] = None,
                 ledger: Optional[int] = None,
                 metric_replicas: Optional[int] = None):
        self._lock = threading.Lock()
        self._verifier = verifier
        self._n = FLEET_REPLICAS if replicas is None \
            else max(1, int(replicas))
        self._divergence_every = DIVERGENCE_EVERY \
            if divergence_every is None else max(1, int(divergence_every))
        self._probation = PROBATION if probation is None \
            else max(1, int(probation))
        self._ledger_cap = LEDGER if ledger is None \
            else max(16, int(ledger))
        self._metric_replicas = METRIC_REPLICAS \
            if metric_replicas is None else max(1, int(metric_replicas))
        self._replicas: List[dict] = []
        self._ledgers: List[Dict[int, tuple]] = []
        if services is not None:
            self._adopt_locked(list(services))
        # fleet-level conservation & evidence counters — all
        # event-count state, mutated only under self._lock
        self._routes = 0
        self._submitted = 0
        self._router_refused = 0
        self._handoffs = 0
        self._divergence_checks = 0
        self._convictions = 0
        self._readmissions = 0
        self._conviction_log: List[dict] = []
        # unified system journal feed (ISSUE 20): one bounded,
        # in-order route/refusal log keyed by a monotone per-router
        # seq — ``stellar_tpu/utils/journal.py`` merges it with the
        # replicas' feeds. Routing is a pure rendezvous draw, so two
        # routers fed the same stream produce bit-identical feeds;
        # the never-evicting totals keep the completeness law
        # checkable after the bounded row log wraps.
        self._route_log: deque = deque(maxlen=self._ledger_cap)
        self._route_seq = 0
        self._route_totals = {"routed": 0, "refused": 0,
                              "rerouted": 0}
        self._running = False

    # ---------------- construction helpers ----------------

    def _adopt_locked(self, services: list) -> None:
        """Wrap each service in its replica record; stamps the fleet
        identity into the service so its decision tuples and
        Overloaded refusals name it."""
        for i, svc in enumerate(services):
            svc.replica = i
            self._replicas.append({
                "service": svc,
                "state": "active",
                "breaker": resilience.CircuitBreaker(
                    name=f"fleet-replica-{i}", failure_threshold=1),
                "probation_due": 0,
                "convictions": 0,
                "routed_submissions": 0,
                "routed_items": 0,
            })
            self._ledgers.append({})

    # ---------------- public API ----------------

    def start(self) -> "FleetRouter":
        """Start every replica (idempotent), register the fleet
        health surface with ``dispatch_health()`` and the ``fleet``
        admin route."""
        with self._lock:
            if not self._running:
                if not self._replicas:
                    v = self._verifier if self._verifier is not None \
                        else batch_verifier.default_verifier()
                    shared = SharedVerifier(v)
                    self._adopt_locked([
                        vs_mod.VerifyService(verifier=shared)
                        for _ in range(self._n)])
                for rep in self._replicas:
                    rep["service"].start()
                self._running = True
        batch_verifier.register_fleet_health(self.snapshot)
        global _fleet
        with _fleet_lock:
            # the fleet route serves the last-started instance (same
            # policy as register_service_health)
            _fleet = self
        return self

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Stop every still-live replica (``drain`` semantics as
        :meth:`VerifyService.stop`)."""
        with self._lock:
            if not self._running:
                return
            self._running = False
            services = [rep["service"] for rep in self._replicas
                        if rep["state"] != "dead"]
        for svc in services:
            svc.stop(drain=drain, timeout=timeout)

    def services(self) -> list:
        """The replica services, index-aligned with their fleet
        identities (read-only convenience for tools/tests)."""
        with self._lock:
            return [rep["service"] for rep in self._replicas]

    def route_of(self, lane: str = "bulk",
                 tenant: Optional[str] = None) -> Optional[int]:
        """Which replica WOULD serve ``(lane, tenant)`` right now —
        a pure read (no counters move, no probation re-admission),
        the surface the determinism selfcheck compares across
        independently constructed routers. None = no routable
        replica."""
        if lane not in vs_mod.LANES:
            raise ValueError(
                f"unknown lane {lane!r} (one of {vs_mod.LANES})")
        tenant = tenant_mod.validate_tenant(tenant)
        with self._lock:
            cands = [i for i, rep in enumerate(self._replicas)
                     if rep["state"] in _ROUTABLE]
        return _pick(cands, route_key(lane, tenant))

    def submit(self, items: Sequence[tuple], lane: str = "bulk",
               tenant: Optional[str] = None,
               trace_lo: Optional[int] = None):
        """Route one submission to its replica and admit it there.
        Raises :class:`Overloaded` exactly as the service would (the
        exception's ``replica`` field names the refusing replica), or
        with ``reason="fleet-quarantined"`` / ``replica=None`` when
        no replica is routable at all. Returns the replica's
        :class:`VerifyTicket`.

        ``trace_lo`` (ISSUE 19) is the wire-ingress pass-through: the
        ingress server allocates the trace block when the frame
        arrives, so the ``trace?id=`` timeline starts on the wire and
        the block survives routing AND any later handoff re-route
        (``_resubmit_locked`` already preserved it). None = the
        router allocates the block itself (ISSUE 20) so the routing
        decision — emitted as a ``fleet.route`` recorder event with
        its rendezvous score BEFORE the replica's ``service.enqueue``
        — is part of the stitched timeline even for direct fleet
        submissions, and a total refusal still names its traces."""
        if lane not in vs_mod.LANES:
            raise ValueError(
                f"unknown lane {lane!r} (one of {vs_mod.LANES})")
        tenant = tenant_mod.validate_tenant(tenant)
        items = list(items)
        n = len(items)
        if trace_lo is None:
            trace_lo = vs_mod._alloc_trace_block(n)
        trange = [[trace_lo, trace_lo + n]] if n else []
        with self._lock:
            if not self._running:
                raise Overloaded(
                    "verify fleet is stopped", kind="rejected",
                    lane=lane, reason="stopped", tenant=tenant,
                    trace_ids=range(trace_lo, trace_lo + n))
            self._routes += 1
            self._submitted += n
            idx = self._route_locked(lane, tenant)
            due = self._routes % self._divergence_every == 0
            if idx is None:
                # every replica convicted/dead: refuse typed — these
                # items reached no replica's counters, so they carry
                # their own conservation terminal (and their trace
                # block: the refusal IS the stitched terminal)
                self._router_refused += n
                registry.meter(
                    "crypto.verify.fleet.router_refused").mark(n)
                self._journal_note_locked(
                    "refused", lane, tenant, None, trace_lo, n,
                    reason="fleet-quarantined")
                batch_verifier.note_trace_event(
                    "fleet.refuse", lane=lane, tenant=tenant,
                    reason="fleet-quarantined", traces=trange,
                    items=n)
                raise Overloaded(
                    "no routable fleet replica (all quarantined or "
                    "dead)", kind="rejected", lane=lane,
                    reason="fleet-quarantined", tenant=tenant,
                    trace_ids=range(trace_lo, trace_lo + n))
            rep = self._replicas[idx]
            rep["routed_submissions"] += 1
            rep["routed_items"] += n
            registry.meter("crypto.verify.fleet.routed").mark(n)
            # the routing decision precedes the replica's
            # service.enqueue/service.reject in the recorder, so the
            # stitched timeline reads wire -> route -> replica in
            # causal order (tracing.trace_timeline relies on it)
            self._journal_note_locked(
                "route", lane, tenant, idx, trace_lo, n,
                score=route_score(route_key(lane, tenant), idx))
            batch_verifier.note_trace_event(
                "fleet.route", lane=lane, tenant=tenant, replica=idx,
                score=route_score(route_key(lane, tenant), idx),
                route=self._routes, traces=trange, items=n)
            try:
                tkt = rep["service"].submit(items, lane=lane,
                                            tenant=tenant,
                                            trace_lo=trace_lo)
            finally:
                # the divergence audit runs on its cadence whether or
                # not this submission was admitted — the replica's
                # reject path writes counters too
                if due:
                    self._divergence_check_locked()
            self._ledger_record_locked(idx, tkt._seq, lane, tenant)
        return tkt

    def verify(self, items: Sequence[tuple], lane: str = "bulk",
               timeout: Optional[float] = None,
               tenant: Optional[str] = None):
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(items, lane=lane,
                           tenant=tenant).result(timeout)

    def kill_replica(self, idx: int,
                     stop_timeout: Optional[float] = None) -> int:
        """The drain/handoff protocol: mark replica ``idx`` dead
        (its key range re-hashes across survivors immediately), move
        its queued submissions to its ``handoff`` terminal and
        re-submit each one through the router with its original trace
        block, chaining the old ticket's future to the new one — zero
        lost tickets, scp lane included. In-flight work finishes
        during the drain stop. Returns the number of handed-off
        items."""
        with self._lock:
            rep = self._replicas[idx]
            if rep["state"] == "dead":
                return 0
            rep["state"] = "dead"
            svc = rep["service"]
            moved = 0
            for tkt in svc.drain_handoff():
                moved += tkt.n_items
                self._handoffs += tkt.n_items
                self._resubmit_locked(tkt)
            if moved:
                registry.meter(
                    "crypto.verify.fleet.handoff").mark(moved)
        # the drain stop blocks on the dispatcher thread — outside
        # the router lock so routing continues while it drains
        svc.stop(drain=True, timeout=stop_timeout)
        return moved

    def convict(self, idx: int, evidence) -> None:
        """Manually convict a replica (operator escape hatch / test
        seam); the standing detector calls the same path."""
        with self._lock:
            self._convict_locked(idx, ("manual", evidence))

    def divergence_check(self) -> list:
        """Run one divergence audit now (the standing detector runs
        the same audit every ``divergence_every`` routes). Returns
        the list of ``(replica, evidence)`` convictions."""
        with self._lock:
            return self._divergence_check_locked()

    def snapshot(self) -> dict:
        """The ``fleet`` admin route / ``dispatch_health()["fleet"]``
        payload: per-replica states and counters, the fleet
        conservation law (residual must read 0), conviction evidence,
        and the knobs. Publishes the fleet gauge set under the
        metric-cardinality guard as a side effect (same policy as the
        tenant top-k publisher)."""
        with self._lock:
            reps = []
            totals = {"submitted": 0, "verified": 0, "rejected": 0,
                      "shed": 0, "failed": 0, "handoff": 0}
            pending = 0
            for i, rep in enumerate(self._replicas):
                s = rep["service"].snapshot()
                t = s["totals"]
                for k in totals:
                    totals[k] += t[k]
                pending += s["pending_items"]
                reps.append({
                    "replica": i,
                    "state": rep["state"],
                    "breaker": rep["breaker"].state,
                    "routed_submissions": rep["routed_submissions"],
                    "routed_items": rep["routed_items"],
                    "convictions": rep["convictions"],
                    "probation_due": (rep["probation_due"]
                                      if rep["state"] == "quarantined"
                                      else None),
                    "running": s["running"],
                    "pending_items": s["pending_items"],
                    "totals": t,
                    "conservation_gap": s["conservation_gap"],
                })
            gap = (self._submitted - totals["verified"]
                   - totals["rejected"] - totals["shed"]
                   - totals["failed"] - pending
                   - self._router_refused)
            snap = {
                "enabled": True,
                "running": self._running,
                "replicas": len(self._replicas),
                "active": sum(1 for rep in self._replicas
                              if rep["state"] in _ROUTABLE),
                "states": [rep["state"] for rep in self._replicas],
                "routes": self._routes,
                "submitted": self._submitted,
                "router_refused": self._router_refused,
                "handoffs": self._handoffs,
                "divergence_checks": self._divergence_checks,
                "divergence_convictions": self._convictions,
                "readmissions": self._readmissions,
                "conviction_log": list(self._conviction_log),
                "route_totals": dict(self._route_totals),
                "pending_items": pending,
                "totals": totals,
                "conservation_gap": gap,
                "per_replica": reps,
                "knobs": {
                    "divergence_every": self._divergence_every,
                    "probation": self._probation,
                    "ledger": self._ledger_cap,
                    "metric_replicas": self._metric_replicas,
                },
            }
            self._publish_metrics_locked(snap)
        return snap

    # ---------------- router internals ----------------
    # _locked helpers are called with self._lock held (the repo-wide
    # naming contract the lock lint encodes).

    def _route_locked(self, lane: str, tenant: str) -> Optional[int]:
        """One routing decision: re-admit any replica whose
        event-count probation is due, then rendezvous-pick among the
        routable candidates."""
        for rep in self._replicas:
            if rep["state"] == "quarantined" and \
                    self._routes >= rep["probation_due"]:
                rep["state"] = "probation"
        cands = [i for i, rep in enumerate(self._replicas)
                 if rep["state"] in _ROUTABLE]
        return _pick(cands, route_key(lane, tenant))

    def _journal_note_locked(self, kind: str, lane: str, tenant,
                             replica, trace_lo, n: int,
                             **extra) -> None:
        """Append one row to the router's journal feed (called with
        the router lock held). Rows are pure functions of the
        submission stream and the rendezvous draw — no clock reads —
        so two routers fed identical streams produce bit-identical
        feeds. The totals obey one exact law the completeness check
        reads: ``routed + rerouted + refused == submitted +
        handoffs`` (every submission routes or refuses; every
        drained ticket re-routes or refuses)."""
        row = {"seq": self._route_seq, "kind": kind, "lane": lane,
               "tenant": tenant, "replica": replica,
               "trace_lo": trace_lo, "n": n}
        if extra:
            row.update(extra)
        self._route_seq += 1
        self._route_log.append(row)
        tot = self._route_totals
        if kind == "route":
            tot["rerouted" if extra.get("handoff") else "routed"] \
                += n
        elif kind == "refused":
            tot["refused"] += n

    def route_log(self, limit: int = 0) -> list:
        """The bounded route/refusal journal feed (ISSUE 20): one
        dict row per routing decision (``route``, with the rendezvous
        score and ``handoff=True`` on a re-route) and per total
        refusal (``refused``), each naming the trace block it covers.
        ``limit`` bounds the tail returned (0 = all retained)."""
        with self._lock:
            log = [dict(r) for r in self._route_log]
        return log[-limit:] if limit else log

    def route_totals(self) -> dict:
        """Never-evicting aggregates behind the route feed — the
        fleet half of the journal completeness law (see
        :func:`stellar_tpu.utils.journal.completeness`)."""
        with self._lock:
            return dict(self._route_totals)

    def services(self) -> list:
        """The replica services, in replica order — the journal
        collector (ISSUE 20) walks them for their per-replica feeds;
        dead replicas stay listed (their journal history is exactly
        what a post-mortem needs)."""
        with self._lock:
            return [rep["service"] for rep in self._replicas]

    def _ledger_record_locked(self, idx: int, seq: int, lane: str,
                              tenant: str) -> None:
        led = self._ledgers[idx]
        led[seq] = (lane, tenant)
        while len(led) > self._ledger_cap:
            # dict preserves insertion order: evict oldest seqs first
            del led[next(iter(led))]

    def _resubmit_locked(self, tkt) -> None:
        """Re-submit one drained ticket to a survivor with its
        original trace block and chain its future. A survivor's
        refusal (or no survivor at all) lands on the original future
        as a typed Overloaded — never silence."""
        idx = self._route_locked(tkt.lane, tkt.tenant)
        if idx is None:
            self._router_refused += tkt.n_items
            registry.meter(
                "crypto.verify.fleet.router_refused"
            ).mark(tkt.n_items)
            self._journal_note_locked(
                "refused", tkt.lane, tkt.tenant, None, tkt.trace_lo,
                tkt.n_items, reason="fleet-quarantined",
                handoff=True)
            batch_verifier.note_trace_event(
                "fleet.refuse", lane=tkt.lane, tenant=tkt.tenant,
                reason="fleet-quarantined", handoff=True,
                traces=[[tkt.trace_lo, tkt.trace_lo + tkt.n_items]],
                items=tkt.n_items)
            tkt._fut.set_exception(Overloaded(
                "no routable fleet replica for handoff",
                kind="rejected", lane=tkt.lane,
                reason="fleet-quarantined", tenant=tkt.tenant,
                trace_ids=tkt.trace_ids))
            return
        rep = self._replicas[idx]
        # the handoff re-route is a first-class routing decision in
        # the stitched timeline (ISSUE 20): it lands BEFORE the
        # survivor's service.enqueue, so a re-homed trace reads
        # handoff -> route -> enqueue -> verdict with no seam
        self._journal_note_locked(
            "route", tkt.lane, tkt.tenant, idx, tkt.trace_lo,
            tkt.n_items,
            score=route_score(route_key(tkt.lane, tkt.tenant), idx),
            handoff=True)
        batch_verifier.note_trace_event(
            "fleet.route", lane=tkt.lane, tenant=tkt.tenant,
            replica=idx, handoff=True,
            score=route_score(route_key(tkt.lane, tkt.tenant), idx),
            route=self._routes,
            traces=[[tkt.trace_lo, tkt.trace_lo + tkt.n_items]],
            items=tkt.n_items)
        try:
            new = rep["service"].submit(tkt._items, lane=tkt.lane,
                                        tenant=tkt.tenant,
                                        trace_lo=tkt.trace_lo)
        except Overloaded as e:
            tkt._fut.set_exception(e)
            return
        rep["routed_submissions"] += 1
        rep["routed_items"] += tkt.n_items
        self._ledger_record_locked(idx, new._seq, tkt.lane,
                                   tkt.tenant)
        _chain_tickets(new, tkt)

    def _divergence_check_locked(self) -> list:
        """The standing integrity audit: validate every retained
        decision/control tuple of every routable replica against the
        router's ledger and the tuples' own invariants. Convictions
        quarantine; a probation replica that survives is promoted
        back to active."""
        self._divergence_checks += 1
        convicted = []
        for i, rep in enumerate(self._replicas):
            if rep["state"] not in _ROUTABLE:
                continue
            ev = _audit_log(rep["service"], i, self._ledgers[i])
            if ev is not None:
                self._convict_locked(i, ev)
                convicted.append((i, ev))
            elif rep["state"] == "probation":
                rep["state"] = "active"
                rep["breaker"].record_success()
                self._readmissions += 1
                registry.counter(
                    "crypto.verify.fleet.readmissions").inc()
        registry.counter("crypto.verify.fleet.divergence_checks").inc()
        return convicted

    def _convict_locked(self, idx: int, evidence: tuple) -> None:
        """Quarantine one replica on log evidence: hard-trip its
        breaker (the device_health discipline — an integrity
        violation gets no more chances), pull it from the candidate
        set (its keys re-hash to survivors on the very next route)
        and schedule event-count probation."""
        rep = self._replicas[idx]
        rep["state"] = "quarantined"
        rep["convictions"] += 1
        rep["probation_due"] = self._routes + self._probation
        rep["breaker"].trip()
        self._convictions += 1
        self._conviction_log.append({
            # monotone conviction seq (ISSUE 20): the journal merge
            # keys fleet conviction events by it
            "seq": self._convictions,
            "replica": idx,
            "at_route": self._routes,
            "probation_due": rep["probation_due"],
            "evidence": [repr(x) for x in evidence],
        })
        del self._conviction_log[:-32]
        registry.counter("crypto.verify.fleet.convictions").inc()
        batch_verifier.note_trace_event(
            "fleet.convict", replica=idx, reason=str(evidence[0]),
            at_route=self._routes)

    def _publish_metrics_locked(self, snap: dict) -> None:
        """Fleet gauge set under the metric-cardinality guard:
        per-replica series only for indices below the cap, the rest
        summed into the reserved ``~other`` rollup."""
        g = registry.gauge
        g("crypto.verify.fleet.replicas").set(snap["replicas"])
        g("crypto.verify.fleet.active").set(snap["active"])
        g("crypto.verify.fleet.pending_items").set(
            snap["pending_items"])
        g("crypto.verify.fleet.conservation_gap").set(
            snap["conservation_gap"])
        other = {"routed_items": 0, "verified": 0, "pending": 0,
                 "quarantined": 0}
        overflow = False
        for r in snap["per_replica"]:
            vals = {
                "routed_items": r["routed_items"],
                "verified": r["totals"]["verified"],
                "pending": r["pending_items"],
                "quarantined": 0 if r["state"] in _ROUTABLE else 1,
            }
            if r["replica"] < self._metric_replicas:
                for k, v in vals.items():
                    g(f"crypto.verify.fleet.replica."
                      f"{r['replica']}.{k}").set(v)
            else:
                overflow = True
                for k in other:
                    other[k] += vals[k]
        if overflow:
            for k, v in other.items():
                g(f"crypto.verify.fleet.replica.~other.{k}").set(v)


def _audit_log(svc, idx: int, ledger: Dict[int, tuple]):
    """Validate one replica's retained logs; returns None (clean) or
    the evidence tuple that convicts — always including the offending
    tuple itself, the ISSUE 4 discipline (conviction from evidence).
    Checks are invariants of the HONEST code path, so an honest
    replica can never fail one:

    * decision tuples are ``(kind, lane, tenant, seq, aux, replica)``
      with ``kind`` in dispatch/shed, a real lane, a str tenant,
      non-negative int seq/aux, and the replica stamp equal to the
      fleet identity;
    * any seq still in the router's ledger must carry the lane and
      tenant the router submitted under (evicted seqs degrade to the
      structural check, never to silence);
    * control tuples are ``(action, seq, max_batch, pipeline_depth,
      highwater_milli, reason)`` with a known action and int/str
      domains."""
    for d in svc.decision_log():
        if not isinstance(d, tuple) or len(d) != 6:
            return ("malformed-decision", d)
        kind, ln, tenant, seq, aux, replica = d
        if kind not in ("dispatch", "shed"):
            return ("bad-decision-kind", d)
        if ln not in vs_mod.LANES:
            return ("bad-decision-lane", d)
        if not isinstance(tenant, str):
            return ("bad-decision-tenant", d)
        if not isinstance(seq, int) or isinstance(seq, bool) \
                or seq < 0:
            return ("bad-decision-seq", d)
        if not isinstance(aux, int) or isinstance(aux, bool) \
                or aux < 0:
            return ("bad-decision-aux", d)
        if replica != idx:
            return ("bad-decision-replica", d)
        want = ledger.get(seq)
        if want is not None and (ln, tenant) != want:
            return ("ledger-mismatch", d, want)
    for c in svc.control_log():
        if not isinstance(c, tuple) or len(c) != 6:
            return ("malformed-control", c)
        action, seq, mb, pd, hw, reason = c
        if action not in ("grow", "shrink", "relax", "hold"):
            return ("bad-control-action", c)
        for v in (seq, mb, pd, hw):
            if not isinstance(v, int) or isinstance(v, bool):
                return ("bad-control-int", c)
        if not isinstance(reason, str):
            return ("bad-control-reason", c)
    return None


# ---------------- process-wide default ----------------

_fleet: Optional[FleetRouter] = None
_fleet_lock = threading.Lock()


def default_fleet() -> FleetRouter:
    """Get-or-start the process-wide fleet (the Application calls
    this when ``VERIFY_FLEET_ENABLED``)."""
    global _fleet
    with _fleet_lock:
        if _fleet is None:
            _fleet = FleetRouter()
        f = _fleet
    return f.start()


def running_fleet() -> Optional[FleetRouter]:
    """The current fleet instance, or None — never constructs."""
    with _fleet_lock:
        return _fleet


def fleet_health() -> dict:
    """The ``fleet`` admin-route payload (served directly — replica
    health matters exactly when the node is struggling)."""
    with _fleet_lock:
        f = _fleet
    if f is None:
        return {"enabled": False}
    return f.snapshot()
