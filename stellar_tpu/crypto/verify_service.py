"""Resident verify service: continuous batching with admission
control, priority lanes, and deterministic load-shed under overload.

The reference serves a *stream* of signature work — Herder TxSet
validation, SCP envelope verification, and overlay peer auth all feed
``PubKeyUtils::verifySig`` continuously — but the batch verifier's
entry point is resolve-a-batch: callers must assemble their own
batches and nothing stands between a traffic spike and unbounded
queueing. This module is the standing stream processor on top of
:class:`stellar_tpu.crypto.batch_verifier.BatchVerifier`
(``docs/robustness.md`` "Overload and load-shed"):

* **priority lanes** (``scp`` > ``auth`` > ``bulk``, mirroring the
  reference's Herder/overlay split): consensus-critical SCP envelope
  verification and overlay peer auth are admitted and scheduled ahead
  of tx-flood backlog, so a flood cannot stall the committee — the
  failure mode "Performance of EdDSA and BLS Signatures in
  Committee-Based Consensus" measures when both share one queue;
* **continuous batching**: a single dispatcher thread coalesces queued
  submissions into the verifier's pipelined jit buckets (up to
  ``MAX_BATCH`` items per dispatch, up to ``PIPELINE_DEPTH`` dispatches
  in flight), overlapping host prep of the next batch with device
  execution of the current one;
* **admission control + backpressure**: every lane has an explicit
  queue-depth and in-flight byte budget; work arriving past a budget
  is refused AT INGRESS with a typed
  :class:`stellar_tpu.utils.resilience.Overloaded` instead of
  buffering to death;
* **deterministic load-shed ladder**: under backlog or global-breaker
  /host-only pressure the service sheds lowest-priority QUEUED work
  first, row selection decided by the content-seeded rule in
  :func:`stellar_tpu.crypto.audit.keep_under_shed` — replicas under
  identical pressure shed identical rows, no clocks or RNG involved
  (this module sits inside the consensus nondet-lint scope). Every
  shed is counted, ticketed back to its caller, and the first onset
  dumps the flight recorder via
  :func:`stellar_tpu.crypto.batch_verifier.note_shed_onset`.

**Starvation-proofing** is sequence-based, not clock-based: every
``AGING_EVERY``-th collected batch serves the lane whose head
submission is globally OLDEST (smallest admission sequence number)
regardless of priority, so the bulk lane always drains — deterministic
in arrival order, no wall-clock reads in any scheduling decision.

**Multi-tenant QoS** (ISSUE 14, ``stellar_tpu/crypto/tenant.py``):
``submit(lane=..., tenant=...)`` keys every submission to a principal.
Per-tenant depth/byte quotas nest inside the lane budgets (refused at
ingress with ``Overloaded(tenant=...)``, reasons ``"tenant-depth"`` /
``"tenant-bytes"``); WITHIN a lane, queued tenants are served by a
deterministic weighted-fair scheduler (start-time fair queueing over
sequence-based virtual time — integer arithmetic, zero clock reads,
same nondet posture as the aging rule); the shed ladder draws
tenant-keyed (``audit.keep_under_shed(..., tenant=...)``) with a
flooding tenant's effective keep fraction scaled down by how far it
sits over its own quota high-water, so its rows shed first; and every
scheduling/shed decision lands BOTH in the flight recorder
(``service.schedule`` / ``service.shed`` events, with the decision's
input window) and in a bounded in-order decision log
(:meth:`VerifyService.decision_log`) — two replicas fed the same
arrival order emit bit-identical decision sequences
(``tools/tenant_selfcheck.py``, tier-1 ``TENANT_QOS_OK``). Per-tenant
work conservation holds exactly (:meth:`VerifyService.
tenant_snapshot`), and per-tenant SLO burn rates ride
:data:`stellar_tpu.crypto.tenant.tenant_slo` under the rank-keyed
metric-cardinality guard.

**Work conservation law** (pinned by ``tools/soak.py`` and the tier-1
``SOAK_OK`` gate): for every lane,

    submitted == verified + rejected + shed + failed + handoff
                 + pending

with ``failed == 0`` in healthy operation — no item is ever silently
dropped; ``snapshot()["conservation_gap"]`` must read 0 at all times.
``handoff`` (ISSUE 17) counts items this replica drained to the fleet
router for re-submission elsewhere — a terminal for THIS replica,
never for the fleet: the router's own conservation law counts each
submission exactly once across all replicas
(:mod:`stellar_tpu.crypto.fleet`, tier-1 ``FLEET_OK``).

**Closed-loop control** (ISSUE 15, ``stellar_tpu/crypto/
controller.py``): when a :class:`~stellar_tpu.crypto.controller.
VerifyController` is attached (``VERIFY_CONTROL_ENABLED``), the
dispatcher assembles an event-count telemetry window every
``CONTROL_EVERY`` collected batches — per-lane SLO burn rates,
queue-wait bubble dominance from the pipeline timeline, lane backlog,
the scp head-of-line sequence age, the shed pressure level — and the
controller adapts ``max_batch``, ``pipeline_depth`` and the
shed-ladder entry highwater within clamped, hysteresis-guarded
bounds. Knob application happens under the service's condition
variable (:meth:`VerifyService._apply_control_locked`), every move is
a ``service.control`` flight-recorder event carrying its full input
window, and the compact trajectory lands in the controller's bounded
``control_log()`` — replayable bit-for-bit
(``tools/control_selfcheck.py``, tier-1 ``CONTROL_OK``).

Clock use in this module is confined to latency STAMPS feeding the
per-lane wait-time histograms (``crypto.verify.service.lane.<lane>.
wait_ms`` — the p50/p99 the soak harness and bench publish); which
rows verify vs shed never depends on them (nondet allowlist,
``stellar_tpu/analysis/nondet.py``).
"""

from __future__ import annotations

import hashlib
import inspect
import os
import threading
import time
from collections import deque
from concurrent.futures import TimeoutError as FuturesTimeout
from typing import Dict, Optional, Sequence

import numpy as np

from stellar_tpu.crypto import audit as audit_mod
from stellar_tpu.crypto import batch_verifier
from stellar_tpu.crypto import controller as controller_mod
from stellar_tpu.crypto import tenant as tenant_mod
from stellar_tpu.utils import metrics as metrics_mod
from stellar_tpu.utils import resilience
from stellar_tpu.utils.metrics import registry
from stellar_tpu.utils.tracing import span

__all__ = ["VerifyService", "VerifyTicket", "Overloaded", "LANES",
           "SHED_LADDER", "configure_service", "default_service",
           "running_service", "service_verified", "service_health",
           "lane_latencies", "SloMonitor", "slo_monitor",
           "configure_slo", "slo_health", "tenant_health",
           "control_health"]

# re-export: the typed admission verdict lives with the resilience
# primitives so TrickleBatcher can raise it without a module cycle
Overloaded = resilience.Overloaded

# priority order, highest first. scp = SCP envelope verification
# (consensus-critical), auth = overlay peer-auth handshakes, bulk =
# tx-flood / catchup backlog.
LANES = ("scp", "auth", "bulk")

# ---------------- service policy knobs ----------------
# Env defaults let tools/soak and tests set these without a Config; a
# node pushes its Config knobs through configure_service() at setup
# (same pattern as batch_verifier.configure_dispatch).

LANE_DEPTH = int(os.environ.get("VERIFY_SERVICE_LANE_DEPTH", "512"))
LANE_BYTES = int(os.environ.get("VERIFY_SERVICE_LANE_BYTES",
                                "16000000"))
MAX_BATCH = int(os.environ.get("VERIFY_SERVICE_MAX_BATCH", "2048"))
PIPELINE_DEPTH = int(os.environ.get("VERIFY_SERVICE_PIPELINE_DEPTH",
                                    "4"))
AGING_EVERY = int(os.environ.get("VERIFY_SERVICE_AGING_EVERY", "4"))
# bounded in-order log of scheduling + shed decisions (ISSUE 14): the
# replica-determinism surface — two services fed identical arrival
# order must produce identical logs (tools/tenant_selfcheck.py)
DECISION_LOG = int(os.environ.get("VERIFY_SERVICE_DECISION_LOG",
                                  "8192"))

# Degradation ladder: pressure level -> {lane: keep_fraction}. A lane
# absent from a level is NEVER shed at that level; scp is absent from
# every level — consensus work is only ever rejected by its own
# ingress budgets, never dropped from the queue.
#   level 1 (backlog): the bulk queue crossed its high-water mark —
#     shed half the flood by content so the queue stays drainable;
#   level 2 (dispatch-degraded): global breaker open or host-only —
#     effective capacity collapsed to the host oracle; keep an eighth
#     of bulk and half of auth so the priority lanes stay live.
SHED_LADDER = {
    1: {"bulk": 0.5},
    2: {"bulk": 0.125, "auth": 0.5},
}
# fraction of LANE_DEPTH at which the bulk queue counts as backlogged
SHED_HIGHWATER_FRAC = 0.75

_defaults_lock = threading.Lock()

# ---------------- per-lane SLO definitions (ISSUE 10) ----------------
# Service-level objectives per lane, Config-pushed (VERIFY_SLO_*):
# a LATENCY objective ("<target> of items complete their lane wait
# under <bound> ms") and a COMPLETION objective ("at most
# <shed budget> of items may be shed/rejected/failed"). The bulk
# lane's generous shed budget is DESIGN, not tolerance — the ladder
# sheds flood backlog on purpose; scp's near-zero budget is the
# consensus-lane contract (the ladder never sheds it, only its own
# ingress bounds can reject). Burn rate = observed bad fraction over
# the sliding window / budgeted bad fraction: 1.0 = burning exactly
# at budget, >1 = the error budget is being consumed faster than the
# objective allows (SRE burn-rate semantics).

SLO_WAIT_BOUND_MS = {
    "scp": float(os.environ.get("VERIFY_SLO_SCP_P99_MS", "5000")),
    "auth": float(os.environ.get("VERIFY_SLO_AUTH_P99_MS", "8000")),
    "bulk": float(os.environ.get("VERIFY_SLO_BULK_P99_MS", "30000")),
}
SLO_LATENCY_TARGET = float(os.environ.get(
    "VERIFY_SLO_LATENCY_TARGET", "0.99"))
SLO_SHED_BUDGET = {
    "scp": 0.001,   # consensus lane: effectively zero tolerance
    "auth": 0.05,
    "bulk": float(os.environ.get("VERIFY_SLO_BULK_SHED_BUDGET",
                                 "0.5")),
}
SLO_WINDOW = int(os.environ.get("VERIFY_SLO_WINDOW", "2048"))


class SloMonitor:
    """Sliding-window error-budget accounting per lane.

    Windows are EVENT-COUNT sliding windows (the last ``window``
    items), not wall-clock buckets: rotation is deterministic in
    arrival order with zero clock reads, which keeps this module's
    nondet posture unchanged — the only clock-derived input is the
    per-item ``wait_ms`` already stamped for the lane histograms
    (allowlisted), and SLO verdicts feed dashboards/burn-rate gauges
    only, never a verify/shed decision.

    A window that has not filled yet is MARKED (``partial: true``) in
    every snapshot — a half-empty window's bad fraction is reported
    with its denominator, never silently presented as a full-window
    rate."""

    def __init__(self, window: Optional[int] = None):
        self._lock = threading.Lock()
        self._window = SLO_WINDOW if window is None \
            else max(8, int(window))
        # lane -> {"events": deque of 0/1 (1 = bad), "bad": int,
        #          "total": int, "bad_total": int}
        self._lat = {ln: self._fresh() for ln in LANES}
        self._comp = {ln: self._fresh() for ln in LANES}

    # window-state machinery is the shared metrics helpers (ONE
    # implementation for the lane and tenant monitors)
    _fresh = staticmethod(metrics_mod.fresh_burn_window)

    def configure(self, window: Optional[int] = None) -> None:
        if window is None:
            return
        with self._lock:
            self._window = max(8, int(window))
            for table in (self._lat, self._comp):
                for st in table.values():
                    self._trim_locked(st)

    def _trim_locked(self, st: dict) -> None:
        metrics_mod.trim_burn_window(st, self._window)

    def _push_locked(self, st: dict, bad: bool, n: int) -> None:
        metrics_mod.push_burn_window(st, bad, n, self._window)

    def note_latency(self, lane: str, wait_ms: float,
                     n: int = 1) -> None:
        """``n`` items of ``lane`` completed with this lane wait."""
        bad = wait_ms > SLO_WAIT_BOUND_MS.get(lane, math_inf)
        with self._lock:
            st = self._lat[lane]
            self._push_locked(st, bad, n)
            burn = self._burn_locked(
                st, max(1e-9, 1.0 - SLO_LATENCY_TARGET))
        # gauge refresh at the FEED site (outside the monitor lock):
        # the Prometheus exposition and the time-series ring must
        # carry live burn rates even when nothing polls the slo route
        registry.gauge(
            f"crypto.verify.service.slo.{lane}.latency_burn_rate"
        ).set(burn)

    def note_completion(self, lane: str, ok: bool,
                        n: int = 1) -> None:
        """``n`` items of ``lane`` reached a terminal state:
        ``ok=False`` for shed / ingress-rejected / failed items (they
        consume the lane's shed budget), True for verified ones."""
        with self._lock:
            st = self._comp[lane]
            self._push_locked(st, not ok, n)
            burn = self._burn_locked(
                st, max(1e-9, SLO_SHED_BUDGET.get(lane, 0.05)))
        registry.gauge(
            f"crypto.verify.service.slo.{lane}.shed_burn_rate"
        ).set(burn)

    @staticmethod
    def _burn_locked(st: dict, budget_frac: float) -> float:
        n = len(st["events"])
        return round((st["bad"] / n) / budget_frac, 4) if n else 0.0

    def snapshot(self) -> dict:
        """The ``slo`` admin-route payload: per lane, both objectives
        with window accounting and burn rates. Also refreshes the
        ``crypto.verify.service.slo.<lane>.*`` burn-rate gauges so
        the Prometheus exposition (and the time-series ring) carry
        live burn rates."""
        with self._lock:
            lanes = {}
            for ln in LANES:
                lat, comp = self._lat[ln], self._comp[ln]
                lat_budget = max(1e-9, 1.0 - SLO_LATENCY_TARGET)
                shed_budget = max(1e-9, SLO_SHED_BUDGET.get(ln, 0.05))
                lanes[ln] = {
                    "latency": self._objective_locked(
                        lat, lat_budget,
                        bound_ms=SLO_WAIT_BOUND_MS.get(ln),
                        target=SLO_LATENCY_TARGET),
                    "completion": self._objective_locked(
                        comp, shed_budget, budget=shed_budget),
                }
            window = self._window
        for ln, obj in lanes.items():
            registry.gauge(
                f"crypto.verify.service.slo.{ln}.latency_burn_rate"
            ).set(obj["latency"]["burn_rate"])
            registry.gauge(
                f"crypto.verify.service.slo.{ln}.shed_burn_rate"
            ).set(obj["completion"]["burn_rate"])
        return {"window": window, "lanes": lanes}

    def _objective_locked(self, st: dict, budget_frac: float,
                          **extra) -> dict:
        n = len(st["events"])
        bad_frac = (st["bad"] / n) if n else 0.0
        return {
            "n": n,
            "window": self._window,
            "partial": n < self._window,
            "bad": st["bad"],
            "bad_frac": round(bad_frac, 6),
            "budget_frac": round(budget_frac, 6),
            "burn_rate": round(bad_frac / budget_frac, 4),
            "total": st["total"],
            "bad_total": st["bad_total"],
            **extra,
        }

    def _reset_for_testing(self) -> None:
        with self._lock:
            self._lat = {ln: self._fresh() for ln in LANES}
            self._comp = {ln: self._fresh() for ln in LANES}


# inf without importing math at call sites (this module avoids new
# imports on the hot path; float("inf") is a constant)
math_inf = float("inf")

# process-wide monitor (every service instance feeds it, like the
# registry meters — one node per process in production)
slo_monitor = SloMonitor()


def configure_slo(scp_p99_ms: Optional[float] = None,
                  auth_p99_ms: Optional[float] = None,
                  bulk_p99_ms: Optional[float] = None,
                  latency_target: Optional[float] = None,
                  bulk_shed_budget: Optional[float] = None,
                  window: Optional[int] = None) -> None:
    """Push SLO knobs (Config / tests); None keeps the current
    value."""
    global SLO_LATENCY_TARGET
    with _defaults_lock:
        if scp_p99_ms is not None:
            SLO_WAIT_BOUND_MS["scp"] = float(scp_p99_ms)
        if auth_p99_ms is not None:
            SLO_WAIT_BOUND_MS["auth"] = float(auth_p99_ms)
        if bulk_p99_ms is not None:
            SLO_WAIT_BOUND_MS["bulk"] = float(bulk_p99_ms)
        if latency_target is not None:
            SLO_LATENCY_TARGET = min(0.999999,
                                     max(0.0, float(latency_target)))
        if bulk_shed_budget is not None:
            SLO_SHED_BUDGET["bulk"] = min(1.0, max(
                1e-6, float(bulk_shed_budget)))
    slo_monitor.configure(window=window)


def slo_health() -> dict:
    """The ``slo`` admin-route payload (served directly — overload is
    exactly when burn rates matter)."""
    return slo_monitor.snapshot()

# ---------------- trace IDs (ISSUE 8) ----------------
# Every submitted item gets a process-unique trace ID at ingress; a
# submission's items take one CONTIGUOUS block so exemplar ranges stay
# compact (batch_verifier.trace_ranges). IDs ride lane queuing, batch
# coalescing, engine sub-chunking, re-shard, audit and host failover —
# and survive shed/reject in the Overloaded ticket. A plain guarded
# counter: no clock, no RNG (this module is nondet-lint scoped).

_trace_lock = threading.Lock()
_trace_next = 1


def _alloc_trace_block(n: int) -> int:
    """Reserve ``n`` contiguous trace IDs; returns the first."""
    global _trace_next
    with _trace_lock:
        lo = _trace_next
        _trace_next += max(1, n)
    return lo


def allocated_traces() -> int:
    """One past the highest trace ID ever issued (IDs start at 1) —
    the typed ``trace?id=`` error path (ISSUE 20) uses it to tell a
    ``never-admitted`` ID from one that was issued but has
    ``expired`` out of the bounded recorder ring."""
    with _trace_lock:
        return _trace_next


def configure_service(lane_depth: Optional[int] = None,
                      lane_bytes: Optional[int] = None,
                      max_batch: Optional[int] = None,
                      pipeline_depth: Optional[int] = None,
                      aging_every: Optional[int] = None) -> None:
    """Push service-policy knobs (Config / tests); None keeps the
    current value. Instances read these at construction — push before
    :func:`default_service` (the Application does)."""
    global LANE_DEPTH, LANE_BYTES, MAX_BATCH, PIPELINE_DEPTH, \
        AGING_EVERY
    with _defaults_lock:
        if lane_depth is not None:
            LANE_DEPTH = max(1, int(lane_depth))
        if lane_bytes is not None:
            LANE_BYTES = max(1, int(lane_bytes))
        if max_batch is not None:
            MAX_BATCH = max(1, int(max_batch))
        if pipeline_depth is not None:
            PIPELINE_DEPTH = max(1, int(pipeline_depth))
        if aging_every is not None:
            AGING_EVERY = max(0, int(aging_every))


class VerifyTicket:
    """Handle for one admitted submission: ``result(timeout)`` blocks
    for the per-item bool array (libsodium-identical decisions, same
    order as the submitted items). Raises
    :class:`Overloaded` with ``kind="shed"`` when the load-shed ladder
    dropped the submission, or the verifier's own exception if the
    batch failed — an admitted submission ALWAYS resolves to exactly
    one of verified / shed / failed, never silence."""

    __slots__ = ("lane", "tenant", "n_items", "trace_lo", "_items",
                 "_nbytes", "_digest", "_seq", "_t_enq", "_fut",
                 "_vstart", "_vfinish")

    def __init__(self, lane: str, items, nbytes: int, digest: bytes,
                 seq: int, t_enq: float, trace_lo: int = 0,
                 tenant: str = tenant_mod.DEFAULT_TENANT):
        from concurrent.futures import Future
        self.lane = lane
        self.tenant = tenant
        self.n_items = len(items)
        self.trace_lo = trace_lo
        self._items = items
        self._nbytes = nbytes
        self._digest = digest
        self._seq = seq
        self._t_enq = t_enq
        self._fut = Future()
        # stamped by the lane's weighted-fair queue at admission
        self._vstart = 0
        self._vfinish = 0

    @property
    def trace_ids(self) -> range:
        """This submission's per-item trace IDs (aligned with the
        submitted items) — the handle the ``trace`` admin route takes
        to reconstruct one item's end-to-end timeline."""
        return range(self.trace_lo, self.trace_lo + self.n_items)

    def done(self) -> bool:
        return self._fut.done()

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        return self._fut.result(timeout)


class VerifyService:
    """The resident stream processor (module docstring). One instance
    owns one dispatcher thread; production uses the process-wide
    :func:`default_service`. ``verifier`` may be any object with the
    ``submit(items) -> resolver`` contract of
    :class:`~stellar_tpu.crypto.batch_verifier.BatchVerifier`; None
    resolves to the default verifier at :meth:`start`."""

    def __init__(self, verifier=None,
                 lane_depth: Optional[int] = None,
                 lane_bytes: Optional[int] = None,
                 max_batch: Optional[int] = None,
                 pipeline_depth: Optional[int] = None,
                 aging_every: Optional[int] = None,
                 shed_highwater_frac: Optional[float] = None,
                 controller=None,
                 control_every: Optional[int] = None,
                 replica: Optional[int] = None):
        self._verifier = verifier
        # fleet replica identity (ISSUE 17): stamped into every
        # decision tuple and Overloaded refusal so fleet-level
        # evidence (divergence conviction, refusal attribution) names
        # the replica that produced it; None = single-service deploy
        self.replica = replica
        # ``lane_depth`` accepts a per-lane dict (ISSUE 17): a
        # replicated fleet concentrates each (lane, tenant) key on
        # ONE replica (rendezvous affinity), so a replica fronting
        # the whole scp key needs a deeper scp queue than its bulk
        # lanes — asymmetric depth is a fleet-sizing knob, not a
        # scheduling change (admission only; shed dynamics key off
        # the bulk depth as before)
        if lane_depth is None:
            self._lane_depth = LANE_DEPTH
        elif isinstance(lane_depth, dict):
            self._lane_depth = {
                ln: max(1, int(lane_depth.get(ln, LANE_DEPTH)))
                for ln in LANES}
        else:
            self._lane_depth = max(1, int(lane_depth))
        self._lane_bytes = LANE_BYTES if lane_bytes is None \
            else max(1, int(lane_bytes))
        self._max_batch = MAX_BATCH if max_batch is None \
            else max(1, int(max_batch))
        self._pipeline_depth = PIPELINE_DEPTH if pipeline_depth is None \
            else max(1, int(pipeline_depth))
        self._aging_every = AGING_EVERY if aging_every is None \
            else max(0, int(aging_every))
        # shed-ladder entry threshold, PER INSTANCE (ISSUE 15): the
        # closed-loop controller adapts it within clamped bounds
        self._shed_highwater_frac = SHED_HIGHWATER_FRAC \
            if shed_highwater_frac is None \
            else min(1.0, max(0.01, float(shed_highwater_frac)))
        # closed-loop controller (ISSUE 15): explicit instance wins;
        # None auto-attaches one iff VERIFY_CONTROL_ENABLED, seeded
        # with THIS instance's configured knobs as the relax baseline
        if controller is None and controller_mod.CONTROL_ENABLED:
            controller = controller_mod.VerifyController(
                self._max_batch, self._pipeline_depth,
                self._shed_highwater_frac)
        self._controller = controller
        self._control_every = max(1, controller_mod.CONTROL_EVERY
                                  if control_every is None
                                  else int(control_every))
        self._control_next = self._control_every
        self._cv = threading.Condition()
        self._queues: Dict[str, tenant_mod.TenantLaneQueue] = {
            ln: tenant_mod.TenantLaneQueue() for ln in LANES}
        self._queued_items = {ln: 0 for ln in LANES}
        self._queued_bytes = {ln: 0 for ln in LANES}
        self._inflight_bytes = {ln: 0 for ln in LANES}
        # per-(lane, tenant) in-flight bytes: the tenant byte quota
        # nests inside the lane's queued+in-flight budget, so it must
        # charge the same window — queued alone would let a tenant
        # hold (pipeline_depth+1)x its quota of lane capacity
        self._tenant_inflight = {ln: {} for ln in LANES}
        self._inflight_items = 0
        self._counts = {ln: {"submitted": 0, "verified": 0,
                             "rejected": 0, "shed": 0, "failed": 0,
                             "handoff": 0}
                        for ln in LANES}
        # per-tenant conservation counters (ISSUE 14): submitted ==
        # verified + rejected + shed + failed + pending PER TENANT;
        # bounded by the tenant tracking cap (overflow folds into the
        # reserved OTHER_TENANT rollup, counted — never silent)
        self._tenant_counts: Dict[str, dict] = {}
        # bounded in-order scheduling/shed decision log (ISSUE 14)
        self._decisions: deque = deque(maxlen=max(16, DECISION_LOG))
        # unified system journal feed (ISSUE 20): one bounded,
        # in-order admission/terminal event log keyed by a monotone
        # per-component seq — ``stellar_tpu/utils/journal.py`` merges
        # these feeds across replicas into the fleet-wide journal.
        # The aggregate totals are plain integers that never evict,
        # so the journal completeness law stays checkable even after
        # the bounded row log wraps.
        self._journal: deque = deque(maxlen=max(16, DECISION_LOG))
        self._jseq = 0
        self._journal_totals = {"submitted": 0, "verified": 0,
                                "failed": 0, "rejected": 0,
                                "shed": 0, "handoff": 0}
        self._seq = 0
        self._batches = 0
        self._pressure = 0
        self._shed_seen = False
        self._running = False
        self._stop = False
        self._drain = True
        self._traceful = False
        self._thread: Optional[threading.Thread] = None

    # ---------------- public API ----------------

    def start(self) -> "VerifyService":
        """Spawn the dispatcher thread (idempotent) and register the
        service's health snapshot with ``dispatch_health()``."""
        with self._cv:
            if self._running:
                return self
            if self._verifier is None:
                self._verifier = batch_verifier.default_verifier()
            # trace-ID propagation (ISSUE 8) rides verifiers whose
            # submit accepts trace_ids (the real engine); duck-typed
            # stand-ins keep working without them
            try:
                self._traceful = "trace_ids" in inspect.signature(
                    self._verifier.submit).parameters
            except (TypeError, ValueError):
                self._traceful = False
            self._running = True
            self._stop = False
            self._drain = True
            # a fleet replica's dispatcher carries its identity in
            # the thread name (ISSUE 20): flight-recorder records tag
            # the emitting thread, so the stitched timeline and the
            # per-replica Chrome tracks can tell replicas apart even
            # though they share one process-wide recorder
            tname = ("verify-service" if self.replica is None
                     else f"verify-service/{self.replica}")
            self._thread = threading.Thread(
                target=self._run, daemon=True, name=tname)
        self._thread.start()
        batch_verifier.register_service_health(self.snapshot)
        global _tenant_provider, _control_provider
        with _service_lock:
            # the tenant/control routes serve the last-started
            # instance (same policy as register_service_health: an
            # embedded service still gets an admin surface)
            _tenant_provider = self.tenant_snapshot
            _control_provider = self.control_snapshot
        return self

    def submit(self, items: Sequence[tuple], lane: str = "bulk",
               tenant: Optional[str] = None,
               trace_lo: Optional[int] = None) -> VerifyTicket:
        """Admit one submission of (pk, msg, sig) triples into
        ``lane`` on behalf of ``tenant`` (None = the quota-exempt
        default tenant). Raises :class:`Overloaded`
        (``kind="rejected"``) at ingress when the lane's queue-depth
        or byte budget is exhausted, the tenant's own depth/byte
        quota inside the lane is exhausted (``reason="tenant-depth"``
        / ``"tenant-bytes"``, ``tenant`` set on the exception), or
        the service is stopping — rejected work never enters a queue,
        so memory stays bounded no matter the offered load.

        ``trace_lo`` (ISSUE 17) lets the fleet router re-submit
        drained work under its ORIGINAL trace block — a handoff keeps
        the items' trace IDs intact; leave None for fresh work."""
        if lane not in LANES:
            raise ValueError(f"unknown lane {lane!r} (one of {LANES})")
        tenant = tenant_mod.validate_tenant(tenant)
        weight, t_depth, t_bytes = tenant_mod.tenant_policy(tenant)
        items = list(items)
        n = len(items)
        nbytes = 0
        h = hashlib.sha256()   # shed digest: incremental, zero copies
        for pk, msg, sig in items:
            nbytes += len(pk) + len(msg) + len(sig)
            h.update(pk)
            h.update(msg)
            h.update(sig)
        digest = h.digest()
        # per-item trace IDs (one contiguous block per submission):
        # assigned BEFORE admission so a rejected submission's trace
        # still exists — tagged in the Overloaded ticket and the
        # recorder's service.reject event. A fleet handoff passes the
        # original block in, so a re-submitted item's trace survives
        # its first replica's death.
        if trace_lo is None:
            trace_lo = _alloc_trace_block(n)
        trange = [[trace_lo, trace_lo + n]] if n else []
        # clock read: latency stamp only — feeds the lane wait-time
        # histogram, never a verify/shed decision (nondet allowlist)
        t_enq = time.monotonic()
        registry.meter("crypto.verify.service.submitted").mark(n)
        registry.meter(
            f"crypto.verify.service.lane.{lane}.submitted").mark(n)
        with self._cv:
            self._counts[lane]["submitted"] += n
            tc = self._tenant_counts_locked(tenant)
            tc["submitted"] += n
            reason = None
            if self._stop or not self._running:
                reason = "stopped"
            elif len(self._queues[lane]) >= self._depth_of(lane):
                reason = "queue-depth"
            elif (self._queued_bytes[lane] + self._inflight_bytes[lane]
                  + nbytes) > self._lane_bytes:
                reason = "bytes"
            # per-tenant quotas NEST inside the lane budgets (ISSUE
            # 14): one tenant exhausts its own slice of the lane and
            # gets a typed, tenant-attributed refusal while in-quota
            # tenants keep submitting
            elif t_depth and \
                    self._queues[lane].depth(tenant) >= t_depth:
                reason = "tenant-depth"
            elif t_bytes and (self._queues[lane].queued_bytes(tenant)
                              + self._tenant_inflight[lane].get(
                                  tenant, 0)
                              + nbytes) > t_bytes:
                reason = "tenant-bytes"
            if reason is not None:
                self._counts[lane]["rejected"] += n
                tc["rejected"] += n
                registry.meter(
                    "crypto.verify.service.rejected").mark(n)
                registry.meter(
                    f"crypto.verify.service.lane.{lane}.rejected"
                ).mark(n)
                if reason.startswith("tenant-"):
                    tc["quota_rejected"] += n
                    registry.meter(
                        "crypto.verify.service.tenant.quota_rejected"
                    ).mark(n)
                # a rejected item is a completion-SLO miss: it
                # consumed the lane's shed/reject budget (ISSUE 10)
                # and the tenant's own budget (ISSUE 14)
                slo_monitor.note_completion(lane, ok=False, n=n)
                tenant_mod.tenant_slo.note_completion(tenant, ok=False,
                                                      n=n)
                batch_verifier.note_trace_event(
                    "service.reject", lane=lane, reason=reason,
                    tenant=tenant, traces=trange, items=n)
                self._journal_note_locked(
                    "rejected", lane, tenant, self._seq, trace_lo, n,
                    reason=reason)
                raise Overloaded(
                    f"verify service {lane} lane over budget "
                    f"({reason})", kind="rejected", lane=lane,
                    reason=reason, tenant=tenant,
                    trace_ids=range(trace_lo, trace_lo + n),
                    replica=self.replica)
            tkt = VerifyTicket(lane, items, nbytes, digest,
                               self._seq, t_enq, trace_lo=trace_lo,
                               tenant=tenant)
            self._seq += 1
            if n == 0:
                tkt._fut.set_result(np.zeros(0, dtype=bool))
                return tkt
            self._queues[lane].push(tkt, weight)
            tc["pending"] += n
            self._queued_items[lane] += n
            self._queued_bytes[lane] += nbytes
            self._publish_lane_gauges_locked(lane)
            # trace milestone: admitted into the lane queue (recorder
            # write routed through the engine — the tracing fence
            # keeps this module duration-blind). Emitted BEFORE the
            # notify, like service.reject above: once the dispatcher
            # wakes it may coalesce and record service.coalesce /
            # service.verdict for these traces, and the reconstructed
            # timeline (trace_timeline) must never see a verdict
            # before its enqueue.
            batch_verifier.note_trace_event(
                "service.enqueue", lane=lane, tenant=tenant,
                traces=trange, seq=tkt._seq, items=n)
            self._journal_note_locked(
                "enqueue", lane, tenant, tkt._seq, trace_lo, n)
            self._cv.notify_all()
        return tkt

    def verify(self, items: Sequence[tuple], lane: str = "bulk",
               timeout: Optional[float] = None,
               tenant: Optional[str] = None) -> np.ndarray:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(items, lane=lane,
                           tenant=tenant).result(timeout)

    def stop(self, drain: bool = True,
             timeout: Optional[float] = None) -> None:
        """Stop the dispatcher. ``drain=True`` (default) keeps
        dispatching until the queues are empty — but the shed ladder
        still applies while draining: a shutdown under persistent
        overload pressure (breaker open / host-only / backlog) must
        bound its own duration, so low-priority backlog may still
        shed (counted + ticketed, like any shed) rather than hold the
        node open. ``drain=False`` sheds the whole queued backlog
        (reason ``"stopped"``) and only finishes work already in
        flight. New submissions are rejected (``"stopped"``) from the
        moment stop is called.

        Terminal guarantee (ISSUE 19): every client-visible ticket
        held across a stop resolves — verified, failed, or a typed
        ``Overloaded`` — even when the dispatcher thread itself died
        (the ``_run`` finally sheds any stranded backlog with reason
        ``"stopped"``), so a wire-ingress responder or a fleet
        ``kill_replica`` composed with a connection close never
        leaves a pending item without a documented terminal."""
        with self._cv:
            if not self._running:
                return
            self._stop = True
            self._drain = drain
            self._cv.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
        with self._cv:
            self._running = False

    def drain_handoff(self) -> list:
        """Fleet drain protocol (ISSUE 17): atomically extract every
        QUEUED submission so the router can re-submit each one to a
        surviving replica with its trace IDs intact. Extracted items
        move to the ``handoff`` terminal of this replica's
        conservation law (they are no longer this replica's to finish
        — they will be counted exactly once more, at the survivor
        that admits them), so both the per-replica and the fleet
        residuals stay exactly 0 through a kill. In-flight work is
        NOT touched: the dispatcher finishes it during the drain stop
        that follows. Returns the extracted tickets with their
        futures still pending — the router chains each future to its
        re-submission, so callers never observe the handoff."""
        out = []
        with self._cv:
            for ln in LANES:
                for tkt in self._queues[ln].drain_if(None):
                    self._queued_items[ln] -= tkt.n_items
                    self._queued_bytes[ln] -= tkt._nbytes
                    self._counts[ln]["handoff"] += tkt.n_items
                    tc = self._tenant_counts_locked(tkt.tenant)
                    tc["handoff"] += tkt.n_items
                    tc["pending"] -= tkt.n_items
                    registry.meter(
                        "crypto.verify.service.handoff"
                    ).mark(tkt.n_items)
                    batch_verifier.note_trace_event(
                        "service.handoff", lane=ln, tenant=tkt.tenant,
                        replica=self.replica,
                        traces=[[tkt.trace_lo,
                                 tkt.trace_lo + tkt.n_items]])
                    self._journal_note_locked(
                        "handoff", ln, tkt.tenant, tkt._seq,
                        tkt.trace_lo, tkt.n_items)
                    out.append(tkt)
                self._publish_lane_gauges_locked(ln)
        return out

    def snapshot(self) -> dict:
        """Health surface (``dispatch_health()["service"]`` / the
        ``service`` admin route): per-lane depths, budgets, the
        conservation-law counters, wait-time percentiles, pressure
        level. ``conservation_gap`` is the law's residual and must
        always read 0."""
        with self._cv:
            lanes = {}
            totals = {"submitted": 0, "verified": 0, "rejected": 0,
                      "shed": 0, "failed": 0, "handoff": 0}
            for ln in LANES:
                c = dict(self._counts[ln])
                for k in totals:
                    totals[k] += c[k]
                t = registry.timer(
                    f"crypto.verify.service.lane.{ln}.wait_ms")
                p50, p99 = t.percentiles_ms((50, 99))
                lanes[ln] = {
                    "queued_submissions": len(self._queues[ln]),
                    "queued_items": self._queued_items[ln],
                    "queued_bytes": self._queued_bytes[ln],
                    "inflight_bytes": self._inflight_bytes[ln],
                    "wait_ms": {"count": t.count,
                                "p50": round(p50, 3),
                                "p99": round(p99, 3)},
                    **c,
                }
            pending = (sum(self._queued_items[ln] for ln in LANES)
                       + self._inflight_items)
            return {
                "running": self._running and not self._stop,
                "pressure": self._pressure,
                "shed_onset_seen": self._shed_seen,
                "batches": self._batches,
                "pending_items": pending,
                "lanes": lanes,
                "totals": totals,
                "conservation_gap": (
                    totals["submitted"] - totals["verified"]
                    - totals["rejected"] - totals["shed"]
                    - totals["failed"] - totals["handoff"] - pending),
                "knobs": {"lane_depth": self._lane_depth,
                          "lane_bytes": self._lane_bytes,
                          "max_batch": self._max_batch,
                          "pipeline_depth": self._pipeline_depth,
                          "aging_every": self._aging_every},
                "control": {
                    "enabled": self._controller is not None,
                    "shed_highwater_frac": self._shed_highwater_frac,
                },
            }

    def tenant_snapshot(self) -> dict:
        """Per-tenant accounting surface (the ``tenant`` admin route,
        ISSUE 14): the conservation counters per tracked tenant, each
        tenant's residual (must read 0 — the per-tenant work
        conservation law), and decision-log accounting. Tenants past
        the tracking cap fold into the reserved ``~other`` rollup
        (counted, never silent)."""
        with self._cv:
            tenants = {t: dict(c)
                       for t, c in self._tenant_counts.items()}
            n_decisions = len(self._decisions)
        gaps = {}
        for t, c in tenants.items():
            c["conservation_gap"] = (
                c["submitted"] - c["verified"] - c["rejected"]
                - c["shed"] - c["failed"] - c.get("handoff", 0)
                - c["pending"])
            if c["conservation_gap"] != 0:
                gaps[t] = c["conservation_gap"]
        return {"tenants": tenants,
                "tracked": len(tenants),
                "track_cap": tenant_mod.TENANT_TRACK_CAP,
                "conservation_violations": gaps,
                "decision_log_len": n_decisions}

    def decision_log(self, limit: int = 0) -> list:
        """The bounded in-order scheduling/shed decision log:
        ``("dispatch", lane, tenant, seq, vfinish, replica)`` per
        weighted-fair pop and ``("shed", lane, tenant, seq, level,
        replica)`` per shed row (``replica`` is this service's fleet
        identity, ISSUE 17 — None outside a fleet). Two replicas fed
        identical arrival order produce identical logs — the
        bit-identical surface ``tools/tenant_selfcheck.py`` gates on,
        and the evidence the fleet divergence detector convicts from.
        ``limit`` bounds the tail returned (0 = all retained)."""
        with self._cv:
            log = list(self._decisions)
        return log[-limit:] if limit else log

    def journal_log(self, limit: int = 0) -> list:
        """The bounded journal feed (ISSUE 20): one dict row per
        admission (``enqueue``) and per terminal (``verified`` /
        ``failed`` / ``rejected`` / ``shed`` / ``handoff``), each
        carrying a monotone per-component ``seq``, the ticket seq,
        the trace block ``(trace_lo, n)`` and — for refusals/sheds —
        the typed reason. Pure content, no clock reads: two replicas
        fed identical arrival order produce identical feeds (the
        bit-identity surface ``stellar_tpu/utils/journal.py`` merges
        and ``tools/journal_selfcheck.py`` gates on). ``limit``
        bounds the tail returned (0 = all retained)."""
        with self._cv:
            log = [dict(r) for r in self._journal]
        return log[-limit:] if limit else log

    def journal_totals(self) -> dict:
        """Never-evicting aggregate counts behind the journal feed:
        items enqueued plus each terminal kind. These reconcile
        EXACTLY with the per-lane conservation counters (``submitted
        == journal.submitted + journal.rejected``; every terminal
        matches), which is half of the journal completeness law —
        :func:`stellar_tpu.utils.journal.completeness` checks it."""
        with self._cv:
            return dict(self._journal_totals)

    def control_log(self, limit: int = 0) -> list:
        """The attached controller's bounded knob-trajectory log
        (ISSUE 15); empty when no controller is attached."""
        ctl = self._controller
        return ctl.control_log(limit) if ctl is not None else []

    def control_snapshot(self) -> dict:
        """The ``control`` admin-route payload: the controller's
        knob/clamp/hysteresis state plus the tail of its trajectory
        log, and the LIVE values the service is currently applying."""
        ctl = self._controller
        with self._cv:
            live = {"max_batch": self._max_batch,
                    "pipeline_depth": self._pipeline_depth,
                    "shed_highwater_frac": self._shed_highwater_frac,
                    "control_every": self._control_every}
        if ctl is None:
            return {"enabled": False, "live": live}
        return {"enabled": True, "live": live,
                "controller": ctl.snapshot(),
                "log_tail": ctl.control_log(limit=32)}

    # ---------------- dispatcher internals ----------------
    # _locked helpers are called with self._cv held (the repo-wide
    # naming contract the lock lint encodes).

    def _journal_note_locked(self, kind: str, lane: str, tenant,
                             seq: int, trace_lo, n: int,
                             **extra) -> None:
        """Append one row to this replica's journal feed (called with
        the cv held). Rows are pure functions of admission content and
        queue state — no clock reads, no RNG — so the feed is
        bit-identical across replicas under identical arrival order.
        The aggregate totals update on the same append path, so the
        bounded row log and the totals can never disagree."""
        row = {"seq": self._jseq, "kind": kind, "lane": lane,
               "tenant": tenant, "ticket": seq,
               "trace_lo": trace_lo, "n": n}
        if extra:
            row.update(extra)
        self._jseq += 1
        self._journal.append(row)
        tot = self._journal_totals
        if kind == "enqueue":
            tot["submitted"] += n
        elif kind in tot:
            tot[kind] += n

    def _tenant_counts_locked(self, tenant: str) -> dict:
        """Get-or-create one tenant's conservation counters, folding
        into the reserved OTHER_TENANT rollup once the tracking cap is
        reached — a tenant folded at submit keeps folding at every
        later transition (entries are never removed), so the rollup's
        own conservation stays exact."""
        tc = self._tenant_counts.get(tenant)
        if tc is None:
            if len(self._tenant_counts) >= \
                    tenant_mod.TENANT_TRACK_CAP and \
                    tenant != tenant_mod.OTHER_TENANT:
                return self._tenant_counts_locked(
                    tenant_mod.OTHER_TENANT)
            tc = self._tenant_counts[tenant] = {
                "submitted": 0, "verified": 0, "rejected": 0,
                "quota_rejected": 0, "shed": 0, "failed": 0,
                "handoff": 0, "pending": 0}
        return tc

    def _publish_lane_gauges_locked(self, ln: str) -> None:
        """Live backlog gauges (ISSUE 10 satellite): queue depth and
        queued+in-flight bytes per lane ride the Prometheus
        exposition, so an operator sees backlog BUILDING before the
        shed ladder fires — the wait histograms only show it after
        the fact."""
        registry.gauge(
            f"crypto.verify.service.lane.{ln}.depth").set(
            len(self._queues[ln]))
        registry.gauge(
            f"crypto.verify.service.lane.{ln}.bytes").set(
            self._queued_bytes[ln] + self._inflight_bytes[ln])

    def _depth_of(self, lane: str) -> int:
        """Admission depth for ``lane`` — scalar or per-lane dict."""
        d = self._lane_depth
        return d[lane] if isinstance(d, dict) else d

    def _pressure_locked(self) -> tuple:
        """(level, why): 2 = dispatch degraded (global breaker open /
        host-only — capacity collapsed to the host oracle), 1 = bulk
        backlog over high-water, 0 = healthy."""
        if batch_verifier.dispatch_degraded():
            return 2, "dispatch-degraded"
        hw = max(1, int(self._depth_of("bulk")
                        * self._shed_highwater_frac))
        if len(self._queues["bulk"]) >= hw:
            return 1, "backlog"
        return 0, ""

    def _shed_pass_locked(self) -> Optional[str]:
        """Apply the shed ladder to the queues at the current pressure
        level. Row selection is the content-seeded rule
        (:func:`stellar_tpu.crypto.audit.keep_under_shed`) with the
        TENANT key mixed in (ISSUE 14), and each tenant's effective
        keep fraction is the ladder fraction scaled down by how far
        that tenant sits over its own quota high-water
        (:func:`stellar_tpu.crypto.tenant.shed_keep_fraction`) — a
        flooding tenant's rows shed first, in-quota tenants keep the
        lane fraction, and replicas under identical arrival order
        still shed identical rows (all inputs are queue state +
        content, no clocks). Every shed is counted, ticketed, logged
        in the decision log. Returns the pressure reason when THIS
        pass was the first-ever shed (the caller fires the
        flight-recorder dump outside the lock), else None."""
        level, why = self._pressure_locked()
        self._pressure = level
        registry.gauge("crypto.verify.service.pressure").set(level)
        ladder = SHED_LADDER.get(level)
        if not ladder:
            return None
        onset = None
        for ln, keep in ladder.items():
            q = self._queues[ln]
            if not q:
                continue
            # per-tenant effective keep fractions, computed ONCE per
            # pass from the queue state this pass sees
            eff = {}
            for t, subs in q.tenant_depths().items():
                _w, t_depth, _b = tenant_mod.tenant_policy(t)
                eff[t] = tenant_mod.shed_keep_fraction(
                    keep, subs, t_depth, level=level)

            def _keep(tkt):
                return audit_mod.keep_under_shed(
                    tkt._digest, eff[tkt.tenant],
                    tenant=tenant_mod.shed_key(tkt.tenant))

            for tkt in q.drain_if(_keep):
                self._queued_items[ln] -= tkt.n_items
                self._queued_bytes[ln] -= tkt._nbytes
                self._counts[ln]["shed"] += tkt.n_items
                tc = self._tenant_counts_locked(tkt.tenant)
                tc["shed"] += tkt.n_items
                tc["pending"] -= tkt.n_items
                self._decisions.append(
                    ("shed", ln, tkt.tenant, tkt._seq, level,
                     self.replica))
                registry.meter(
                    "crypto.verify.service.shed").mark(tkt.n_items)
                registry.meter(
                    f"crypto.verify.service.lane.{ln}.shed"
                ).mark(tkt.n_items)
                slo_monitor.note_completion(ln, ok=False,
                                            n=tkt.n_items)
                tenant_mod.tenant_slo.note_completion(
                    tkt.tenant, ok=False, n=tkt.n_items)
                if not self._shed_seen:
                    self._shed_seen = True
                    onset = why
                self._journal_note_locked(
                    "shed", ln, tkt.tenant, tkt._seq, tkt.trace_lo,
                    tkt.n_items, reason=why, level=level)
                batch_verifier.note_trace_event(
                    "service.shed", lane=ln, reason=why, level=level,
                    tenant=tkt.tenant,
                    keep_fraction=round(eff[tkt.tenant], 6),
                    traces=[[tkt.trace_lo,
                             tkt.trace_lo + tkt.n_items]])
                tkt._fut.set_exception(Overloaded(
                    f"shed under overload (level {level}: {why})",
                    kind="shed", lane=ln, reason=why,
                    tenant=tkt.tenant, trace_ids=tkt.trace_ids,
                    replica=self.replica))
            self._publish_lane_gauges_locked(ln)
        return onset

    def _abort_queues_locked(self) -> None:
        """Non-drain stop: shed every queued submission (counted,
        ticketed — reason ``"stopped"``, never silent)."""
        for ln in LANES:
            for tkt in self._queues[ln].drain_if(None):
                self._queued_items[ln] -= tkt.n_items
                self._queued_bytes[ln] -= tkt._nbytes
                self._counts[ln]["shed"] += tkt.n_items
                tc = self._tenant_counts_locked(tkt.tenant)
                tc["shed"] += tkt.n_items
                tc["pending"] -= tkt.n_items
                registry.meter(
                    "crypto.verify.service.shed").mark(tkt.n_items)
                registry.meter(
                    f"crypto.verify.service.lane.{ln}.shed"
                ).mark(tkt.n_items)
                slo_monitor.note_completion(ln, ok=False,
                                            n=tkt.n_items)
                tenant_mod.tenant_slo.note_completion(
                    tkt.tenant, ok=False, n=tkt.n_items)
                batch_verifier.note_trace_event(
                    "service.shed", lane=ln, reason="stopped",
                    tenant=tkt.tenant,
                    traces=[[tkt.trace_lo,
                             tkt.trace_lo + tkt.n_items]])
                self._journal_note_locked(
                    "shed", ln, tkt.tenant, tkt._seq, tkt.trace_lo,
                    tkt.n_items, reason="stopped")
                tkt._fut.set_exception(Overloaded(
                    "service stopped without drain", kind="shed",
                    lane=ln, reason="stopped", tenant=tkt.tenant,
                    trace_ids=tkt.trace_ids, replica=self.replica))
            self._publish_lane_gauges_locked(ln)

    def _pick_lane_locked(self) -> Optional[str]:
        """Priority order, with sequence-based aging: every
        ``aging_every``-th batch serves the lane whose head submission
        is globally oldest, so the bulk lane cannot starve behind a
        sustained priority stream. Clock-free and deterministic in
        arrival order."""
        nonempty = [ln for ln in LANES if self._queues[ln]]
        if not nonempty:
            return None
        if len(nonempty) > 1 and self._aging_every > 0 and \
                self._batches % self._aging_every == \
                self._aging_every - 1:
            return min(nonempty,
                       key=lambda ln: self._queues[ln].oldest_seq())
        return nonempty[0]

    def _collect_locked(self):
        """Coalesce queued submissions of ONE lane into a batch of up
        to ``max_batch`` items (continuous batching into the
        verifier's jit buckets), serving tenants in deterministic
        weighted-fair order within the lane (ISSUE 14). An oversize
        single submission rides alone — the verifier chunks it.
        Returns (lane, items, parts, tids, decisions) or None; parts
        are (ticket, item_offset) pairs, decisions the weighted-fair
        pop records (the caller emits them as ``service.schedule``
        flight-recorder events outside this lock)."""
        ln = self._pick_lane_locked()
        if ln is None:
            return None
        q = self._queues[ln]
        items: list = []
        parts = []
        tids: list = []
        decisions: list = []
        while q:
            head = q.peek()
            if items and len(items) + head.n_items > self._max_batch:
                break
            tkt, dec = q.pop(head)
            dec["traces"] = [[tkt.trace_lo,
                              tkt.trace_lo + tkt.n_items]]
            decisions.append(dec)
            self._decisions.append(
                ("dispatch", ln, tkt.tenant, tkt._seq, tkt._vfinish,
                 self.replica))
            parts.append((tkt, len(items)))
            items.extend(tkt._items)
            tids.extend(tkt.trace_ids)
            self._queued_items[ln] -= tkt.n_items
            self._queued_bytes[ln] -= tkt._nbytes
            self._inflight_bytes[ln] += tkt._nbytes
            ti = self._tenant_inflight[ln]
            ti[tkt.tenant] = ti.get(tkt.tenant, 0) + tkt._nbytes
        self._inflight_items += len(items)
        self._batches += 1
        # (the pre-ISSUE-10 `crypto.verify.service.depth.<lane>`
        # gauge is superseded by `lane.<lane>.depth`, published at
        # every queue transition instead of only at batch pick)
        self._publish_lane_gauges_locked(ln)
        return (ln, items, parts, tids, decisions)

    def _resolve_one(self, ln: str, parts, resolver,
                     traces=None) -> None:
        """Block on one in-flight dispatch and complete its tickets.
        Counters update BEFORE futures complete, so a caller that
        wakes on its ticket already sees consistent accounting."""
        out = None
        err: Optional[BaseException] = None
        rs_attrs = {"lane": ln}
        if traces:
            rs_attrs["traces"] = traces
        with span("service.resolve", **rs_attrs):
            try:
                out = np.asarray(resolver())
            except BaseException as e:  # ticketed, never silent
                err = e
        n = sum(t.n_items for t, _ in parts)
        nbytes = sum(t._nbytes for t, _ in parts)
        tenants = _part_tenants(parts)
        if err is not None:
            with self._cv:
                self._inflight_items -= n
                self._inflight_bytes[ln] -= nbytes
                self._counts[ln]["failed"] += n
                self._tenant_terminal_locked(ln, parts, "failed")
                self._publish_lane_gauges_locked(ln)
            registry.meter("crypto.verify.service.failed").mark(n)
            registry.meter(
                f"crypto.verify.service.lane.{ln}.failed").mark(n)
            slo_monitor.note_completion(ln, ok=False, n=n)
            for tkt, _off in parts:
                tenant_mod.tenant_slo.note_completion(
                    tkt.tenant, ok=False, n=tkt.n_items)
            batch_verifier.note_trace_event(
                "service.verdict", lane=ln, failed=True,
                tenants=tenants, traces=traces or [], items=n)
            for tkt, _off in parts:
                tkt._fut.set_exception(err)
            return
        with self._cv:
            self._inflight_items -= n
            self._inflight_bytes[ln] -= nbytes
            self._counts[ln]["verified"] += n
            self._tenant_terminal_locked(ln, parts, "verified")
            self._publish_lane_gauges_locked(ln)
        registry.meter("crypto.verify.service.verified").mark(n)
        registry.meter(
            f"crypto.verify.service.lane.{ln}.verified").mark(n)
        slo_monitor.note_completion(ln, ok=True, n=n)
        # trace milestone: each verdict carries its trace — the END of
        # the trace route's reconstructed timeline
        batch_verifier.note_trace_event(
            "service.verdict", lane=ln, tenants=tenants,
            traces=traces or [], items=n)
        # clock read: wait-time histogram stamp only (nondet allowlist)
        now = time.monotonic()
        timer = registry.timer(
            f"crypto.verify.service.lane.{ln}.wait_ms")
        for tkt, off in parts:
            wait_ms = (now - tkt._t_enq) * 1000.0
            timer.update_ms(wait_ms)
            # SLO accounting (ISSUE 10/14): the lane AND tenant
            # latency objectives read the SAME allowlisted stamp the
            # histogram does; the verdict below never depends on it
            slo_monitor.note_latency(ln, wait_ms, n=tkt.n_items)
            tenant_mod.tenant_slo.note_latency(
                tkt.tenant, wait_ms, n=tkt.n_items)
            tenant_mod.tenant_slo.note_completion(
                tkt.tenant, ok=True, n=tkt.n_items)
            tkt._fut.set_result(
                np.array(out[off:off + tkt.n_items], dtype=bool))

    def _tenant_terminal_locked(self, ln: str, parts,
                                outcome: str) -> None:
        """Move every part's items from pending to a terminal
        per-tenant counter and release the tenant's in-flight bytes
        (called with the cv held)."""
        ti = self._tenant_inflight[ln]
        for tkt, _off in parts:
            tc = self._tenant_counts_locked(tkt.tenant)
            tc[outcome] += tkt.n_items
            tc["pending"] -= tkt.n_items
            self._journal_note_locked(
                outcome, ln, tkt.tenant, tkt._seq, tkt.trace_lo,
                tkt.n_items)
            left = ti.get(tkt.tenant, 0) - tkt._nbytes
            if left > 0:
                ti[tkt.tenant] = left
            else:
                ti.pop(tkt.tenant, None)

    # ---------------- closed-loop control (ISSUE 15) ----------------

    def _control_window_locked(self) -> dict:
        """The deterministic half of one telemetry window (called with
        the cv held): batch/pressure counters, per-lane backlog, and
        the scp head-of-line SEQUENCE age — the clock-free latency
        proxy (how many submissions were admitted after the oldest
        queued scp submission)."""
        scp_head = self._queues["scp"].oldest_seq()
        lanes = {ln: {
            "queued_submissions": len(self._queues[ln]),
            "queued_items": self._queued_items[ln],
        } for ln in LANES}
        return {
            "batches": self._batches,
            "pressure": self._pressure,
            # the controller reasons about the BULK admission depth
            # (its highwater knob keys off it); per-lane dicts stay
            # a service-local sizing detail
            "lane_depth": self._depth_of("bulk"),
            "scp_hol_age": (self._seq - scp_head)
            if scp_head is not None else 0,
            "lanes": lanes,
            "knobs": {"max_batch": self._max_batch,
                      "pipeline_depth": self._pipeline_depth,
                      "shed_highwater_frac":
                          self._shed_highwater_frac},
        }

    def _apply_control_locked(self, knobs: dict) -> None:
        """THE knob application point (called with the cv held): the
        controller's clamped values become the scheduling knobs the
        next collect/pressure pass reads — one consistent set, never
        a half-applied mix."""
        self._max_batch = max(1, int(knobs["max_batch"]))
        self._pipeline_depth = max(1, int(knobs["pipeline_depth"]))
        self._shed_highwater_frac = min(1.0, max(
            0.01, float(knobs["shed_highwater_frac"])))

    def _maybe_control(self) -> None:
        """One controller step when the batch cadence is due: assemble
        the window (deterministic half under the cv, advisory burn/
        bubble half outside it), step the controller, apply any moved
        knobs under the cv, and emit each move as a ``service.control``
        flight-recorder event carrying the full window."""
        ctl = self._controller
        if ctl is None:
            return
        with self._cv:
            if self._batches < self._control_next:
                return
            self._control_next = self._batches + self._control_every
            window = self._control_window_locked()
        _control_advisories(window)
        decisions = ctl.step(window)
        knobs = ctl.knobs()
        with self._cv:
            self._apply_control_locked(knobs)
        if decisions:
            registry.meter("crypto.verify.control.decisions").mark(
                len(decisions))
            batch_verifier.note_trace_event(
                "service.control", window=window,
                decisions=decisions)
        registry.gauge("crypto.verify.control.max_batch").set(
            knobs["max_batch"])
        registry.gauge("crypto.verify.control.pipeline_depth").set(
            knobs["pipeline_depth"])
        registry.gauge(
            "crypto.verify.control.shed_highwater_frac").set(
            knobs["shed_highwater_frac"])
        registry.gauge("crypto.verify.control.moves").set(ctl.moves)

    def _run(self) -> None:
        """Dispatcher entry: the loop body, wrapped so that EVERY
        client-visible ticket reaches a documented terminal even if
        the loop dies on an unexpected exception (ISSUE 19 drain-gap
        fix). On any exit — clean stop or crash — the finally block
        re-flags stop (so new submissions are rejected ``"stopped"``
        instead of queueing behind a dead dispatcher) and sheds the
        queued backlog (reason ``"stopped"``, counted + ticketed); a
        crash additionally fails every still-in-flight part's future
        with the error through the ordinary ``failed`` terminal. A
        clean drain makes both a no-op (queues and inflight are
        already empty), so the conservation law holds either way."""
        inflight: deque = deque()
        try:
            self._run_loop(inflight)
        except BaseException as err:
            while inflight:
                ln, parts, _resolver, tr = inflight.popleft()
                self._resolve_failed(ln, parts, err, traces=tr)
            raise
        finally:
            with self._cv:
                self._stop = True
                self._abort_queues_locked()

    def _run_loop(self, inflight: deque) -> None:
        # in-flight dispatches are LOCAL to the dispatcher thread (the
        # only thread that touches them); shared state stays under cv
        while True:
            onset = None
            batch = None
            stopping = False
            with self._cv:
                while True:
                    if self._stop and not self._drain:
                        self._abort_queues_locked()
                    o = self._shed_pass_locked()
                    onset = onset or o
                    batch = self._collect_locked()
                    stopping = self._stop
                    if batch is not None or inflight or stopping:
                        break
                    self._cv.wait(0.05)
            if onset:
                batch_verifier.note_shed_onset(onset)
            if batch is not None:
                ln, items, parts, tids, decisions = batch
                tenants = _part_tenants(parts)
                # every weighted-fair pop is a flight-recorder event
                # with its input window (ISSUE 14): tenant, virtual
                # times, lane vtime, candidate count, trace range —
                # the replay-testable record of the decision
                for dec in decisions:
                    batch_verifier.note_trace_event(
                        "service.schedule", lane=ln, **dec)
                tr = batch_verifier.trace_ranges(tids)
                batch_verifier.note_trace_event(
                    "service.coalesce", lane=ln, tenants=tenants,
                    traces=tr, items=len(items), tickets=len(parts))
                resolver = None
                err: Optional[BaseException] = None
                # the batch's trace-ID list rides the dispatch span as
                # exemplar ranges (compressed, exact — never truncated)
                with span("service.dispatch", lane=ln,
                          tenants=tenants, items=len(items),
                          traces=tr):
                    try:
                        if self._traceful:
                            resolver = self._verifier.submit(
                                items, trace_ids=tids)
                        else:
                            resolver = self._verifier.submit(items)
                    except BaseException as e:
                        err = e
                if err is not None:
                    self._resolve_failed(ln, parts, err, traces=tr)
                else:
                    inflight.append((ln, parts, resolver, tr))
                # closed-loop control rides the batch cadence
                # (event-count, never a timer) — evaluated after the
                # dispatch so the window sees this batch's backlog
                # drain (ISSUE 15)
                self._maybe_control()
            if inflight and (batch is None or
                             len(inflight) >= self._pipeline_depth):
                self._resolve_one(*inflight.popleft())
            if stopping and batch is None and not inflight:
                break

    def _resolve_failed(self, ln: str, parts, err: BaseException,
                        traces=None) -> None:
        """A dispatch (host prep) failure: ticketed + counted as
        failed — the collect already moved the items in-flight."""
        n = sum(t.n_items for t, _ in parts)
        nbytes = sum(t._nbytes for t, _ in parts)
        with self._cv:
            self._inflight_items -= n
            self._inflight_bytes[ln] -= nbytes
            self._counts[ln]["failed"] += n
            self._tenant_terminal_locked(ln, parts, "failed")
            self._publish_lane_gauges_locked(ln)
        registry.meter("crypto.verify.service.failed").mark(n)
        registry.meter(
            f"crypto.verify.service.lane.{ln}.failed").mark(n)
        slo_monitor.note_completion(ln, ok=False, n=n)
        for tkt, _off in parts:
            tenant_mod.tenant_slo.note_completion(
                tkt.tenant, ok=False, n=tkt.n_items)
        batch_verifier.note_trace_event(
            "service.verdict", lane=ln, failed=True,
            tenants=_part_tenants(parts), traces=traces or [],
            items=n)
        for tkt, _off in parts:
            tkt._fut.set_exception(err)


def _part_tenants(parts) -> list:
    """Unique tenants of a coalesced batch, in part order — the
    ``tenants`` attribute of coalesce/dispatch/verdict records, so a
    batch's queue wait is attributable to its principals from the
    admin routes alone (ISSUE 14 trace satellite)."""
    seen: list = []
    for tkt, _off in parts:
        if tkt.tenant not in seen:
            seen.append(tkt.tenant)
    return seen


def _control_advisories(window: dict) -> None:
    """Merge the advisory half of a control window in place: per-lane
    SLO burn rates (latency + completion, from the process-wide
    monitor) and queue-wait bubble dominance from the pipeline
    timeline. These are REPORTED numbers — the controller itself
    reads no clock; replaying the logged windows reproduces the
    trajectory whatever these advisories were."""
    slo = slo_monitor.snapshot()
    for ln, objs in slo.get("lanes", {}).items():
        lane = window["lanes"].setdefault(ln, {})
        lane["latency_burn"] = objs["latency"]["burn_rate"]
        lane["shed_burn"] = objs["completion"]["burn_rate"]
    from stellar_tpu.utils.timeline import pipeline_timeline
    bub = pipeline_timeline.totals().get("bubble_ms") or {}
    total = sum(bub.values())
    window["queue_wait_frac"] = round(
        bub.get("queue_wait", 0.0) / total, 4) if total else 0.0


def lane_latencies() -> Dict[str, dict]:
    """Per-lane wait-time histogram summaries (count/p50/p90/p99/sum)
    — what ``bench.py``'s ``service`` record section and the soak
    harness publish (``docs/benchmarks.md``)."""
    out = {}
    for ln in LANES:
        t = registry.timer(f"crypto.verify.service.lane.{ln}.wait_ms")
        p50, p90, p99 = t.percentiles_ms((50, 90, 99))
        out[ln] = {"count": t.count, "p50_ms": round(p50, 3),
                   "p90_ms": round(p90, 3), "p99_ms": round(p99, 3),
                   "sum_ms": round(t.sum_ms(), 3)}
    return out


# ---------------- process-wide service ----------------

_service: Optional[VerifyService] = None
_service_lock = threading.Lock()
# tenant_snapshot / control_snapshot of the process-wide service, else
# the last-started instance (set under _service_lock in
# VerifyService.start)
_tenant_provider = None
_control_provider = None


def default_service(start: bool = True) -> VerifyService:
    """Process-wide resident service over the default verifier
    (created on first call; Application starts it when
    ``VERIFY_SERVICE_ENABLED``)."""
    global _service
    with _service_lock:
        if _service is None:
            _service = VerifyService()
        svc = _service
    if start:
        svc.start()
    return svc


def running_service() -> Optional[VerifyService]:
    """The process-wide service IF it exists and is accepting work,
    else ``None`` — the adoption check for call sites (herder SCP
    envelopes, overlay pre-verify) that ride the priority lanes when
    ``VERIFY_SERVICE_ENABLED`` started the service but must keep
    their direct path otherwise. Never creates or starts a service
    as a side effect (that is :func:`default_service`'s job)."""
    with _service_lock:
        svc = _service
    if svc is None:
        return None
    with svc._cv:
        if svc._running and not svc._stop:
            return svc
    return None


# Wedged-dispatcher cool-down for the lane adopters: one result
# timeout (the hung-fetch signature — Overloaded fast-fails and never
# arms this) opens a bypass window so subsequent cache misses degrade
# to the direct path INSTANTLY instead of each serially paying the
# full wait — without it, a wedged dispatcher costs every cache-miss
# crank/handshake/close ``timeout`` seconds until the lane queue
# fills (depth x timeout of serial stalls), not the "degrade in one
# timeout" the adopters advertise.
ADOPTER_COOLDOWN_S = 30.0
_adopter_cooldown_until = 0.0


def _adopter_fallback(lane: str, reason: str, n: int) -> None:
    """Every ``service_verified`` fallback is counted, per lane and
    reason — a fleet silently riding the direct path (service absent,
    wedged, or throwing on a bad call) must be distinguishable from
    one riding the lanes, from metrics alone."""
    registry.meter("crypto.verify.service.adopter_fallback").mark(n)
    registry.meter(
        f"crypto.verify.service.adopter_fallback.{lane}.{reason}"
    ).mark(n)


def service_verified(items: Sequence[tuple], lane: str,
                     timeout: float = 10.0,
                     tenant: Optional[str] = None) -> Optional[list]:
    """One cache-seeding service round trip for the signature hot
    paths (herder SCP envelopes, peer auth certs, overlay tx-flood
    pre-verify — the three lane adopters share THIS block so their
    fallback/seeding semantics can never diverge): per-item bools via
    the resident service, with every verdict re-seeded into keys'
    ``verify_sig`` cache, or ``None`` when the service is absent or
    fails in ANY way — Overloaded at ingress, stop mid-call, dispatch
    failure, or the ``timeout`` expiring on an unresolved ticket. The
    wait is BOUNDED by default, and a result timeout additionally
    arms the :data:`ADOPTER_COOLDOWN_S` bypass window: a wedged
    dispatcher (the tunnel's hung-fetch failure mode) must degrade
    the caller to its direct path — once, not once per cache miss —
    and never park a consensus crank, a peer handshake, or a ledger
    close on a future that will not resolve. Every ``None`` is
    metered per lane+reason (``crypto.verify.service.
    adopter_fallback.*``). ``None`` means "you decide" — the direct
    path is bit-identical, so the service can only ever change
    latency, never validity. ``tenant`` attributes the round trip to
    a principal (ISSUE 15 follow-on: the herder/overlay adopters pass
    ``tenant_mod.peer_tenant(<peer id>)`` so real peers ride
    per-tenant quotas once ``VERIFY_TENANT_FROM_PEER`` is on; None —
    the default — keeps the quota-exempt un-tenanted stream)."""
    global _adopter_cooldown_until
    n = len(items)
    # clock read: cool-down bypass decides only WHICH bit-identical
    # path serves (service lane vs direct verify), never a verdict
    # (nondet allowlist)
    with _service_lock:
        cooling = time.monotonic() < _adopter_cooldown_until
    if cooling:
        _adopter_fallback(lane, "cooldown", n)
        return None
    svc = running_service()
    if svc is None:
        _adopter_fallback(lane, "absent", n)
        return None
    try:
        # the un-tenanted call keeps the legacy shape, so duck-typed
        # service stand-ins (tests, embedders) without a tenant
        # parameter keep working until they opt into tenancy
        if tenant is None:
            ok = svc.verify(items, lane=lane, timeout=timeout)
        else:
            ok = svc.verify(items, lane=lane, timeout=timeout,
                            tenant=tenant)
    except (FuturesTimeout, TimeoutError):
        with _service_lock:
            _adopter_cooldown_until = (time.monotonic()
                                       + ADOPTER_COOLDOWN_S)
        _adopter_fallback(lane, "timeout", n)
        return None
    except Overloaded:
        _adopter_fallback(lane, "overloaded", n)
        return None
    except Exception:
        # programming errors degrade too (the direct path is the safe,
        # bit-identical choice for peer auth / consensus cranks) — but
        # never silently: the "error" meter is the tripwire
        _adopter_fallback(lane, "error", n)
        return None
    from stellar_tpu.crypto.keys import seed_verify_cache
    out = [bool(o) for o in ok]
    seed_verify_cache([(pk, msg, sig, o)
                       for (pk, msg, sig), o in zip(items, out)])
    return out


def service_health() -> dict:
    """The ``service`` admin-route payload: the process-wide service's
    snapshot; falls back to whichever service instance last registered
    with the dispatch layer (a node embedding its own instance still
    gets an admin surface), else ``{"running": False}``."""
    with _service_lock:
        svc = _service
    if svc is not None:
        return svc.snapshot()
    return batch_verifier.service_health_snapshot()


def control_health() -> dict:
    """The ``control`` admin-route payload (ISSUE 15): the closed-loop
    controller's knob/clamp/hysteresis state, the live values the
    service applies, and the tail of the trajectory log. Served
    directly — the controller matters exactly when the node is under
    load (same policy as ``slo``/``tenant``)."""
    with _service_lock:
        svc = _service
        provider = _control_provider
    if svc is not None:
        provider = svc.control_snapshot
    if provider is None:
        return {"enabled": False}
    return provider()


def tenant_health() -> dict:
    """The ``tenant`` admin-route payload (ISSUE 14): per-tenant SLO
    burn rates (top-K + rollup, refreshing the rank-keyed gauges) and
    the process-wide service's per-tenant conservation counters.
    Served directly — tenant isolation matters exactly when the node
    is overloaded."""
    out = {"slo": tenant_mod.tenant_slo.snapshot()}
    with _service_lock:
        svc = _service
        provider = _tenant_provider
    if svc is not None:
        provider = svc.tenant_snapshot
    out["service"] = provider() if provider is not None else {
        "tenants": {}, "tracked": 0, "conservation_violations": {},
        "decision_log_len": 0}
    return out
