import os
import sys

from stellar_tpu.main.cli import main

try:
    sys.exit(main())
except BrokenPipeError:
    # downstream consumer (e.g. `| head`) closed the pipe mid-write;
    # point stdout at devnull so the interpreter-shutdown flush doesn't
    # hit the broken pipe again and taint the exit status
    os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    sys.exit(0)
