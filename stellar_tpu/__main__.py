import sys

from stellar_tpu.main.cli import main

sys.exit(main())
