import sys

from stellar_tpu.main.cli import main

try:
    sys.exit(main())
except BrokenPipeError:
    # downstream consumer (e.g. `| head`) closed the pipe mid-write
    sys.exit(0)
