"""Metrics registry (the reference vendors libmedida: meters, counters,
timers, histograms keyed by dotted names, exported via the HTTP
``metrics`` endpoint — ``docs/metrics.md``).

Thread safety: metrics are marked from resolve-watchdog threads,
trickle-batch leaders, probe threads, and breaker transition callbacks
concurrently, so every read-modify-write (counter increments, the
meter's sliding-window push/evict, timer accumulators + reservoir
replacement, the registry's get-or-create) holds the instance lock.
The lock discipline is enforced by ``stellar_tpu/analysis/locks.py``
(tier-1 via ``tools/analyze.py``).

Timers are HISTOGRAMS (ISSUE 5): alongside the running count/min/mean/
max/stddev they keep a fixed-size reservoir sample of observations, so
``to_dict`` (and the Prometheus exposition, :meth:`MetricsRegistry.
to_prometheus`) exports p50/p90/p99 — the dispatch-floor work needs
latency *distributions*, not means (arXiv:2302.00418's measurement
methodology; the reference exports medida percentiles the same way,
``docs/metrics.md``). Same classes, same dotted names: every existing
``registry.timer(...)`` call site gained percentiles in place.
"""

from __future__ import annotations

import math
import random
import re
import threading
import time
from collections import deque
from typing import Dict, List

__all__ = ["Counter", "Meter", "Timer", "Gauge", "MetricsRegistry",
           "registry", "RESERVOIR_SIZE", "TimeSeriesRing", "timeseries",
           "fresh_burn_window", "push_burn_window", "trim_burn_window"]


# ---------------- SLO burn-window helpers ----------------
# ONE implementation of the event-count sliding-window error-budget
# state (deque of 0/1 + running bad/total counters), shared by the
# per-lane SloMonitor (crypto/verify_service.py) and the per-tenant
# TenantSloMonitor (crypto/tenant.py): the window invariant must not
# fork. Pure dict-state functions — the OWNING monitor holds its lock
# around every call (these never lock).


def fresh_burn_window() -> dict:
    return {"events": deque(), "bad": 0, "total": 0, "bad_total": 0}


def trim_burn_window(st: dict, window: int) -> None:
    while len(st["events"]) > window:
        st["bad"] -= st["events"].popleft()


def push_burn_window(st: dict, bad: bool, n: int,
                     window: int) -> None:
    flag = 1 if bad else 0
    for _ in range(n):
        st["events"].append(flag)
    st["bad"] += flag * n
    st["total"] += n
    st["bad_total"] += flag * n
    trim_burn_window(st, window)


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def inc(self, n: int = 1):
        with self._lock:
            self.count += n

    def dec(self, n: int = 1):
        with self._lock:
            self.count -= n

    def to_dict(self):
        return {"type": "counter", "count": self.count}


# sliding-window length for meters/rates (reference
# HISTOGRAM_WINDOW_SIZE; pushed from Config by the Application —
# default matches the Config default so changed()-gated pushes stay
# consistent)
WINDOW_SECONDS = 300.0

# reservoir sample size for timer percentiles (pushed from Config's
# METRICS_RESERVOIR_SIZE by the Application; read at update time, so a
# push before traffic starts sizes every timer)
RESERVOIR_SIZE = 512


def _interp_percentile(data: List[float], q: float) -> float:
    """Linear-interpolated percentile over an already-sorted sample;
    0.0 on empty."""
    if not data:
        return 0.0
    k = (len(data) - 1) * (q / 100.0)
    f = math.floor(k)
    c = math.ceil(k)
    if f == c:
        return data[int(k)]
    return data[f] + (data[c] - data[f]) * (k - f)


class Meter:
    """Event rate: count + sliding-window rate (window length from
    HISTOGRAM_WINDOW_SIZE; the exported JSON names the window so
    consumers never misread the rate's denominator)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._events: List[float] = []

    def mark(self, n: int = 1):
        now = time.monotonic()
        cutoff = now - WINDOW_SECONDS
        with self._lock:
            # push + evict under the lock: a concurrent pop(0) between
            # another thread's emptiness check and its pop is an
            # IndexError waiting for a loaded host
            self.count += n
            self._events.append(now)
            while self._events and self._events[0] < cutoff:
                self._events.pop(0)

    def windowed_rate(self) -> float:
        return len(self._events) / WINDOW_SECONDS

    # historical name, kept for callers that predate the configurable
    # window
    one_minute_rate = windowed_rate

    def to_dict(self):
        return {"type": "meter", "count": self.count,
                "window_s": WINDOW_SECONDS,
                "rate": round(self.windowed_rate(), 4)}


class Timer:
    """Duration stats: count/min/mean/max/stddev (ms) + a reservoir
    sample for percentiles (p50/p90/p99).

    The reservoir is the classic replace-with-probability-k/n scheme,
    driven by a per-instance seeded RNG: percentile estimates must not
    perturb (or depend on) the process RNG state, and timers live
    outside every consensus decision path — the nondet lint fences the
    clock-bearing tracing layer that feeds them out of consensus
    modules (``stellar_tpu/analysis/nondet.py``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._sum = 0.0
        self._sum2 = 0.0
        self.min_ms = math.inf
        self.max_ms = 0.0
        self._reservoir: List[float] = []
        self._rng = random.Random(0x5EED)

    def update_ms(self, ms: float):
        size = max(1, int(RESERVOIR_SIZE))
        with self._lock:
            self.count += 1
            self._sum += ms
            self._sum2 += ms * ms
            self.min_ms = min(self.min_ms, ms)
            self.max_ms = max(self.max_ms, ms)
            # reservoir replacement is a read-modify-write on both the
            # sample list and the RNG stream: under the lock with the
            # accumulators. A shrunken RESERVOIR_SIZE push truncates,
            # or the tail indices would freeze stale samples into the
            # percentiles forever.
            if len(self._reservoir) > size:
                del self._reservoir[size:]
            if len(self._reservoir) < size:
                self._reservoir.append(ms)
            else:
                j = self._rng.randrange(self.count)
                if j < size:
                    self._reservoir[j] = ms

    def record_total(self, count: int, sum_ms: float):
        """Fold an externally-aggregated (count, sum) pair into the
        totals — the flush path of tracing's root-attributed phase
        collectors (``span.attr.*`` timers, ISSUE 8). The reservoir
        and min/max take the batch MEAN once per flush: these timers
        exist for exact count/sum attribution deltas
        (``timer_totals``), and pretending per-event resolution from
        an aggregate would fabricate percentiles."""
        n = int(count)
        if n <= 0:
            return
        mean = sum_ms / n
        size = max(1, int(RESERVOIR_SIZE))
        with self._lock:
            self.count += n
            self._sum += sum_ms
            self._sum2 += mean * mean * n
            self.min_ms = min(self.min_ms, mean)
            self.max_ms = max(self.max_ms, mean)
            if len(self._reservoir) > size:
                del self._reservoir[size:]
            if len(self._reservoir) < size:
                self._reservoir.append(mean)
            else:
                j = self._rng.randrange(self.count)
                if j < size:
                    self._reservoir[j] = mean

    def time(self):
        t0 = time.perf_counter()
        timer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                timer.update_ms((time.perf_counter() - t0) * 1000.0)
                return False
        return _Ctx()

    def mean_ms(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def stddev_ms(self) -> float:
        if self.count < 2:
            return 0.0
        m = self.mean_ms()
        var = max(0.0, self._sum2 / self.count - m * m)
        return math.sqrt(var)

    def sum_ms(self) -> float:
        """Total observed time — the quantity span attribution sums
        (``batch_verifier.dispatch_attribution``)."""
        with self._lock:
            return self._sum

    def percentiles_ms(self, qs) -> List[float]:
        """Linear-interpolated percentiles (each q in [0, 100]) from
        ONE locked, sorted reservoir snapshot — exports ask for three
        quantiles at a time, and per-quantile re-sorting on a polled
        scrape path is wasted work."""
        with self._lock:
            data = sorted(self._reservoir)
        return [_interp_percentile(data, q) for q in qs]

    def percentile_ms(self, q: float) -> float:
        return self.percentiles_ms((q,))[0]

    def to_dict(self):
        # one locked snapshot: a count/sum pair torn across a
        # concurrent update_ms must not reach the export
        with self._lock:
            count = self.count
            s = self._sum
            s2 = self._sum2
            mn = self.min_ms
            mx = self.max_ms
            data = sorted(self._reservoir)
        mean = s / count if count else 0.0
        var = max(0.0, s2 / count - mean * mean) if count >= 2 else 0.0
        p50, p90, p99 = (_interp_percentile(data, q)
                         for q in (50, 90, 99))
        return {"type": "timer", "count": count,
                "min_ms": 0.0 if math.isinf(mn) else round(mn, 3),
                "mean_ms": round(mean, 3),
                "max_ms": round(mx, 3),
                "stddev_ms": round(math.sqrt(var), 3),
                "sum_ms": round(s, 3),
                "p50_ms": round(p50, 3),
                "p90_ms": round(p90, 3),
                "p99_ms": round(p99, 3)}


class Gauge:
    """Last-written value (numeric or label, e.g. a breaker state) —
    the degradation-visibility primitive: unlike a counter it answers
    "what is it NOW", which is what the info endpoint needs for
    breaker state / deadline knobs."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def set(self, value):
        with self._lock:
            self.value = value

    def to_dict(self):
        return {"type": "gauge", "value": self.value}


# Prometheus exposition-format helpers: metric names may only be
# [a-zA-Z_:][a-zA-Z0-9_:]*, so dotted registry names mangle dots (and
# any other byte) to underscores.
_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            # get-or-create must be atomic: two threads racing the
            # first mark of a meter would otherwise each create one,
            # and whichever registers second silently eats the other's
            # counts
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def to_dict(self) -> dict:
        return self.find("")

    def find(self, prefix: str) -> dict:
        """Rendered snapshot of every metric whose dotted name starts
        with ``prefix`` (``""`` = the whole registry — ``to_dict``) —
        the subsystem-scoped export behind the soak harness's
        accounting cross-check (``tools/soak.py`` proves the verify
        service's conservation counters against the
        ``crypto.verify.service.*`` meters) and ad-hoc admin queries.
        The name walk snapshots under the registry lock (iterating the
        live dict while a first-mark thread inserts would raise on the
        metrics endpoint); rendering happens outside it, on the
        per-metric locks."""
        with self._lock:
            items = sorted((name, m) for name, m in
                           self._metrics.items()
                           if name.startswith(prefix))
        return {name: m.to_dict() for name, m in items}

    def timer_totals(self) -> Dict[str, dict]:
        """``{name: {"count", "sum_ms"}}`` for every timer — the cheap
        accessor behind ``tracing.span_totals()``: no reservoir sorts,
        no meter/gauge rendering, just the two fields attribution
        deltas need."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: {"count": m.count, "sum_ms": m.sum_ms()}
                for name, m in items if isinstance(m, Timer)}

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the whole
        registry — the ``metrics?format=prometheus`` admin surface
        (the reference serves its medida registry over HTTP the same
        way, ``docs/metrics.md``). Counters export as counters, meters
        as a ``_total`` counter + ``_rate`` gauge, timers as summaries
        (quantile-labeled samples + ``_sum``/``_count``), gauges as
        gauges (non-numeric values become a ``value`` label)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            base = _prom_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {base} counter")
                lines.append(f"{base} {m.count}")
            elif isinstance(m, Meter):
                lines.append(f"# TYPE {base}_total counter")
                lines.append(f"{base}_total {m.count}")
                lines.append(f"# TYPE {base}_rate gauge")
                lines.append(f"{base}_rate {m.windowed_rate():.6f}")
            elif isinstance(m, Timer):
                lines.append(f"# TYPE {base}_ms summary")
                for q, v in zip((50, 90, 99),
                                m.percentiles_ms((50, 90, 99))):
                    lines.append(
                        f'{base}_ms{{quantile="{q / 100}"}} {v:.6f}')
                lines.append(f"{base}_ms_sum {m.sum_ms():.6f}")
                lines.append(f"{base}_ms_count {m.count}")
            elif isinstance(m, Gauge):
                v = m.value
                lines.append(f"# TYPE {base} gauge")
                if isinstance(v, bool):
                    lines.append(f"{base} {int(v)}")
                elif isinstance(v, (int, float)) and not (
                        isinstance(v, float) and math.isnan(v)):
                    lines.append(f"{base} {v}")
                elif v is None:
                    lines.append(f'{base}{{value="none"}} 1')
                else:
                    lines.append(
                        f'{base}{{value="{_prom_label_escape(str(v))}"'
                        f"}} 1")
        return "\n".join(lines) + "\n"

    def clear(self):
        with self._lock:
            self._metrics.clear()


# process-wide registry (the reference's per-app medida registry; one
# node per process in production)
registry = MetricsRegistry()


# ---------------- in-process time-series ring (ISSUE 10) ----------------
# Counters/exports answer "how much so far" and "what now"; nothing
# answered "what was it two minutes ago, while the soak was running".
# The ring keeps a bounded fixed-interval history per metric so a live
# node can show the scp-lane p99 *trajectory* and an EWMA z-score
# watcher can catch a sustained excursion WHILE it happens (firing a
# flight-recorder dump so the spans leading into the regression
# survive) — not only between committed BENCH records. Served by the
# ``timeseries`` admin route; Config sizes it
# (METRICS_TIMESERIES_SAMPLES / _INTERVAL_S, METRICS_ANOMALY_*).

# defaults; Config pushes through TimeSeriesRing.configure()
TIMESERIES_SAMPLES = 512
TIMESERIES_INTERVAL_S = 1.0
ANOMALY_Z = 6.0          # |z| threshold per sample
ANOMALY_SUSTAIN = 3      # consecutive excursions before firing
ANOMALY_MIN_SAMPLES = 32  # EWMA warm-up before any alerting
_EWMA_ALPHA = 0.1
# hard cap on tracked series: per-lane meters etc. can mint names, and
# the ring must stay bounded no matter what — overflow is COUNTED
# (dropped_series in the snapshot), never silent. Per-instance
# override via TimeSeriesRing.configure(max_series=...) — the tenant
# QoS layer (ISSUE 14) additionally publishes per-tenant burn rates
# under RANK-keyed names (crypto.verify.tenant.topk.<rank>.*) exactly
# so tenant cardinality can never race this cap, however many tenants
# churn (tests/test_timeline.py pins the interplay)
MAX_SERIES = 1024

# series timestamps: monotonic seconds since module import (no wall
# clock — same policy as the tracing epoch)
_TS_EPOCH = time.monotonic()


class TimeSeriesRing:
    """Bounded per-metric history of fixed-interval snapshots, plus
    the EWMA z-score anomaly watcher.

    What each metric type contributes per tick:

    * counters / meters — the per-interval DELTA (a cumulative count's
      z-score is meaningless; its rate's is exactly what an anomaly
      watcher wants);
    * gauges — the numeric value (non-numeric gauges are skipped);
    * timers — ``p50`` / ``p99`` from the reservoir plus the count
      delta.

    Every mutation and every read snapshot happens under the instance
    lock (one tick appends to all series atomically), so a reader
    sampling concurrently with a resolving engine can never see a torn
    window — and a window that simply has not filled yet is MARKED
    (``partial: true``), never silently averaged."""

    def __init__(self, reg: MetricsRegistry,
                 prefixes=("crypto.",)):
        self._registry = reg
        self._prefixes = tuple(prefixes)
        self._lock = threading.Lock()
        self._series: Dict[str, List] = {}   # name -> [(t_s, value)]
        self._last_raw: Dict[str, float] = {}
        self._anom: Dict[str, dict] = {}
        self._anomalies: List[dict] = []
        self._samples = TIMESERIES_SAMPLES
        self._z = ANOMALY_Z
        self._sustain = ANOMALY_SUSTAIN
        self._min_samples = ANOMALY_MIN_SAMPLES
        self._interval_s = TIMESERIES_INTERVAL_S
        self._ticks = 0
        self._dropped_series = 0
        # None = follow the module-level MAX_SERIES default
        self._max_series = None
        self._thread = None
        self._stop_evt = threading.Event()

    def configure(self, samples=None, interval_s=None, z=None,
                  sustain=None, min_samples=None,
                  max_series=None) -> None:
        """Config push (METRICS_TIMESERIES_* / METRICS_ANOMALY_*);
        None keeps the current value. ``max_series`` overrides the
        module-level hard cap for THIS ring (never below 8 — the cap
        is a guard, not an off switch)."""
        with self._lock:
            if samples is not None:
                self._samples = max(8, int(samples))
                for buf in self._series.values():
                    if len(buf) > self._samples:
                        del buf[:len(buf) - self._samples]
            if interval_s is not None:
                self._interval_s = max(0.01, float(interval_s))
            if z is not None:
                self._z = max(1.0, float(z))
            if sustain is not None:
                self._sustain = max(1, int(sustain))
            if min_samples is not None:
                self._min_samples = max(2, int(min_samples))
            if max_series is not None:
                self._max_series = max(8, int(max_series))

    # ---------------- sampling ----------------

    def sample_once(self) -> int:
        """One snapshot tick over every matching metric; returns the
        number of series updated. Callable directly (tests, the soak
        harness) or driven by :meth:`start`'s daemon thread."""
        t = time.monotonic() - _TS_EPOCH
        with self._registry._lock:
            items = [(n, m) for n, m in self._registry._metrics.items()
                     if n.startswith(self._prefixes)]
        # render OUTSIDE the registry lock (per-metric locks suffice)
        points: List[tuple] = []   # (series, raw, is_cumulative)
        for name, m in items:
            if isinstance(m, (Counter, Meter)):
                points.append((name + ".count", float(m.count), True))
            elif isinstance(m, Gauge):
                v = m.value
                if isinstance(v, bool):
                    points.append((name, float(v), False))
                elif isinstance(v, (int, float)) and not (
                        isinstance(v, float) and math.isnan(v)):
                    points.append((name, float(v), False))
            elif isinstance(m, Timer):
                p50, p99 = m.percentiles_ms((50, 99))
                points.append((name + ".p50_ms", p50, False))
                points.append((name + ".p99_ms", p99, False))
                points.append((name + ".count", float(m.count), True))
        fired: List[dict] = []
        updated = 0
        with self._lock:
            self._ticks += 1
            for series, raw, cumulative in points:
                if cumulative:
                    prev = self._last_raw.get(series)
                    self._last_raw[series] = raw
                    value = raw - prev if prev is not None else 0.0
                else:
                    value = raw
                buf = self._series.get(series)
                if buf is None:
                    cap = self._max_series if self._max_series \
                        is not None else MAX_SERIES
                    if len(self._series) >= cap:
                        self._dropped_series += 1
                        continue
                    buf = self._series[series] = []
                buf.append((round(t, 3), round(value, 6)))
                if len(buf) > self._samples:
                    del buf[:len(buf) - self._samples]
                updated += 1
                a = self._check_anomaly_locked(series, value, t)
                if a is not None:
                    fired.append(a)
        for a in fired:
            self._fire_anomaly(a)
        return updated

    def _check_anomaly_locked(self, series: str, value: float,
                              t: float):
        """EWMA mean/variance z-score per series; returns an anomaly
        record when a deviation has SUSTAINED (>= sustain consecutive
        excursions past the z threshold, after warm-up), exactly once
        per excursion (re-arms when the series normalizes)."""
        st = self._anom.get(series)
        if st is None:
            st = self._anom[series] = {
                "mu": value, "var": 0.0, "n": 1, "streak": 0,
                "alerting": False}
            return None
        st["n"] += 1
        sd = math.sqrt(st["var"])
        z = None
        if st["n"] > self._min_samples:
            if sd > 0:
                z = (value - st["mu"]) / sd
            elif value != st["mu"]:
                # a jump off a perfectly constant baseline: variance 0
                # would leave z undefined exactly when the deviation
                # is most obvious — capped, not infinite (JSON-safe)
                z = 1e9 if value > st["mu"] else -1e9
        out = None
        excursion = z is not None and abs(z) > self._z
        if excursion:
            st["streak"] += 1
            if st["streak"] >= self._sustain and not st["alerting"]:
                st["alerting"] = True
                out = {"series": series, "t_s": round(t, 3),
                       "value": value, "mu": round(st["mu"], 6),
                       "z": round(z, 2)}
                self._anomalies.append(out)
                del self._anomalies[:-32]
        else:
            st["streak"] = 0
            st["alerting"] = False
        # EWMA update AFTER the test (the sample being judged must not
        # have already dragged the baseline toward itself), and
        # excursion samples fold in at 1/10 weight: full weight would
        # let the first outlier inflate the variance enough to mask
        # the rest of a sustained excursion, while zero weight would
        # freeze the baseline and alert on a true level shift forever
        alpha = _EWMA_ALPHA * (0.1 if excursion else 1.0)
        d = value - st["mu"]
        st["mu"] += alpha * d
        st["var"] = (1 - alpha) * (st["var"] + alpha * d * d)
        return out

    def _fire_anomaly(self, rec: dict) -> None:
        """A sustained deviation: count it and dump the flight
        recorder so the spans/events leading into the excursion
        survive to be read (same policy as breaker trips and shed
        onsets). The tracing import is lazy — tracing imports this
        module at load time, and the sampler only ever runs long after
        both are imported."""
        registry.counter("metrics.timeseries.anomalies").inc()
        try:
            from stellar_tpu.utils import tracing
            tracing.flight_recorder.dump(
                f"timeseries-anomaly:{rec['series']}")
        except ImportError:  # pragma: no cover — import-order edge
            pass

    # ---------------- sampler thread ----------------

    def start(self, interval_s=None) -> "TimeSeriesRing":
        """Spawn the fixed-interval sampler daemon (idempotent)."""
        with self._lock:
            if interval_s is not None:
                self._interval_s = max(0.01, float(interval_s))
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop_evt = threading.Event()
            self._thread = threading.Thread(
                target=self._run, args=(self._stop_evt,),
                daemon=True, name="metrics-timeseries")
        self._thread.start()
        return self

    def _run(self, stop_evt) -> None:
        while not stop_evt.wait(self._interval_s):
            self.sample_once()

    def stop(self) -> None:
        with self._lock:
            t = self._thread
            self._thread = None
            evt = self._stop_evt
        evt.set()
        if t is not None:
            t.join(timeout=5.0)

    # ---------------- introspection ----------------

    def snapshot(self, series=None, limit: int = 0) -> dict:
        """The ``timeseries`` admin-route payload. ``series`` filters
        by name prefix; ``limit`` bounds samples per series (0 = all
        retained). Partial windows are marked, never hidden."""
        limit = max(0, int(limit))
        with self._lock:
            names = sorted(n for n in self._series
                           if series is None or n.startswith(series))
            out_series = {}
            for n in names:
                buf = self._series[n]
                pts = buf[-limit:] if limit else list(buf)
                out_series[n] = {
                    "n": len(buf),
                    "window": self._samples,
                    "partial": len(buf) < self._samples,
                    "samples": [list(p) for p in pts],
                }
            running = self._thread is not None and \
                self._thread.is_alive()
            return {
                "series": out_series,
                "anomalies": [dict(a) for a in self._anomalies],
                "sampling": {"running": running,
                             "interval_s": self._interval_s,
                             "ticks": self._ticks,
                             "window": self._samples,
                             "tracked_series": len(self._series),
                             "max_series": self._max_series
                             if self._max_series is not None
                             else MAX_SERIES,
                             "dropped_series": self._dropped_series,
                             "z": self._z,
                             "sustain": self._sustain,
                             "min_samples": self._min_samples},
            }

    def _reset_for_testing(self) -> None:
        self.stop()
        with self._lock:
            self._series.clear()
            self._last_raw.clear()
            self._anom.clear()
            self._anomalies = []
            self._ticks = 0
            self._dropped_series = 0


# process-wide ring over the process-wide registry (sampler started by
# the Application when METRICS_TIMESERIES_ENABLED, by tools/soak.py
# for soak windows, and by tests directly via sample_once)
timeseries = TimeSeriesRing(registry)
