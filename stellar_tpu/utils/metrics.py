"""Metrics registry (the reference vendors libmedida: meters, counters,
timers, histograms keyed by dotted names, exported via the HTTP
``metrics`` endpoint — ``docs/metrics.md``).

Thread safety: metrics are marked from resolve-watchdog threads,
trickle-batch leaders, probe threads, and breaker transition callbacks
concurrently, so every read-modify-write (counter increments, the
meter's sliding-window push/evict, timer accumulators, the registry's
get-or-create) holds the instance lock. The lock discipline is enforced
by ``stellar_tpu/analysis/locks.py`` (tier-1 via ``tools/analyze.py``).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, List

__all__ = ["Counter", "Meter", "Timer", "Gauge", "MetricsRegistry",
           "registry"]


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def inc(self, n: int = 1):
        with self._lock:
            self.count += n

    def dec(self, n: int = 1):
        with self._lock:
            self.count -= n

    def to_dict(self):
        return {"type": "counter", "count": self.count}


# sliding-window length for meters/rates (reference
# HISTOGRAM_WINDOW_SIZE; pushed from Config by the Application —
# default matches the Config default so changed()-gated pushes stay
# consistent)
WINDOW_SECONDS = 300.0


class Meter:
    """Event rate: count + sliding-window rate (window length from
    HISTOGRAM_WINDOW_SIZE; the exported JSON names the window so
    consumers never misread the rate's denominator)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._events: List[float] = []

    def mark(self, n: int = 1):
        now = time.monotonic()
        cutoff = now - WINDOW_SECONDS
        with self._lock:
            # push + evict under the lock: a concurrent pop(0) between
            # another thread's emptiness check and its pop is an
            # IndexError waiting for a loaded host
            self.count += n
            self._events.append(now)
            while self._events and self._events[0] < cutoff:
                self._events.pop(0)

    def windowed_rate(self) -> float:
        return len(self._events) / WINDOW_SECONDS

    # historical name, kept for callers that predate the configurable
    # window
    one_minute_rate = windowed_rate

    def to_dict(self):
        return {"type": "meter", "count": self.count,
                "window_s": WINDOW_SECONDS,
                "rate": round(self.windowed_rate(), 4)}


class Timer:
    """Duration stats: count/min/mean/max/stddev (ms)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._sum = 0.0
        self._sum2 = 0.0
        self.min_ms = math.inf
        self.max_ms = 0.0

    def update_ms(self, ms: float):
        with self._lock:
            self.count += 1
            self._sum += ms
            self._sum2 += ms * ms
            self.min_ms = min(self.min_ms, ms)
            self.max_ms = max(self.max_ms, ms)

    def time(self):
        t0 = time.perf_counter()
        timer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                timer.update_ms((time.perf_counter() - t0) * 1000.0)
                return False
        return _Ctx()

    def mean_ms(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def stddev_ms(self) -> float:
        if self.count < 2:
            return 0.0
        m = self.mean_ms()
        var = max(0.0, self._sum2 / self.count - m * m)
        return math.sqrt(var)

    def to_dict(self):
        return {"type": "timer", "count": self.count,
                "min_ms": 0.0 if math.isinf(self.min_ms) else
                round(self.min_ms, 3),
                "mean_ms": round(self.mean_ms(), 3),
                "max_ms": round(self.max_ms, 3),
                "stddev_ms": round(self.stddev_ms(), 3)}


class Gauge:
    """Last-written value (numeric or label, e.g. a breaker state) —
    the degradation-visibility primitive: unlike a counter it answers
    "what is it NOW", which is what the info endpoint needs for
    breaker state / deadline knobs."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def set(self, value):
        with self._lock:
            self.value = value

    def to_dict(self):
        return {"type": "gauge", "value": self.value}


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            # get-or-create must be atomic: two threads racing the
            # first mark of a meter would otherwise each create one,
            # and whichever registers second silently eats the other's
            # counts
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def to_dict(self) -> dict:
        with self._lock:
            # snapshot under the lock: iterating the live dict while a
            # first-mark thread inserts raises "dictionary changed size
            # during iteration" on the metrics endpoint
            items = sorted(self._metrics.items())
        return {name: m.to_dict() for name, m in items}

    def clear(self):
        with self._lock:
            self._metrics.clear()


# process-wide registry (the reference's per-app medida registry; one
# node per process in production)
registry = MetricsRegistry()
