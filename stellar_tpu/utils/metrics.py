"""Metrics registry (the reference vendors libmedida: meters, counters,
timers, histograms keyed by dotted names, exported via the HTTP
``metrics`` endpoint — ``docs/metrics.md``).

Thread safety: metrics are marked from resolve-watchdog threads,
trickle-batch leaders, probe threads, and breaker transition callbacks
concurrently, so every read-modify-write (counter increments, the
meter's sliding-window push/evict, timer accumulators + reservoir
replacement, the registry's get-or-create) holds the instance lock.
The lock discipline is enforced by ``stellar_tpu/analysis/locks.py``
(tier-1 via ``tools/analyze.py``).

Timers are HISTOGRAMS (ISSUE 5): alongside the running count/min/mean/
max/stddev they keep a fixed-size reservoir sample of observations, so
``to_dict`` (and the Prometheus exposition, :meth:`MetricsRegistry.
to_prometheus`) exports p50/p90/p99 — the dispatch-floor work needs
latency *distributions*, not means (arXiv:2302.00418's measurement
methodology; the reference exports medida percentiles the same way,
``docs/metrics.md``). Same classes, same dotted names: every existing
``registry.timer(...)`` call site gained percentiles in place.
"""

from __future__ import annotations

import math
import random
import re
import threading
import time
from typing import Dict, List

__all__ = ["Counter", "Meter", "Timer", "Gauge", "MetricsRegistry",
           "registry", "RESERVOIR_SIZE"]


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0

    def inc(self, n: int = 1):
        with self._lock:
            self.count += n

    def dec(self, n: int = 1):
        with self._lock:
            self.count -= n

    def to_dict(self):
        return {"type": "counter", "count": self.count}


# sliding-window length for meters/rates (reference
# HISTOGRAM_WINDOW_SIZE; pushed from Config by the Application —
# default matches the Config default so changed()-gated pushes stay
# consistent)
WINDOW_SECONDS = 300.0

# reservoir sample size for timer percentiles (pushed from Config's
# METRICS_RESERVOIR_SIZE by the Application; read at update time, so a
# push before traffic starts sizes every timer)
RESERVOIR_SIZE = 512


def _interp_percentile(data: List[float], q: float) -> float:
    """Linear-interpolated percentile over an already-sorted sample;
    0.0 on empty."""
    if not data:
        return 0.0
    k = (len(data) - 1) * (q / 100.0)
    f = math.floor(k)
    c = math.ceil(k)
    if f == c:
        return data[int(k)]
    return data[f] + (data[c] - data[f]) * (k - f)


class Meter:
    """Event rate: count + sliding-window rate (window length from
    HISTOGRAM_WINDOW_SIZE; the exported JSON names the window so
    consumers never misread the rate's denominator)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._events: List[float] = []

    def mark(self, n: int = 1):
        now = time.monotonic()
        cutoff = now - WINDOW_SECONDS
        with self._lock:
            # push + evict under the lock: a concurrent pop(0) between
            # another thread's emptiness check and its pop is an
            # IndexError waiting for a loaded host
            self.count += n
            self._events.append(now)
            while self._events and self._events[0] < cutoff:
                self._events.pop(0)

    def windowed_rate(self) -> float:
        return len(self._events) / WINDOW_SECONDS

    # historical name, kept for callers that predate the configurable
    # window
    one_minute_rate = windowed_rate

    def to_dict(self):
        return {"type": "meter", "count": self.count,
                "window_s": WINDOW_SECONDS,
                "rate": round(self.windowed_rate(), 4)}


class Timer:
    """Duration stats: count/min/mean/max/stddev (ms) + a reservoir
    sample for percentiles (p50/p90/p99).

    The reservoir is the classic replace-with-probability-k/n scheme,
    driven by a per-instance seeded RNG: percentile estimates must not
    perturb (or depend on) the process RNG state, and timers live
    outside every consensus decision path — the nondet lint fences the
    clock-bearing tracing layer that feeds them out of consensus
    modules (``stellar_tpu/analysis/nondet.py``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._sum = 0.0
        self._sum2 = 0.0
        self.min_ms = math.inf
        self.max_ms = 0.0
        self._reservoir: List[float] = []
        self._rng = random.Random(0x5EED)

    def update_ms(self, ms: float):
        size = max(1, int(RESERVOIR_SIZE))
        with self._lock:
            self.count += 1
            self._sum += ms
            self._sum2 += ms * ms
            self.min_ms = min(self.min_ms, ms)
            self.max_ms = max(self.max_ms, ms)
            # reservoir replacement is a read-modify-write on both the
            # sample list and the RNG stream: under the lock with the
            # accumulators. A shrunken RESERVOIR_SIZE push truncates,
            # or the tail indices would freeze stale samples into the
            # percentiles forever.
            if len(self._reservoir) > size:
                del self._reservoir[size:]
            if len(self._reservoir) < size:
                self._reservoir.append(ms)
            else:
                j = self._rng.randrange(self.count)
                if j < size:
                    self._reservoir[j] = ms

    def record_total(self, count: int, sum_ms: float):
        """Fold an externally-aggregated (count, sum) pair into the
        totals — the flush path of tracing's root-attributed phase
        collectors (``span.attr.*`` timers, ISSUE 8). The reservoir
        and min/max take the batch MEAN once per flush: these timers
        exist for exact count/sum attribution deltas
        (``timer_totals``), and pretending per-event resolution from
        an aggregate would fabricate percentiles."""
        n = int(count)
        if n <= 0:
            return
        mean = sum_ms / n
        size = max(1, int(RESERVOIR_SIZE))
        with self._lock:
            self.count += n
            self._sum += sum_ms
            self._sum2 += mean * mean * n
            self.min_ms = min(self.min_ms, mean)
            self.max_ms = max(self.max_ms, mean)
            if len(self._reservoir) > size:
                del self._reservoir[size:]
            if len(self._reservoir) < size:
                self._reservoir.append(mean)
            else:
                j = self._rng.randrange(self.count)
                if j < size:
                    self._reservoir[j] = mean

    def time(self):
        t0 = time.perf_counter()
        timer = self

        class _Ctx:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                timer.update_ms((time.perf_counter() - t0) * 1000.0)
                return False
        return _Ctx()

    def mean_ms(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def stddev_ms(self) -> float:
        if self.count < 2:
            return 0.0
        m = self.mean_ms()
        var = max(0.0, self._sum2 / self.count - m * m)
        return math.sqrt(var)

    def sum_ms(self) -> float:
        """Total observed time — the quantity span attribution sums
        (``batch_verifier.dispatch_attribution``)."""
        with self._lock:
            return self._sum

    def percentiles_ms(self, qs) -> List[float]:
        """Linear-interpolated percentiles (each q in [0, 100]) from
        ONE locked, sorted reservoir snapshot — exports ask for three
        quantiles at a time, and per-quantile re-sorting on a polled
        scrape path is wasted work."""
        with self._lock:
            data = sorted(self._reservoir)
        return [_interp_percentile(data, q) for q in qs]

    def percentile_ms(self, q: float) -> float:
        return self.percentiles_ms((q,))[0]

    def to_dict(self):
        # one locked snapshot: a count/sum pair torn across a
        # concurrent update_ms must not reach the export
        with self._lock:
            count = self.count
            s = self._sum
            s2 = self._sum2
            mn = self.min_ms
            mx = self.max_ms
            data = sorted(self._reservoir)
        mean = s / count if count else 0.0
        var = max(0.0, s2 / count - mean * mean) if count >= 2 else 0.0
        p50, p90, p99 = (_interp_percentile(data, q)
                         for q in (50, 90, 99))
        return {"type": "timer", "count": count,
                "min_ms": 0.0 if math.isinf(mn) else round(mn, 3),
                "mean_ms": round(mean, 3),
                "max_ms": round(mx, 3),
                "stddev_ms": round(math.sqrt(var), 3),
                "sum_ms": round(s, 3),
                "p50_ms": round(p50, 3),
                "p90_ms": round(p90, 3),
                "p99_ms": round(p99, 3)}


class Gauge:
    """Last-written value (numeric or label, e.g. a breaker state) —
    the degradation-visibility primitive: unlike a counter it answers
    "what is it NOW", which is what the info endpoint needs for
    breaker state / deadline knobs."""

    def __init__(self):
        self._lock = threading.Lock()
        self.value = None

    def set(self, value):
        with self._lock:
            self.value = value

    def to_dict(self):
        return {"type": "gauge", "value": self.value}


# Prometheus exposition-format helpers: metric names may only be
# [a-zA-Z_:][a-zA-Z0-9_:]*, so dotted registry names mangle dots (and
# any other byte) to underscores.
_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_escape(value: str) -> str:
    return (value.replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls):
        with self._lock:
            # get-or-create must be atomic: two threads racing the
            # first mark of a meter would otherwise each create one,
            # and whichever registers second silently eats the other's
            # counts
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls()
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def to_dict(self) -> dict:
        return self.find("")

    def find(self, prefix: str) -> dict:
        """Rendered snapshot of every metric whose dotted name starts
        with ``prefix`` (``""`` = the whole registry — ``to_dict``) —
        the subsystem-scoped export behind the soak harness's
        accounting cross-check (``tools/soak.py`` proves the verify
        service's conservation counters against the
        ``crypto.verify.service.*`` meters) and ad-hoc admin queries.
        The name walk snapshots under the registry lock (iterating the
        live dict while a first-mark thread inserts would raise on the
        metrics endpoint); rendering happens outside it, on the
        per-metric locks."""
        with self._lock:
            items = sorted((name, m) for name, m in
                           self._metrics.items()
                           if name.startswith(prefix))
        return {name: m.to_dict() for name, m in items}

    def timer_totals(self) -> Dict[str, dict]:
        """``{name: {"count", "sum_ms"}}`` for every timer — the cheap
        accessor behind ``tracing.span_totals()``: no reservoir sorts,
        no meter/gauge rendering, just the two fields attribution
        deltas need."""
        with self._lock:
            items = list(self._metrics.items())
        return {name: {"count": m.count, "sum_ms": m.sum_ms()}
                for name, m in items if isinstance(m, Timer)}

    def to_prometheus(self) -> str:
        """Prometheus text exposition (format 0.0.4) of the whole
        registry — the ``metrics?format=prometheus`` admin surface
        (the reference serves its medida registry over HTTP the same
        way, ``docs/metrics.md``). Counters export as counters, meters
        as a ``_total`` counter + ``_rate`` gauge, timers as summaries
        (quantile-labeled samples + ``_sum``/``_count``), gauges as
        gauges (non-numeric values become a ``value`` label)."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            base = _prom_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {base} counter")
                lines.append(f"{base} {m.count}")
            elif isinstance(m, Meter):
                lines.append(f"# TYPE {base}_total counter")
                lines.append(f"{base}_total {m.count}")
                lines.append(f"# TYPE {base}_rate gauge")
                lines.append(f"{base}_rate {m.windowed_rate():.6f}")
            elif isinstance(m, Timer):
                lines.append(f"# TYPE {base}_ms summary")
                for q, v in zip((50, 90, 99),
                                m.percentiles_ms((50, 90, 99))):
                    lines.append(
                        f'{base}_ms{{quantile="{q / 100}"}} {v:.6f}')
                lines.append(f"{base}_ms_sum {m.sum_ms():.6f}")
                lines.append(f"{base}_ms_count {m.count}")
            elif isinstance(m, Gauge):
                v = m.value
                lines.append(f"# TYPE {base} gauge")
                if isinstance(v, bool):
                    lines.append(f"{base} {int(v)}")
                elif isinstance(v, (int, float)) and not (
                        isinstance(v, float) and math.isnan(v)):
                    lines.append(f"{base} {v}")
                elif v is None:
                    lines.append(f'{base}{{value="none"}} 1')
                else:
                    lines.append(
                        f'{base}{{value="{_prom_label_escape(str(v))}"'
                        f"}} 1")
        return "\n".join(lines) + "\n"

    def clear(self):
        with self._lock:
            self._metrics.clear()


# process-wide registry (the reference's per-app medida registry; one
# node per process in production)
registry = MetricsRegistry()
