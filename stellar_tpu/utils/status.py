"""Human-readable node status lines by category (reference
``src/util/StatusManager.h``: per-category message set/cleared by the
owning subsystem, surfaced in the ``info`` admin response)."""

from __future__ import annotations

from typing import Dict, List

__all__ = ["StatusManager", "StatusCategory"]


class StatusCategory:
    HISTORY_CATCHUP = "history-catchup"
    HISTORY_PUBLISH = "history-publish"
    REQUIRES_UPGRADES = "requires-upgrades"
    # verify dispatch degraded: breaker open/half-open, signatures
    # served by the host oracle (set/cleared via Application.info)
    VERIFY_DEVICE = "verify-device"
    # (reference also has NTP; no time-sync subsystem here)


class StatusManager:
    def __init__(self):
        self._messages: Dict[str, str] = {}

    def set_status(self, category: str, message: str) -> None:
        self._messages[category] = message

    def remove_status(self, category: str) -> None:
        self._messages.pop(category, None)

    def get_status(self, category: str) -> str:
        return self._messages.get(category, "")

    def status_lines(self) -> List[str]:
        """Insertion-ordered status messages (the info payload form)."""
        return [m for m in self._messages.values() if m]
