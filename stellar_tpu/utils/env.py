"""Shared environment-knob parsing.

ONE env-bool rule for the opt-in ``VERIFY_*`` flags
(``VERIFY_CONTROL_ENABLED``, ``VERIFY_TENANT_FROM_PEER``, ...), so
two knobs can never parse the same string differently."""

from __future__ import annotations

import os

__all__ = ["env_true"]


def env_true(name: str, default: str = "0") -> bool:
    """Truthy is EXPLICIT — an unrecognized value ("off", "disabled",
    a typo) leaves an opt-in feature OFF rather than silently
    enabling it."""
    return os.environ.get(name, default).strip().lower() in (
        "1", "true", "yes", "on")
