"""Partitioned logging.

The reference's spdlog setup has 13 compile-time partitions
(``src/util/LogPartitions.def``) each independently leveled at runtime via
the ``ll`` admin endpoint. Same model here on top of :mod:`logging`:
``get_logger(partition)`` and ``set_log_level(partition_or_None, level)``.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
]

_configured = False


def _configure():
    global _configured
    if _configured:
        return
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s [%(name)s %(levelname)s] %(message)s"))
    root = logging.getLogger("stellar_tpu")
    root.addHandler(h)
    root.setLevel(logging.WARNING)
    root.propagate = False
    _configured = True


def get_logger(partition: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"stellar_tpu.{partition}")


def set_log_level(partition: Optional[str], level) -> None:
    """``partition=None`` sets every partition (the ``ll`` endpoint
    semantics)."""
    _configure()
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    if partition is None:
        logging.getLogger("stellar_tpu").setLevel(level)
        for p in PARTITIONS:
            logging.getLogger(f"stellar_tpu.{p}").setLevel(level)
    else:
        logging.getLogger(f"stellar_tpu.{partition}").setLevel(level)
