"""Partitioned logging.

The reference's spdlog setup has 13 compile-time partitions
(``src/util/LogPartitions.def``) each independently leveled at runtime via
the ``ll`` admin endpoint. Same model here on top of :mod:`logging`:
``get_logger(partition)`` and ``set_log_level(partition_or_None, level)``.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
]

_configured = False


def _configure():
    global _configured
    if _configured:
        return
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s [%(name)s %(levelname)s] %(message)s"))
    root = logging.getLogger("stellar_tpu")
    root.addHandler(h)
    root.setLevel(logging.WARNING)
    root.propagate = False
    _configured = True


_COLORS = {"DEBUG": "\x1b[36m", "INFO": "\x1b[32m",
           "WARNING": "\x1b[33m", "ERROR": "\x1b[31m",
           "CRITICAL": "\x1b[35m"}


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        out = super().format(record)
        c = _COLORS.get(record.levelname)
        return f"{c}{out}\x1b[0m" if c else out


def set_log_color(enabled: bool) -> None:
    """Colorized console output (reference LOG_COLOR)."""
    _configure()
    fmt = "%(asctime)s [%(name)s %(levelname)s] %(message)s"
    for h in logging.getLogger("stellar_tpu").handlers:
        if isinstance(h, logging.StreamHandler) and \
                not isinstance(h, logging.FileHandler):
            h.setFormatter(_ColorFormatter(fmt) if enabled
                           else logging.Formatter(fmt))


def get_logger(partition: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"stellar_tpu.{partition}")


def set_log_level(partition: Optional[str], level) -> None:
    """``partition=None`` sets every partition (the ``ll`` endpoint
    semantics)."""
    _configure()
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    if partition is None:
        logging.getLogger("stellar_tpu").setLevel(level)
        for p in PARTITIONS:
            logging.getLogger(f"stellar_tpu.{p}").setLevel(level)
    else:
        logging.getLogger(f"stellar_tpu.{partition}").setLevel(level)


def append_jsonl_capped(path: str, rec: dict,
                        max_bytes: int = 4_000_000,
                        keep: int = 1) -> None:
    """Size-bounded JSONL append with rotation: when ``path`` would
    grow past ``max_bytes``, shift ``path`` → ``path.1`` → ... →
    ``path.<keep>`` (the oldest generation is dropped) before
    appending. Evidence streams written by unattended daemons
    (``DEVICE_PROBES.jsonl`` from ``tools/device_watch.py``) keep the
    recent history without ever filling the disk."""
    import json
    import os
    line = json.dumps(rec) + "\n"
    try:
        size = os.path.getsize(path)
    except OSError:
        size = 0
    if size and size + len(line) > max_bytes:
        for g in range(keep, 0, -1):
            src = path if g == 1 else f"{path}.{g - 1}"
            try:
                os.replace(src, f"{path}.{g}")
            except OSError:
                pass  # missing generation: nothing to shift
    with open(path, "a") as f:
        f.write(line)
