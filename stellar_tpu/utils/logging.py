"""Partitioned logging.

The reference's spdlog setup has 13 compile-time partitions
(``src/util/LogPartitions.def``) each independently leveled at runtime via
the ``ll`` admin endpoint. Same model here on top of :mod:`logging`:
``get_logger(partition)`` and ``set_log_level(partition_or_None, level)``.
"""

from __future__ import annotations

import logging
import sys
from typing import Optional

PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
]

_configured = False


def _configure():
    global _configured
    if _configured:
        return
    h = logging.StreamHandler(sys.stderr)
    h.setFormatter(logging.Formatter(
        "%(asctime)s [%(name)s %(levelname)s] %(message)s"))
    root = logging.getLogger("stellar_tpu")
    root.addHandler(h)
    root.setLevel(logging.WARNING)
    root.propagate = False
    _configured = True


_COLORS = {"DEBUG": "\x1b[36m", "INFO": "\x1b[32m",
           "WARNING": "\x1b[33m", "ERROR": "\x1b[31m",
           "CRITICAL": "\x1b[35m"}


class _ColorFormatter(logging.Formatter):
    def format(self, record):
        out = super().format(record)
        c = _COLORS.get(record.levelname)
        return f"{c}{out}\x1b[0m" if c else out


def set_log_color(enabled: bool) -> None:
    """Colorized console output (reference LOG_COLOR)."""
    _configure()
    fmt = "%(asctime)s [%(name)s %(levelname)s] %(message)s"
    for h in logging.getLogger("stellar_tpu").handlers:
        if isinstance(h, logging.StreamHandler) and \
                not isinstance(h, logging.FileHandler):
            h.setFormatter(_ColorFormatter(fmt) if enabled
                           else logging.Formatter(fmt))


def get_logger(partition: str) -> logging.Logger:
    _configure()
    return logging.getLogger(f"stellar_tpu.{partition}")


def set_log_level(partition: Optional[str], level) -> None:
    """``partition=None`` sets every partition (the ``ll`` endpoint
    semantics)."""
    _configure()
    if isinstance(level, str):
        level = getattr(logging, level.upper())
    if partition is None:
        logging.getLogger("stellar_tpu").setLevel(level)
        for p in PARTITIONS:
            logging.getLogger(f"stellar_tpu.{p}").setLevel(level)
    else:
        logging.getLogger(f"stellar_tpu.{partition}").setLevel(level)
