"""VirtualClock + VirtualTimer: the node's deterministic event loop core.

Re-creates the reference's ``src/util/Timer.h:66-217`` semantics:

  * ``VirtualClock`` owns *the* time source for a node, in one of two
    modes — REAL_TIME (wall clock) or VIRTUAL_TIME (time advances only
    when the event loop is idle, jumping straight to the next scheduled
    event).  VIRTUAL_TIME is what makes multi-node consensus tests
    deterministic and fast.
  * ``VirtualTimer`` schedules callbacks at a time point; cancellation
    invokes handlers with ``cancelled=True`` (asio error_code style).
  * ``crank(block=False)`` runs due timers + queued actions; returns the
    number of work items performed.
  * ``post_to_main`` / ``post_action`` enqueue callables, mirroring
    ``postOnMainThread`` + the Scheduler action queues.

Single-threaded consensus discipline: everything posted here runs on
whichever thread cranks the clock, one item at a time — the structural
concurrency model of the reference (``docs/architecture.md:24-27``).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time as _time
from typing import Callable, List, Optional

from stellar_tpu.utils.scheduler import ActionType, Scheduler

__all__ = ["VirtualClock", "VirtualTimer", "REAL_TIME", "VIRTUAL_TIME"]

REAL_TIME = "REAL_TIME"
VIRTUAL_TIME = "VIRTUAL_TIME"


class _Event:
    __slots__ = ("when", "seq", "callback", "cancelled")

    def __init__(self, when: float, seq: int, callback):
        self.when = when
        self.seq = seq
        self.callback = callback
        self.cancelled = False

    def __lt__(self, other):
        return (self.when, self.seq) < (other.when, other.seq)


class VirtualClock:
    def __init__(self, mode: str = VIRTUAL_TIME):
        if mode not in (REAL_TIME, VIRTUAL_TIME):
            raise ValueError(f"bad clock mode {mode}")
        self.mode = mode
        self._virtual_now = 0.0
        self._real_base = _time.monotonic()
        self._events: List[_Event] = []
        self._seq = itertools.count()
        self._scheduler = Scheduler(self)
        self._lock = threading.Lock()          # guards cross-thread posts
        self._main_queue: List[Callable] = []  # post_to_main from any thread
        self._main_thread = threading.current_thread()
        self._stopped = False

    # ---- time ----

    def now(self) -> float:
        """Seconds since clock epoch (monotonic)."""
        if self.mode == REAL_TIME:
            return _time.monotonic() - self._real_base
        return self._virtual_now

    def system_now(self) -> int:
        """Wall-clock seconds (close times). In VIRTUAL_TIME this is the
        virtual offset applied to a fixed epoch so tests are reproducible."""
        if self.mode == REAL_TIME:
            return int(_time.time())
        return int(VirtualClock.VIRTUAL_EPOCH + self._virtual_now)

    # Fixed epoch for virtual wall time: 2025-01-01T00:00:00Z.
    VIRTUAL_EPOCH = 1735689600

    def set_current_virtual_time(self, t: float):
        if self.mode != VIRTUAL_TIME:
            raise RuntimeError("not a virtual clock")
        if t < self._virtual_now:
            raise RuntimeError("virtual time cannot go backwards")
        self._virtual_now = t

    def sleep_for(self, seconds: float):
        """Advance time by cranking (virtual) or sleeping (real)."""
        deadline = self.now() + seconds
        while self.now() < deadline and not self._stopped:
            if self.crank(block=False) == 0:
                if self.mode == VIRTUAL_TIME:
                    nxt = self._next_event_time()
                    self._virtual_now = (min(nxt, deadline)
                                         if nxt is not None else deadline)
                else:
                    # a slow crank can overrun the deadline between the
                    # loop check and here; never sleep a negative span
                    _time.sleep(max(0.0, min(0.001,
                                             deadline - self.now())))

    # ---- event scheduling ----

    def _enqueue(self, ev: _Event):
        heapq.heappush(self._events, ev)

    def _next_event_time(self) -> Optional[float]:
        while self._events and self._events[0].cancelled:
            heapq.heappop(self._events)
        return self._events[0].when if self._events else None

    def post_to_main(self, fn: Callable, name: str = "main",
                     action_type: ActionType = ActionType.NORMAL):
        """Thread-safe enqueue onto the cranking thread (reference
        ``postOnMainThread``)."""
        if threading.current_thread() is self._main_thread:
            self._scheduler.enqueue(name, fn, action_type)
        else:
            with self._lock:
                self._main_queue.append((name, fn, action_type))

    def post_action(self, fn: Callable, name: str = "action",
                    action_type: ActionType = ActionType.NORMAL):
        self._scheduler.enqueue(name, fn, action_type)

    @property
    def scheduler(self) -> Scheduler:
        return self._scheduler

    def is_stopped(self) -> bool:
        return self._stopped

    def stop(self):
        self._stopped = True

    # ---- the crank ----

    def _drain_cross_thread(self):
        with self._lock:
            pending, self._main_queue = self._main_queue, []
        for name, fn, at in pending:
            self._scheduler.enqueue(name, fn, at)

    def crank(self, block: bool = False) -> int:
        """Run one batch of due work; the reference's
        ``VirtualClock::crank`` (``Timer.h:193``). Returns #items run."""
        if self._stopped:
            return 0
        progress = 0
        self._drain_cross_thread()
        # 1. fire due timers
        now = self.now()
        while self._events and self._events[0].when <= now:
            ev = heapq.heappop(self._events)
            if not ev.cancelled:
                ev.callback(False)
                progress += 1
        # 2. run queued actions (bounded batch for fairness with timers)
        progress += self._scheduler.run_some(max_items=64)
        if progress == 0 and block:
            if self.mode == VIRTUAL_TIME:
                nxt = self._next_event_time()
                if nxt is not None:
                    self._virtual_now = max(self._virtual_now, nxt)
                    return self.crank(block=False)
            else:
                nxt = self._next_event_time()
                wait = 0.001 if nxt is None else max(0.0, min(nxt - now, 0.05))
                _time.sleep(wait)
                return self.crank(block=False)
        return progress

    def crank_until(self, pred: Callable[[], bool], timeout: float) -> bool:
        """Crank until pred() or ``timeout`` (clock-relative) elapses."""
        deadline = self.now() + timeout
        while not pred():
            if self.now() >= deadline or self._stopped:
                return pred()
            if self.crank(block=True) == 0 and self.mode == VIRTUAL_TIME \
                    and self._next_event_time() is None \
                    and self._scheduler.size() == 0:
                return pred()  # fully idle virtual clock: nothing will change
        return True


class VirtualTimer:
    """One-shot timer bound to a VirtualClock (``Timer.h:222``).
    ``cancel`` fires the cancel handler of **every** pending wait, like
    the reference's asio timer cancellation."""

    def __init__(self, clock: VirtualClock):
        self._clock = clock
        self._events: List[_Event] = []
        self._when: Optional[float] = None

    def expires_at(self, when: float):
        self.cancel()
        self._when = when

    def expires_from_now(self, seconds: float):
        self.expires_at(self._clock.now() + seconds)

    def async_wait(self, on_fire: Callable[[], None],
                   on_cancel: Optional[Callable[[], None]] = None):
        if self._when is None:
            raise RuntimeError("async_wait before expires_at/from_now")

        def handler(cancelled: bool):
            if cancelled:
                if on_cancel is not None:
                    on_cancel()
            else:
                on_fire()
        ev = _Event(self._when, next(self._clock._seq), handler)
        self._events.append(ev)
        self._clock._enqueue(ev)

    def cancel(self):
        pending, self._events = self._events, []
        self._when = None
        for ev in pending:
            if not ev.cancelled:
                ev.cancelled = True
                ev.callback(True)

    def seconds_remaining(self) -> float:
        live = [ev.when for ev in self._events if not ev.cancelled]
        if not live:
            return 0.0
        return max(0.0, min(live) - self._clock.now())
