"""RandomEvictionCache: fixed-size map with uniform-random eviction.

Same contract as the reference's ``src/util/RandomEvictionCache.h`` (used
for the 0xffff-entry signature-verify cache, ``crypto/SecretKey.cpp:44-48``):
O(1) put/get/exists, evicts a uniformly random resident entry when full,
and tracks hit/miss counters for metrics export.
"""

from __future__ import annotations

import random
from typing import Any, Dict, Generic, List, Optional, TypeVar

K = TypeVar("K")
V = TypeVar("V")

__all__ = ["RandomEvictionCache"]


class RandomEvictionCache(Generic[K, V]):
    __slots__ = ("_max", "_map", "_keys", "_pos", "_rng", "hits", "misses")

    def __init__(self, max_size: int, rng: Optional[random.Random] = None):
        if max_size <= 0:
            raise ValueError("max_size must be positive")
        self._max = max_size
        self._map: Dict[K, V] = {}
        self._keys: List[K] = []        # dense array for O(1) random pick
        self._pos: Dict[K, int] = {}    # key -> index in _keys
        self._rng = rng or random.Random()
        self.hits = 0
        self.misses = 0

    def __len__(self) -> int:
        return len(self._map)

    def max_size(self) -> int:
        return self._max

    def put(self, key: K, value: V) -> None:
        if key in self._map:
            self._map[key] = value
            return
        if len(self._map) >= self._max:
            self._evict_one()
        self._map[key] = value
        self._pos[key] = len(self._keys)
        self._keys.append(key)

    def _evict_one(self) -> None:
        i = self._rng.randrange(len(self._keys))
        victim = self._keys[i]
        last = self._keys[-1]
        self._keys[i] = last
        self._pos[last] = i
        self._keys.pop()
        del self._pos[victim]
        del self._map[victim]

    def exists(self, key: K, count_stats: bool = True) -> bool:
        ok = key in self._map
        if count_stats:
            if ok:
                self.hits += 1
            else:
                self.misses += 1
        return ok

    def get(self, key: K) -> V:
        """Counts a hit/miss like the reference's maybeGet+get pairing."""
        if key not in self._map:
            self.misses += 1
            raise KeyError(key)
        self.hits += 1
        return self._map[key]

    def maybe_get(self, key: K) -> Optional[V]:
        if key in self._map:
            self.hits += 1
            return self._map[key]
        self.misses += 1
        return None

    def erase_if(self, pred) -> None:
        doomed = [k for k in self._keys if pred(self._map[k])]
        for k in doomed:
            i = self._pos[k]
            last = self._keys[-1]
            self._keys[i] = last
            self._pos[last] = i
            self._keys.pop()
            del self._pos[k]
            del self._map[k]

    def clear(self) -> None:
        self._map.clear()
        self._keys.clear()
        self._pos.clear()
