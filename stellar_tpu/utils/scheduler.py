"""Fair multi-queue action scheduler with load shedding.

The reference's ``src/util/Scheduler.h:20-121``: actions are enqueued into
named queues; queues are serviced in least-recently-serviced order
(approximate fairness); DROPPABLE actions are shed when the scheduler's
aggregate queue latency exceeds a threshold. The reference uses this to
keep consensus responsive under overlay flood load.
"""

from __future__ import annotations

import enum
import time as _time
from collections import deque
from typing import Callable, Deque, Dict, Tuple

__all__ = ["ActionType", "Scheduler"]


class ActionType(enum.Enum):
    NORMAL = 0
    DROPPABLE = 1


class _Queue:
    __slots__ = ("name", "items", "last_service", "total_service_time")

    def __init__(self, name: str):
        self.name = name
        self.items: Deque[Tuple[Callable, ActionType, float]] = deque()
        self.last_service = 0.0
        self.total_service_time = 0.0


class Scheduler:
    # Shed DROPPABLE work when the oldest queued action has waited longer
    # than this many (clock) seconds — the reference's latency window.
    LATENCY_WINDOW = 5.0

    def __init__(self, clock=None):
        self._clock = clock
        self._queues: Dict[str, _Queue] = {}
        self._size = 0
        self.actions_run = 0
        self.actions_dropped = 0

    def _now(self) -> float:
        return self._clock.now() if self._clock is not None \
            else _time.monotonic()

    def enqueue(self, name: str, fn: Callable,
                action_type: ActionType = ActionType.NORMAL):
        q = self._queues.get(name)
        if q is None:
            q = self._queues[name] = _Queue(name)
        now = self._now()
        if action_type is ActionType.DROPPABLE and self._overloaded(now):
            self.actions_dropped += 1
            return
        q.items.append((fn, action_type, now))
        self._size += 1

    def _overloaded(self, now: float) -> bool:
        oldest = None
        for q in self._queues.values():
            if q.items:
                t = q.items[0][2]
                oldest = t if oldest is None else min(oldest, t)
        return oldest is not None and (now - oldest) > self.LATENCY_WINDOW

    def size(self) -> int:
        return self._size

    def queue_sizes(self) -> Dict[str, int]:
        return {n: len(q.items) for n, q in self._queues.items() if q.items}

    def run_one(self) -> bool:
        """Service the least-recently-serviced non-empty queue."""
        best = None
        for q in self._queues.values():
            if q.items and (best is None
                            or q.last_service < best.last_service):
                best = q
        if best is None:
            return False
        fn, action_type, enq_time = best.items.popleft()
        self._size -= 1
        now = self._now()
        best.last_service = now
        if action_type is ActionType.DROPPABLE and \
                (now - enq_time) > self.LATENCY_WINDOW:
            self.actions_dropped += 1
            return True
        fn()
        self.actions_run += 1
        best.total_service_time += self._now() - now
        return True

    def run_some(self, max_items: int = 64) -> int:
        n = 0
        while n < max_items and self.run_one():
            n += 1
        return n

    def stats(self) -> dict:
        return {"run": self.actions_run, "dropped": self.actions_dropped,
                "queued": self._size}
