"""Pipeline-bubble profiler: per-device busy/idle timelines per
resolve (ISSUE 10).

The ROADMAP's #1 perf lever — dispatch-floor demolition — prescribes
overlapping host prep with in-flight device work and coalescing
per-device dispatches, but nothing could *measure* overlap: spans
attribute where time went inside one blocking resolve (ISSUE 5) and
the transfer ledger counts round trips and bytes (ISSUE 8), yet device
idle gaps between dispatches, the host/device concurrency fraction,
and bubble attribution were all invisible. This module is the
instrument: the batch engine (:mod:`stellar_tpu.parallel.batch_engine`)
stamps every committed dispatch and every delivery point here (the
same single-delivery-point discipline as the transfer ledger), plus
the host-side work intervals (prep/bucket, blocking fetch, audit,
host fallback), and each resolve yields

* per-device **busy intervals** — ``[dispatch commit, delivery]``:
  the window the host has work in flight on that device. This is
  pipeline occupancy as the HOST sees it (it includes on-device queue
  time), which is exactly the quantity async dispatch must maximize;
* **bubbles** — the per-device idle gaps inside the resolve wall,
  each attributed to a class by what the host was doing during the
  gap: ``prep`` (host was encoding/padding), ``fetch`` (host was
  parked on another device's result), ``audit`` / ``host_fallback``
  (host re-computation), ``queue_wait`` (the unattributed part of the
  lead gap before the device's FIRST dispatch — e.g. an injected
  inter-dispatch stall delaying its kernel call), and ``gap``
  (unattributed idle after the first dispatch — a pure scheduling
  hole);
* ``busy_frac`` = Σ busy / (n_devices × wall), ``overlap_frac`` =
  host-prep time concurrent with in-flight device work / total prep
  (the async-dispatch before/after number: 0.0 for today's
  prep-then-dispatch engine), and a ``reconciliation`` ratio
  (busy + attributed bubbles over device-wall — the self-check
  quantity tier-1's ``PIPELINE_OBS_OK`` gate pins ≥ 95% against an
  independently measured wall clock).

Records land in a bounded per-resolve ring
(``PIPELINE_TIMELINE_RESOLVES``) plus running process totals, surfaced
by the ``pipeline`` admin route, the ``crypto.pipeline.*`` metrics,
Chrome-trace counter tracks
(:meth:`stellar_tpu.utils.tracing.FlightRecorder.to_chrome_trace`),
and every bench record's ``pipeline`` section (sentinel-gated). See
``docs/observability.md`` §9.

Timestamps share the span clock (:func:`stellar_tpu.utils.tracing.
_now_ms` — monotonic ms since tracing import), so a chrome://tracing
load shows spans and utilization counters on one time axis. The
engine-facing API is **duration-blind** (tokens + context managers,
stamps taken internally), same policy as the tracing fence: the
engine sits in the nondet-lint scope and must never read a clock
value from here. All shared state mutates under the instance lock
(lock-lint scope)."""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional

from stellar_tpu.utils import tracing
from stellar_tpu.utils.metrics import registry

__all__ = ["PipelineTimeline", "ResolveTimeline", "pipeline_timeline",
           "BUBBLE_CLASSES", "HOST_KINDS"]

_NS = "crypto.pipeline"

# defaults; Config pushes PIPELINE_TIMELINE_RESOLVES through configure()
DEFAULT_RESOLVES = 256

# host work-interval kinds the engine records, in gap-attribution
# priority order: a gap overlapping a prep interval is a prep bubble
# before anything else (the host was demonstrably busy encoding)
HOST_KINDS = ("prep", "fetch", "audit", "host_fallback")
# every bubble class a record reports (zero-ms classes included, so a
# consumer never key-errors on a clean resolve)
BUBBLE_CLASSES = ("queue_wait", "prep", "fetch", "audit",
                  "host_fallback", "gap")

# per-device busy-interval retention inside one record (chrome counter
# export); beyond the cap only the aggregate survives — the cap is
# recorded in the record (`intervals_capped`), never silent
MAX_INTERVALS_PER_DEVICE = 64


def _merge(intervals: List[List[float]]) -> List[List[float]]:
    """Union of possibly-overlapping [t0, t1] intervals (a survivor
    device serving several re-sharded sub-chunks has overlapping
    in-flight windows)."""
    out: List[List[float]] = []
    for t0, t1 in sorted(intervals):
        if out and t0 <= out[-1][1]:
            out[-1][1] = max(out[-1][1], t1)
        else:
            out.append([t0, t1])
    return out


def _overlap_ms(seg0: float, seg1: float,
                intervals: List[List[float]]) -> float:
    """Total overlap of [seg0, seg1] with a sorted interval list."""
    total = 0.0
    for t0, t1 in intervals:
        lo = max(seg0, t0)
        hi = min(seg1, t1)
        if hi > lo:
            total += hi - lo
    return total


def _subtract(segments: List[List[float]],
              intervals: List[List[float]]) -> List[List[float]]:
    """Remove ``intervals`` from ``segments`` (both sorted, merged)."""
    out: List[List[float]] = []
    for s0, s1 in segments:
        cur = s0
        for t0, t1 in intervals:
            if t1 <= cur or t0 >= s1:
                continue
            if t0 > cur:
                out.append([cur, t0])
            cur = max(cur, t1)
            if cur >= s1:
                break
        if cur < s1:
            out.append([cur, s1])
    return out


class ResolveTimeline:
    """Accumulator for ONE resolve's pipeline events (opaque token:
    the engine threads it through dispatch and fetch closures; all
    fields mutate under the owning profiler's lock)."""

    __slots__ = ("ns", "t0", "host", "open_parts", "parts",
                 "delivered", "finished")

    def __init__(self, ns: str, t0: float):
        self.ns = ns
        self.t0 = t0
        # host work intervals: (kind, t0, t1)
        self.host: List[tuple] = []
        # device -> FIFO of open dispatch stamps (a device can hold
        # several in-flight sub-chunks under degraded re-shard)
        self.open_parts: Dict[int, List[float]] = {}
        # closed busy intervals: (device, t_dispatch, t_close, ok)
        self.parts: List[tuple] = []
        self.delivered = 0
        self.finished = False


class _HostPhase:
    """Duration-blind context manager for one host work interval —
    the engine never sees a clock value (nondet fence policy)."""

    __slots__ = ("_pl", "_tok", "_kind", "_t0")

    def __init__(self, pl: "PipelineTimeline",
                 tok: Optional[ResolveTimeline], kind: str):
        self._pl = pl
        self._tok = tok
        self._kind = kind

    def __enter__(self):
        self._t0 = self._pl._now()
        return self

    def __exit__(self, *exc):
        if self._tok is not None:
            self._pl._record_host(self._tok, self._kind, self._t0,
                                  self._pl._now())
        return False


class PipelineTimeline:
    """Process-wide pipeline profiler: running totals + a bounded ring
    of per-resolve busy/bubble records."""

    def __init__(self, resolves: int = DEFAULT_RESOLVES):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(4, int(resolves)))
        self._resolves = 0
        self._device_wall_ms = 0.0
        self._busy_ms = 0.0
        self._prep_ms = 0.0
        self._overlap_ms = 0.0
        self._bubble_ms = {c: 0.0 for c in BUBBLE_CLASSES}
        self._bubble_count = 0
        self._largest_bubble_ms = 0.0
        self._largest_bubble_class: Optional[str] = None
        self._parts = 0
        self._delivered = 0

    # the one clock read site — tests monkeypatch this for scripted
    # timelines; production shares the span clock so chrome tracks and
    # B/E spans land on one axis
    def _now(self) -> float:
        return tracing._now_ms()

    def configure(self, resolves: Optional[int] = None) -> None:
        """Config push (PIPELINE_TIMELINE_RESOLVES); None keeps
        current."""
        if resolves is None:
            return
        cap = max(4, int(resolves))
        with self._lock:
            if cap != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=cap)

    # ---------------- per-resolve recording ----------------

    def begin(self, ns: str) -> ResolveTimeline:
        """Open a per-resolve token (not registered anywhere until
        :meth:`finish` — a resolver the caller drops is just
        garbage-collected)."""
        return ResolveTimeline(ns, self._now())

    def host_phase(self, tok: Optional[ResolveTimeline],
                   kind: str) -> _HostPhase:
        """``with pipeline_timeline.host_phase(tok, "prep"): ...`` —
        record one host work interval (duration-blind for the
        caller)."""
        return _HostPhase(self, tok, kind)

    def _record_host(self, tok: ResolveTimeline, kind: str,
                     t0: float, t1: float) -> None:
        with self._lock:
            if not tok.finished:
                tok.host.append((kind, t0, t1))

    def note_dispatch(self, tok: Optional[ResolveTimeline],
                      device: Optional[int]) -> None:
        """One committed kernel call on ``device`` (None = the
        single-device path) — opens a busy interval."""
        if tok is None:
            return
        t = self._now()
        d = -1 if device is None else int(device)
        with self._lock:
            if not tok.finished:
                tok.open_parts.setdefault(d, []).append(t)

    def note_delivery(self, tok: Optional[ResolveTimeline],
                      device: Optional[int],
                      delivered: bool = True) -> None:
        """The engine stopped waiting on one of ``device``'s in-flight
        parts: a result was ACCEPTED at the single delivery point
        (``delivered=True``) or the part failed/was abandoned
        (deadline miss, fetch exception, breaker short-circuit of an
        already-dispatched part). Closes the OLDEST open interval —
        FIFO, matching the engine's in-order part walk."""
        if tok is None:
            return
        t = self._now()
        d = -1 if device is None else int(device)
        with self._lock:
            if tok.finished:
                return
            stamps = tok.open_parts.get(d)
            if not stamps:
                return
            t0 = stamps.pop(0)
            tok.parts.append((d, t0, t, delivered))
            if delivered:
                tok.delivered += 1

    def finish(self, tok: Optional[ResolveTimeline],
               transfer: Optional[dict] = None) -> Optional[dict]:
        """Close a resolve's token: reconstruct the per-device
        timeline, classify bubbles, fold into totals + metrics, and
        append the record to the ring (idempotent — a resolver
        resolved twice records once). ``transfer`` is the resolve's
        transfer-ledger record, embedded so one ring entry carries
        bytes AND utilization (the chrome counter tracks read both)."""
        if tok is None:
            return None
        t_end = self._now()
        with self._lock:
            if tok.finished:
                return None
            tok.finished = True
            # abandoned in-flight parts (resolver dropped mid-fetch):
            # closed at the resolve end, never delivered
            for d, stamps in tok.open_parts.items():
                for t0 in stamps:
                    tok.parts.append((d, t0, t_end, False))
            tok.open_parts.clear()
            rec = self._build_record_locked(tok, t_end, transfer)
            self._ring.append(rec)
            self._resolves += 1
            self._parts += rec["parts"]
            self._delivered += rec["delivered"]
            self._prep_ms += rec["prep_ms"]
            if rec["n_devices"]:
                self._device_wall_ms += rec["device_wall_ms"]
                self._busy_ms += rec["busy_ms"]
                self._overlap_ms += rec["overlap_ms"]
                for c in BUBBLE_CLASSES:
                    self._bubble_ms[c] += rec["bubbles"][c]
                self._bubble_count += rec["bubble_count"]
                if rec["largest_bubble_ms"] > self._largest_bubble_ms:
                    self._largest_bubble_ms = rec["largest_bubble_ms"]
                    self._largest_bubble_class = \
                        rec["largest_bubble_class"]
            bubbles = rec["gap_list"]
        # metrics OUTSIDE the profiler lock (the registry locks itself)
        registry.counter(f"{_NS}.resolves").inc()
        if rec["n_devices"]:
            registry.gauge(f"{_NS}.busy_frac").set(rec["busy_frac"])
            if rec["overlap_frac"] is not None:
                registry.gauge(f"{_NS}.overlap_frac").set(
                    rec["overlap_frac"])
            registry.counter(f"{_NS}.bubbles").inc(rec["bubble_count"])
            for cls, ms in bubbles:
                registry.timer(f"{_NS}.bubble_ms").update_ms(ms)
                registry.timer(f"{_NS}.bubble.{cls}").update_ms(ms)
        return rec

    def _build_record_locked(self, tok: ResolveTimeline, t_end: float,
                             transfer: Optional[dict]) -> dict:
        wall = max(0.0, t_end - tok.t0)
        host_by_kind = {k: _merge([[t0, t1] for kind, t0, t1 in tok.host
                                   if kind == k])
                        for k in HOST_KINDS}
        prep_ms = sum(t1 - t0 for t0, t1 in host_by_kind["prep"])
        by_dev: Dict[int, List[List[float]]] = {}
        for d, t0, t1, _ok in tok.parts:
            by_dev.setdefault(d, []).append([t0, t1])
        all_busy = _merge([iv for ivs in by_dev.values() for iv in ivs])
        overlap = sum(_overlap_ms(t0, t1, all_busy)
                      for t0, t1 in host_by_kind["prep"])
        devices = {}
        busy_total = 0.0
        bubbles_total = {c: 0.0 for c in BUBBLE_CLASSES}
        gap_list: List[tuple] = []   # (class, ms) per attributed gap
        bubble_count = 0
        largest = 0.0
        largest_class: Optional[str] = None
        capped = False
        for d in sorted(by_dev):
            merged = _merge(by_dev[d])
            busy = sum(t1 - t0 for t0, t1 in merged)
            busy_total += busy
            first_dispatch = merged[0][0]
            # the complement of busy within [t0, t_end] — the bubbles
            gaps = _subtract([[tok.t0, t_end]], merged)
            dev_bubbles = {c: 0.0 for c in BUBBLE_CLASSES}
            dev_largest = 0.0
            dev_largest_class = None
            for g0, g1 in gaps:
                segs = [[g0, g1]]
                attributed: List[tuple] = []
                for kind in HOST_KINDS:
                    ivs = host_by_kind[kind]
                    if not ivs:
                        continue
                    covered = sum(_overlap_ms(s0, s1, ivs)
                                  for s0, s1 in segs)
                    if covered > 0.0:
                        attributed.append((kind, covered))
                        segs = _subtract(segs, ivs)
                rest = sum(s1 - s0 for s0, s1 in segs)
                if rest > 0.0:
                    rest_cls = "queue_wait" if g0 < first_dispatch \
                        else "gap"
                    attributed.append((rest_cls, rest))
                for cls, ms in attributed:
                    dev_bubbles[cls] += ms
                    bubbles_total[cls] += ms
                    gap_list.append((cls, ms))
                    bubble_count += 1
                    if ms > dev_largest:
                        dev_largest, dev_largest_class = ms, cls
                    if ms > largest:
                        largest, largest_class = ms, cls
            if len(merged) > MAX_INTERVALS_PER_DEVICE:
                merged = merged[:MAX_INTERVALS_PER_DEVICE]
                capped = True
            devices[str(d)] = {
                "busy_ms": round(busy, 3),
                "intervals": [[round(a, 3), round(b, 3)]
                              for a, b in merged],
                "bubbles": {c: round(v, 3)
                            for c, v in dev_bubbles.items()},
                "largest_bubble_ms": round(dev_largest, 3),
                "largest_bubble_class": dev_largest_class,
            }
        n_dev = len(by_dev)
        device_wall = n_dev * wall
        attributed_ms = busy_total + sum(bubbles_total.values())
        rec = {
            "ns": tok.ns,
            "t0_ms": round(tok.t0, 3),
            "t1_ms": round(t_end, 3),
            "wall_ms": round(wall, 3),
            "n_devices": n_dev,
            "devices": devices,
            "parts": len(tok.parts),
            "delivered": tok.delivered,
            "busy_ms": round(busy_total, 3),
            "busy_frac": round(busy_total / device_wall, 4)
            if device_wall > 0 else None,
            "prep_ms": round(prep_ms, 3),
            "overlap_ms": round(overlap, 3),
            "overlap_frac": round(overlap / prep_ms, 4)
            if prep_ms > 0 else None,
            "bubbles": {c: round(v, 3)
                        for c, v in bubbles_total.items()},
            "bubble_count": bubble_count,
            "largest_bubble_ms": round(largest, 3),
            "largest_bubble_class": largest_class,
            "device_wall_ms": round(device_wall, 3),
            # busy + attributed bubbles vs n_devices x wall: ~1.0 when
            # every hook fired and the interval math is consistent;
            # the tier-1 self-check ALSO pins wall_ms against an
            # independently measured wall clock (>= 0.95)
            "reconciliation": round(attributed_ms / device_wall, 4)
            if device_wall > 0 else None,
            "intervals_capped": capped,
            "gap_list": gap_list,
        }
        if transfer is not None:
            rec["transfer"] = {
                k: transfer.get(k, 0)
                for k in ("round_trips", "bytes_h2d", "bytes_d2h",
                          "redundant_constant_bytes")}
        return rec

    # ---------------- introspection ----------------

    def totals(self) -> dict:
        """Running process totals — the bench-record delta input and
        the ``pipeline`` admin route's summary block."""
        with self._lock:
            device_wall = self._device_wall_ms
            busy = self._busy_ms
            prep = self._prep_ms
            overlap = self._overlap_ms
            bubbles = dict(self._bubble_ms)
            return {
                "resolves": self._resolves,
                "parts": self._parts,
                "delivered": self._delivered,
                "device_wall_ms": round(device_wall, 3),
                "busy_ms": round(busy, 3),
                "busy_frac": round(busy / device_wall, 4)
                if device_wall > 0 else None,
                "prep_ms": round(prep, 3),
                "overlap_ms": round(overlap, 3),
                "overlap_frac": round(overlap / prep, 4)
                if prep > 0 else None,
                "bubble_ms": {c: round(v, 3)
                              for c, v in bubbles.items()},
                "bubble_count": self._bubble_count,
                "largest_bubble_ms": round(self._largest_bubble_ms, 3),
                "largest_bubble_class": self._largest_bubble_class,
            }

    def recent(self, limit: int = 8) -> list:
        """The most recent per-resolve records (``gap_list`` working
        field stripped); ``limit=0`` means none."""
        limit = max(0, int(limit))
        with self._lock:
            tail = list(self._ring)[-limit:] if limit else []
        return [{k: v for k, v in r.items() if k != "gap_list"}
                for r in tail]

    def snapshot(self, limit: int = 8) -> dict:
        """The ``pipeline`` admin-route payload: process totals +
        derived fractions + the most recent per-resolve records."""
        out = self.totals()
        out["ring_capacity"] = self._ring.maxlen
        out["recent"] = self.recent(limit)
        return out

    def chrome_counter_events(self) -> List[dict]:
        """Chrome ``trace_event`` counter samples (``ph: "C"``) from
        the ring: a per-device in-flight track (1 inside each busy
        interval, 0 outside), a per-resolve ``busy_frac`` track, and
        cumulative transfer byte counters at each resolve end — merged
        into :meth:`FlightRecorder.to_chrome_trace` so one
        chrome://tracing load shows spans, bytes and utilization on a
        shared clock."""
        with self._lock:
            recs = [dict(r) for r in self._ring]
        events: List[dict] = []

        def counter(name, ts_ms, **vals):
            events.append({"name": name, "ph": "C", "pid": 1,
                           "tid": 0, "ts": round(ts_ms * 1000.0, 1),
                           "args": vals})

        cum_h2d = cum_d2h = 0
        for rec in recs:
            for d, dev in sorted(rec.get("devices", {}).items()):
                for t0, t1 in dev["intervals"]:
                    counter(f"pipeline.dev{d}.inflight", t0, inflight=1)
                    counter(f"pipeline.dev{d}.inflight", t1, inflight=0)
            if rec.get("busy_frac") is not None:
                counter("pipeline.busy_frac", rec["t1_ms"],
                        busy_frac=rec["busy_frac"])
            tr = rec.get("transfer")
            if tr:
                cum_h2d += tr.get("bytes_h2d", 0)
                cum_d2h += tr.get("bytes_d2h", 0)
                counter("transfer.bytes", rec["t1_ms"],
                        h2d=cum_h2d, d2h=cum_d2h)
        events.sort(key=lambda e: e["ts"])
        return events

    def _reset_for_testing(self) -> None:
        """Fresh profiler state (ring + totals). Cumulative registry
        metrics are untouched — same policy as the transfer ledger."""
        with self._lock:
            self._ring.clear()
            self._resolves = 0
            self._device_wall_ms = 0.0
            self._busy_ms = 0.0
            self._prep_ms = 0.0
            self._overlap_ms = 0.0
            self._bubble_ms = {c: 0.0 for c in BUBBLE_CLASSES}
            self._bubble_count = 0
            self._largest_bubble_ms = 0.0
            self._largest_bubble_class = None
            self._parts = 0
            self._delivered = 0


# process-wide profiler (one node per process, like the registry, the
# flight recorder, and the transfer ledger)
pipeline_timeline = PipelineTimeline()
