"""ctypes bridge to the C++ bucket-stream runtime
(``native/bucket_stream.cpp``): record-framed stream hashing, joining,
splitting, and the sorted merge plan behind bucket merges.

The library is compiled on first use with the system ``g++`` and cached
under ``build/``; every entry point has a pure-Python fallback so the
framework runs (slower) without a toolchain. Differential tests pin the
two implementations together.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import struct
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

__all__ = ["available", "sha256", "hash_frames", "join_frames",
           "split_frames", "merge_plan"]

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "bucket_stream.cpp")
_LIB = os.path.join(_REPO_ROOT, "build", "libbucketstream.so")

_lock = threading.Lock()
_lib = None
_tried = False


def _load():
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        try:
            if not os.path.exists(_LIB) or \
                    os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                os.makedirs(os.path.dirname(_LIB), exist_ok=True)
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-o", _LIB, _SRC],
                    check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(_LIB)
            lib.bs_sha256.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                      ctypes.c_char_p]
            lib.bs_hash_frames.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_char_p]
            lib.bs_join_frames.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64, ctypes.c_char_p]
            lib.bs_join_frames.restype = ctypes.c_uint64
            lib.bs_count_frames.argtypes = [ctypes.c_char_p,
                                            ctypes.c_uint64]
            lib.bs_count_frames.restype = ctypes.c_uint64
            lib.bs_split_frames.argtypes = [
                ctypes.c_char_p, ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64)]
            lib.bs_split_frames.restype = ctypes.c_uint64
            lib.bs_merge_plan.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64),
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_uint64),
                ctypes.POINTER(ctypes.c_uint64)]
            lib.bs_merge_plan.restype = ctypes.c_uint64
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def sha256(data: bytes) -> bytes:
    lib = _load()
    if lib is None:
        return hashlib.sha256(data).digest()
    out = ctypes.create_string_buffer(32)
    lib.bs_sha256(data, len(data), out)
    return out.raw


def _pack_lens(lens: Sequence[int]):
    return (ctypes.c_uint64 * len(lens))(*lens)


def hash_frames(frames: Sequence[bytes]) -> bytes:
    """SHA-256 of the record-marked stream of ``frames`` (the bucket
    content hash)."""
    lib = _load()
    if lib is None:
        h = hashlib.sha256()
        for f in frames:
            h.update(struct.pack(">I", 0x80000000 | len(f)))
            h.update(f)
        return h.digest()
    blob = b"".join(frames)
    out = ctypes.create_string_buffer(32)
    lib.bs_hash_frames(blob, _pack_lens([len(f) for f in frames]),
                       len(frames), out)
    return out.raw


def join_frames(frames: Sequence[bytes]) -> bytes:
    lib = _load()
    if lib is None:
        return b"".join(struct.pack(">I", 0x80000000 | len(f)) + f
                        for f in frames)
    blob = b"".join(frames)
    total = len(blob) + 4 * len(frames)
    out = ctypes.create_string_buffer(total)
    n = lib.bs_join_frames(blob, _pack_lens([len(f) for f in frames]),
                           len(frames), out)
    return out.raw[:n]


def split_frames(raw: bytes) -> List[bytes]:
    lib = _load()
    if lib is None:
        out = []
        pos = 0
        while pos < len(raw):
            (marked,) = struct.unpack_from(">I", raw, pos)
            n = marked & 0x7FFFFFFF
            pos += 4
            out.append(raw[pos:pos + n])
            pos += n
        return out
    count = lib.bs_count_frames(raw, len(raw))
    if count == ctypes.c_uint64(-1).value:
        raise ValueError("corrupt record framing")
    offs = (ctypes.c_uint64 * count)()
    lens = (ctypes.c_uint64 * count)()
    lib.bs_split_frames(raw, len(raw), offs, lens)
    return [raw[offs[i]:offs[i] + lens[i]] for i in range(count)]


def merge_plan(keys_old: Sequence[bytes], keys_new: Sequence[bytes]
               ) -> List[Tuple[int, int, int]]:
    """Sorted two-way merge plan: [(side, i_old, i_new)] with side
    0=old-only, 1=new-only, 2=equal keys. Inputs sorted ascending."""
    lib = _load()
    if lib is None:
        out = []
        i = j = 0
        while i < len(keys_old) and j < len(keys_new):
            if keys_old[i] < keys_new[j]:
                out.append((0, i, 0))
                i += 1
            elif keys_new[j] < keys_old[i]:
                out.append((1, 0, j))
                j += 1
            else:
                out.append((2, i, j))
                i += 1
                j += 1
        out.extend((0, k, 0) for k in range(i, len(keys_old)))
        out.extend((1, 0, k) for k in range(j, len(keys_new)))
        return out
    n_old, n_new = len(keys_old), len(keys_new)
    total = n_old + n_new
    sides = (ctypes.c_uint8 * max(1, total))()
    io = (ctypes.c_uint64 * max(1, total))()
    jn = (ctypes.c_uint64 * max(1, total))()
    w = lib.bs_merge_plan(
        b"".join(keys_old), _pack_lens([len(k) for k in keys_old]), n_old,
        b"".join(keys_new), _pack_lens([len(k) for k in keys_new]), n_new,
        sides, io, jn)
    return [(sides[k], io[k], jn[k]) for k in range(w)]
