"""Force jax onto its CPU backend and deregister the axon TPU plugin.

The ambient environment pins ``JAX_PLATFORMS=axon`` (the real TPU via a
tunnel) and a sitecustomize hook registers the axon PJRT plugin in EVERY
interpreter. JAX initializes registered plugins even when
``JAX_PLATFORMS=cpu``, so with the tunnel unhealthy the first array
creation hangs forever. Any CPU-side consumer (the test suite, jaxpr
tracing in ``tools/kernel_cost.py``) must therefore both override the
platform config *and* deregister the axon backend factory BEFORE any
backend is initialized.

This is the single shared copy of that hang-prevention dance — it pokes
jax private API (``_backend_factories``/``_backend_lock``), so keeping
one implementation is what stops the copies from drifting. Only
``bench.py`` talks to the real chip.
"""

from __future__ import annotations

import os

__all__ = ["force_cpu"]


def force_cpu(compilation_cache_dir: str | None = None) -> None:
    """Pin jax to CPU and drop non-CPU backend factories. Idempotent;
    a no-op (beyond the config update) once backends are initialized —
    by then it is too late to deregister anything safely.

    ``compilation_cache_dir``: optionally also point jax's persistent
    compilation cache there (the verify-kernel compiles dominate suite
    time).
    """
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        import jax._src.xla_bridge as xb
    except Exception:
        return
    try:
        # The axon register hook hard-sets jax_platforms="axon,cpu" in
        # the config (env var alone doesn't win); point it back at cpu.
        jax.config.update("jax_platforms", "cpu")
        if compilation_cache_dir:
            try:
                jax.config.update(
                    "jax_compilation_cache_dir", compilation_cache_dir)
                jax.config.update(
                    "jax_persistent_cache_min_compile_time_secs", 2.0)
            except Exception:
                pass
        with xb._backend_lock:
            if xb._backends:
                return  # backends already initialized; too late, leave it
            for name in list(xb._backend_factories):
                if name not in ("cpu", "interpreter"):
                    del xb._backend_factories[name]
    except Exception:
        pass
