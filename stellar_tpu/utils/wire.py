"""Length-prefixed binary frame codec for the streaming verify
ingress (ISSUE 19) — the wire half of ``stellar_tpu/crypto/ingress``.

The grammar is the gRPC-compatible shape: every frame is a fixed
5-byte header ``type:u8 || length:u32be`` followed by exactly
``length`` payload bytes. Four frame types:

* ``SUBMIT`` (0x01), client → server::

      req_id:u32be || lane_len:u8 || lane || tenant_len:u8 || tenant
      || count:u16be || count * (pk_len:u8 || pk || sig_len:u8 || sig
                                 || msg_len:u32be || msg)

  ``tenant_len == 0`` encodes the quota-exempt default tenant
  (``None``). ``req_id`` is a client-chosen correlation id echoed in
  the response frame — responses need no ordering guarantee.
  ``pk_len``/``sig_len`` are canonically :data:`PK_LEN` (32) /
  :data:`SIG_LEN` (64) but deliberately NOT enforced by the codec:
  the verifier is the sole authority on key/signature validity, so a
  structurally invalid key rides the wire and comes back as verdict
  ``False`` — byte-identical semantics with a direct in-process
  submission.

* ``VERDICT`` (0x02), server → client::

      req_id:u32be || trace_lo:u64be || count:u16be || count * u8

  one 0/1 byte per item, index-aligned with the submission; the
  items' trace IDs are ``range(trace_lo, trace_lo + count)`` — the
  wire is where a ``trace?id=`` timeline starts and ends.

* ``REFUSAL`` (0x03), server → client: a canonical-JSON rendering of
  a typed :class:`~stellar_tpu.utils.resilience.Overloaded`
  (kind/lane/reason/tenant/replica/trace_lo/n/req_id/message).
  Canonical = ``sort_keys=True`` + ``separators=(",", ":")`` — two
  servers refusing the same submission for the same reason emit
  BYTE-IDENTICAL frames (pinned by ``tools/ingress_selfcheck.py``).

* ``ERROR`` (0x04), server → client: a canonical-JSON wire-protocol
  error (``reason`` ∈ ``{"garbage", "oversize", "deadline",
  "byte-budget", "truncated-item", "trailing-bytes", "slow-frame",
  "stopped"}``) sent best-effort before the server closes a
  connection it can no longer trust to be in frame sync.

Decoding is STREAMING and tear-proof: :class:`FrameDecoder` may be
fed any byte-split of a valid frame sequence and yields exactly the
same frames as feeding it whole (the torn-frame fuzz corpus in
``tests/test_wire.py`` sweeps every split point). Anything that is
not a well-formed frame raises :class:`MalformedFrame` with a typed
``reason`` — never a panic, and never a silent resync: after a
malformed frame the decoder is poisoned (``dead``) because frame
boundaries are no longer trustworthy; the transport must drop the
connection (exactly what the ingress server does).

This module is a PURE codec: no sockets, no threads, no locks, no
clock or RNG reads — it sits in both consensus lint scopes
(``analysis/nondet.py`` HOST_ORACLE_FILES, ``analysis/locks.py``
SCOPE) with NO allowlist entries (pinned in ``tests/test_analysis.py``):
two nodes decoding the same bytes must always agree on what arrived.
"""

from __future__ import annotations

import json
import struct
from typing import Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "SUBMIT", "VERDICT", "REFUSAL", "ERROR", "HEADER_LEN",
    "MAX_FRAME_BYTES", "PK_LEN", "SIG_LEN", "MalformedFrame",
    "FrameDecoder", "encode_submit", "encode_verdict",
    "encode_refusal", "encode_error", "decode_payload",
    "decode_submit", "decode_verdict", "decode_json", "frame",
    "split_points",
]

SUBMIT = 0x01
VERDICT = 0x02
REFUSAL = 0x03
ERROR = 0x04

_TYPES = frozenset((SUBMIT, VERDICT, REFUSAL, ERROR))

HEADER_LEN = 5
PK_LEN = 32
SIG_LEN = 64

# the default frame ceiling: a declared length above this is refused
# as ``oversize`` WITHOUT buffering the body — a client cannot make
# the server reserve memory by declaring a huge frame
MAX_FRAME_BYTES = 1 << 20

_HDR = struct.Struct(">BI")
_U16 = struct.Struct(">H")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")


class MalformedFrame(ValueError):
    """A typed wire-protocol violation. ``reason`` is the machine
    name the ingress counters and the ERROR reply carry:
    ``"garbage"`` (unknown frame type — includes any garbage-prefix
    attack byte), ``"oversize"`` (declared length over the ceiling),
    ``"truncated-item"`` (payload too short for its own counts),
    ``"trailing-bytes"`` (payload longer than its counts account
    for), ``"bad-json"`` (REFUSAL/ERROR payload not canonical
    JSON)."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"malformed frame ({reason})"
                         + (f": {detail}" if detail else ""))
        self.reason = reason


def frame(ftype: int, payload: bytes) -> bytes:
    """One encoded frame: header + payload."""
    return _HDR.pack(ftype, len(payload)) + payload


# ---------------- encoders ----------------

def encode_submit(items: Sequence[tuple], lane: str = "bulk",
                  tenant: Optional[str] = None,
                  req_id: int = 0) -> bytes:
    """Encode ``(pk, msg, sig)`` triples into one SUBMIT frame."""
    lane_b = lane.encode()
    ten_b = (tenant or "").encode()
    if len(lane_b) > 255 or len(ten_b) > 255:
        raise ValueError("lane/tenant over 255 bytes")
    if len(items) > 0xFFFF:
        raise ValueError("over 65535 items per frame")
    parts = [_U32.pack(req_id & 0xFFFFFFFF),
             bytes([len(lane_b)]), lane_b,
             bytes([len(ten_b)]), ten_b,
             _U16.pack(len(items))]
    for pk, msg, sig in items:
        if len(pk) > 255 or len(sig) > 255:
            raise ValueError("pk/sig over 255 bytes")
        parts.append(bytes([len(pk)]))
        parts.append(bytes(pk))
        parts.append(bytes([len(sig)]))
        parts.append(bytes(sig))
        parts.append(_U32.pack(len(msg)))
        parts.append(bytes(msg))
    return frame(SUBMIT, b"".join(parts))


def encode_verdict(req_id: int, trace_lo: int,
                   verdicts: Sequence) -> bytes:
    """Encode one per-item 0/1 verdict vector."""
    body = bytes(1 if bool(v) else 0 for v in verdicts)
    return frame(VERDICT, _U32.pack(req_id & 0xFFFFFFFF)
                 + _U64.pack(trace_lo) + _U16.pack(len(body)) + body)


def _canonical_json(obj: dict) -> bytes:
    return json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode()


def encode_refusal(req_id: int, *, kind: str, lane: Optional[str],
                   reason: str, tenant: Optional[str],
                   replica: Optional[int], trace_lo: int, n: int,
                   message: str = "") -> bytes:
    """Canonical-JSON refusal: field-for-field the typed
    ``Overloaded`` the server raised. Two servers refusing the same
    submission emit byte-identical frames — the determinism the
    ingress selfcheck pins."""
    return frame(REFUSAL, _canonical_json({
        "req_id": int(req_id), "kind": kind, "lane": lane,
        "reason": reason, "tenant": tenant, "replica": replica,
        "trace_lo": int(trace_lo), "n": int(n), "message": message,
    }))


def encode_error(reason: str, detail: str = "") -> bytes:
    """Canonical-JSON wire-protocol error (sent before close)."""
    return frame(ERROR, _canonical_json(
        {"reason": reason, "detail": detail}))


# ---------------- payload decoders ----------------

def decode_submit(payload) -> Tuple[int, str, Optional[str], list]:
    """``(req_id, lane, tenant, items)`` from a SUBMIT payload.

    ``payload`` may be a :class:`memoryview` into a reusable host
    buffer: each item's ``msg`` is returned as a zero-copy slice of
    it (``pk``/``sig`` are materialized as :class:`bytes` — 96 fixed
    bytes per item, and downstream caches key on them, so they must
    be hashable). The caller owns keeping the backing buffer alive
    until the items reach a terminal."""
    mv = memoryview(payload)
    try:
        req_id = _U32.unpack_from(mv, 0)[0]
        pos = 4
        lane_len = mv[pos]
        pos += 1
        lane = bytes(mv[pos:pos + lane_len]).decode()
        pos += lane_len
        ten_len = mv[pos]
        pos += 1
        tenant = bytes(mv[pos:pos + ten_len]).decode() or None
        pos += ten_len
        count = _U16.unpack_from(mv, pos)[0]
        pos += 2
    except (struct.error, IndexError):
        raise MalformedFrame("truncated-item", "submit preamble")
    items = []
    end = len(mv)
    for _ in range(count):
        # pk/sig carry their own u8 lengths (canonically PK_LEN /
        # SIG_LEN, but NOT enforced here: the verifier is the
        # authority on key validity — a structurally invalid key must
        # ride the wire and come back as verdict False, exactly like
        # a direct in-process submission)
        if pos + 1 > end:
            raise MalformedFrame("truncated-item",
                                 f"item {len(items)} pk length")
        pklen = mv[pos]
        pos += 1
        if pos + pklen + 1 > end:
            raise MalformedFrame("truncated-item",
                                 f"item {len(items)} pk")
        pk = bytes(mv[pos:pos + pklen])
        pos += pklen
        siglen = mv[pos]
        pos += 1
        if pos + siglen + 4 > end:
            raise MalformedFrame("truncated-item",
                                 f"item {len(items)} sig")
        sig = bytes(mv[pos:pos + siglen])
        pos += siglen
        mlen = _U32.unpack_from(mv, pos)[0]
        pos += 4
        if pos + mlen > end:
            raise MalformedFrame("truncated-item",
                                 f"item {len(items)} body")
        items.append((pk, mv[pos:pos + mlen], sig))
        pos += mlen
    if pos != end:
        raise MalformedFrame("trailing-bytes",
                             f"{end - pos} bytes after last item")
    return req_id, lane, tenant, items


def decode_verdict(payload) -> Tuple[int, int, list]:
    """``(req_id, trace_lo, [bool])`` from a VERDICT payload."""
    mv = memoryview(payload)
    try:
        req_id = _U32.unpack_from(mv, 0)[0]
        trace_lo = _U64.unpack_from(mv, 4)[0]
        count = _U16.unpack_from(mv, 12)[0]
    except struct.error:
        raise MalformedFrame("truncated-item", "verdict preamble")
    if len(mv) != 14 + count:
        raise MalformedFrame("trailing-bytes", "verdict body")
    return req_id, trace_lo, [b != 0 for b in bytes(mv[14:])]


def decode_json(payload) -> dict:
    """REFUSAL / ERROR payload → dict."""
    try:
        obj = json.loads(bytes(payload).decode())
    except (ValueError, UnicodeDecodeError):
        raise MalformedFrame("bad-json")
    if not isinstance(obj, dict):
        raise MalformedFrame("bad-json", "not an object")
    return obj


def decode_payload(ftype: int, payload):
    """Dispatch a payload to its typed decoder — the ONE parsing
    path both the streaming decoder and the ingress server's
    read-exact path share."""
    if ftype == SUBMIT:
        return decode_submit(payload)
    if ftype == VERDICT:
        return decode_verdict(payload)
    if ftype in (REFUSAL, ERROR):
        return decode_json(payload)
    raise MalformedFrame("garbage", f"frame type {ftype:#x}")


# ---------------- streaming decoder ----------------

class FrameDecoder:
    """Incremental frame splitter: feed arbitrary byte chunks, get
    complete ``(type, payload, raw_len)`` frames out. Tear-proof by
    construction — partial bytes accumulate until the frame
    completes; the torn-frame fuzz corpus sweeps every split point.

    On any :class:`MalformedFrame` the decoder poisons itself
    (``dead=True``): a stream that has lost framing cannot be
    resynced safely, so every later ``feed`` raises the original
    error again. The transport must close the connection."""

    def __init__(self, max_frame_bytes: int = MAX_FRAME_BYTES):
        self.max_frame_bytes = int(max_frame_bytes)
        self.dead: Optional[MalformedFrame] = None
        self._buf = bytearray()

    @property
    def partial_bytes(self) -> int:
        """Bytes buffered toward an incomplete frame — the ingress
        server's mid-frame read-deadline trigger."""
        return len(self._buf)

    def feed(self, data) -> List[tuple]:
        """Buffer ``data`` and return every now-complete frame as
        ``(ftype, payload_bytes, frame_len)``."""
        if self.dead is not None:
            raise self.dead
        self._buf.extend(data)
        out: List[tuple] = []
        while True:
            if len(self._buf) < HEADER_LEN:
                return out
            ftype, length = _HDR.unpack_from(self._buf, 0)
            if ftype not in _TYPES:
                raise self._poison(MalformedFrame(
                    "garbage", f"frame type {ftype:#x}"))
            if length > self.max_frame_bytes:
                raise self._poison(MalformedFrame(
                    "oversize",
                    f"declared {length} > {self.max_frame_bytes}"))
            if len(self._buf) < HEADER_LEN + length:
                return out
            payload = bytes(self._buf[HEADER_LEN:HEADER_LEN + length])
            del self._buf[:HEADER_LEN + length]
            out.append((ftype, payload, HEADER_LEN + length))

    def feed_decoded(self, data) -> Iterator[tuple]:
        """``feed`` + ``decode_payload``: yields ``(ftype, decoded)``
        and poisons on a payload-level violation too."""
        for ftype, payload, _raw in self.feed(data):
            try:
                yield ftype, decode_payload(ftype, payload)
            except MalformedFrame as e:
                raise self._poison(e)

    def _poison(self, err: MalformedFrame) -> MalformedFrame:
        self.dead = err
        return err


def split_points(blob: bytes) -> range:
    """Every proper split point of an encoded frame sequence — the
    torn-frame fuzz corpus's iteration domain."""
    return range(1, len(blob))
