"""Generic resilience primitives: circuit breaker + deadlines.

The verify boundary must stay as dependable as the reference's
``PubKeyUtils::verifySig`` even when the accelerator tunnel dies
mid-flight — a node that hangs in ledger close is worse than a slow
node. These are the domain-free building blocks; the verify-specific
policy (what counts as a failure, what the fallback is) lives in
:mod:`stellar_tpu.crypto.batch_verifier`.

* :class:`CircuitBreaker` — closed → open on a consecutive-failure
  threshold → half-open re-probe after an exponential backoff window
  (with jitter so a fleet of nodes doesn't re-probe in lockstep).
* :class:`Deadline` / :func:`call_with_deadline` — watchdogged
  execution budgets for calls whose observed failure mode is a HANG,
  not an exception (``jax.devices()`` / device-array fetches through a
  dead tunnel block forever).
* :class:`WatchdogPool` — the persistent worker pool behind
  :func:`call_with_deadline`: one short-lived thread per guarded call
  (the PR 2 shape) cost a spawn per chunk fetch; the pool reuses a
  small set of daemon workers and only spawns when every idle worker
  is busy, so the steady-state guarded fetch is a queue hand-off, not
  a thread start.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, Optional

from stellar_tpu.utils import tracing

__all__ = [
    "CLOSED", "OPEN", "HALF_OPEN",
    "CircuitBreaker", "Deadline", "DeadlineExceeded", "Overloaded",
    "WatchdogPool", "call_with_deadline", "watchdog_stats",
]

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class DeadlineExceeded(Exception):
    """A guarded call did not finish within its budget."""


class Overloaded(RuntimeError):
    """Typed admission-control verdict: the system chose to REFUSE or
    DROP work rather than buffer unboundedly (docs/robustness.md,
    "Overload and load-shed"). Two kinds:

    * ``kind="rejected"`` — refused at INGRESS (queue depth or byte
      budget exceeded, or the service is stopping): the work never
      entered a queue;
    * ``kind="shed"`` — admitted, then dropped by the deterministic
      load-shed ladder under overload pressure: the caller learns via
      this exception from its ticket, never by silence.

    ``lane`` names the priority lane (or ``"trickle"`` for the
    micro-batch window), ``reason`` the specific budget that tripped
    (``"queue-depth"``, ``"bytes"``, ``"tenant-depth"``,
    ``"tenant-bytes"``, ``"backlog"``, ...); ``tenant`` names the
    submitting tenant when the verdict was tenant-scoped (ISSUE 14) —
    a per-tenant quota refusal or a tenant-keyed shed is attributable
    to its principal from the exception alone; ``replica`` names the
    refusing fleet replica (ISSUE 17) — in an N-replica deployment a
    refusal attributes to the replica that issued it (``None`` for a
    single-service deployment or a router-level refusal); ``trace_ids``
    carries the refused/shed items' trace IDs (ISSUE 8) — an item's
    trace survives even when the answer is "no", so the ``trace``
    admin route can show WHERE a submission died."""

    def __init__(self, message: str, *, kind: str = "rejected",
                 lane: Optional[str] = None, reason: str = "",
                 tenant: Optional[str] = None, trace_ids=None,
                 replica: Optional[int] = None):
        super().__init__(message)
        self.kind = kind
        self.lane = lane
        self.reason = reason
        self.tenant = tenant
        self.trace_ids = trace_ids if trace_ids is not None else ()
        self.replica = replica


class Deadline:
    """A monotonic time budget threaded through a multi-step operation
    so each step races against what is LEFT, not a fresh allowance."""

    def __init__(self, budget_s: float, clock=time.monotonic):
        self._clock = clock
        self._t0 = clock()
        self.budget_s = float(budget_s)

    @classmethod
    def from_ms(cls, budget_ms: float, clock=time.monotonic) -> "Deadline":
        return cls(budget_ms / 1000.0, clock=clock)

    def elapsed(self) -> float:
        return self._clock() - self._t0

    def remaining(self) -> float:
        return self.budget_s - self.elapsed()

    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(self, what: str = "operation") -> None:
        if self.expired():
            raise DeadlineExceeded(
                f"{what}: {self.budget_s:.3f}s budget exhausted")


class WatchdogPool:
    """Persistent daemon-worker pool for deadline-guarded calls.

    Invariants:

    * a submitted job is picked up immediately — ``submit`` spawns a
      fresh worker whenever the queue outnumbers idle workers, so a
      guarded call never waits behind another caller's work;
    * a worker whose job HANGS is simply absent from the idle set (it
      is parked inside ``fn()``); capacity self-heals because the next
      submit spawns, and if the hung call ever returns the worker
      rejoins the pool on its own;
    * at most ``max_idle`` workers linger between bursts — extras exit
      once the queue drains, so a resolve storm doesn't leave a
      thread-per-chunk residue (the pre-pool behavior).

    All shared state (queue, idle/worker counts) mutates under the
    pool's condition variable — the lock-discipline lint covers this
    module.
    """

    def __init__(self, name: str = "watchdog", max_idle: int = 4):
        self.name = name
        self._max_idle = max_idle
        self._cv = threading.Condition()
        self._jobs: deque = deque()
        self._idle = 0
        self._workers = 0
        self._spawned_total = 0

    def _loop(self) -> None:
        while True:
            with self._cv:
                if not self._jobs:
                    if self._idle >= self._max_idle:
                        self._workers -= 1
                        return
                    self._idle += 1
                    while not self._jobs:
                        self._cv.wait()
                    self._idle -= 1
                job = self._jobs.popleft()
            try:
                # trace-context propagation (ISSUE 5): spans opened
                # inside the guarded call parent under the submitter's
                # live span — a HUNG fetch shows up in a flight-recorder
                # dump linked to the resolve that dispatched it
                with tracing.span_context(job["ctx"]):
                    job["box"]["out"] = job["fn"]()
            except BaseException as e:  # re-raised on the caller's thread
                job["box"]["err"] = e
            finally:
                job["done"].set()

    def submit(self, fn: Callable) -> dict:
        """Queue ``fn`` for a pool worker; returns the job record
        (``done`` event + ``box`` result slot). Never blocks."""
        job = {"fn": fn, "box": {}, "done": threading.Event(),
               "ctx": tracing.current_context()}
        with self._cv:
            self._jobs.append(job)
            if self._idle >= len(self._jobs):
                self._cv.notify()
            else:
                # every queued job beyond the idle set gets a fresh
                # worker NOW — hung workers (absent from _idle) can
                # never make a guarded call wait behind their hang
                self._workers += 1
                self._spawned_total += 1
                threading.Thread(target=self._loop, daemon=True,
                                 name=f"{self.name}-worker").start()
        return job

    def stats(self) -> dict:
        with self._cv:
            return {"workers": self._workers, "idle": self._idle,
                    "queued": len(self._jobs),
                    "spawned_total": self._spawned_total}


# process-wide pool behind call_with_deadline (ROADMAP "pool the
# resolve watchdog"): the verify resolve path guards one fetch per
# chunk, so reuse beats a thread spawn per chunk
_pool = WatchdogPool(name="resilience-watchdog")


def watchdog_stats() -> dict:
    """Observability: worker/idle/spawn accounting of the shared pool
    (surfaced by ``batch_verifier.dispatch_health``)."""
    return _pool.stats()


def call_with_deadline(fn: Callable, budget_s: Optional[float],
                       name: str = "guarded-call"):
    """Run ``fn()`` on a pooled watchdog worker; raise
    :class:`DeadlineExceeded` if it doesn't finish within ``budget_s``
    (None = no guard, direct call). Python cannot kill the worker: on
    timeout the job is ABANDONED — its worker stays parked on whatever
    hung (and rejoins the pool by itself if the hang ever resolves) —
    so callers must treat the underlying resource as suspect afterwards
    (that is the circuit breaker's job). An exception from ``fn`` is
    re-raised verbatim."""
    if budget_s is None:
        return fn()
    if budget_s <= 0:
        raise DeadlineExceeded(f"{name}: no budget left")
    job = _pool.submit(fn)
    if not job["done"].wait(budget_s):
        raise DeadlineExceeded(
            f"{name} exceeded {budget_s:.3f}s budget")
    box = job["box"]
    if "err" in box:
        raise box["err"]
    return box.get("out")


class CircuitBreaker:
    """Thread-safe consecutive-failure circuit breaker.

    States: ``closed`` (healthy — every call allowed; failures counted),
    ``open`` (tripped — calls refused until the backoff window expires),
    ``half-open`` (window expired — ONE probe call allowed; its outcome
    decides: success re-closes, failure re-opens with doubled backoff).

    A half-open probe grant expires after the current backoff interval,
    so a probe that itself hangs and never reports can't wedge the
    breaker half-open forever.

    ``on_transition(old, new)`` fires OUTSIDE the internal lock (it may
    log or update metrics; it must not need the breaker's lock-step
    consistency).
    """

    def __init__(self, name: str = "breaker", failure_threshold: int = 3,
                 backoff_min_s: float = 1.0, backoff_max_s: float = 120.0,
                 backoff_factor: float = 2.0, jitter_frac: float = 0.1,
                 clock=time.monotonic, rng=random.random,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        self.name = name
        self._lock = threading.Lock()
        self._clock = clock
        self._rng = rng
        self._on_transition = on_transition
        self._state = CLOSED
        self._failures = 0
        self._opened_total = 0
        self._open_until = 0.0
        self._grant_expires = 0.0
        self.configure(failure_threshold=failure_threshold,
                       backoff_min_s=backoff_min_s,
                       backoff_max_s=backoff_max_s,
                       backoff_factor=backoff_factor,
                       jitter_frac=jitter_frac)

    def configure(self, failure_threshold: Optional[int] = None,
                  backoff_min_s: Optional[float] = None,
                  backoff_max_s: Optional[float] = None,
                  backoff_factor: Optional[float] = None,
                  jitter_frac: Optional[float] = None) -> None:
        """Update policy knobs in place (config push); None keeps the
        current value. Does not change the current state."""
        with self._lock:
            if failure_threshold is not None:
                self._threshold = max(1, int(failure_threshold))
            if backoff_min_s is not None:
                self._backoff_min = max(0.001, float(backoff_min_s))
            if backoff_max_s is not None:
                self._backoff_max = float(backoff_max_s)
            if backoff_factor is not None:
                self._factor = max(1.0, float(backoff_factor))
            if jitter_frac is not None:
                self._jitter = max(0.0, float(jitter_frac))
            self._backoff_max = max(self._backoff_max, self._backoff_min)
            cur = getattr(self, "_backoff_cur", None)
            self._backoff_cur = self._backoff_min if cur is None else \
                min(max(cur, self._backoff_min), self._backoff_max)

    # ---------------- state machine ----------------

    def _transition_locked(self, new: str) -> Optional[tuple]:
        old = self._state
        if old == new:
            return None
        self._state = new
        if new == OPEN:
            self._opened_total += 1
        return (old, new)

    def _fire(self, change: Optional[tuple]) -> None:
        if change is not None and self._on_transition is not None:
            try:
                self._on_transition(*change)
            except Exception:
                pass  # observability must never break the guarded path

    def allow(self) -> bool:
        """May a call proceed right now? In ``open``, flips to
        ``half-open`` once the backoff window has expired and grants
        exactly one probe per grant window."""
        change = None
        with self._lock:
            if self._state == CLOSED:
                return True
            now = self._clock()
            if self._state == OPEN:
                if now < self._open_until:
                    return False
                change = self._transition_locked(HALF_OPEN)
                self._grant_expires = now + self._backoff_cur
                ok = True
            else:  # HALF_OPEN: one outstanding probe per grant window
                ok = now >= self._grant_expires
                if ok:
                    self._grant_expires = now + self._backoff_cur
        self._fire(change)
        return ok

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._backoff_cur = self._backoff_min
            change = self._transition_locked(CLOSED)
        self._fire(change)

    def record_failure(self) -> bool:
        """Returns True when THIS call transitioned the breaker to
        ``open`` (computed under the lock, so concurrent failure
        reports can't both claim the same onset — callers use it to
        count quarantine onsets exactly once)."""
        change = None
        with self._lock:
            self._failures += 1
            now = self._clock()
            if self._state == CLOSED:
                if self._failures >= self._threshold:
                    change = self._transition_locked(OPEN)
                    self._arm_locked(now)
            elif self._state == HALF_OPEN:
                # the probe failed: back off harder
                self._backoff_cur = min(self._backoff_cur * self._factor,
                                        self._backoff_max)
                change = self._transition_locked(OPEN)
                self._arm_locked(now)
            # already OPEN: a straggler failure report; don't extend
        self._fire(change)
        return change is not None and change[1] == OPEN

    def trip(self) -> None:
        """Force the breaker OPEN immediately, regardless of the
        failure streak — the hard-quarantine primitive. A
        result-INTEGRITY violation (a device returning wrong bits, not
        hanging) must not get ``threshold - 1`` more chances to decide
        signature validity; from half-open the backoff doubles exactly
        as a failed probe would."""
        with self._lock:
            now = self._clock()
            self._failures = max(self._failures, self._threshold)
            if self._state == HALF_OPEN:
                self._backoff_cur = min(self._backoff_cur * self._factor,
                                        self._backoff_max)
            change = self._transition_locked(OPEN)
            self._arm_locked(now)
        self._fire(change)

    def _arm_locked(self, now: float) -> None:
        jittered = self._backoff_cur * (1.0 + self._jitter * self._rng())
        self._open_until = now + jittered

    # ---------------- introspection ----------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def seconds_until_retry(self) -> float:
        """0 when calls are (or may be) allowed; else time left in the
        open backoff window."""
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._open_until - self._clock())

    def snapshot(self) -> dict:
        """Observability payload (info endpoint / metrics push)."""
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._failures,
                "failure_threshold": self._threshold,
                "backoff_s": round(self._backoff_cur, 3),
                "retry_in_s": round(
                    max(0.0, self._open_until - self._clock()), 3)
                if self._state == OPEN else 0.0,
                "opened_total": self._opened_total,
            }
