"""Unified deterministic system journal (ISSUE 20).

PRs 14-19 gave every component of the verify plane a bounded,
clock-free decision log: the service's scheduling/shed
``decision_log()`` and its admission/terminal journal feed, the
controller's knob trajectory, the fleet router's route/refusal feed
and conviction ledger, and the wire ingress's conservation counters.
Each log is bit-identical across replicas under identical input
(tier-1 ``TENANT_QOS_OK`` / ``CONTROL_OK`` / ``FLEET_OK``) — but they
were four unconnected surfaces. This module merges them into ONE
event stream an operator (or ``tools/journal_selfcheck.py``) can
reason about:

**Event model.** Every row is a plain dict with a ``component``
name, a per-component monotone ``seq``, a ``kind``, and — wherever
the row concerns admitted work — the trace block it covers
(``trace_lo``/``n``), which is the cross-reference that joins journal
rows to the flight recorder's stitched ``trace?id=`` timeline. The
merge key is ``(component, seq)``: within a component, seq order IS
causal order; across components the interleave is the deterministic
``(seq, component)`` lexicographic merge, and per-trace causality is
recovered through the trace-ID cross-references (the stitched
timeline), never through clocks.

**Determinism classes.** Route feeds, replica feeds, decision logs,
control logs and conviction ledgers are DETERMINISTIC: two replicas
(or two independent collections of one frozen system) produce
bit-identical rows, so :func:`merge` refuses conflicting payloads
under the same key (:class:`JournalDivergence`) and
:func:`canonical_bytes` over the deterministic sections is a fair
equality surface. The ingress wire counters depend on socket timing,
so they ride in the separate ``nondet`` section — reconciled by the
completeness law, excluded from bit-identity.

**Completeness law** (:func:`completeness`). At any snapshot the
merged journal must reconcile EXACTLY with the conservation counters
of every layer: per replica, journal admissions equal counted
admissions and every terminal kind matches its counter; the fleet's
route totals obey ``routed + rerouted + refused == submitted +
handoffs``; the ingress wire residual is 0; and over the retained
(unwrapped) window every admitted trace ID reaches EXACTLY one
terminal — a handoff is a hop, not a terminal, so a re-homed trace's
second admission balances its handoff debit. The returned ``gap`` is
the sum of absolute residuals and must read 0 (the
``journal.completeness_gap`` perf-sentinel row pins it).

Everything here is a pure function of the logs it is handed: no
clocks, no RNG, no allowlist entries in either lint scope
(``tests/test_analysis.py`` pins both).
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Sequence

__all__ = ["JournalDivergence", "canonical_bytes", "collect",
           "merge", "canonical", "completeness", "stitch_fraction"]


class JournalDivergence(Exception):
    """Two journals disagree about the SAME ``(component, seq)`` key
    (or the same deterministic totals) — the merge refuses to paper
    over it, exactly like the fleet's divergence conviction: a
    deterministic component that produced two different rows for one
    seq is evidence, not noise."""


def canonical_bytes(obj) -> bytes:
    """The bit-identity surface: canonical JSON (sorted keys, no
    whitespace, ASCII) — two equal journals canonicalize to equal
    bytes, byte for byte."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"),
                      ensure_ascii=True).encode("ascii")


# ---------------- collection ----------------

def _control_rows(svc) -> List[dict]:
    """Render a service's attached-controller log as journal rows —
    the window seq is already monotone and deterministic, so it keys
    the component directly."""
    return [
        {"seq": seq, "kind": "control", "action": action,
         "max_batch": mb, "pipeline_depth": pd,
         "highwater_milli": hw, "reason": reason}
        for action, seq, mb, pd, hw, reason in svc.control_log()]


def _decision_rows(svc) -> List[dict]:
    """Render the scheduling/shed decision log as journal rows. The
    tuples carry no per-row counter, so rows are keyed by their index
    in the retained window — stable for same-window collections
    (which is what merge compares); a wrapped log shifts the base,
    which :func:`completeness` detects via the replica feed."""
    rows = []
    for i, d in enumerate(svc.decision_log()):
        if d[0] == "dispatch":
            _k, lane, tenant, seq, vfinish, replica = d
            rows.append({"seq": i, "kind": "dispatch", "lane": lane,
                         "tenant": tenant, "ticket": seq,
                         "vfinish": vfinish, "replica": replica})
        else:
            _k, lane, tenant, seq, level, replica = d
            rows.append({"seq": i, "kind": "shed", "lane": lane,
                         "tenant": tenant, "ticket": seq,
                         "level": level, "replica": replica})
    return rows


def collect(fleet=None, services: Optional[Sequence] = None,
            ingress=None) -> dict:
    """Collect one journal snapshot from live components. Any subset
    may be present: a bare service window journals alone, a fleet
    brings its replicas (``services`` overrides), the wire ingress
    adds the nondeterministic wire totals. The result is a plain
    JSON-serializable dict — what :func:`merge` consumes and the
    ``journal`` admin route serves."""
    comps: Dict[str, List[dict]] = {}
    totals: Dict[str, dict] = {}
    nondet: Dict[str, dict] = {}
    if fleet is not None:
        fsnap = fleet.snapshot()
        comps["fleet"] = fleet.route_log()
        comps["fleet.convictions"] = [
            {"seq": c.get("seq", i + 1), "kind": "convict",
             "replica": c["replica"], "at_route": c["at_route"],
             "probation_due": c["probation_due"],
             "evidence": list(c["evidence"])}
            for i, c in enumerate(fsnap["conviction_log"])]
        totals["fleet"] = {
            "submitted": fsnap["submitted"],
            "router_refused": fsnap["router_refused"],
            "handoffs": fsnap["handoffs"],
            "pending_items": fsnap["pending_items"],
            "conservation_gap": fsnap["conservation_gap"],
            "route_totals": dict(fsnap["route_totals"]),
        }
        if services is None:
            services = fleet.services()
    for i, svc in enumerate(services or []):
        name = svc.replica if svc.replica is not None else i
        comps[f"replica/{name}"] = svc.journal_log()
        comps[f"decisions/{name}"] = _decision_rows(svc)
        ctl = _control_rows(svc)
        if ctl:
            comps[f"control/{name}"] = ctl
        snap = svc.snapshot()
        totals[f"replica/{name}"] = {
            "journal": svc.journal_totals(),
            "counts": {k: int(v) for k, v in snap["totals"].items()},
            "pending_items": snap["pending_items"],
            "conservation_gap": snap["conservation_gap"],
        }
    if ingress is not None:
        nondet["ingress"] = ingress.journal_totals()
    return {"components": comps, "totals": totals, "nondet": nondet}


# ---------------- merge ----------------

def merge(*journals: dict) -> dict:
    """Merge N collected journals into one. Events are unioned under
    their ``(component, seq)`` key; the SAME key with a DIFFERENT
    payload raises :class:`JournalDivergence` (deterministic
    components cannot honestly disagree), as do conflicting
    deterministic totals. Nondeterministic sections are not
    equality-checked (wire counters move between scrapes); the last
    journal's view wins. The merged stream is ordered by
    ``(seq, component)`` — deterministic, and order-insensitive in
    the inputs: merging the same journals in any order yields
    bit-identical output."""
    events: Dict[tuple, tuple] = {}
    totals: Dict[str, dict] = {}
    nondet: Dict[str, dict] = {}
    for j in journals:
        for comp, rows in j.get("components", {}).items():
            for row in rows:
                key = (comp, row["seq"])
                payload = canonical_bytes(row)
                prior = events.get(key)
                if prior is not None and prior[0] != payload:
                    raise JournalDivergence(
                        f"component {comp!r} seq {row['seq']}: "
                        f"conflicting rows {prior[1]!r} != {row!r}")
                events[key] = (payload, row)
        for comp, tot in j.get("totals", {}).items():
            if comp in totals and totals[comp] != tot:
                raise JournalDivergence(
                    f"component {comp!r}: conflicting totals "
                    f"{totals[comp]!r} != {tot!r}")
            totals[comp] = tot
        nondet.update(j.get("nondet", {}))
    comps: Dict[str, List[dict]] = {}
    stream: List[dict] = []
    for comp, seq in sorted(events, key=lambda k: (k[1], k[0])):
        row = events[(comp, seq)][1]
        comps.setdefault(comp, []).append(row)
        stream.append(dict(row, component=comp))
    for rows in comps.values():
        rows.sort(key=lambda r: r["seq"])
    return {"components": comps, "events": stream, "totals": totals,
            "nondet": nondet}


def canonical(journal: dict) -> bytes:
    """Canonical bytes over the DETERMINISTIC sections only
    (components + totals): the surface two independently-merged
    journals must match bit for bit (tier-1 ``JOURNAL_OK``)."""
    return canonical_bytes({
        "components": journal.get("components", {}),
        "totals": journal.get("totals", {})})


# ---------------- the completeness law ----------------

_TERMINALS = ("verified", "failed", "rejected", "shed", "handoff")


def _sweep(deltas: Dict[int, list]) -> List[tuple]:
    """Difference-array sweep over trace-ID range endpoints: yields
    ``(width, net_admits, terminals)`` per constant segment — O(rows)
    memory no matter how many trace IDs the window covers."""
    out = []
    admits = terms = 0
    prev = None
    for point in sorted(deltas):
        if prev is not None and point > prev and (admits or terms):
            out.append((point - prev, admits, terms))
        da, dt = deltas[point]
        admits += da
        terms += dt
        prev = point
    return out


def completeness(journal: dict, drained: bool = False) -> dict:
    """Check the journal completeness law against a merged (or
    single-collection) journal. Returns ``{"gap", "checks",
    "wrapped"}`` where ``gap`` is the sum of absolute residuals —
    exactly 0 on an honest system:

    - per replica: journal admissions + journal rejections equal the
      counted submissions; every terminal kind's journal total equals
      its conservation counter; journal pending (admitted minus
      terminals) equals the counted pending items; the replica's own
      conservation residual is 0.
    - fleet: ``routed + rerouted + refused == submitted + handoffs``
      and journal refusals equal ``router_refused``; the fleet
      conservation residual is 0. When replica feeds ride along, the
      cross-layer law ``Σ replica admissions+rejections == routed +
      rerouted`` holds (same sole-client assumption as the fleet
      conservation law itself).
    - ingress (nondet): the wire-extended residual recomputed from
      the totals is 0.
    - exactly-once terminals: over the retained window — skipped per
      component once its bounded log has wrapped (reported in
      ``wrapped``, never silently) — no trace ID carries more
      terminals than net admissions (enqueues minus handoff hops);
      with ``drained=True`` (no pending work) every admitted ID must
      carry EXACTLY one.
    """
    checks: Dict[str, int] = {}
    wrapped: List[str] = []
    totals = journal.get("totals", {})
    comps = journal.get("components", {})

    replica_admit = 0
    for comp, tot in totals.items():
        if not comp.startswith("replica/"):
            continue
        jt, counts = tot["journal"], tot["counts"]
        checks[f"{comp}.admit"] = (jt["submitted"] + jt["rejected"]
                                   - counts["submitted"])
        for k in _TERMINALS:
            checks[f"{comp}.{k}"] = jt[k] - counts.get(k, 0)
        checks[f"{comp}.pending"] = (
            jt["submitted"] - jt["verified"] - jt["failed"]
            - jt["shed"] - jt["handoff"] - tot["pending_items"])
        checks[f"{comp}.conservation"] = tot["conservation_gap"]
        replica_admit += jt["submitted"] + jt["rejected"]

    ftot = totals.get("fleet")
    if ftot is not None:
        rt = ftot["route_totals"]
        checks["fleet.route_law"] = (
            rt["routed"] + rt["rerouted"] + rt["refused"]
            - ftot["submitted"] - ftot["handoffs"])
        checks["fleet.refused"] = (rt["refused"]
                                   - ftot["router_refused"])
        checks["fleet.conservation"] = ftot["conservation_gap"]
        if any(c.startswith("replica/") for c in totals):
            checks["fleet.cross_admit"] = (
                replica_admit - rt["routed"] - rt["rerouted"])

    ing = journal.get("nondet", {}).get("ingress")
    if ing is not None:
        wire = (ing["frames_received"] - ing["decoded_frames"]
                - ing["malformed_frames"])
        admit = ing["items_decoded"] - ing["accepted"] - ing["refused"]
        term = (ing["accepted"] - ing["resolved"] - ing["shed"]
                - ing["failed"] - ing["pending"])
        checks["ingress.conservation"] = (abs(wire) + abs(admit)
                                          + abs(term))

    # exactly-once terminals over the retained (unwrapped) window
    deltas: Dict[int, list] = {}

    def add(lo, n, da, dt):
        if lo is None or not n:
            return
        deltas.setdefault(lo, [0, 0])
        deltas.setdefault(lo + n, [0, 0])
        deltas[lo][0] += da
        deltas[lo][1] += dt
        deltas[lo + n][0] -= da
        deltas[lo + n][1] -= dt

    window_ok = True
    for comp, rows in comps.items():
        feed = (comp == "fleet" or comp.startswith("replica/"))
        if not feed:
            continue
        if rows and rows[0]["seq"] != 0:
            wrapped.append(comp)
            window_ok = False
            continue
        for row in rows:
            kind = row["kind"]
            if comp == "fleet":
                if kind == "refused":
                    add(row["trace_lo"], row["n"], 1, 1)
            elif kind == "enqueue":
                add(row["trace_lo"], row["n"], 1, 0)
            elif kind == "handoff":
                add(row["trace_lo"], row["n"], -1, 0)
            elif kind in ("verified", "failed", "shed"):
                add(row["trace_lo"], row["n"], 0, 1)
    violations = 0
    if window_ok:
        for width, admits, terms in _sweep(deltas):
            if drained:
                violations += width * abs(terms - admits)
            else:
                violations += width * max(0, terms - admits)
        checks["traces.exactly_once"] = violations

    gap = sum(abs(v) for v in checks.values())
    return {"gap": gap, "checks": checks, "wrapped": wrapped}


# ---------------- trace stitching ----------------

def stitch_fraction(trace_ids: Sequence[int], recorder,
                    require: Sequence[str] = ("enqueue",
                                              "terminal")) -> float:
    """The fraction of ``trace_ids`` whose stitched ``trace?id=``
    timeline contains every required segment (``wire`` / ``route`` /
    ``enqueue`` / ``terminal``) AND is seam-free (every handoff
    followed by a re-admission). The ``trace.stitch_frac``
    perf-sentinel row pins this at 1.0 on selfcheck windows; callers
    pick ``require`` to match the window's shape (a bare service
    window has no wire or route segments to demand). ``recorder`` is
    passed in (``tracing.flight_recorder``) rather than imported —
    tracing is clock-bearing by design and this module must stay
    duration-blind (the nondet lint enforces it)."""
    ids = list(trace_ids)
    if not ids:
        return 1.0
    ok = 0
    for tid in ids:
        st = recorder.trace_timeline(tid).get("stitch", {})
        if not st.get("seamless", False):
            continue
        good = True
        for seg in require:
            if seg == "terminal":
                good = good and st.get("terminal") is not None
            else:
                good = good and bool(st.get(seg))
        ok += good
    return ok / len(ids)
