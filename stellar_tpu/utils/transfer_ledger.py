"""Transfer ledger: per-resolve host↔device byte accounting (ISSUE 8).

The ROADMAP's #1 perf lever — dispatch-floor demolition — indicts three
quantities nothing measured until now: tunnel ROUND TRIPS per resolve,
host↔device BYTES moved, and CONSTANT-TABLE RE-UPLOADS per bucket (the
base/A-table claim: identical bytes shipped again and again because
nothing keeps them resident on device). This module is the instrument:
the batch engine (:mod:`stellar_tpu.parallel.batch_engine`) records
every ``device_put``/dispatch upload and every blocking fetch here, so
each resolve yields

* ``round_trips`` — blocking device fetches (one kernel call whose
  result the host waited on = one tunnel round trip);
* ``bytes_h2d`` / ``bytes_d2h`` — payload bytes each direction;
* ``redundant_constant_bytes`` — bytes whose CONTENT FINGERPRINT
  (SHA-256 of the uploaded bytes) was already uploaded before: the
  smoking gun for re-shipped constants. The device-resident constant
  cache (:mod:`stellar_tpu.parallel.residency`) now suppresses these
  re-uploads entirely — a recurring operand is served from the
  resident buffer and recorded here as a ``resident_hit`` (bytes the
  engine did NOT move) instead of h2d traffic, so after warm-up this
  counter sits at ~0 and any regrowth is a regression
  (``tools/perf_sentinel.py`` pins it to a near-zero ceiling).

Totals surface in ``dispatch_health()["transfer"]``, the Prometheus
export (``crypto.transfer.*`` counters), and every ``bench.py`` record
next to ``dispatch_attribution``; the tier-1 ``TRANSFER_LEDGER_OK``
gate (``tools/transfer_selfcheck.py``) reconciles the ledger's byte
totals against the engine's own independent accounting of what it
shipped, so a new transfer path can never go unrecorded silently
(``docs/observability.md`` "Transfer ledger").

Determinism: this module is in the nondet-lint scope — fingerprints
are content-derived (SHA-256, no salts), no clocks, no RNG. Per-event
mutation happens under the instance lock (lock-lint scope); per-resolve
tokens are handed out by :meth:`TransferLedger.begin` and accumulate
under the same lock, so concurrent resolves never tear each other's
records.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict, deque
from typing import Optional, Sequence

from stellar_tpu.utils.metrics import registry

__all__ = ["TransferLedger", "ResolveLog", "transfer_ledger"]

# defaults; Config pushes TRANSFER_LEDGER_RESOLVES /
# TRANSFER_LEDGER_FINGERPRINTS / TRANSFER_LEDGER_FP_MAX_BYTES
# through configure()
DEFAULT_RESOLVES = 256
DEFAULT_FINGERPRINTS = 4096
# content-fingerprint size cap: hashing runs on the dispatch hot path
# (inside the resolve the instrument is measuring), so uploads larger
# than this are counted bytes-only — never falsely redundant, never
# paying an unbounded SHA-256 — and surfaced in
# ``unfingerprinted_uploads`` so the detector's blind spot is visible
# rather than silent. Today's largest real operand tuple (2048-sig
# batch) is well under this; raise the knob to widen coverage.
DEFAULT_FP_MAX_BYTES = 1 << 20

_NS = "crypto.transfer"

# sentinel: "no precomputed fingerprint passed" (None is a legitimate
# value meaning "over the size cap — count bytes-only")
_UNSET = object()


class ResolveLog:
    """Accumulator for ONE resolve's transfers (opaque token: the
    engine threads it through dispatch and fetch closures; all fields
    mutate under the owning ledger's lock)."""

    __slots__ = ("ns", "round_trips", "bytes_h2d", "bytes_d2h",
                 "device_puts", "fetches", "redundant_constant_bytes",
                 "redundant_uploads", "resident_hits",
                 "resident_bytes", "finished")

    def __init__(self, ns: str):
        self.ns = ns
        self.round_trips = 0
        self.bytes_h2d = 0
        self.bytes_d2h = 0
        self.device_puts = 0
        self.fetches = 0
        self.redundant_constant_bytes = 0
        self.redundant_uploads = 0
        self.resident_hits = 0
        self.resident_bytes = 0
        self.finished = False

    def snapshot_locked(self) -> dict:
        return {"ns": self.ns,
                "round_trips": self.round_trips,
                "bytes_h2d": self.bytes_h2d,
                "bytes_d2h": self.bytes_d2h,
                "device_puts": self.device_puts,
                "fetches": self.fetches,
                "redundant_constant_bytes":
                    self.redundant_constant_bytes,
                "redundant_uploads": self.redundant_uploads,
                "resident_hits": self.resident_hits,
                "resident_bytes": self.resident_bytes}


class TransferLedger:
    """Process-wide transfer accounting: running totals, a bounded
    ring of per-resolve records, and a bounded LRU of upload content
    fingerprints for redundancy detection."""

    def __init__(self, resolves: int = DEFAULT_RESOLVES,
                 fingerprints: int = DEFAULT_FINGERPRINTS,
                 fp_max_bytes: int = DEFAULT_FP_MAX_BYTES):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(4, int(resolves)))
        self._fp_cap = max(16, int(fingerprints))
        self._fp_max_bytes = max(0, int(fp_max_bytes))
        self._unfingerprinted_uploads = 0
        self._unfingerprinted_bytes = 0
        # fingerprint -> times uploaded (bounded LRU: eviction only
        # forgets OLD constants, so a long-lived table re-shipped every
        # bucket keeps counting as redundant)
        self._fingerprints: OrderedDict = OrderedDict()
        self._round_trips = 0
        self._bytes_h2d = 0
        self._bytes_d2h = 0
        self._device_puts = 0
        self._fetches = 0
        self._redundant_constant_bytes = 0
        self._redundant_uploads = 0
        self._resident_hits = 0
        self._resident_bytes = 0
        self._resolves_finished = 0

    def configure(self, resolves: Optional[int] = None,
                  fingerprints: Optional[int] = None,
                  fp_max_bytes: Optional[int] = None) -> None:
        """Config push (TRANSFER_LEDGER_*); None keeps current."""
        with self._lock:
            if resolves is not None:
                cap = max(4, int(resolves))
                if cap != self._ring.maxlen:
                    self._ring = deque(self._ring, maxlen=cap)
            if fingerprints is not None:
                self._fp_cap = max(16, int(fingerprints))
                while len(self._fingerprints) > self._fp_cap:
                    self._fingerprints.popitem(last=False)
            if fp_max_bytes is not None:
                self._fp_max_bytes = max(0, int(fp_max_bytes))

    # ---------------- per-resolve recording ----------------

    def begin(self, ns: str) -> ResolveLog:
        """Open a per-resolve token (not registered anywhere until
        :meth:`finish` — a resolver the caller drops just gets
        garbage-collected; its event-level totals were already
        counted)."""
        return ResolveLog(ns)

    def record_h2d(self, tok: Optional[ResolveLog], arr,
                   device: Optional[int] = None, fp=_UNSET) -> int:
        """One host→device upload (``device_put`` or a committed
        dispatch operand). Fingerprints the CONTENT: a fingerprint
        seen before means these exact bytes were already shipped —
        redundant re-upload. Uploads larger than the fingerprint cap
        (``TRANSFER_LEDGER_FP_MAX_BYTES``) are counted bytes-only:
        the hash runs on the dispatch hot path, so its cost must stay
        bounded, and a sampled/partial hash could convict different
        content as redundant — the skipped uploads are tallied in
        ``unfingerprinted_uploads`` instead. ``fp`` lets the engine
        pass the fingerprint it already computed for the resident
        cache (one SHA-256 per upload, not two); omit it and the
        ledger hashes for itself. Returns the byte count."""
        nbytes = int(arr.nbytes)
        if fp is _UNSET and nbytes <= self._fp_max_bytes:
            # zero-copy for the engine's C-contiguous operands (axis-0
            # slices / concatenate results); tobytes() only as the
            # fallback for exotic layouts
            try:
                buf = memoryview(arr)
                if not buf.c_contiguous:
                    buf = arr.tobytes()
            except TypeError:
                buf = arr.tobytes()
            fp = hashlib.sha256(buf).digest()[:16]
        elif fp is _UNSET:
            fp = None
        with self._lock:
            if fp is not None:
                seen = self._fingerprints.pop(fp, 0)
                self._fingerprints[fp] = seen + 1
                while len(self._fingerprints) > self._fp_cap:
                    self._fingerprints.popitem(last=False)
            else:
                seen = 0
                self._unfingerprinted_uploads += 1
                self._unfingerprinted_bytes += nbytes
            self._bytes_h2d += nbytes
            self._device_puts += 1
            redundant = seen > 0
            if redundant:
                self._redundant_constant_bytes += nbytes
                self._redundant_uploads += 1
            if tok is not None:
                tok.bytes_h2d += nbytes
                tok.device_puts += 1
                if redundant:
                    tok.redundant_constant_bytes += nbytes
                    tok.redundant_uploads += 1
        registry.counter(f"{_NS}.bytes_h2d").inc(nbytes)
        registry.counter(f"{_NS}.device_puts").inc()
        if redundant:
            registry.counter(
                f"{_NS}.redundant_constant_bytes").inc(nbytes)
            registry.counter(f"{_NS}.redundant_uploads").inc()
        return nbytes

    def record_h2d_many(self, tok: Optional[ResolveLog],
                        arrays: Sequence,
                        device: Optional[int] = None) -> int:
        """Upload of one operand tuple; returns total bytes."""
        return sum(self.record_h2d(tok, a, device=device)
                   for a in arrays)

    def record_resident_hit(self, tok: Optional[ResolveLog], arr,
                            device: Optional[int] = None) -> int:
        """One operand served from the device-resident constant cache
        (:mod:`stellar_tpu.parallel.residency`): NO bytes moved, no
        fingerprint churn — the upload the redundancy detector used
        to convict simply never happens. Tallied separately so the
        bench record shows both sides of the rework: h2d collapsing
        AND the resident hits that replaced it. Returns the byte
        count the hit avoided."""
        nbytes = int(arr.nbytes)
        with self._lock:
            self._resident_hits += 1
            self._resident_bytes += nbytes
            if tok is not None:
                tok.resident_hits += 1
                tok.resident_bytes += nbytes
        registry.counter(f"{_NS}.resident_hits").inc()
        registry.counter(f"{_NS}.resident_bytes").inc(nbytes)
        return nbytes

    def record_d2h(self, tok: Optional[ResolveLog], arr,
                   device: Optional[int] = None) -> int:
        """One blocking device→host fetch — BY DEFINITION one tunnel
        round trip (the host parked on this result). Returns bytes."""
        nbytes = int(arr.nbytes)
        with self._lock:
            self._bytes_d2h += nbytes
            self._fetches += 1
            self._round_trips += 1
            if tok is not None:
                tok.bytes_d2h += nbytes
                tok.fetches += 1
                tok.round_trips += 1
        registry.counter(f"{_NS}.bytes_d2h").inc(nbytes)
        registry.counter(f"{_NS}.fetches").inc()
        registry.counter(f"{_NS}.round_trips").inc()
        return nbytes

    def finish(self, tok: Optional[ResolveLog]) -> Optional[dict]:
        """Close a resolve's token into the per-resolve ring
        (idempotent — a resolver resolved twice records once)."""
        if tok is None:
            return None
        with self._lock:
            rec = tok.snapshot_locked()
            if not tok.finished:
                tok.finished = True
                self._ring.append(rec)
                self._resolves_finished += 1
        return rec

    # ---------------- introspection ----------------

    def totals(self) -> dict:
        """Running process totals — the ``dispatch_health()``
        ``transfer`` block and the bench-record embed."""
        with self._lock:
            return {
                "round_trips": self._round_trips,
                "bytes_h2d": self._bytes_h2d,
                "bytes_d2h": self._bytes_d2h,
                "device_puts": self._device_puts,
                "fetches": self._fetches,
                "redundant_constant_bytes":
                    self._redundant_constant_bytes,
                "redundant_uploads": self._redundant_uploads,
                "resident_hits": self._resident_hits,
                "resident_bytes": self._resident_bytes,
                "resolves_recorded": self._resolves_finished,
                "fingerprints_tracked": len(self._fingerprints),
                "unfingerprinted_uploads":
                    self._unfingerprinted_uploads,
                "unfingerprinted_bytes": self._unfingerprinted_bytes,
            }

    def recent(self, limit: int = 32) -> list:
        """The most recent per-resolve records (admin/bench drill-in);
        ``limit=0`` means none."""
        limit = max(0, int(limit))
        with self._lock:
            return [dict(r) for r in
                    (list(self._ring)[-limit:] if limit else [])]

    def _reset_for_testing(self) -> None:
        """Fresh ledger state (per-resolve ring, fingerprints, totals).
        Cumulative registry counters are untouched — same policy as
        the dispatch layer's reset."""
        with self._lock:
            self._ring.clear()
            self._fingerprints.clear()
            self._unfingerprinted_uploads = 0
            self._unfingerprinted_bytes = 0
            self._round_trips = 0
            self._bytes_h2d = 0
            self._bytes_d2h = 0
            self._device_puts = 0
            self._fetches = 0
            self._redundant_constant_bytes = 0
            self._redundant_uploads = 0
            self._resident_hits = 0
            self._resident_bytes = 0
            self._resolves_finished = 0


# process-wide ledger (one node per process, like the registry and the
# flight recorder)
transfer_ledger = TransferLedger()
