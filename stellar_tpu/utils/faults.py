"""Fault-injection harness for chaos testing the dispatch layer.

Production code plants named injection points (``faults.inject("...")``)
at the spots whose real-world failure modes we must survive — the
device probe, the kernel dispatch, the device-array resolve. With no
fault armed the call is a dict lookup on an empty dict; with one armed
it misbehaves in a controlled, configurable way so
``tests/test_chaos_dispatch.py`` can drive the breaker/deadline/
failover machinery on CPU, no broken tunnel required.

Faults are armed programmatically (:func:`set_fault`) or via the
``STELLAR_TPU_FAULTS`` environment variable, e.g.::

    STELLAR_TPU_FAULTS="device.resolve=hang:2;device.probe=raise"

Modes (``mode[:arg]``):

* ``raise[:msg]``   — raise :class:`FaultInjected` on every call;
* ``hang[:secs]``   — sleep ``secs`` (default 30) per call: the
  dead-tunnel shape, where calls block instead of raising;
* ``flake[:k]``     — raise on every k-th call (default 2): an
  intermittently healthy link;
* ``failn[:n]``     — raise on the first ``n`` calls (default 1), then
  behave: a link that recovers (breaker re-close path).

Per-device modes (``mode:<device_index>`` — fault ONE chip of a mesh,
the fault-domain chaos shapes of ``docs/robustness.md``):

* ``fail-device:<idx>``    — raise on every call attributed to mesh
  device ``idx``; other devices behave (single-chip outage);
* ``flaky-device:<idx>``   — raise on every 2nd call attributed to
  device ``idx`` (an intermittently sick chip, breaker flapping);
* ``corrupt-device:<idx>`` — never raises: calls succeed, but verdict
  arrays fetched from device ``idx`` come back BIT-FLIPPED via
  :func:`corrupt_verdicts` — the silently-corrupting-chip shape that
  only the result-integrity audit can catch;
* ``stall-device:<idx>``   — never raises: sleeps
  :data:`STALL_DEVICE_SECONDS` (``set_fault(..., seconds=)``
  overrides) before every call attributed to device ``idx`` — the
  host-side inter-dispatch stall shape the pipeline-bubble profiler
  must attribute as a bubble (ISSUE 10,
  ``tools/pipeline_selfcheck.py``);
* ``stall-transfer:<idx>`` — never raises: same sleep, but armed at
  the H2D UPLOAD point (``device.transfer``) instead of the kernel
  call — a slow host→device transfer lane, distinguishable from a
  slow kernel enqueue. The profiler must attribute the delay as
  ``queue_wait`` on the stalled device (the host was moving bytes,
  not encoding — prep-vs-queue_wait attribution, ISSUE 12).

Production code attributes a call to a device by passing
``inject(point, device=i)``; calls with ``device=None`` (single-device
dispatch) never match a per-device fault.

Wire modes (ISSUE 19 — the misbehaving-client shapes of the ingress
chaos gate, ``tools/ingress_selfcheck.py``). These are armed at
CLIENT-side points (by convention ``wire.client.<shape>``) and fire
through :func:`wire_plan` / :func:`send_mangled`, never through
``trip()`` — they mangle what a client PUTS ON THE WIRE, they do not
make server code misbehave:

* ``torn-frame``          — split every send at deterministic,
  call-count-derived byte offsets (every fragment is a legal TCP
  segmentation the server must reassemble);
* ``slow-client:<bytes/s>`` — trickle the send in small chunks with
  pacing sleeps: the slow-loris shape the per-connection read
  deadline must bound;
* ``disconnect-mid-batch`` — send roughly half the frame, then close
  the connection;
* ``garbage-prefix``      — prepend junk bytes that are not a valid
  frame type (the server must reject typed and drop the connection,
  never desync);
* ``oversize-frame``      — send a header declaring a length over the
  server's frame ceiling (the server must refuse WITHOUT buffering).

All five plans are deterministic — offsets and junk derive from the
fault's own call counter, never an RNG (``faults.py`` sits in the
lock-lint scope; the chaos mesh stays replayable).

Injection points currently planted:

* ``device.probe``    — inside the backend probe thread
  (``batch_verifier.start_device_probe``);
* ``device.transfer`` — immediately before the h2d operand upload
  (``device_put`` — per-device on the sub-chunk path, once per
  assigned device on the coalesced per-mesh upload);
* ``device.dispatch`` — immediately before the jitted kernel call
  (device-attributed on the per-device mesh path);
* ``device.resolve``  — inside the (deadline-guarded) device-array
  fetch (device-attributed; also the ``corrupt_verdicts`` hook).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Optional

__all__ = ["FaultInjected", "inject", "corrupt_verdicts", "is_active",
           "set_fault", "clear", "counters", "load_spec",
           "wire_plan", "send_mangled", "WIRE_MODES"]

PROBE = "device.probe"
TRANSFER = "device.transfer"
DISPATCH = "device.dispatch"
RESOLVE = "device.resolve"

WIRE_MODES = ("torn-frame", "slow-client", "disconnect-mid-batch",
              "garbage-prefix", "oversize-frame")
_MODES = ("raise", "hang", "flake", "failn",
          "fail-device", "flaky-device", "corrupt-device",
          "stall-device", "stall-transfer") + WIRE_MODES
_DEVICE_MODES = ("fail-device", "flaky-device", "corrupt-device",
                 "stall-device", "stall-transfer")

# default sleep for stall-device (set_fault's ``seconds`` overrides)
STALL_DEVICE_SECONDS = 0.05

_lock = threading.Lock()
_active: Dict[str, "_Fault"] = {}


class FaultInjected(RuntimeError):
    """The exception raised by armed ``raise``/``flake``/``failn``
    faults — deliberately NOT a subclass of anything the dispatch layer
    special-cases, so injected faults exercise the generic handlers."""


class _Fault:
    def __init__(self, point: str, mode: str, arg: Optional[float],
                 seconds: Optional[float] = None):
        if mode not in _MODES:
            raise ValueError(f"unknown fault mode {mode!r} "
                             f"(one of {_MODES})")
        if mode in _DEVICE_MODES and arg is None:
            raise ValueError(f"{mode} needs a device index "
                             f"({mode}:<idx>)")
        self.point = point
        self.mode = mode
        self.arg = arg
        self.seconds = seconds
        self.calls = 0   # times the injection point was reached
        self.fired = 0   # times it actually misbehaved

    def trip(self, device: Optional[int] = None) -> None:
        if self.mode in WIRE_MODES:
            # wire faults mangle client SENDS (wire_plan), they never
            # fire at an inject() site
            return
        if self.mode in _DEVICE_MODES:
            # device-scoped faults only see (and only count) calls
            # attributed to their device; corruption never raises —
            # it is applied to the fetched verdicts, see
            # corrupt_verdicts()
            if device is None or int(device) != int(self.arg) or \
                    self.mode == "corrupt-device":
                return
        with _lock:
            self.calls += 1
            n = self.calls
        if self.mode in ("raise", "hang", "fail-device",
                         "stall-device", "stall-transfer"):
            fire = True
        elif self.mode in ("flake", "flaky-device"):
            k = 2 if self.mode == "flaky-device" else \
                int(self.arg if self.arg else 2)
            fire = n % k == 0
        else:  # failn
            fire = n <= int(self.arg if self.arg is not None else 1)
        if not fire:
            return
        with _lock:
            self.fired += 1
        if self.mode == "hang":
            time.sleep(float(self.arg) if self.arg is not None else 30.0)
            return
        if self.mode in ("stall-device", "stall-transfer"):
            # a stall, not a failure: the dispatch/upload proceeds
            # after the sleep — the bubble profiler must SEE the
            # delay, nothing in the fault-tolerance machinery should
            # trip on it
            time.sleep(self.seconds if self.seconds is not None
                       else STALL_DEVICE_SECONDS)
            return
        raise FaultInjected(f"injected fault at {self.point} "
                            f"({self.mode}, call #{n})")


def inject(point: str, device: Optional[int] = None) -> None:
    """Trip the fault armed at ``point``; no-op when nothing is armed.
    This is the call production code plants at an injection site.
    ``device`` attributes the call to one mesh device so per-device
    fault shapes can single it out."""
    if not _active:  # fast path: chaos off
        return
    f = _active.get(point)
    if f is not None:
        f.trip(device=device)


def corrupt_verdicts(point: str, device: Optional[int], arr):
    """Result-corruption hook: with ``corrupt-device:<idx>`` armed at
    ``point`` and ``device`` matching, returns the verdict array
    BIT-FLIPPED (and counts a fire); otherwise returns ``arr``
    unchanged. Planted where the dispatch layer materializes device
    verdicts — the silently-wrong-bits chip that hangs nothing and
    raises nothing, detectable only by re-verifying results."""
    if not _active:  # fast path: chaos off
        return arr
    f = _active.get(point)
    if f is None or f.mode != "corrupt-device" or device is None or \
            int(device) != int(f.arg):
        return arr
    with _lock:
        f.calls += 1
        f.fired += 1
    return ~arr


def is_active(point: str) -> bool:
    return point in _active


def set_fault(point: str, mode: str, arg: Optional[float] = None,
              seconds: Optional[float] = None) -> None:
    """Arm ``point`` with ``mode`` (see module docstring);
    ``seconds`` overrides the stall-device sleep."""
    f = _Fault(point, mode, arg, seconds=seconds)
    with _lock:
        _active[point] = f


def clear(point: Optional[str] = None) -> None:
    """Disarm one point, or every point when ``point`` is None."""
    with _lock:
        if point is None:
            _active.clear()
        else:
            _active.pop(point, None)


def counters() -> Dict[str, dict]:
    """Per-point {calls, fired} — how often each armed site was reached
    and how often it actually misbehaved (chaos-test assertions)."""
    with _lock:
        return {p: {"mode": f.mode, "calls": f.calls, "fired": f.fired}
                for p, f in _active.items()}


def wire_plan(point: str, nbytes: int) -> Optional[dict]:
    """The mangling plan for one client send of ``nbytes`` at
    ``point`` — None when no wire fault is armed there. Plans are
    pure functions of the fault's own call counter (no RNG, no
    clock), so a chaos run's byte stream is replayable. Counts a
    call AND a fire per consult — every armed send misbehaves."""
    if not _active:  # fast path: chaos off
        return None
    f = _active.get(point)
    if f is None or f.mode not in WIRE_MODES:
        return None
    with _lock:
        f.calls += 1
        f.fired += 1
        n = f.calls
    if f.mode == "torn-frame":
        span = max(1, nbytes - 1)
        splits = sorted({1 + (n * 7) % span,
                         1 + (n * 13 + 3) % span,
                         1 + (n * 29 + 11) % span})
        return {"mode": "torn-frame", "splits": splits}
    if f.mode == "slow-client":
        rate = float(f.arg) if f.arg else 4096.0
        chunk = 16
        return {"mode": "slow-client", "chunk": chunk,
                "sleep_s": chunk / max(1.0, rate)}
    if f.mode == "disconnect-mid-batch":
        return {"mode": "disconnect-mid-batch",
                "cut": max(1, nbytes // 2)}
    if f.mode == "garbage-prefix":
        junk = bytes(16 + (n * 31 + i * 7) % 224 for i in range(8))
        return {"mode": "garbage-prefix", "junk": junk}
    # oversize-frame: a header declaring arg (default 2x the codec
    # ceiling) payload bytes, plus a little filler so the server's
    # reject provably fires on the DECLARATION, not a read timeout
    declared = int(f.arg) if f.arg else 2 * (1 << 20)
    return {"mode": "oversize-frame", "declared": declared}


def send_mangled(sock, data, point: str) -> bool:
    """Send ``data`` on ``sock`` through the wire fault armed at
    ``point`` (plain ``sendall`` when none is). Returns False when
    the plan deliberately closed the connection (the
    disconnect-mid-batch shape), True otherwise. Never called with a
    lock held — sends and pacing sleeps block."""
    plan = wire_plan(point, len(data))
    if plan is None:
        sock.sendall(data)
        return True
    mode = plan["mode"]
    if mode == "torn-frame":
        pos = 0
        for cut in plan["splits"] + [len(data)]:
            if cut > pos:
                sock.sendall(data[pos:cut])
                pos = cut
        return True
    if mode == "slow-client":
        for off in range(0, len(data), plan["chunk"]):
            sock.sendall(data[off:off + plan["chunk"]])
            time.sleep(plan["sleep_s"])
        return True
    if mode == "disconnect-mid-batch":
        sock.sendall(data[:plan["cut"]])
        # shutdown acts on the connection itself (close alone leaves
        # the kernel description alive while a reader thread is
        # blocked in recv — no FIN would reach the server)
        import socket as _socket
        try:
            sock.shutdown(_socket.SHUT_RDWR)
        except OSError:
            pass
        sock.close()
        return False
    if mode == "garbage-prefix":
        sock.sendall(plan["junk"] + bytes(data))
        return True
    # oversize-frame: bogus SUBMIT header + filler instead of data
    import struct as _struct
    sock.sendall(_struct.pack(">BI", 0x01, plan["declared"])
                 + b"\x00" * 16)
    return True


def load_spec(spec: str) -> None:
    """Parse a ``point=mode[:arg][;point=mode[:arg]...]`` spec string
    (the ``STELLAR_TPU_FAULTS`` grammar) and arm each entry."""
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        point, _, rhs = part.partition("=")
        mode, _, arg = rhs.partition(":")
        set_fault(point.strip(), mode.strip(),
                  float(arg) if arg else None)


_env_spec = os.environ.get("STELLAR_TPU_FAULTS", "")
if _env_spec:
    load_spec(_env_spec)
