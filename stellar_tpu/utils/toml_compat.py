"""Minimal TOML parser used when stdlib ``tomllib`` is unavailable
(Python < 3.11 — this container ships 3.10 and nothing may be installed).

Covers exactly the subset the node config format uses (see
``docs/stellar_tpu_example.cfg`` and ``Config.from_toml``):

* comments, blank lines
* ``key = value`` with bare or quoted keys
* basic/literal strings, integers, floats, booleans
* arrays, including multi-line arrays and trailing commas
* ``[table]`` / ``[dotted.table]`` headers
* ``[[array.of.tables]]`` headers

Deliberately NOT covered (the config never uses them, and a strict
error beats silent misparsing): datetimes, inline tables, multi-line
strings, dotted keys on the left-hand side, exotic escapes.

API matches the two entry points ``Config.from_toml`` needs:
``load(binary_fp)`` and ``loads(text)``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

__all__ = ["load", "loads", "TOMLDecodeError"]


class TOMLDecodeError(ValueError):
    pass


def load(fp) -> Dict[str, Any]:
    data = fp.read()
    if isinstance(data, bytes):
        data = data.decode("utf-8")
    return loads(data)


def loads(text: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    current = root
    declared = set()  # [table] headers seen, for tomllib-equal strictness
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i]).strip()
        i += 1
        if not line:
            continue
        if line.startswith("[["):
            if not line.endswith("]]"):
                raise TOMLDecodeError(f"bad table-array header: {line}")
            parent, leaf = _walk(root, line[2:-2].strip())
            arr = parent.setdefault(leaf, [])
            if not isinstance(arr, list):
                raise TOMLDecodeError(f"{leaf} is not a table array")
            current = {}
            arr.append(current)
            continue
        if line.startswith("["):
            if not line.endswith("]"):
                raise TOMLDecodeError(f"bad table header: {line}")
            name = line[1:-1].strip()
            if name in declared:
                # stdlib tomllib rejects re-declared tables; silently
                # merging here would make config validity depend on the
                # Python version
                raise TOMLDecodeError(f"cannot declare table twice: {name}")
            declared.add(name)
            parent, leaf = _walk(root, name)
            current = parent.setdefault(leaf, {})
            if not isinstance(current, dict):
                raise TOMLDecodeError(f"{leaf} is not a table")
            continue
        if "=" not in line:
            raise TOMLDecodeError(f"expected key = value: {line}")
        key, _, rest = line.partition("=")
        key = _parse_key(key.strip())
        rest = rest.strip()
        # multi-line arrays: keep consuming lines until brackets balance
        while _open_brackets(rest) > 0:
            if i >= len(lines):
                raise TOMLDecodeError(f"unterminated array for key {key}")
            rest += " " + _strip_comment(lines[i]).strip()
            i += 1
        value, pos = _parse_value(rest, 0)
        if rest[pos:].strip():
            raise TOMLDecodeError(
                f"trailing garbage after value for {key}: {rest[pos:]!r}")
        if key in current:
            raise TOMLDecodeError(f"duplicate key {key}")
        current[key] = value
    return root


def _walk(root: Dict[str, Any], dotted: str) -> Tuple[Dict[str, Any], str]:
    """Resolve a dotted table path, returning (parent_table, leaf_name).
    Intermediate array-of-tables segments resolve to their last element."""
    parts = [p.strip() for p in dotted.split(".")]
    if not parts or any(not p for p in parts):
        raise TOMLDecodeError(f"bad table name: {dotted}")
    node = root
    for part in parts[:-1]:
        part = _parse_key(part)
        nxt = node.setdefault(part, {})
        if isinstance(nxt, list):
            if not nxt:
                raise TOMLDecodeError(f"empty table array {part}")
            nxt = nxt[-1]
        if not isinstance(nxt, dict):
            raise TOMLDecodeError(f"{part} is not a table")
        node = nxt
    return node, _parse_key(parts[-1])


def _parse_key(key: str) -> str:
    if len(key) >= 2 and key[0] == key[-1] and key[0] in "\"'":
        return key[1:-1]
    if not key or not all(c.isalnum() or c in "-_" for c in key):
        raise TOMLDecodeError(f"bad key: {key!r}")
    return key


def _strip_comment(line: str) -> str:
    """Drop a # comment, ignoring # inside strings (backslash escapes
    only count inside basic strings — literal '...' strings have none)."""
    quote = None
    idx = 0
    while idx < len(line):
        c = line[idx]
        if quote is None:
            if c in "\"'":
                quote = c
            elif c == "#":
                return line[:idx]
        elif quote == '"' and c == "\\":
            idx += 1  # skip the escaped character (e.g. \" or \\)
        elif c == quote:
            quote = None
        idx += 1
    return line


def _open_brackets(s: str) -> int:
    depth = 0
    quote = None
    for c in s:
        if quote is None:
            if c in "\"'":
                quote = c
            elif c == "[":
                depth += 1
            elif c == "]":
                depth -= 1
        elif c == quote:
            quote = None
    return depth


_ESCAPES = {"n": "\n", "t": "\t", "r": "\r", '"': '"', "\\": "\\",
            "b": "\b", "f": "\f"}


def _parse_value(s: str, pos: int) -> Tuple[Any, int]:
    while pos < len(s) and s[pos].isspace():
        pos += 1
    if pos >= len(s):
        raise TOMLDecodeError("expected a value")
    c = s[pos]
    if c == "[":
        return _parse_array(s, pos)
    if c == '"' or c == "'":
        return _parse_string(s, pos)
    # bare scalar: booleans, ints, floats
    end = pos
    while end < len(s) and s[end] not in ",]\t #":
        end += 1
    tok = s[pos:end].strip()
    if tok == "true":
        return True, end
    if tok == "false":
        return False, end
    try:
        if any(ch in tok for ch in ".eE") and not tok.startswith("0x"):
            return float(tok), end
        return int(tok.replace("_", ""), 0), end
    except ValueError:
        raise TOMLDecodeError(f"bad value: {tok!r}")


def _parse_string(s: str, pos: int) -> Tuple[str, int]:
    quote = s[pos]
    out: List[str] = []
    i = pos + 1
    while i < len(s):
        c = s[i]
        if c == "\\" and quote == '"':
            if i + 1 >= len(s):
                raise TOMLDecodeError("dangling escape")
            nxt = s[i + 1]
            if nxt == "u" and i + 5 < len(s):
                out.append(chr(int(s[i + 2:i + 6], 16)))
                i += 6
                continue
            if nxt not in _ESCAPES:
                raise TOMLDecodeError(f"unsupported escape \\{nxt}")
            out.append(_ESCAPES[nxt])
            i += 2
            continue
        if c == quote:
            return "".join(out), i + 1
        out.append(c)
        i += 1
    raise TOMLDecodeError("unterminated string")


def _parse_array(s: str, pos: int) -> Tuple[List[Any], int]:
    out: List[Any] = []
    i = pos + 1
    while True:
        while i < len(s) and s[i] in " \t,":
            i += 1
        if i >= len(s):
            raise TOMLDecodeError("unterminated array")
        if s[i] == "]":
            return out, i + 1
        val, i = _parse_value(s, i)
        out.append(val)
