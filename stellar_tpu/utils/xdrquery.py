"""xdrquery-lite: field-path filters over decoded XDR values (reference
``src/util/xdrquery`` — the mini DSL behind ``dump-ledger --filter``).

Grammar (scoped): ``<path> <op> <value>`` joined by ``&&``; ops are
``== != < <= > >=``. A path walks struct fields dot-separated; union
values are transparent (a segment applies to the active arm's payload),
and the special leading segment ``type`` resolves an entry's
LedgerEntryType name. Values: integers, single-quoted strings, or hex
byte strings.
"""

from __future__ import annotations

import re
from typing import Any, Callable, List

__all__ = ["compile_query"]

_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
}

_TERM = re.compile(
    r"^\s*([A-Za-z_][A-Za-z0-9_.]*)\s*(==|!=|<=|>=|<|>)\s*(.+?)\s*$")


def _value_for(raw: str, got):
    """Interpret the literal in the light of what it compares against
    (a digit string can be an int or hex bytes)."""
    raw = raw.strip()
    if raw.startswith("'") and raw.endswith("'"):
        return raw[1:-1]
    if isinstance(got, (bytes, bytearray)):
        h = raw.removeprefix("0x")
        if re.fullmatch(r"[0-9a-fA-F]+", h) and len(h) % 2 == 0:
            return bytes.fromhex(h)
        return raw.encode()
    if re.fullmatch(r"-?[0-9]+", raw):
        return int(raw)
    return raw


def _walk(obj: Any, segments: List[str]):
    """Resolve a dotted path; unions are transparent."""
    for seg in segments:
        # unwrap union values until a struct with the field appears
        for _ in range(4):
            if hasattr(obj, seg):
                break
            if hasattr(obj, "value"):
                obj = obj.value
            else:
                raise AttributeError(seg)
        obj = getattr(obj, seg)
    # final unwrap for comparisons against payloads
    return obj


def compile_query(query: str) -> Callable[[Any], bool]:
    """Compile ``query`` into a predicate over LedgerEntry values."""
    terms = []
    for part in query.split("&&"):
        m = _TERM.match(part)
        if m is None:
            raise ValueError(f"bad query term: {part!r}")
        path, op, raw = m.groups()
        terms.append((path.split("."), _OPS[op], raw))

    def predicate(entry) -> bool:
        for segments, op, raw in terms:
            try:
                if segments[0] == "type":
                    from stellar_tpu.xdr.types import LedgerEntryType
                    got = LedgerEntryType.name_of(entry.data.arm)
                else:
                    got = _walk(entry, segments)
                    # unwrap simple wrappers (Union.Value payloads)
                    for _ in range(2):
                        if isinstance(got, (int, str, bytes, bytearray,
                                            bool)):
                            break
                        inner = getattr(got, "value", None)
                        if inner is None:
                            break
                        got = inner
                want = _value_for(raw, got)
                if isinstance(got, (bytes, bytearray)) and \
                        isinstance(want, str):
                    got = got.decode("utf-8", "replace")
                if not op(got, want):
                    return False
            except Exception:
                return False
        return True

    return predicate
