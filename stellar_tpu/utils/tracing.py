"""Structured tracing: spans + zones + the resolve flight recorder
(reference: Tracy ``ZoneScoped`` annotations — 672 across ``src/`` —
plus ``util/LogSlowExecution.h`` wall-time watchdogs; ISSUE 5 grows
the zone layer into structured spans with IDs, parent links and
cross-thread context propagation).

Model:

* a :class:`span` is a context manager that times one phase of work.
  On entry it draws a process-unique ``span_id``, links to the
  innermost live span of the current thread as ``parent_id``, and
  registers an OPEN record with the :class:`FlightRecorder`; on exit
  it feeds the inclusive duration into the registry timer
  ``span.<name>`` (a reservoir histogram — p50/p90/p99 export) and
  moves the record into the recorder's bounded ring.
* :class:`zone` is the historical spelling (timer prefix ``zone.``);
  it is a span, so every existing ``with zone(...)`` call site gained
  span IDs and recorder coverage for free.
* **cross-thread propagation**: :func:`current_context` captures the
  caller's innermost span id; :class:`span_context` installs it as the
  parent on another thread. ``resilience.WatchdogPool`` does this for
  every guarded call, so a span opened inside a pooled device fetch
  parents correctly under the resolve that submitted it — which is
  exactly what makes a HUNG fetch attributable in a dump.
* the :class:`FlightRecorder` keeps the last N completed spans plus
  every still-open span in memory; ``dump(reason)`` snapshots both on
  breaker trips, audit mismatches, watchdog timeouts and the verify
  service's first load-shed onset (``service-shed:<why>`` —
  ``crypto/batch_verifier.py`` wires all the triggers) so the spans
  leading into a failure survive to be read from the ``spans`` admin
  route. See ``docs/observability.md``.
* **span phase families**: ``verify.*`` phases attribute one blocking
  resolve (``batch_verifier.RESOLVE_PHASES``); ``service.dispatch`` /
  ``service.resolve`` wrap the resident verify service's continuous-
  batching cycle (``crypto/verify_service.py``), so a recorder dump
  taken under overload shows which lane's batch each in-flight
  dispatch is serving.

Determinism: this module is clock-bearing BY DESIGN (``perf_counter``
pairs). Its timings feed metrics and the recorder, never decisions —
and the nondet lint (``stellar_tpu/analysis/nondet.py``) fences
everything except the duration-blind context managers out of the
consensus modules.

Zone times are inclusive, like Tracy; the thread-local stack exists
for parent links and the ``current_zones`` introspection.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from stellar_tpu.utils.metrics import registry

__all__ = ["span", "zone", "LogSlowExecution", "current_zones",
           "current_context", "span_context", "frame_mark",
           "FlightRecorder", "flight_recorder", "span_totals"]

_log = logging.getLogger("stellar_tpu.perf")

_tls = threading.local()

# span ids: process-unique, monotone. itertools.count.__next__ is a
# single C call (atomic under the GIL).
_ids = itertools.count(1)

# time origin for span start stamps: milliseconds since tracing
# import, monotonic — no wall clock enters the records
_EPOCH = time.perf_counter()


def _now_ms() -> float:
    return (time.perf_counter() - _EPOCH) * 1000.0


def _stack() -> list:
    s = getattr(_tls, "zones", None)
    if s is None:
        s = _tls.zones = []
    return s


def current_zones() -> List[str]:
    """The live zone/span names of this thread (innermost last);
    context anchors are invisible."""
    return [e.name for e in _stack() if e.name is not None]


def current_context() -> Optional[int]:
    """The innermost live span id of this thread (None outside any
    span) — hand it to another thread via :class:`span_context` so
    spans opened there parent under this one."""
    s = _stack()
    return s[-1].span_id if s else None


class FlightRecorder:
    """Bounded in-memory ring of span records + the set of still-open
    spans, dumped on failure triggers (breaker trips, audit
    mismatches, watchdog timeouts).

    Records are plain dicts: ``{"id", "parent", "name", "thread",
    "start_ms", "dur_ms"}`` (+ optional ``attrs`` / ``event`` /
    ``open`` / ``abandoned`` flags). ``dur_ms`` is None while a span
    is open — a dump therefore shows exactly where each in-flight
    thread is parked, with parent links back to the resolve that got
    it there. All shared state mutates under the instance lock (the
    lock-discipline lint covers this module)."""

    DEFAULT_CAPACITY = 4096

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._active: Dict[int, dict] = {}
        self._dumps: deque = deque(maxlen=8)
        self._dumps_total = 0
        self._recorded_total = 0

    def configure(self, capacity: Optional[int] = None) -> None:
        """Config push (FLIGHT_RECORDER_SPANS); None keeps current."""
        if capacity is None:
            return
        cap = max(16, int(capacity))
        with self._lock:
            if cap != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=cap)

    # ---------------- span lifecycle ----------------

    def start_span(self, rec: dict) -> None:
        with self._lock:
            self._active[rec["id"]] = rec

    def finish_span(self, rec: dict) -> None:
        with self._lock:
            self._active.pop(rec["id"], None)
            self._ring.append(rec)
            self._recorded_total += 1

    def abandon_span(self, rec: dict) -> None:
        """A span whose ``__exit__`` never ran (orphan found by an
        outer span's defensive pop): closed into the ring with an
        ``abandoned`` flag and no duration."""
        rec["abandoned"] = True
        self.finish_span(rec)

    def note(self, name: str, **attrs) -> None:
        """Instant event record (duration 0) — audit verdicts,
        re-shard decisions — parented under the caller's live span."""
        rec = {"id": next(_ids), "parent": current_context(),
               "name": name,
               "thread": threading.current_thread().name,
               "start_ms": round(_now_ms(), 3), "dur_ms": 0.0,
               "event": True}
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self._ring.append(rec)
            self._recorded_total += 1

    # ---------------- failure dumps / introspection ----------------

    def dump(self, reason: str, limit: int = 256) -> dict:
        """Snapshot the open spans + the ring tail under ``reason``;
        kept in a bounded dump list (``spans`` admin route) and
        counted in ``tracing.recorder.dumps``."""
        limit = max(0, int(limit))
        with self._lock:
            open_spans = [dict(r, open=True)
                          for r in self._active.values()]
            tail = list(self._ring)[-limit:] if limit else []
            d = {"reason": reason, "seq": self._dumps_total + 1,
                 "open_spans": open_spans,
                 "spans": [dict(r) for r in tail]}
            self._dumps.append(d)
            self._dumps_total += 1
        registry.counter("tracing.recorder.dumps").inc()
        _log.warning("flight recorder dump (%s): %d open spans, "
                     "%d recent records", reason, len(open_spans),
                     len(d["spans"]))
        return d

    def dumps(self) -> List[dict]:
        with self._lock:
            return list(self._dumps)

    def stats(self) -> dict:
        """Accounting only (the ``dispatch_health`` embed): no record
        copies, minimal time under the recorder lock."""
        with self._lock:
            return {"capacity": self._ring.maxlen,
                    "recorded_total": self._recorded_total,
                    "dumps_total": self._dumps_total,
                    "dump_reasons": [d["reason"]
                                     for d in self._dumps]}

    def snapshot(self, limit: int = 128) -> dict:
        """The ``spans`` admin-route payload: open spans, the most
        recent completed records, and dump accounting. ``limit=0``
        means NO recent records (accounting only — what
        ``dispatch_health`` wants), never the whole ring."""
        limit = max(0, int(limit))
        with self._lock:
            tail = list(self._ring)[-limit:] if limit else []
            return {
                "active": [dict(r) for r in self._active.values()],
                "recent": [dict(r) for r in tail],
                "capacity": self._ring.maxlen,
                "recorded_total": self._recorded_total,
                "dumps_total": self._dumps_total,
                "dump_reasons": [d["reason"] for d in self._dumps],
            }

    def clear(self) -> None:
        """Tests: drop every record, open span, dump and the
        accounting counters — a fresh recorder."""
        with self._lock:
            self._ring.clear()
            self._active.clear()
            self._dumps.clear()
            self._dumps_total = 0
            self._recorded_total = 0


# process-wide recorder (one node per process, like the registry)
flight_recorder = FlightRecorder()


class span:
    """``with span("verify.fetch", device=3): ...`` — inclusive wall
    time into the registry histogram ``span.<name>``, plus a recorder
    record carrying span id, parent link, thread and attrs."""

    _PREFIX = "span"
    __slots__ = ("name", "attrs", "span_id", "parent_id", "_t0",
                 "_rec")

    def __init__(self, name: str, **attrs):
        self.name = name
        self.attrs = attrs

    def __enter__(self):
        st = _stack()
        self.parent_id = st[-1].span_id if st else None
        self.span_id = next(_ids)
        self._rec = {"id": self.span_id, "parent": self.parent_id,
                     "name": f"{self._PREFIX}.{self.name}",
                     "thread": threading.current_thread().name,
                     "start_ms": round(_now_ms(), 3), "dur_ms": None}
        if self.attrs:
            self._rec["attrs"] = dict(self.attrs)
        st.append(self)
        flight_recorder.start_span(self._rec)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._rec.get("abandoned"):
            # already swept into the ring by an outer span's (or
            # anchor's) defensive pop — a late __exit__ (closed
            # generator, GC) must not fabricate a duration spanning
            # the gap nor re-append the record
            return False
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        registry.timer(f"{self._PREFIX}.{self.name}").update_ms(dt_ms)
        self._rec["dur_ms"] = round(dt_ms, 3)
        flight_recorder.finish_span(self._rec)
        # Defensive pop back to SELF: an inner span abandoned mid-flight
        # (entered by hand, a generator that never resumed, an exit
        # skipped by interpreter shutdown) must not leave orphan stack
        # entries poisoning parent links for the rest of the thread's
        # life. Entries above this span are closed into the recorder as
        # abandoned; if this span is not on the stack at all (its own
        # entry was already swept by an outer pop), the stack is left
        # untouched.
        st = _stack()
        if any(e is self for e in st):
            while st:
                top = st.pop()
                if top is self:
                    break
                top._abandon()
        return False

    def _abandon(self):
        rec = getattr(self, "_rec", None)
        if rec is not None and rec.get("dur_ms") is None:
            flight_recorder.abandon_span(rec)


class zone(span):
    """Historical spelling (timer prefix ``zone.``): the ZoneScoped
    analog. A full span — IDs, parent links, recorder coverage."""

    _PREFIX = "zone"
    __slots__ = ()


class _Anchor:
    """Stack entry carrying a borrowed parent span id (cross-thread
    context): invisible to ``current_zones``, never timed."""

    __slots__ = ("span_id", "name")

    def __init__(self, span_id: int):
        self.span_id = span_id
        self.name = None

    def _abandon(self):
        pass


class span_context:
    """Install ``parent_id`` as this thread's innermost span, so spans
    opened here link under a span living on another thread:

        ctx = tracing.current_context()      # caller thread
        with tracing.span_context(ctx): ...  # worker thread

    ``parent_id=None`` is a no-op (callers need no outside-any-span
    special case)."""

    __slots__ = ("_anchor",)

    def __init__(self, parent_id: Optional[int]):
        self._anchor = _Anchor(parent_id) if parent_id is not None \
            else None

    def __enter__(self):
        if self._anchor is not None:
            _stack().append(self._anchor)
        return self

    def __exit__(self, *exc):
        if self._anchor is not None:
            st = _stack()
            if any(e is self._anchor for e in st):
                while st:
                    top = st.pop()
                    if top is self._anchor:
                        break
                    # orphans above the anchor (a span abandoned
                    # inside the pooled fn) get the same treatment as
                    # span.__exit__'s defensive sweep — closed into
                    # the ring as abandoned, never stuck in _active
                    top._abandon()
        return False


def span_totals() -> Dict[str, dict]:
    """``{timer_name: {"count", "sum_ms"}}`` snapshot of every
    registry timer — the delta input of
    ``batch_verifier.dispatch_attribution`` (bench takes one before
    and one after the measured reps). Reads the registry's cheap
    totals accessor, not the full percentile-rendering ``to_dict``."""
    return registry.timer_totals()


class LogSlowExecution:
    """Warn when a scope overruns its budget (reference
    ``LogSlowExecution``: construct at scope entry, log on exit if the
    elapsed wall time exceeds the threshold)."""

    __slots__ = ("name", "threshold_ms", "_t0")

    def __init__(self, name: str, threshold_ms: float = 1000.0):
        self.name = name
        self.threshold_ms = threshold_ms

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        if dt_ms > self.threshold_ms:
            registry.counter(f"slow.{self.name}").inc()
            _log.warning("'%s' hung for %.0f ms (threshold %.0f ms)",
                         self.name, dt_ms, self.threshold_ms)
        return False


def frame_mark() -> None:
    """Per-ledger frame boundary (reference ``FrameMark`` at the end of
    closeLedger, ``LedgerManagerImpl.cpp:1121``)."""
    registry.meter("frame.ledger_close").mark()
