"""Structured tracing: spans + zones + the resolve flight recorder
(reference: Tracy ``ZoneScoped`` annotations — 672 across ``src/`` —
plus ``util/LogSlowExecution.h`` wall-time watchdogs; ISSUE 5 grows
the zone layer into structured spans with IDs, parent links and
cross-thread context propagation).

Model:

* a :class:`span` is a context manager that times one phase of work.
  On entry it draws a process-unique ``span_id``, links to the
  innermost live span of the current thread as ``parent_id``, and
  registers an OPEN record with the :class:`FlightRecorder`; on exit
  it feeds the inclusive duration into the registry timer
  ``span.<name>`` (a reservoir histogram — p50/p90/p99 export) and
  moves the record into the recorder's bounded ring.
* :class:`zone` is the historical spelling (timer prefix ``zone.``);
  it is a span, so every existing ``with zone(...)`` call site gained
  span IDs and recorder coverage for free.
* **cross-thread propagation**: :func:`current_context` captures the
  caller's innermost span id; :class:`span_context` installs it as the
  parent on another thread. ``resilience.WatchdogPool`` does this for
  every guarded call, so a span opened inside a pooled device fetch
  parents correctly under the resolve that submitted it — which is
  exactly what makes a HUNG fetch attributable in a dump.
* the :class:`FlightRecorder` keeps the last N completed spans plus
  every still-open span in memory; ``dump(reason)`` snapshots both on
  breaker trips, audit mismatches, watchdog timeouts and the verify
  service's first load-shed onset (``service-shed:<why>`` —
  ``crypto/batch_verifier.py`` wires all the triggers) so the spans
  leading into a failure survive to be read from the ``spans`` admin
  route. See ``docs/observability.md``.
* **span phase families**: ``verify.*`` phases attribute one blocking
  resolve (``batch_verifier.RESOLVE_PHASES``); ``service.dispatch`` /
  ``service.resolve`` wrap the resident verify service's continuous-
  batching cycle (``crypto/verify_service.py``), so a recorder dump
  taken under overload shows which lane's batch each in-flight
  dispatch is serving.

Determinism: this module is clock-bearing BY DESIGN (``perf_counter``
pairs). Its timings feed metrics and the recorder, never decisions —
and the nondet lint (``stellar_tpu/analysis/nondet.py``) fences
everything except the duration-blind context managers out of the
consensus modules.

Zone times are inclusive, like Tracy; the thread-local stack exists
for parent links and the ``current_zones`` introspection.
"""

from __future__ import annotations

import itertools
import logging
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from stellar_tpu.utils.metrics import registry

__all__ = ["span", "zone", "LogSlowExecution", "current_zones",
           "current_context", "span_context", "frame_mark",
           "FlightRecorder", "flight_recorder", "span_totals",
           "trace_matches"]

_log = logging.getLogger("stellar_tpu.perf")

_tls = threading.local()

# span ids: process-unique, monotone. itertools.count.__next__ is a
# single C call (atomic under the GIL).
_ids = itertools.count(1)

# time origin for span start stamps: milliseconds since tracing
# import, monotonic — no wall clock enters the records
_EPOCH = time.perf_counter()


def _now_ms() -> float:
    return (time.perf_counter() - _EPOCH) * 1000.0


def _stack() -> list:
    s = getattr(_tls, "zones", None)
    if s is None:
        s = _tls.zones = []
    return s


def current_zones() -> List[str]:
    """The live zone/span names of this thread (innermost last);
    context anchors are invisible."""
    return [e.name for e in _stack() if e.name is not None]


def current_context() -> Optional[int]:
    """The innermost live span id of this thread (None outside any
    span) — hand it to another thread via :class:`span_context` so
    spans opened there parent under this one."""
    s = _stack()
    return s[-1].span_id if s else None


def trace_matches(rec: dict, trace_id: int) -> bool:
    """True when a span/event record carries ``trace_id`` in its
    ``traces`` exemplar ranges (ISSUE 8). Trace exemplars are stored
    COMPRESSED as ``[lo, hi)`` pairs (``batch_engine.trace_ranges``)
    so a 2048-item batch costs a handful of ints in the record, not a
    2048-element list — and matching stays exact, never truncated."""
    attrs = rec.get("attrs")
    if not attrs:
        return False
    for pair in attrs.get("traces") or ():
        try:
            lo, hi = pair
        except (TypeError, ValueError):
            continue
        if lo <= trace_id < hi:
            return True
    return False


class FlightRecorder:
    """Bounded in-memory ring of span records + the set of still-open
    spans, dumped on failure triggers (breaker trips, audit
    mismatches, watchdog timeouts).

    Records are plain dicts: ``{"id", "parent", "name", "thread",
    "start_ms", "dur_ms"}`` (+ optional ``attrs`` / ``event`` /
    ``open`` / ``abandoned`` flags). ``dur_ms`` is None while a span
    is open — a dump therefore shows exactly where each in-flight
    thread is parked, with parent links back to the resolve that got
    it there. All shared state mutates under the instance lock (the
    lock-discipline lint covers this module)."""

    DEFAULT_CAPACITY = 4096

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=max(16, int(capacity)))
        self._active: Dict[int, dict] = {}
        self._dumps: deque = deque(maxlen=8)
        self._dumps_total = 0
        self._recorded_total = 0

    def configure(self, capacity: Optional[int] = None) -> None:
        """Config push (FLIGHT_RECORDER_SPANS); None keeps current."""
        if capacity is None:
            return
        cap = max(16, int(capacity))
        with self._lock:
            if cap != self._ring.maxlen:
                self._ring = deque(self._ring, maxlen=cap)

    # ---------------- span lifecycle ----------------

    def start_span(self, rec: dict) -> None:
        with self._lock:
            self._active[rec["id"]] = rec

    def finish_span(self, rec: dict) -> None:
        with self._lock:
            self._active.pop(rec["id"], None)
            self._ring.append(rec)
            self._recorded_total += 1

    def abandon_span(self, rec: dict) -> None:
        """A span whose ``__exit__`` never ran (orphan found by an
        outer span's defensive pop): closed into the ring with an
        ``abandoned`` flag and no duration."""
        rec["abandoned"] = True
        self.finish_span(rec)

    def note(self, name: str, **attrs) -> None:
        """Instant event record (duration 0) — audit verdicts,
        re-shard decisions — parented under the caller's live span."""
        rec = {"id": next(_ids), "parent": current_context(),
               "name": name,
               "thread": threading.current_thread().name,
               "start_ms": round(_now_ms(), 3), "dur_ms": 0.0,
               "event": True}
        if attrs:
            rec["attrs"] = attrs
        with self._lock:
            self._ring.append(rec)
            self._recorded_total += 1

    # ---------------- failure dumps / introspection ----------------

    def dump(self, reason: str, limit: int = 256) -> dict:
        """Snapshot the open spans + the ring tail under ``reason``;
        kept in a bounded dump list (``spans`` admin route) and
        counted in ``tracing.recorder.dumps``."""
        limit = max(0, int(limit))
        with self._lock:
            open_spans = [dict(r, open=True)
                          for r in self._active.values()]
            tail = list(self._ring)[-limit:] if limit else []
            d = {"reason": reason, "seq": self._dumps_total + 1,
                 "open_spans": open_spans,
                 "spans": [dict(r) for r in tail]}
            self._dumps.append(d)
            self._dumps_total += 1
        registry.counter("tracing.recorder.dumps").inc()
        _log.warning("flight recorder dump (%s): %d open spans, "
                     "%d recent records", reason, len(open_spans),
                     len(d["spans"]))
        return d

    def dumps(self) -> List[dict]:
        with self._lock:
            return list(self._dumps)

    def stats(self) -> dict:
        """Accounting only (the ``dispatch_health`` embed): no record
        copies, minimal time under the recorder lock."""
        with self._lock:
            return {"capacity": self._ring.maxlen,
                    "recorded_total": self._recorded_total,
                    "dumps_total": self._dumps_total,
                    "dump_reasons": [d["reason"]
                                     for d in self._dumps]}

    def snapshot(self, limit: int = 128) -> dict:
        """The ``spans`` admin-route payload: open spans, the most
        recent completed records, and dump accounting. ``limit=0``
        means NO recent records (accounting only — what
        ``dispatch_health`` wants), never the whole ring."""
        limit = max(0, int(limit))
        with self._lock:
            tail = list(self._ring)[-limit:] if limit else []
            return {
                "active": [dict(r) for r in self._active.values()],
                "recent": [dict(r) for r in tail],
                "capacity": self._ring.maxlen,
                "recorded_total": self._recorded_total,
                "dumps_total": self._dumps_total,
                "dump_reasons": [d["reason"] for d in self._dumps],
            }

    def trace_timeline(self, trace_id: int) -> dict:
        """Reconstruct one trace's end-to-end timeline (ISSUE 8): every
        record in the ring, the open-span set, and the failure dumps
        whose ``traces`` exemplar ranges contain ``trace_id``, sorted
        by start time, plus derived milestones (queue wait, coalesce,
        dispatch-to-verdict) when the service notes are present. The
        ring is bounded, so a trace older than the retention window
        reconstructs partially (``found`` stays True if anything
        matched) — the ``trace`` admin route serves this payload."""
        tid = int(trace_id)
        with self._lock:
            recs = {r["id"]: dict(r) for r in self._ring
                    if trace_matches(r, tid)}
            for r in self._active.values():
                if trace_matches(r, tid):
                    recs.setdefault(r["id"], dict(r, open=True))
            for d in self._dumps:
                for r in d["spans"] + d["open_spans"]:
                    if trace_matches(r, tid):
                        recs.setdefault(r["id"], dict(r))
        records = sorted(recs.values(),
                         key=lambda r: (r["start_ms"], r["id"]))

        def first(name):
            for r in records:
                if r["name"] == name:
                    return r
            return None

        phases: Dict[str, dict] = {}
        for r in records:
            if r.get("event") or r.get("dur_ms") is None:
                continue
            p = phases.setdefault(r["name"],
                                  {"count": 0, "total_ms": 0.0})
            p["count"] += 1
            p["total_ms"] = round(p["total_ms"] + r["dur_ms"], 3)
        summary = {}
        enq = first("service.enqueue")
        coal = first("service.coalesce")
        verdict = first("service.verdict")
        disp = first("span.service.dispatch")
        # tenant attribution (ISSUE 14): the enqueue milestone carries
        # the submitting tenant, so one item's queue wait is
        # attributable to its principal from the trace route alone
        # (shed/reject milestones carry it too — the fallback covers
        # items refused before any enqueue was recorded)
        for rec in (enq, first("service.shed"),
                    first("service.reject")):
            tenant = (rec or {}).get("attrs", {}).get("tenant")
            if tenant is not None:
                summary["tenant"] = tenant
                break
        if enq and coal:
            summary["queue_wait_ms"] = round(
                coal["start_ms"] - enq["start_ms"], 3)
        if disp and verdict:
            summary["dispatch_to_verdict_ms"] = round(
                verdict["start_ms"] - disp["start_ms"], 3)
        if enq and verdict:
            summary["enqueue_to_verdict_ms"] = round(
                verdict["start_ms"] - enq["start_ms"], 3)
        shed = first("service.shed") or first("service.reject")
        if shed is not None:
            summary["dropped"] = shed["name"]

        # cross-replica stitch summary (ISSUE 20): which segments of
        # the wire -> route -> replica -> verdict path are present,
        # the replica hop sequence (one entry per fleet.route, with
        # the rendezvous score; handoff re-routes flagged), and the
        # seam check — every service.handoff must be followed by a
        # re-admission on a survivor, so a re-homed trace's timeline
        # reads handoff -> route -> enqueue -> verdict with no gap.
        def every(name):
            return [r for r in records if r["name"] == name]

        routes = every("fleet.route")
        handoffs = every("service.handoff")
        enqueues = every("service.enqueue")
        terminal = (first("service.verdict") or first("service.shed")
                    or first("service.reject")
                    or first("fleet.refuse"))
        hops = [{"replica": r.get("attrs", {}).get("replica"),
                 "score": r.get("attrs", {}).get("score"),
                 "handoff": bool(r.get("attrs", {}).get("handoff"))}
                for r in routes]
        order = {r["id"]: i for i, r in enumerate(records)}
        seamless = all(
            any(order[e["id"]] > order[h["id"]] for e in enqueues)
            for h in handoffs)
        stitch = {
            "wire": bool(first("ingress.frame")),
            "route": bool(routes),
            "enqueue": bool(enqueues),
            "terminal": terminal["name"] if terminal else None,
            "hops": hops,
            "handoffs": len(handoffs),
            "seamless": seamless,
            "end_to_end": (bool(first("ingress.frame"))
                           and bool(routes) and terminal is not None
                           and seamless),
        }
        return {"trace": tid, "found": bool(records),
                "records": records, "phases": phases,
                "summary": summary, "stitch": stitch}

    def to_chrome_trace(self, by_replica: bool = False) -> dict:
        """Render the recorder as Chrome ``trace_event`` JSON (the
        ``chrome://tracing`` / Perfetto import format): thread-named
        tracks (metadata ``M`` events), completed spans as properly
        nested ``B``/``E`` pairs, instant events and still-open /
        abandoned spans as ``i`` instants (an open span has no duration
        yet — an instant marks where it is parked). Nesting is derived
        from the records' PARENT LINKS (same-thread), not from interval
        arithmetic, and child intervals are clamped inside their
        parent's, so float rounding can never emit a crossing
        begin/end pair. Counter tracks (``C`` events) from the
        pipeline-bubble profiler ride alongside — per-device in-flight
        state, busy fractions and cumulative transfer bytes share the
        span clock, so one chrome://tracing load shows spans, bytes
        AND utilization (ISSUE 10). Served by ``spans?format=chrome``
        and the ``tools/trace_export.py`` CLI
        (docs/observability.md).

        ``by_replica=True`` (ISSUE 20, ``spans?format=chrome&
        fleet=true``): the whole-fleet window. Records attributable
        to a fleet replica — a ``replica`` attribute, or a
        ``verify-service/<i>`` dispatcher thread — move to per-replica
        process tracks (pid ``2 + i``, named by ``process_name``
        metadata) while everything else stays on the host track (pid
        1). All tracks share the ONE recorder clock, so cross-replica
        ordering in the merged view is real, not cosmetic."""
        with self._lock:
            done = [dict(r) for r in self._ring]
            open_ = [dict(r, open=True)
                     for r in self._active.values()]
        spans = [r for r in done
                 if not r.get("event") and r.get("dur_ms") is not None]
        instants = [r for r in done
                    if r.get("event") or r.get("dur_ms") is None]
        instants += open_
        tids: Dict[str, int] = {}
        seen_tracks: Dict[tuple, str] = {}

        def tid_of(thread: str) -> int:
            if thread not in tids:
                tids[thread] = len(tids) + 1
            return tids[thread]

        def pid_of(r) -> int:
            if not by_replica:
                return 1
            rep = (r.get("attrs") or {}).get("replica")
            if rep is None:
                th = r.get("thread", "")
                if th.startswith("verify-service/"):
                    tail = th.rsplit("/", 1)[1]
                    if tail.isdigit():
                        rep = int(tail)
            try:
                return 1 if rep is None else 2 + int(rep)
            except (TypeError, ValueError):
                return 1

        def track(pid: int, r) -> int:
            tid = tid_of(r["thread"])
            seen_tracks.setdefault((pid, tid), r["thread"])
            return tid

        by_id = {r["id"]: r for r in spans}
        children: Dict[int, list] = {}
        roots: Dict[str, list] = {}
        for r in spans:
            p = r.get("parent")
            if p in by_id and by_id[p]["thread"] == r["thread"]:
                children.setdefault(p, []).append(r)
            else:
                roots.setdefault(r["thread"], []).append(r)
        events: List[dict] = []

        def emit(r, lo_ms: float, hi_ms: float, pid: int) -> float:
            """Emit one span's B/E pair (and its subtree), clamped to
            the parent interval [lo_ms, hi_ms]; returns this span's
            end so siblings can't overlap. The subtree inherits the
            root's pid — nesting must stay within one track."""
            t0 = min(max(r["start_ms"], lo_ms), hi_ms)
            t1 = min(max(t0, r["start_ms"] + r["dur_ms"]), hi_ms)
            tid = track(pid, r)
            args = {"id": r["id"]}
            if r.get("attrs"):
                args.update(r["attrs"])
            events.append({"name": r["name"], "ph": "B", "pid": pid,
                           "tid": tid, "ts": round(t0 * 1000.0, 1),
                           "args": args})
            cursor = t0
            for c in sorted(children.get(r["id"], []),
                            key=lambda x: (x["start_ms"], x["id"])):
                cursor = emit(c, max(cursor, t0), t1, pid)
            events.append({"name": r["name"], "ph": "E", "pid": pid,
                           "tid": tid, "ts": round(t1 * 1000.0, 1)})
            return t1

        for thread, rs in sorted(roots.items()):
            cursor = 0.0
            for r in sorted(rs, key=lambda x: (x["start_ms"], x["id"])):
                cursor = emit(r, max(cursor, r["start_ms"]),
                              float("inf"), pid_of(r))
        for r in instants:
            args = {"id": r["id"]}
            if r.get("attrs"):
                args.update(r["attrs"])
            if r.get("open"):
                args["open"] = True
            if r.get("abandoned"):
                args["abandoned"] = True
            pid = pid_of(r)
            events.append({"name": r["name"], "ph": "i", "pid": pid,
                           "tid": track(pid, r), "s": "t",
                           "ts": round(r["start_ms"] * 1000.0, 1),
                           "args": args})
        # pipeline utilization + transfer-byte counter tracks
        # (ISSUE 10): lazy import — timeline imports this module at
        # load time, and the export path only ever runs long after
        # both are imported
        try:
            from stellar_tpu.utils.timeline import pipeline_timeline
            events += pipeline_timeline.chrome_counter_events()
        except ImportError:  # pragma: no cover — import-order edge
            pass
        meta = [{"name": "thread_name", "ph": "M", "pid": pid,
                 "tid": tid, "args": {"name": thread}}
                for (pid, tid), thread in sorted(seen_tracks.items())]
        if by_replica:
            pids = sorted({pid for pid, _tid in seen_tracks})
            meta += [{"name": "process_name", "ph": "M", "pid": pid,
                      "tid": 0,
                      "args": {"name": ("host" if pid == 1 else
                                        f"replica {pid - 2}")}}
                     for pid in pids]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def clear(self) -> None:
        """Tests: drop every record, open span, dump and the
        accounting counters — a fresh recorder."""
        with self._lock:
            self._ring.clear()
            self._active.clear()
            self._dumps.clear()
            self._dumps_total = 0
            self._recorded_total = 0


# process-wide recorder (one node per process, like the registry)
flight_recorder = FlightRecorder()


class span:
    """``with span("verify.fetch", device=3): ...`` — inclusive wall
    time into the registry histogram ``span.<name>``, plus a recorder
    record carrying span id, parent link, thread and attrs.

    ``_collect`` (ISSUE 8) makes a span a ROOT-ATTRIBUTED collector:
    same-thread descendant spans whose names are in the set fold their
    inclusive durations into the collector, and the collector flushes
    the totals into ``span.attr.<name>`` timers only when IT exits.
    That is what makes ``phase_attribution`` idempotent under
    re-shard/retry re-entry: a phase re-entered inside a resolve that
    has not completed contributes nothing to the attribution timers,
    so a ``span_totals()`` snapshot taken mid-resolve can never count
    a phase whose blocking root is still open (the phases' own
    ``span.<name>`` timers update per-exit as before — the recorder
    and per-phase histograms are unchanged)."""

    _PREFIX = "span"
    __slots__ = ("name", "attrs", "span_id", "parent_id", "_t0",
                 "_rec", "_collect", "_collected")

    def __init__(self, name: str, _collect=None, **attrs):
        self.name = name
        self.attrs = attrs
        self._collect = None if _collect is None else frozenset(_collect)
        self._collected = None if _collect is None else {}

    def __enter__(self):
        st = _stack()
        self.parent_id = st[-1].span_id if st else None
        self.span_id = next(_ids)
        self._rec = {"id": self.span_id, "parent": self.parent_id,
                     "name": f"{self._PREFIX}.{self.name}",
                     "thread": threading.current_thread().name,
                     "start_ms": round(_now_ms(), 3), "dur_ms": None}
        if self.attrs:
            self._rec["attrs"] = dict(self.attrs)
        st.append(self)
        flight_recorder.start_span(self._rec)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        if self._rec.get("abandoned"):
            # already swept into the ring by an outer span's (or
            # anchor's) defensive pop — a late __exit__ (closed
            # generator, GC) must not fabricate a duration spanning
            # the gap nor re-append the record
            return False
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        registry.timer(f"{self._PREFIX}.{self.name}").update_ms(dt_ms)
        self._rec["dur_ms"] = round(dt_ms, 3)
        flight_recorder.finish_span(self._rec)
        # Root-attributed phase accounting (ISSUE 8): fold this span's
        # inclusive time into the nearest enclosing collector on THIS
        # thread that registered its name. The collector's dict is
        # touched only from its own thread (the stack is thread-local),
        # so no lock is needed.
        st = _stack()
        for e in reversed(st):
            if e is self:
                continue
            coll = getattr(e, "_collect", None)
            if coll is not None and self.name in coll:
                tot = e._collected.get(self.name)
                if tot is None:
                    e._collected[self.name] = [1, dt_ms]
                else:
                    tot[0] += 1
                    tot[1] += dt_ms
                break
        if self._collect is not None and self._collected:
            # flush AFTER this root's own timer updated: a snapshot
            # racing the flush sees the root without its phases
            # (coverage dips toward under-attribution, never inflates
            # past 1 by a phantom in-flight resolve)
            for name, (cnt, sum_ms) in self._collected.items():
                registry.timer(f"span.attr.{name}").record_total(
                    cnt, sum_ms)
        # Defensive pop back to SELF: an inner span abandoned mid-flight
        # (entered by hand, a generator that never resumed, an exit
        # skipped by interpreter shutdown) must not leave orphan stack
        # entries poisoning parent links for the rest of the thread's
        # life. Entries above this span are closed into the recorder as
        # abandoned; if this span is not on the stack at all (its own
        # entry was already swept by an outer pop), the stack is left
        # untouched.
        st = _stack()
        if any(e is self for e in st):
            while st:
                top = st.pop()
                if top is self:
                    break
                top._abandon()
        return False

    def _abandon(self):
        rec = getattr(self, "_rec", None)
        if rec is not None and rec.get("dur_ms") is None:
            flight_recorder.abandon_span(rec)


class zone(span):
    """Historical spelling (timer prefix ``zone.``): the ZoneScoped
    analog. A full span — IDs, parent links, recorder coverage."""

    _PREFIX = "zone"
    __slots__ = ()


class _Anchor:
    """Stack entry carrying a borrowed parent span id (cross-thread
    context): invisible to ``current_zones``, never timed."""

    __slots__ = ("span_id", "name")

    def __init__(self, span_id: int):
        self.span_id = span_id
        self.name = None

    def _abandon(self):
        pass


class span_context:
    """Install ``parent_id`` as this thread's innermost span, so spans
    opened here link under a span living on another thread:

        ctx = tracing.current_context()      # caller thread
        with tracing.span_context(ctx): ...  # worker thread

    ``parent_id=None`` is a no-op (callers need no outside-any-span
    special case)."""

    __slots__ = ("_anchor",)

    def __init__(self, parent_id: Optional[int]):
        self._anchor = _Anchor(parent_id) if parent_id is not None \
            else None

    def __enter__(self):
        if self._anchor is not None:
            _stack().append(self._anchor)
        return self

    def __exit__(self, *exc):
        if self._anchor is not None:
            st = _stack()
            if any(e is self._anchor for e in st):
                while st:
                    top = st.pop()
                    if top is self._anchor:
                        break
                    # orphans above the anchor (a span abandoned
                    # inside the pooled fn) get the same treatment as
                    # span.__exit__'s defensive sweep — closed into
                    # the ring as abandoned, never stuck in _active
                    top._abandon()
        return False


def span_totals() -> Dict[str, dict]:
    """``{timer_name: {"count", "sum_ms"}}`` snapshot of every
    registry timer — the delta input of
    ``batch_verifier.dispatch_attribution`` (bench takes one before
    and one after the measured reps). Reads the registry's cheap
    totals accessor, not the full percentile-rendering ``to_dict``."""
    return registry.timer_totals()


class LogSlowExecution:
    """Warn when a scope overruns its budget (reference
    ``LogSlowExecution``: construct at scope entry, log on exit if the
    elapsed wall time exceeds the threshold)."""

    __slots__ = ("name", "threshold_ms", "_t0")

    def __init__(self, name: str, threshold_ms: float = 1000.0):
        self.name = name
        self.threshold_ms = threshold_ms

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        if dt_ms > self.threshold_ms:
            registry.counter(f"slow.{self.name}").inc()
            _log.warning("'%s' hung for %.0f ms (threshold %.0f ms)",
                         self.name, dt_ms, self.threshold_ms)
        return False


def frame_mark() -> None:
    """Per-ledger frame boundary (reference ``FrameMark`` at the end of
    closeLedger, ``LedgerManagerImpl.cpp:1121``)."""
    registry.meter("frame.ledger_close").mark()
