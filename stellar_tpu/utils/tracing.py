"""Lightweight tracing: nested zones + slow-execution watchdogs
(reference: Tracy ``ZoneScoped`` annotations — 672 across ``src/`` —
and ``util/LogSlowExecution.h`` wall-time watchdogs, e.g. the ledger
close monitor at ``ledger/LedgerManagerImpl.cpp:817``).

Zones are always-on but cheap: one ``perf_counter`` pair and a registry
timer update per zone. A thread-local stack records nesting so a zone's
metric name reflects its own cost (not children's) is NOT attempted —
like Tracy, zone times are inclusive; the stack exists for the ``info``
introspection of where time goes (``current_zones``).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List

from stellar_tpu.utils.metrics import registry

__all__ = ["zone", "LogSlowExecution", "current_zones", "frame_mark"]

_log = logging.getLogger("stellar_tpu.perf")

_tls = threading.local()


def _stack() -> List[str]:
    s = getattr(_tls, "zones", None)
    if s is None:
        s = _tls.zones = []
    return s


def current_zones() -> List[str]:
    """The live zone stack of this thread (innermost last)."""
    return list(_stack())


class zone:
    """``with zone("ledger.close"): ...`` — inclusive wall time into the
    registry timer ``zone.<name>`` (the ZoneScoped analog)."""

    __slots__ = ("name", "_t0")

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        _stack().append(self.name)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        registry.timer(f"zone.{self.name}").update_ms(dt_ms)
        s = _stack()
        if s and s[-1] == self.name:
            s.pop()
        return False


class LogSlowExecution:
    """Warn when a scope overruns its budget (reference
    ``LogSlowExecution``: construct at scope entry, log on exit if the
    elapsed wall time exceeds the threshold)."""

    __slots__ = ("name", "threshold_ms", "_t0")

    def __init__(self, name: str, threshold_ms: float = 1000.0):
        self.name = name
        self.threshold_ms = threshold_ms

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1000.0
        if dt_ms > self.threshold_ms:
            registry.counter(f"slow.{self.name}").inc()
            _log.warning("'%s' hung for %.0f ms (threshold %.0f ms)",
                         self.name, dt_ms, self.threshold_ms)
        return False


def frame_mark() -> None:
    """Per-ledger frame boundary (reference ``FrameMark`` at the end of
    closeLedger, ``LedgerManagerImpl.cpp:1121``)."""
    registry.meter("frame.ledger_close").mark()
