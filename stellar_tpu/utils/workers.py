"""Process-wide background worker pool for off-crank ledger work —
bucket merges, eviction-scan enumeration, and other deferred
computation (reference: the worker thread pool behind
``Application::postOnBackgroundThread``, ``src/main/Application.h`` —
FutureBucket merges, the background eviction scan, and overlay
pre-verification all ride it).

Everything submitted here must be a PURE computation over immutable
inputs: results are resolved at deterministic points in the crank, so
scheduling can never change consensus state — only when the work
happens. ``set_background(False)`` turns the pool into synchronous
inline execution (tests pin result-identity between the two modes).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Optional

__all__ = ["run_async", "set_background", "background_enabled",
           "shutdown"]

_pool: Optional[ThreadPoolExecutor] = None
_lock = threading.Lock()
_background = True


def _get_pool() -> ThreadPoolExecutor:
    global _pool
    with _lock:
        if _pool is None:
            workers = min(4, max(2, (os.cpu_count() or 2) - 1))
            _pool = ThreadPoolExecutor(max_workers=workers,
                                       thread_name_prefix="bg-work")
        return _pool


def set_background(enabled: bool) -> None:
    """Toggle background execution (False = run submissions inline;
    used by determinism tests and the ARTIFICIALLY_* config knobs)."""
    global _background
    with _lock:
        _background = enabled


def background_enabled() -> bool:
    return _background


def run_async(fn: Callable, *args) -> Future:
    """Submit a pure computation; returns a Future. In synchronous
    mode the call runs inline and the Future is already resolved."""
    if not _background:
        f: Future = Future()
        try:
            f.set_result(fn(*args))
        except BaseException as e:  # deferred, raised at .result()
            f.set_exception(e)
        return f
    return _get_pool().submit(fn, *args)


def shutdown() -> None:
    global _pool
    with _lock:
        pool, _pool = _pool, None
    if pool is not None:
        # outside _lock: waiting for in-flight work while holding the
        # submission lock would wedge any concurrent run_async caller
        pool.shutdown(wait=True)
