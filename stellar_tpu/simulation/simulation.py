"""Multi-node in-process simulation (reference
``src/simulation/Simulation.h:29-132`` + ``Topologies.cpp``): N complete
Applications in one process over loopback transports, cranked in
lockstep on one shared VIRTUAL_TIME clock — the load-bearing mechanism
that lets a consensus network be tested deterministically on one
machine."""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from stellar_tpu.crypto.keys import SecretKey
from stellar_tpu.main.application import Application
from stellar_tpu.main.config import Config
from stellar_tpu.overlay.loopback import connect_loopback
from stellar_tpu.scp.quorum import make_node_id
from stellar_tpu.utils.timer import VIRTUAL_TIME, VirtualClock
from stellar_tpu.xdr.scp import SCPQuorumSet

__all__ = ["Simulation", "Topologies"]


class Simulation:
    OVER_LOOPBACK = "loopback"
    OVER_TCP = "tcp"

    def __init__(self, mode: str = OVER_LOOPBACK,
                 network_passphrase: str = "simulation network"):
        self.mode = mode
        self.network_passphrase = network_passphrase
        # loopback: ONE shared virtual clock cranked in lockstep;
        # tcp: per-node real-time clocks + real sockets on localhost
        # (reference Simulation::OVER_TCP)
        self.clock = VirtualClock(VIRTUAL_TIME)
        self.nodes: Dict[bytes, Application] = {}
        self.drivers: Dict[bytes, object] = {}
        self.pending_connections: List = []

    # ---------------- construction ----------------

    def add_node(self, seed: SecretKey, qset: SCPQuorumSet,
                 accounts=None, config: Optional[Config] = None
                 ) -> Application:
        cfg = config if config is not None else Config()
        if config is None:
            # reference test harness parity (test.cpp:321): in-process
            # simulation nodes skip the background quorum-intersection
            # recheck unless a test opts in — 16-validator storms would
            # otherwise spend their wall time in bounded sat searches
            cfg.QUORUM_INTERSECTION_CHECKER = False
        cfg.NODE_SEED = seed
        cfg.QUORUM_SET = qset
        cfg.NETWORK_PASSPHRASE = self.network_passphrase
        root = None
        if accounts:
            from stellar_tpu.tx.tx_test_utils import (
                seed_root_with_accounts,
            )
            root = seed_root_with_accounts(list(accounts))
        if self.mode == self.OVER_TCP:
            from stellar_tpu.overlay.tcp import TCPDriver
            from stellar_tpu.utils.timer import REAL_TIME
            app = Application(cfg, clock=VirtualClock(REAL_TIME),
                              root=root)
            self.drivers[seed.public_key.raw] = TCPDriver(
                app, listen_port=0)
        else:
            app = Application(cfg, clock=self.clock, root=root)
        self.nodes[seed.public_key.raw] = app
        return app

    def add_connection(self, node_a: bytes, node_b: bytes):
        if self.mode == self.OVER_TCP:
            return self.drivers[node_a].connect(
                "127.0.0.1", self.drivers[node_b].door.port)
        return connect_loopback(self.nodes[node_a], self.nodes[node_b])

    def start_all_nodes(self):
        for app in self.nodes.values():
            app.start()

    def close(self):
        """Tear down TCP listeners/sockets (no-op for loopback)."""
        for d in self.drivers.values():
            d.close()

    # ---------------- cranking ----------------

    def crank_all_nodes(self, n: int = 1) -> int:
        progress = 0
        if self.mode == self.OVER_TCP:
            for _ in range(n):
                for app in self.nodes.values():
                    progress += app.crank(block=False)
            return progress
        for _ in range(n):
            progress += self.clock.crank(block=True)
        return progress

    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 120.0) -> bool:
        if self.mode == self.OVER_TCP:
            import time as _time
            deadline = _time.monotonic() + timeout
            while _time.monotonic() < deadline:
                if pred():
                    return True
                worked = self.crank_all_nodes()
                if not worked:
                    _time.sleep(0.005)
            return pred()
        return self.clock.crank_until(pred, timeout)

    def crank_until_ledger(self, seq: int, timeout: float = 120.0) -> bool:
        return self.crank_until(
            lambda: all(a.lm.ledger_seq >= seq
                        for a in self.nodes.values()), timeout)

    # ---------------- convenience ----------------

    def ledger_hashes(self) -> set:
        return {a.lm.last_closed_hash for a in self.nodes.values()}

    def in_consensus(self) -> bool:
        return len(self.ledger_hashes()) == 1


class Topologies:
    """Standard test topologies (reference ``Topologies.cpp``)."""

    @staticmethod
    def core(n: int, sim: Optional[Simulation] = None, accounts=None,
             threshold: Optional[int] = None):
        """Fully connected clique of n validators sharing one qset
        (reference ``Topologies::core``)."""
        sim = sim if sim is not None else Simulation()
        keys = [SecretKey.from_seed_str(f"sim-node-{i}")
                for i in range(n)]
        qset = SCPQuorumSet(
            threshold=threshold if threshold is not None
            else n - (n - 1) // 3,
            validators=[make_node_id(k.public_key.raw) for k in keys],
            innerSets=[])
        for k in keys:
            sim.add_node(k, qset, accounts=accounts)
        ids = [k.public_key.raw for k in keys]
        for i in range(n):
            for j in range(i + 1, n):
                sim.add_connection(ids[i], ids[j])
        return sim

    @staticmethod
    def core4(sim=None, accounts=None):
        return Topologies.core(4, sim, accounts)

    @staticmethod
    def pair(sim: Optional[Simulation] = None, accounts=None):
        """Two mutually trusting validators (reference
        ``Topologies::pair``)."""
        return Topologies.core(2, sim, accounts, threshold=2)

    @staticmethod
    def branched_cycle(n: int, sim: Optional[Simulation] = None,
                       accounts=None):
        """Ring of n core validators, each with one leaf validator
        hanging off it (reference ``Topologies::branchedcycle``): the
        leaf trusts {self, core} (both required); the core nodes run
        the cycle quorum. Exercises asymmetric trust + non-clique
        connectivity."""
        sim = Topologies.cycle(n, sim, accounts)
        core_ids = list(sim.nodes)[-n:]  # the nodes cycle() just added
        for i, core_id in enumerate(core_ids):
            leaf = SecretKey.from_seed_str(f"sim-leaf-{i}")
            qset = SCPQuorumSet(
                threshold=2,
                validators=[make_node_id(leaf.public_key.raw),
                            make_node_id(core_id)],
                innerSets=[])
            sim.add_node(leaf, qset, accounts=accounts)
            sim.add_connection(leaf.public_key.raw, core_id)
        return sim

    @staticmethod
    def hierarchical_quorum(n_core: int = 4, n_branches: int = 2,
                            branch_size: int = 3,
                            sim: Optional[Simulation] = None,
                            accounts=None):
        """Tiered quorums (reference ``Topologies::hierarchicalQuorum``):
        a BFT core clique, plus branches of validators whose quorum
        requires BOTH a core majority and a branch majority."""
        sim = sim if sim is not None else Simulation()
        # the BFT core clique is exactly Topologies.core
        sim = Topologies.core(n_core, sim, accounts)
        core_ids = list(sim.nodes)[-n_core:]
        core_qset = sim.nodes[core_ids[0]].config.QUORUM_SET
        for b in range(n_branches):
            branch_keys = [
                SecretKey.from_seed_str(f"sim-hq-b{b}-{i}")
                for i in range(branch_size)]
            branch_set = SCPQuorumSet(
                threshold=branch_size // 2 + 1,
                validators=[make_node_id(k.public_key.raw)
                            for k in branch_keys],
                innerSets=[])
            qset = SCPQuorumSet(threshold=2, validators=[],
                                innerSets=[core_qset, branch_set])
            for k in branch_keys:
                sim.add_node(k, qset, accounts=accounts)
            bids = [k.public_key.raw for k in branch_keys]
            for i in range(branch_size):
                for j in range(i + 1, branch_size):
                    sim.add_connection(bids[i], bids[j])
                # every branch node also talks to every core node
                for cid in core_ids:
                    sim.add_connection(bids[i], cid)
        return sim

    @staticmethod
    def cycle(n: int, sim: Optional[Simulation] = None, accounts=None):
        """Ring: each node trusts itself + both neighbours, all three
        required — adjacent slices overlap, so quorum intersection
        holds (threshold 2 would admit disjoint quorums)."""
        sim = sim if sim is not None else Simulation()
        keys = [SecretKey.from_seed_str(f"sim-ring-{i}")
                for i in range(n)]
        for i, k in enumerate(keys):
            left = keys[(i - 1) % n]
            right = keys[(i + 1) % n]
            qset = SCPQuorumSet(
                threshold=3,
                validators=[make_node_id(x.public_key.raw)
                            for x in (k, left, right)],
                innerSets=[])
            sim.add_node(k, qset, accounts=accounts)
        ids = [k.public_key.raw for k in keys]
        for i in range(n):
            sim.add_connection(ids[i], ids[(i + 1) % n])
        return sim
