"""Load generation + apply-load benchmarking (reference
``src/simulation/LoadGenerator.h:30-49`` modes and ``ApplyLoad.h:14-55``
— synthetic tx queues driven through the real close pipeline, measuring
the ``ledger.ledger.close`` timer)."""

from __future__ import annotations

import time
from typing import List, Optional

from stellar_tpu.crypto.keys import SecretKey
from stellar_tpu.herder.tx_set import make_tx_set_from_transactions
from stellar_tpu.ledger.ledger_manager import LedgerCloseData, LedgerManager
from stellar_tpu.utils.metrics import registry

__all__ = ["LoadGenerator", "apply_load"]

XLM = 10_000_000


class LoadGenerator:
    """Paced synthetic traffic through a real herder (reference
    ``LoadGenerator``: CREATE + PAY modes)."""

    def __init__(self, app, n_accounts: int = 16):
        self.app = app
        self.accounts: List[SecretKey] = [
            SecretKey.from_seed_str(f"loadgen-{i}")
            for i in range(n_accounts)]
        self.seqs = {}
        self.submitted = 0

    def account_keys(self):
        return self.accounts

    def generate_load(self, n_txs: int, source_balances_known=True):
        """Submit n payment txs round-robin across accounts."""
        from stellar_tpu.ledger.ledger_txn import key_bytes
        from stellar_tpu.tx.op_frame import account_key
        from stellar_tpu.tx.tx_test_utils import make_tx, payment_op
        from stellar_tpu.xdr.types import account_id
        herder = self.app.herder
        for i in range(n_txs):
            src = self.accounts[i % len(self.accounts)]
            dst = self.accounts[(i + 1) % len(self.accounts)]
            raw = src.public_key.raw
            if raw not in self.seqs:
                e = herder.lm.root.store.get(
                    key_bytes(account_key(account_id(raw))))
                if e is None:
                    continue
                self.seqs[raw] = e.data.value.seqNum
            self.seqs[raw] += 1
            tx = make_tx(src, self.seqs[raw], [payment_op(dst, XLM)],
                         network_id=herder.network_id)
            herder.recv_transaction(tx)
            self.submitted += 1


def apply_load(n_ledgers: int = 10, txs_per_ledger: int = 100,
               n_accounts: int = 64) -> dict:
    """Standalone close-ledger benchmark (reference ``apply-load``):
    build txsets from a synthetic queue and drive closeLedger, reporting
    the close-timer distribution."""
    from stellar_tpu.tx.tx_test_utils import (
        TEST_NETWORK_ID, make_tx, payment_op, seed_root_with_accounts,
    )
    keys = [SecretKey.from_seed_str(f"applyload-{i}")
            for i in range(n_accounts)]
    root = seed_root_with_accounts([(k, 10**13) for k in keys])
    lm = LedgerManager(TEST_NETWORK_ID, root)
    lm.last_closed_header.maxTxSetSize = max(1000, txs_per_ledger * 2)
    close_timer = registry.timer("ledger.ledger.close")
    seqs = {k.public_key.raw: (1 << 32) for k in keys}
    total_applied = 0
    for ledger_i in range(n_ledgers):
        frames = []
        for t in range(txs_per_ledger):
            src = keys[t % len(keys)]
            dst = keys[(t + 1) % len(keys)]
            seqs[src.public_key.raw] += 1
            frames.append(make_tx(
                src, seqs[src.public_key.raw], [payment_op(dst, XLM)]))
        txset, excluded = make_tx_set_from_transactions(
            frames, lm.last_closed_header, lm.last_closed_hash)
        with close_timer.time():
            res = lm.close_ledger(LedgerCloseData(
                lm.ledger_seq + 1, txset,
                lm.last_closed_header.scpValue.closeTime + 5))
        if res.failed_count:
            raise RuntimeError(f"apply-load tx failures: "
                               f"{res.failed_count}")
        total_applied += res.applied_count
    stats = close_timer.to_dict()
    return {
        "ledgers": n_ledgers,
        "txs_per_ledger": txs_per_ledger,
        "total_applied": total_applied,
        "close_min_ms": stats["min_ms"],
        "close_mean_ms": stats["mean_ms"],
        "close_max_ms": stats["max_ms"],
        "close_stddev_ms": stats["stddev_ms"],
        "tx_apply_per_sec": round(
            total_applied / (stats["mean_ms"] * n_ledgers / 1000.0), 1)
        if stats["mean_ms"] else 0.0,
    }


def catchup_replay_bench(n_ledgers: int = 256,
                         txs_per_ledger: int = 20) -> dict:
    """BASELINE config #3 shape: publish a chain, then time a fresh
    node's COMPLETE replay (signature-bound without the batch
    verifier)."""
    import tempfile
    import time as _time
    from stellar_tpu.catchup.catchup import (
        CatchupConfiguration, CatchupWork,
    )
    from stellar_tpu.history.history_manager import (
        FileArchive, HistoryManager,
    )
    from stellar_tpu.tx.tx_test_utils import (
        TEST_NETWORK_ID, make_tx, payment_op, seed_root_with_accounts,
    )
    from stellar_tpu.utils.timer import VIRTUAL_TIME, VirtualClock
    from stellar_tpu.work.work import State, WorkScheduler

    keys = [SecretKey.from_seed_str(f"cr-{i}") for i in range(8)]
    root = seed_root_with_accounts([(k, 10**13) for k in keys])
    lm = LedgerManager(TEST_NETWORK_ID, root)
    lm.last_closed_header.maxTxSetSize = max(1000, txs_per_ledger * 2)
    tmp = tempfile.mkdtemp(prefix="stpu-catchup-bench-")
    hm = HistoryManager([FileArchive(tmp)], "bench")
    seqs = {k.public_key.raw: (1 << 32) for k in keys}
    for i in range(n_ledgers):
        frames = []
        for t in range(txs_per_ledger):
            src = keys[t % len(keys)]
            seqs[src.public_key.raw] += 1
            frames.append(make_tx(
                src, seqs[src.public_key.raw],
                [payment_op(keys[(t + 1) % len(keys)], XLM)]))
        txset, _ = make_tx_set_from_transactions(
            frames, lm.last_closed_header, lm.last_closed_hash)
        res = lm.close_ledger(LedgerCloseData(
            lm.ledger_seq + 1, txset,
            lm.last_closed_header.scpValue.closeTime + 5))
        hm.ledger_closed(res, txset, lm.bucket_list)

    root2 = seed_root_with_accounts([(k, 10**13) for k in keys])
    lm2 = LedgerManager(TEST_NETWORK_ID, root2)
    # genesis must match the published chain's bit-for-bit
    lm2.last_closed_header.maxTxSetSize = \
        max(1000, txs_per_ledger * 2)
    ws = WorkScheduler(VirtualClock(VIRTUAL_TIME))
    target = hm.published_checkpoints[-1]
    work = CatchupWork(lm2, FileArchive(tmp),
                       CatchupConfiguration(target))
    t0 = _time.perf_counter()
    ws.schedule(work)
    ws.run_until_done(timeout=3600)
    dt = _time.perf_counter() - t0
    assert work.state == State.SUCCESS
    replayed = lm2.ledger_seq - 2
    return {
        "scenario": "catchup-replay",
        "replayed_ledgers": replayed,
        "txs_per_ledger": txs_per_ledger,
        "wall_s": round(dt, 2),
        "ledgers_per_sec": round(replayed / dt, 2),
        "txs_per_sec": round(replayed * txs_per_ledger / dt, 1),
    }


def scp_storm_bench(n_validators: int = 16, n_rounds: int = 5) -> dict:
    """BASELINE config #4 shape: N validators × M consensus rounds on
    the loopback overlay; reports rounds/sec and envelope counts."""
    import time as _time
    from stellar_tpu.simulation.simulation import Topologies
    sim = Topologies.core(n_validators)
    sim.start_all_nodes()
    apps = list(sim.nodes.values())
    ok = sim.crank_until(
        lambda: all(a.overlay.authenticated_count() >= n_validators - 1
                    for a in apps), 60)
    assert ok, "mesh never authenticated"
    start_seq = apps[0].lm.ledger_seq
    t0 = _time.perf_counter()
    assert sim.crank_until_ledger(start_seq + n_rounds, timeout=600)
    dt = _time.perf_counter() - t0
    assert sim.in_consensus()
    envelopes = sum(
        len(slot.statements_history)
        for a in apps for slot in a.herder.scp.known_slots.values())
    return {
        "scenario": "scp-storm",
        "validators": n_validators,
        "rounds": n_rounds,
        "wall_s": round(dt, 2),
        "rounds_per_sec": round(n_rounds / dt, 3),
        "total_statements": envelopes,
    }
